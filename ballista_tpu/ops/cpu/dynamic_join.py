"""Mid-stage dynamic join selection.

Rebuild of the reference's deferred-join-decision node
(scheduler/src/state/aqe/execution_plan/dynamic_join.rs:53 +
optimizer_rule/join_selection.rs). The reference's operator is a pure
placeholder — an AQE optimizer rule must replace it at stage resolution or
execute() errors (dynamic_join.rs:104-115). This engine keeps the
resolution-time path (scheduler/aqe/rules.py resolves the node when input
stats are known) but the operator is ALSO executable: when a stage runs
with unknown input sizes, it observes its inputs at first-batch time —
BufferExec's dam semantics (ops/cpu/range_repartition.py:79) applied to
both join inputs — and only then instantiates the concrete HashJoinExec.

Decision matrix (mirrors dynamic_join.rs:214-330's to_actual_join):
 * build side = the smaller side whose TOTAL size the dam proved (a side
   that exhausted under the byte budget has exact bytes/rows; one that
   overflowed is only known to be "big");
 * collect_left (broadcast-style collected build) when the chosen build
   fits the broadcast byte threshold AND the row threshold AND the
   (possibly swapped) join type only emits probe-side rows — the same
   safety rule the static planner applies (physical_planner.py:548-550,
   reference collect_left_broadcast_safe);
 * otherwise a partitioned hash join, swapped onto the proven-smaller
   build side when one exists;
 * both sides overflowed ⇒ the planned partitioned join runs unchanged.

Observed batches are never re-read from the child: replay sources hand
them back to the concrete join, then continue the live iterators.
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator, Optional

import pyarrow as pa

from ballista_tpu.config import (
    BROADCAST_JOIN_ROWS_THRESHOLD,
    BROADCAST_JOIN_THRESHOLD,
)
from ballista_tpu.plan.expressions import Column, Expr
from ballista_tpu.plan.physical import (
    ExecutionPlan,
    HashJoinExec,
    ProjectionExec,
    TaskContext,
)
from ballista_tpu.plan.schema import DFSchema

log = logging.getLogger(__name__)

# join types whose collected build may be shared by independently decoded
# probe tasks: they never emit rows on BEHALF of the build side
_COLLECT_SAFE = frozenset({"inner", "right", "right_semi", "right_anti"})

_SWAP = {
    "inner": "inner", "full": "full", "left": "right", "right": "left",
    "left_semi": "right_semi", "right_semi": "left_semi",
    "left_anti": "right_anti", "right_anti": "left_anti",
}


def select_strategy(l_bytes: int, l_rows: int, l_known: bool,
                    r_bytes: int, r_rows: int, r_known: bool,
                    join_type: str, probe_single_partition: bool,
                    byte_thr: int, rows_thr: int) -> tuple[str, bool, str]:
    """The decision matrix, pure (dynamic_join.rs:214-330's to_actual_join).

    `*_known` = the side's TOTAL size is proven (stage stats at resolution
    time, or the dam exhausted the side at first-batch time). Returns
    (decision_label, swap, mode). A byte threshold of 0 disables promotion
    entirely; the row threshold is a conjunct, mirroring the static planner.
    """
    if byte_thr <= 0 or (not l_known and not r_known):
        return "AsPlanned", False, "partitioned"
    if l_known and r_known:
        swap = r_bytes < l_bytes
    else:
        swap = r_known  # only one side proven: build from it
    b_bytes, b_rows = (r_bytes, r_rows) if swap else (l_bytes, l_rows)
    jt = _SWAP[join_type] if swap else join_type
    collect = (
        b_bytes <= byte_thr
        and b_rows <= rows_thr
        and (jt in _COLLECT_SAFE or probe_single_partition)
    )
    if collect:
        return ("BroadcastSwapped" if swap else "Broadcast"), swap, "collect_left"
    return ("PartitionedSwapped" if swap else "Partitioned"), swap, "partitioned"


class _Observation:
    """One side's dam result: buffered batches per partition plus any
    still-open iterator, with exact totals when the side exhausted."""

    def __init__(self):
        self.buffered: dict[int, list[pa.RecordBatch]] = {}
        self.open_iters: dict[int, Iterator[pa.RecordBatch]] = {}
        self.nbytes = 0
        self.rows = 0
        self.complete = False


def _observe(child: ExecutionPlan, ctx: TaskContext, budget: int) -> _Observation:
    """BufferExec's dam applied across ALL partitions of one input: buffer
    until the byte budget overflows or the side exhausts. An exhausted side
    has exact size; an overflowed one is proven bigger than the budget."""
    obs = _Observation()
    for p in range(child.output_partition_count()):
        it = iter(child.execute(p, ctx))
        obs.buffered[p] = []
        for b in it:
            obs.buffered[p].append(b)
            obs.nbytes += b.nbytes
            obs.rows += b.num_rows
            if obs.nbytes > budget:
                obs.open_iters[p] = it
                return obs
    obs.complete = True
    return obs


class _ReplaySource(ExecutionPlan):
    """Serves a child's partitions, replaying what the dam buffered before
    continuing the live iterator (partitions the dam never started execute
    fresh). Buffers are handed out once and released."""

    def __init__(self, child: ExecutionPlan, obs: _Observation):
        super().__init__(child.df_schema)
        self.child = child
        self.obs = obs
        self._lock = threading.Lock()

    def children(self):
        return [self.child]

    def with_children(self, c):
        return _ReplaySource(c[0], self.obs)

    def output_partition_count(self) -> int:
        return self.child.output_partition_count()

    def node_str(self) -> str:
        return "ReplaySource"

    def execute(self, partition: int, ctx: TaskContext):
        with self._lock:
            held = self.obs.buffered.pop(partition, None)
            live = self.obs.open_iters.pop(partition, None)
        if held is None:
            yield from self.child.execute(partition, ctx)
            return
        yield from held
        if live is not None:
            yield from live


class DynamicJoinSelectionExec(ExecutionPlan):
    """Deferred join decision (reference dynamic_join.rs:53). `mode` is the
    planner's fallback (always 'partitioned' at insertion); the concrete
    join is chosen at stage resolution (aqe/rules.py, stats known) or at
    first-batch time right here (stats unknown)."""

    def __init__(self, left: ExecutionPlan, right: ExecutionPlan,
                 on: list[tuple[Expr, Expr]], join_type: str,
                 filter: Optional[Expr], df_schema: DFSchema,
                 mode: str = "partitioned", planned_mode: str = "partitioned"):
        super().__init__(df_schema)
        self.left = left
        self.right = right
        self.on = on
        self.join_type = join_type
        self.filter = filter
        self.mode = mode
        # what the STATIC planner would have committed to without the
        # deferral — "collect_left" marks a hedged broadcast whose build
        # estimate sat inside the hedge band; runtime resolution against it
        # is what distinguishes a broadcast DEMOTION from a confirmation
        self.planned_mode = planned_mode
        self._lock = threading.Lock()
        self._resolved: ExecutionPlan | None = None
        self.decision: str = ""  # Broadcast | BroadcastSwapped | Partitioned | PartitionedSwapped | AsPlanned

    def children(self):
        return [self.left, self.right]

    def with_children(self, c):
        return DynamicJoinSelectionExec(
            c[0], c[1], self.on, self.join_type, self.filter, self.df_schema,
            self.mode, self.planned_mode)

    def output_partition_count(self) -> int:
        return self.right.output_partition_count()

    def node_str(self) -> str:
        on = ", ".join(f"{l} = {r}" for l, r in self.on)
        d = f" decision={self.decision}" if self.decision else ""
        h = " planned=collect_left" if self.planned_mode == "collect_left" else ""
        return f"DynamicJoinSelectionExec: type={self.join_type}, on=[{on}]{h}{d}"

    def _note_switch(self, mode: str) -> None:
        """Count a runtime reversal of the planned strategy (best-effort)."""
        from ballista_tpu.ops.tpu import aqe_stats

        if self.planned_mode == "collect_left" and mode == "partitioned":
            aqe_stats.note_broadcast_demotion()
        elif self.planned_mode != "collect_left" and mode == "collect_left":
            aqe_stats.note_broadcast_promotion()

    # ------------------------------------------------------------- execute

    def execute(self, partition: int, ctx: TaskContext):
        with self._lock:
            if self._resolved is None:
                self._resolved = self._decide(ctx)
        return self._timed(self._resolved.execute(partition, ctx))

    def _decide(self, ctx: TaskContext) -> ExecutionPlan:
        byte_thr = int(ctx.config.get(BROADCAST_JOIN_THRESHOLD))
        rows_thr = int(ctx.config.get(BROADCAST_JOIN_ROWS_THRESHOLD))
        if byte_thr <= 0:
            # a 0 byte threshold disables dynamic promotion entirely — the
            # same contract as the reference's static planner and AQE
            # (dynamic_join.rs:266-270)
            self.decision = "AsPlanned"
            return self._as_planned(None, None)

        probe_single = self.right.output_partition_count() == 1
        l_obs = _observe(self.left, ctx, byte_thr)
        # short-circuit: when the planned build alone already proves an
        # as-is Broadcast, observing the probe could only trade it for a
        # marginally smaller swapped build at the cost of buffering (and,
        # in a partition-sliced task, re-fetching) up to another byte_thr
        # of probe data that the replay may never hand out
        if select_strategy(l_obs.nbytes, l_obs.rows, l_obs.complete,
                           0, 0, False, self.join_type, probe_single,
                           byte_thr, rows_thr)[0] == "Broadcast":
            r_obs = _Observation()  # untouched: replays nothing, child runs fresh
        else:
            r_obs = _observe(self.right, ctx, byte_thr)

        # the dam proved exact totals only for sides that exhausted;
        # build from the proven-smaller side (dynamic_join.rs:246-255:
        # measure the input the executor actually builds from)
        # probe partition count: the two sides are co-partitioned at
        # insertion, so the unswapped probe's count answers for both
        # orientations
        self.decision, swap, mode = select_strategy(
            l_obs.nbytes, l_obs.rows, l_obs.complete,
            r_obs.nbytes, r_obs.rows, r_obs.complete,
            self.join_type,
            probe_single,
            byte_thr, rows_thr,
        )
        self._note_switch(mode)
        if self.decision == "AsPlanned":
            out = self._as_planned(l_obs, r_obs)
        else:
            out = self._concrete(swap, mode, _ReplaySource(self.left, l_obs),
                                 _ReplaySource(self.right, r_obs))
        log.info(
            "dynamic join decision: %s (left: %d bytes/%d rows%s, right: %d bytes/%d "
            "rows%s, byte_thr=%d, rows_thr=%d)",
            self.decision, l_obs.nbytes, l_obs.rows, "" if l_obs.complete else "+",
            r_obs.nbytes, r_obs.rows, "" if r_obs.complete else "+", byte_thr, rows_thr,
        )
        return out

    def _as_planned(self, l_obs, r_obs) -> ExecutionPlan:
        left = _ReplaySource(self.left, l_obs) if l_obs is not None else self.left
        right = _ReplaySource(self.right, r_obs) if r_obs is not None else self.right
        return HashJoinExec(left, right, self.on, self.join_type, self.filter,
                            self.mode, self.df_schema)

    def resolve_with_stats(self, l_bytes: int, l_rows: int,
                           r_bytes: int, r_rows: int,
                           byte_thr: int, rows_thr: int) -> ExecutionPlan:
        """Resolution-time form (the reference's optimizer-rule replacement,
        optimizer_rule/join_selection.rs): both input sizes are exact stage
        stats, so the concrete join is built over the ORIGINAL children —
        no dam, no replay. Called by scheduler/aqe/rules.py."""
        self.decision, swap, mode = select_strategy(
            l_bytes, l_rows, True, r_bytes, r_rows, True, self.join_type,
            self.right.output_partition_count() == 1, byte_thr, rows_thr,
        )
        self._note_switch(mode)
        if self.decision == "AsPlanned":
            return self._as_planned(None, None)
        return self._concrete(swap, mode, self.left, self.right)

    def _concrete(self, swap: bool, mode: str, left: ExecutionPlan,
                  right: ExecutionPlan) -> ExecutionPlan:
        from ballista_tpu.engine.physical_planner import _join_exec_schema

        if not swap:
            return HashJoinExec(left, right, self.on, self.join_type, self.filter,
                                mode, self.df_schema)
        jt = _SWAP[self.join_type]
        on = [(r, l) for (l, r) in self.on]
        schema = _join_exec_schema(right.df_schema, left.df_schema, jt)
        j = HashJoinExec(right, left, on, jt, self.filter, mode, schema)
        if jt in ("inner", "left", "right", "full"):
            # the merged output is the other orientation's permutation:
            # restore the declared column order (planner swap pattern,
            # physical_planner.py:563-565)
            order = [Column(f.name, f.qualifier) for f in self.df_schema]
            return ProjectionExec(j, order, self.df_schema)
        return j
