"""Window function computation over one physical partition.

The operator contract (plan/physical.py::WindowExec): rows of one window
partition-key group never span physical partitions (the physical planner
hash-repartitions on PARTITION BY, or coalesces to one partition when
there is none), so each partition computes independently:

sort by (partition keys, order keys) → segment boundaries → vectorized
per-segment kernels → scatter results back to input row order. Window
expressions sharing a (PARTITION BY, ORDER BY) spec share one sort and
one set of boundaries.

Frames follow SQL defaults: aggregates with ORDER BY run RANGE UNBOUNDED
PRECEDING..CURRENT ROW (peer rows share a value — implemented by reading
the running value at each peer group's LAST row); without ORDER BY the
whole partition. The reference defers all of this to DataFusion's window
operators (SURVEY.md §1 layer 0 — engine under it all).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu.errors import ExecutionError
from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
from ballista_tpu.plan.expressions import WindowFunction
from ballista_tpu.plan.schema import DFSchema


@dataclass
class _Frame:
    """Shared per-(partition_by, order_by) sort state."""

    idx: np.ndarray        # sorted row order
    inv: np.ndarray        # inverse permutation: original pos → sorted pos
    new_part: np.ndarray   # bool: row starts a new window partition
    new_peer: np.ndarray   # bool: row starts a new peer group
    seg_start: np.ndarray  # per row: index of its partition's first row
    seg_end: np.ndarray    # per row: index of its partition's last row


def compute_windows(batch: pa.RecordBatch, window_exprs: list[WindowFunction],
                    schema: DFSchema) -> list[pa.Array]:
    frames: dict[tuple, _Frame] = {}
    out = []
    for w in window_exprs:
        key = (
            tuple(str(e) for e in w.partition_by),
            tuple(str(k) for k in w.order_by),
        )
        fr = frames.get(key)
        if fr is None:
            fr = _build_frame(batch, w, schema)
            frames[key] = fr
        out.append(_compute_one(batch, w, schema, fr))
    return out


def _sort_indices(key_arrays: list[pa.Array],
                  orders: list[tuple[bool, bool]]) -> np.ndarray:
    """Lexicographic sort honoring per-key nulls placement: each nullable
    key gets a null-rank prefix column, so NULLS FIRST/LAST is exact
    regardless of pyarrow's global null_placement."""
    cols: dict[str, pa.Array] = {}
    sort_keys = []
    for i, (a, (asc, nulls_first)) in enumerate(zip(key_arrays, orders)):
        if a.null_count:
            rank = pc.cast(a.is_null(), pa.int8())
            cols[f"n{i}"] = rank
            sort_keys.append((f"n{i}", "descending" if nulls_first else "ascending"))
        cols[f"k{i}"] = a
        sort_keys.append((f"k{i}", "ascending" if asc else "descending"))
    idx = pc.sort_indices(pa.table(cols), sort_keys=sort_keys)
    return idx.to_numpy(zero_copy_only=False).astype(np.int64)


def _changes(arrays: list[pa.Array], idx: np.ndarray) -> np.ndarray:
    """bool[n]: row i (in sorted order) starts a new group of the given
    keys. Row 0 is always True. Nulls compare equal for grouping."""
    n = len(idx)
    out = np.zeros(n, dtype=bool)
    if n == 0:
        return out
    out[0] = True
    for a in arrays:
        taken = a.take(pa.array(idx))
        neq = pc.fill_null(pc.not_equal(taken.slice(1), taken.slice(0, n - 1)), False)
        lv = taken.is_valid().to_numpy(zero_copy_only=False)
        neq_np = neq.to_numpy(zero_copy_only=False).astype(bool)
        valid_change = lv[1:] != lv[:-1]
        out[1:] |= neq_np | valid_change
    return out


def _build_frame(batch: pa.RecordBatch, w: WindowFunction, schema: DFSchema) -> _Frame:
    n = batch.num_rows
    part_arrays = [evaluate_to_array(bind_expr(e, schema), batch) for e in w.partition_by]
    order_arrays = [evaluate_to_array(bind_expr(k.expr, schema), batch) for k in w.order_by]
    keys = part_arrays + order_arrays
    orders = [(True, False)] * len(part_arrays) + [
        (k.ascending, k.nulls_first) for k in w.order_by
    ]
    idx = _sort_indices(keys, orders) if keys else np.arange(n, dtype=np.int64)
    inv = np.empty(n, dtype=np.int64)
    inv[idx] = np.arange(n, dtype=np.int64)

    new_part = _changes(part_arrays, idx) if part_arrays else _first_only(n)
    new_peer = new_part | (_changes(order_arrays, idx) if order_arrays else np.zeros(n, bool))
    arange = np.arange(n, dtype=np.int64)
    seg_start = np.maximum.accumulate(np.where(new_part, arange, 0))
    starts = np.flatnonzero(new_part)
    ends = np.r_[starts[1:] - 1, n - 1] if len(starts) else np.array([], dtype=np.int64)
    counts = ends - starts + 1 if len(starts) else np.array([], dtype=np.int64)
    seg_end = np.repeat(ends, counts) if len(starts) else np.zeros(n, dtype=np.int64)
    return _Frame(idx, inv, new_part, new_peer, seg_start, seg_end)


def _compute_one(batch: pa.RecordBatch, w: WindowFunction, schema: DFSchema,
                 fr: _Frame) -> pa.Array:
    n = batch.num_rows
    out_type = w.data_type(schema)
    if n == 0:
        return pa.array([], out_type)

    arange = np.arange(n, dtype=np.int64)
    func = w.func
    if func == "row_number":
        out_sorted = arange - fr.seg_start + 1
    elif func == "rank":
        peer_start = np.maximum.accumulate(np.where(fr.new_peer, arange, 0))
        out_sorted = peer_start - fr.seg_start + 1
    elif func == "dense_rank":
        cum = np.cumsum(fr.new_peer.astype(np.int64))
        out_sorted = cum - cum[fr.seg_start] + 1
    elif func in ("lag", "lead"):
        return _lag_lead(batch, w, schema, fr, arange, n, out_type)
    elif func in ("sum", "avg", "min", "max", "count"):
        return _window_agg(batch, w, schema, fr, n, out_type)
    else:
        raise ExecutionError(f"unknown window function {func}")

    out = np.empty(n, dtype=np.int64)
    out[fr.idx] = out_sorted
    return pa.array(out, out_type)


def _first_only(n: int) -> np.ndarray:
    out = np.zeros(n, dtype=bool)
    if n:
        out[0] = True
    return out


def _peer_last(new_peer: np.ndarray, n: int) -> np.ndarray:
    """index of the LAST row of each row's peer group (sorted order)."""
    b = np.flatnonzero(new_peer)
    ends = np.r_[b[1:] - 1, n - 1]
    counts = ends - b + 1
    return np.repeat(ends, counts)


def _decimal_prepare(arr, w, out_type):
    """Exact decimal policy for window aggregates: narrow decimal128 input
    becomes unscaled int64 (exact sums/extremes in integer space; the
    emitter reconstructs the decimal); wide decimals and avg fall to
    float64. Returns (arr, dec_scale_or_None)."""
    t = arr.type
    if (w.func in ("sum", "min", "max") and pa.types.is_decimal128(t)
            and t.precision - t.scale <= 14 and pa.types.is_decimal(out_type)):
        filled = pc.fill_null(arr, 0)
        scaled = pc.multiply(filled, pa.scalar(10 ** t.scale, pa.int64())) if t.scale else filled
        return pc.cast(scaled, pa.int64()), t.scale
    return pc.cast(arr, pa.float64()), None


def _emit_agg(out: np.ndarray, out_type, mask, dec_scale):
    """Build the output array, reconstructing decimals from unscaled int64
    (via decimal256 headroom) or from the float fallback."""
    import decimal as _d

    if pa.types.is_decimal(out_type):
        if dec_scale is not None and out.dtype.kind == "i":
            a = pa.array(out, pa.int64(), mask=mask).cast(pa.decimal256(38, 0))
            if dec_scale:
                a = pc.multiply(a, pc.cast(pa.scalar(_d.Decimal(1).scaleb(-dec_scale)),
                                           pa.decimal256(1, dec_scale)))
            return pc.cast(a, out_type)
        return pa.array(out, pa.float64(), mask=mask).cast(out_type)
    return pa.array(out, out_type, mask=mask)


def _window_agg(batch, w, schema, fr: _Frame, n, out_type):
    seg_start = fr.seg_start
    dec_scale = None
    if w.args:
        arr = evaluate_to_array(bind_expr(w.args[0], schema), batch).take(pa.array(fr.idx))
        valid = arr.is_valid().to_numpy(zero_copy_only=False).astype(bool)
        if pa.types.is_decimal(arr.type):
            arr, dec_scale = _decimal_prepare(arr, w, out_type)
    else:  # count(*)
        arr = None
        valid = np.ones(n, dtype=bool)
    if w.frame is not None:
        return _rows_frame_agg(w, fr, arr, valid, n, out_type, dec_scale)
    last = _peer_last(fr.new_peer, n)

    if w.func == "count":
        cum = np.cumsum(valid.astype(np.int64))
        excl = cum[seg_start] - valid[seg_start]
        out_sorted = cum[last] - excl
        out = np.empty(n, dtype=np.int64)
        out[fr.idx] = out_sorted
        return pa.array(out, out_type)

    vals = arr.to_numpy(zero_copy_only=False)
    if w.func in ("sum", "avg"):
        as_float = (pa.types.is_floating(out_type) or w.func == "avg"
                    or np.issubdtype(np.asarray(vals).dtype, np.floating))
        v = np.asarray(vals, dtype=np.float64 if as_float else np.int64)
        v = np.where(valid, v, 0)
        cum = np.cumsum(v)
        excl = cum[seg_start] - v[seg_start]
        sums = cum[last] - excl
        ccum = np.cumsum(valid.astype(np.int64))
        cexcl = ccum[seg_start] - valid[seg_start]
        cnts = ccum[last] - cexcl
        if w.func == "avg":
            out_sorted = np.where(cnts > 0, sums / np.maximum(cnts, 1), np.nan)
        else:
            out_sorted = sums
        mask_sorted = cnts == 0  # SQL: aggregate over zero rows is NULL
    else:  # min / max: running extremes with segment resets (python per
        # segment boundary, vectorized inside via np.minimum.accumulate)
        fn = np.minimum if w.func == "min" else np.maximum
        is_f = np.issubdtype(np.asarray(vals).dtype, np.floating) or pa.types.is_floating(out_type)
        v = np.asarray(vals, dtype=np.float64 if is_f else np.int64)
        sentinel = np.inf if w.func == "min" else -np.inf
        if not is_f:
            sentinel = np.iinfo(np.int64).max if w.func == "min" else np.iinfo(np.int64).min
        v = np.where(valid, v, sentinel)
        out_sorted = np.empty_like(v)
        starts = np.flatnonzero(fr.new_part)
        bounds = np.r_[starts, n]
        for i in range(len(starts)):
            seg = slice(bounds[i], bounds[i + 1])
            out_sorted[seg] = fn.accumulate(v[seg])
        out_sorted = out_sorted[last]  # peers share
        ccum = np.cumsum(valid.astype(np.int64))
        cexcl = ccum[seg_start] - valid[seg_start]
        mask_sorted = (ccum[last] - cexcl) == 0

    out = np.empty(n, dtype=out_sorted.dtype)
    out[fr.idx] = out_sorted
    mask = np.empty(n, dtype=bool)
    mask[fr.idx] = mask_sorted
    return _emit_agg(out, out_type, mask, dec_scale)


def _rows_frame_agg(w, fr: _Frame, arr, valid, n, out_type, dec_scale=None):
    """Explicit ROWS BETWEEN frames: per-row [lo, hi] windows clipped to the
    partition; sums/counts via prefix differences, min/max via per-row
    slices (frames are exact row offsets — no peer sharing)."""
    _, start, end = w.frame
    arange = np.arange(n, dtype=np.int64)
    lo = fr.seg_start if start is None else np.maximum(fr.seg_start, arange + start)
    hi = fr.seg_end if end is None else np.minimum(fr.seg_end, arange + end)
    # frames wholly before/after the partition are EMPTY (0 / NULL) — decide
    # before clamping, or boundary rows would be dragged into range
    empty = hi < lo
    lo = np.clip(lo, fr.seg_start, fr.seg_end)
    hi = np.clip(hi, fr.seg_start, fr.seg_end)

    vcum = np.concatenate([[0], np.cumsum(valid.astype(np.int64))])
    counts = np.where(empty, 0, vcum[np.clip(hi, 0, n - 1) + 1] - vcum[np.clip(lo, 0, n - 1)])

    if w.func == "count":
        out = np.empty(n, dtype=np.int64)
        out[fr.idx] = counts
        return pa.array(out, out_type)

    vals = arr.to_numpy(zero_copy_only=False)
    as_float = (pa.types.is_floating(out_type) or w.func == "avg"
                or np.issubdtype(np.asarray(vals).dtype, np.floating))
    if w.func in ("sum", "avg"):
        v = np.asarray(vals, dtype=np.float64 if as_float else np.int64)
        v = np.where(valid, v, 0)
        csum = np.concatenate([[0], np.cumsum(v)])
        sums = np.where(empty, 0, csum[np.clip(hi, 0, n - 1) + 1] - csum[np.clip(lo, 0, n - 1)])
        if w.func == "avg":
            out_sorted = np.where(counts > 0, sums / np.maximum(counts, 1), np.nan)
        else:
            out_sorted = sums
    else:  # min / max: vectorized per SEGMENT (accumulate for one-sided
        # frames, sentinel-padded sliding windows for bounded ones)
        is_f = np.issubdtype(np.asarray(vals).dtype, np.floating) or pa.types.is_floating(out_type)
        v = np.asarray(vals, dtype=np.float64 if is_f else np.int64)
        sentinel = (np.inf if w.func == "min" else -np.inf) if is_f else (
            np.iinfo(np.int64).max if w.func == "min" else np.iinfo(np.int64).min
        )
        v = np.where(valid, v, sentinel)
        red = np.minimum if w.func == "min" else np.maximum
        out_sorted = np.full(n, sentinel, dtype=v.dtype)
        starts = np.flatnonzero(fr.new_part)
        seg_bounds = np.r_[starts, n]
        for si in range(len(starts)):
            s0, s1 = int(seg_bounds[si]), int(seg_bounds[si + 1])
            seg = v[s0:s1]
            local = np.arange(len(seg))
            if start is None and end is None:
                out_sorted[s0:s1] = red.reduce(seg)
            elif start is None:  # running extreme up to hi
                acc = red.accumulate(seg)
                out_sorted[s0:s1] = acc[np.clip(hi[s0:s1] - s0, 0, len(seg) - 1)]
            elif end is None:  # extreme from lo to segment end
                racc = red.accumulate(seg[::-1])[::-1]
                out_sorted[s0:s1] = racc[np.clip(lo[s0:s1] - s0, 0, len(seg) - 1)]
            else:
                width = end - start + 1
                if width >= 1:
                    pad = np.full(width - 1, sentinel, dtype=v.dtype)
                    padded = np.concatenate([pad, seg, pad])
                    sw = np.lib.stride_tricks.sliding_window_view(padded, width)
                    idxs = np.clip(local + start + (width - 1), 0, len(sw) - 1)
                    out_sorted[s0:s1] = red.reduce(sw[idxs], axis=1)
    mask_sorted = counts == 0
    out = np.empty(n, dtype=out_sorted.dtype)
    out[fr.idx] = out_sorted
    mask = np.empty(n, dtype=bool)
    mask[fr.idx] = mask_sorted
    return _emit_agg(out, out_type, mask, dec_scale)


def _lag_lead(batch, w, schema, fr: _Frame, arange, n, out_type):
    if not w.args:
        raise ExecutionError(f"{w.func} requires a value argument")
    arr = evaluate_to_array(bind_expr(w.args[0], schema), batch).take(pa.array(fr.idx))
    offset = int(_literal_value(w.args[1])) if len(w.args) > 1 else 1
    default = _literal_value(w.args[2]) if len(w.args) > 2 else None

    src = arange - offset if w.func == "lag" else arange + offset
    # guard BOTH bounds: a negative offset must not walk into a neighboring
    # window partition
    ok = (src >= fr.seg_start) & (src <= fr.seg_end)
    srcc = np.clip(src, 0, n - 1)
    shifted = arr.take(pa.array(srcc))
    if shifted.type != out_type:
        shifted = shifted.cast(out_type)
    from ballista_tpu.ops.phys_expr import py_for_type

    res_sorted = pc.if_else(pa.array(ok), shifted, pa.scalar(py_for_type(default, out_type), out_type))
    # scatter back to original row order
    return res_sorted.take(pa.array(fr.inv))


def _literal_value(e):
    from ballista_tpu.plan.expressions import Literal, Negative

    if isinstance(e, Literal):
        return e.value
    if isinstance(e, Negative):
        return -_literal_value(e.expr)
    raise ExecutionError(f"lag/lead offset/default must be literal, got {e}")
