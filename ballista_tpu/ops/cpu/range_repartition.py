"""Runtime-statistics pipeline for dynamic range repartitioning.

Rebuild of the reference's distributed-sort trio
(core/src/execution_plans/{runtime_stats,buffer,unordered_range_repartition}.rs):

- RuntimeStatsExec: passthrough tap — per-partition row counts + a T-Digest
  sketch over the first sort key; snapshot readable mid-stream.
- BufferExec: flow-control dam — buffers input up to a byte budget before
  releasing, giving the stats tap time to observe data before routing
  decisions downstream.
- UnorderedRangeRepartitionExec: on first demand walks its subtree for the
  sibling RuntimeStatsExec, takes K-1 quantile cuts from the merged digest,
  and routes rows into K range buckets. Bucket i's values all sort before
  bucket i+1's, so per-bucket sorts concatenate into a total order without
  a merge (the distributed ORDER BY pattern).
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.errors import ExecutionError
from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
from ballista_tpu.plan.expressions import Expr, SortKey
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext, _empty_batch
from ballista_tpu.utils.tdigest import TDigest


def _as_float(arr: pa.Array) -> np.ndarray:
    t = arr.type
    if pa.types.is_date(t):
        arr = arr.cast(pa.int32())
    return arr.cast(pa.float64(), safe=False).to_numpy(zero_copy_only=False)


def _is_string_key(t: pa.DataType) -> bool:
    return (pa.types.is_string(t) or pa.types.is_large_string(t)
            or pa.types.is_dictionary(t))


def _key_values(arr: pa.Array) -> np.ndarray:
    """Sort-key values for digesting/routing: float64 for orderable numeric
    and temporal types; object-dtype strings (lexicographic, NULL → "")
    for string keys — a T-Digest cannot hold strings, but exact
    quantile-position cuts over the dammed batches can."""
    if _is_string_key(arr.type):
        if pa.types.is_dictionary(arr.type):
            arr = arr.cast(arr.type.value_type)
        vals = arr.to_numpy(zero_copy_only=False)
        if arr.null_count:
            vals = np.array(["" if v is None else v for v in vals], dtype=object)
        return vals
    return _as_float(arr)


class RuntimeStatsExec(ExecutionPlan):
    def __init__(self, input: ExecutionPlan, sort_expr: Optional[Expr] = None):
        super().__init__(input.df_schema)
        self.input = input
        self.sort_expr = sort_expr
        self._lock = threading.Lock()
        self.row_counts: dict[int, int] = {}
        self.digest = TDigest()

    def children(self):
        return [self.input]

    def with_children(self, c):
        return RuntimeStatsExec(c[0], self.sort_expr)

    def node_str(self) -> str:
        s = f" sketch({self.sort_expr})" if self.sort_expr is not None else ""
        return f"RuntimeStatsExec:{s}"

    def snapshot(self) -> tuple[int, TDigest]:
        with self._lock:
            d = TDigest.from_list(self.digest.to_list())
            return sum(self.row_counts.values()), d

    def execute(self, partition: int, ctx: TaskContext):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        bound = bind_expr(self.sort_expr, self.df_schema) if self.sort_expr is not None else None
        for b in self.input.execute(partition, ctx):
            if b.num_rows:
                with self._lock:
                    self.row_counts[partition] = self.row_counts.get(partition, 0) + b.num_rows
                    if bound is not None:
                        vals = evaluate_to_array(bound, b)
                        # string keys can't be digested; the router computes
                        # exact positional cuts from the dammed batches
                        if not _is_string_key(vals.type):
                            self.digest.add_array(_as_float(vals))
            yield b


class BufferExec(ExecutionPlan):
    """Buffer-then-release dam (buffer.rs:125)."""

    def __init__(self, input: ExecutionPlan, max_bytes: int = 64 * 1024 * 1024):
        super().__init__(input.df_schema)
        self.input = input
        self.max_bytes = max_bytes

    def children(self):
        return [self.input]

    def with_children(self, c):
        return BufferExec(c[0], self.max_bytes)

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _run(self, partition, ctx):
        held: list[pa.RecordBatch] = []
        held_bytes = 0
        it = self.input.execute(partition, ctx)
        for b in it:
            held.append(b)
            held_bytes += b.nbytes
            if held_bytes > self.max_bytes:
                break
        yield from held
        yield from it


def retarget_routers(plan: ExecutionPlan, n: int) -> ExecutionPlan:
    """Rebuild every UnorderedRangeRepartitionExec in `plan` with `n`
    buckets. INVARIANT shared by all AQE rewrites that change a stage's
    task slate (reader coalescing, fan-out shrink): a passthrough task
    drains exactly its own router bucket, so the router's bucket count
    must equal the stage's task count or buckets >= that count are routed
    but never read (silent row loss)."""
    kids = plan.children()
    new_kids = [retarget_routers(c, n) for c in kids]
    if any(a is not b for a, b in zip(new_kids, kids)):
        plan = plan.with_children(new_kids)
    if isinstance(plan, UnorderedRangeRepartitionExec) and plan.n != n:
        plan = UnorderedRangeRepartitionExec(plan.input, plan.key, n)
    return plan


class UnorderedRangeRepartitionExec(ExecutionPlan):
    """Quantile-cut range router (unordered_range_repartition.rs:107)."""

    def __init__(self, input: ExecutionPlan, key: SortKey, n: int):
        super().__init__(input.df_schema)
        self.input = input
        self.key = key
        self.n = n
        self._lock = threading.Lock()
        self._cache: list[list[pa.RecordBatch]] | None = None

    def children(self):
        return [self.input]

    def with_children(self, c):
        return UnorderedRangeRepartitionExec(c[0], self.key, self.n)

    def output_partition_count(self) -> int:
        return self.n

    def node_str(self) -> str:
        return f"UnorderedRangeRepartitionExec: key={self.key}, n={self.n}"

    def _find_stats(self) -> RuntimeStatsExec | None:
        def walk(node):
            if isinstance(node, RuntimeStatsExec):
                return node
            for c in node.children():
                r = walk(c)
                if r is not None:
                    return r
            return None

        return walk(self.input)

    def execute(self, partition, ctx):
        return self._timed(self._run(partition, ctx))

    def _materialize(self, ctx):
        with self._lock:
            if self._cache is not None:
                return self._cache
            outs: list[list[pa.RecordBatch]] = [[] for _ in range(self.n)]
            bound = bind_expr(self.key.expr, self.input.df_schema)
            pending: list[pa.RecordBatch] = []
            # drain the input fully (the dam upstream bounds memory growth
            # before stats stabilize), then cut on the observed digest
            for p in range(self.input.output_partition_count()):
                pending.extend(b for b in self.input.execute(p, ctx) if b.num_rows)
            stats = self._find_stats()
            string_key = bool(pending) and _is_string_key(
                evaluate_to_array(bound, pending[0]).type)
            keyed: list[tuple] = []
            if string_key:
                # evaluate + convert each batch's key ONCE, reused for cuts
                # and routing (object-array conversion is Python-speed);
                # cuts are exact positional quantiles over the NON-NULL
                # values — nulls reroute to an end bucket below, and
                # counting them here would collapse leading cuts to "" and
                # starve buckets. The numeric path stays lazy-per-batch
                # (no up-front float copies of the whole pending set).
                keyed = [(b, evaluate_to_array(bound, b)) for b in pending]
                key_vals = [_key_values(arr) for _, arr in keyed]
                nn = [v[~np.asarray(arr.is_null())] if arr.null_count else v
                      for (_, arr), v in zip(keyed, key_vals)]
                svals = np.sort(np.concatenate(nn)) if nn else np.zeros(0, dtype=object)
                cuts = [svals[min(len(svals) - 1, (len(svals) * i) // self.n)]
                        for i in range(1, self.n)] if len(svals) else []
                routed = zip(keyed, key_vals)
            elif stats is not None and stats.digest.count > 0:
                # cuts come from the tap's digest: route lazily per batch,
                # no up-front float copy of the whole pending set
                cuts = stats.digest.quantile_cuts(self.n)

                def lazy():
                    for b in pending:
                        arr = evaluate_to_array(bound, b)
                        yield (b, arr), _as_float(arr)

                routed = lazy()
            else:
                # no digest: the cuts need every value anyway — evaluate
                # each batch ONCE and reuse the arrays for routing
                keyed = [(b, evaluate_to_array(bound, b)) for b in pending]
                key_vals = [_as_float(arr) for _, arr in keyed]
                vals = np.concatenate(key_vals) if key_vals else np.zeros(0)
                d = TDigest()
                d.add_array(vals)
                cuts = d.quantile_cuts(self.n) if len(vals) else []
                routed = zip(keyed, key_vals)
            cuts_arr = np.array(cuts, dtype=object if string_key else None)
            for (b, arr), v in routed:
                bucket = np.searchsorted(cuts_arr, v, side="right") if cuts else np.zeros(len(v), dtype=int)
                if not self.key.ascending:
                    bucket = (self.n - 1) - bucket
                if arr.null_count:
                    # concatenated-range order must equal the sort's null
                    # placement: nulls to the first or last FINAL bucket
                    nulls = np.asarray(arr.is_null())
                    bucket = np.where(
                        nulls, 0 if self.key.nulls_first else self.n - 1, bucket)
                for k in np.unique(bucket):
                    sel = np.nonzero(bucket == k)[0]
                    outs[int(k)].append(b.take(pa.array(sel)))
            self._cache = outs
            return outs

    def _run(self, partition, ctx):
        outs = self._materialize(ctx)
        if not outs[partition]:
            yield _empty_batch(self.schema())
            return
        yield from outs[partition]
