"""Mesh-wide stage execution: the on-device form of a hash exchange.

The distributed planner's mesh post-pass (scheduler/planner.py
merge_mesh_stages) fuses a hash-shuffle producer stage into its single
consumer: the producer's ShuffleWriterExec(hash K) and the consumer's
reader collapse into one `MeshExchangeExec` node inside ONE stage, and the
whole stage ships as ONE task spanning every partition. The exchange that
used to round-trip through Arrow IPC files and Flight RPCs becomes an
on-device `all_to_all` over a `make_mesh()` device mesh
(parallel/exchange.py) — Theseus's thesis (arXiv:2508.05029): distributed
accelerator engines win or lose on data movement.

Execution ladder, most- to least-capable, every rung recorded as
`mesh_mode_reason` in RUN_STATS:

1. **mesh** — producer partitions run (device-compiled where the TPU engine
   lowered them), output rows encode to int64 lanes, and one
   `hash_exchange_table` all_to_all routes them by the engine-wide row hash
   (ops/hashing.py `hash_arrays`, the bit-exact twin of the file shuffle's
   routing). Zero shuffle files, zero Flight fetches for this edge.
2. **demoted:…** — capacity overflow (`ExchangeCapacityExceeded`), too few
   devices, an un-encodable column dtype, a tiny input, or an AQE veto
   drop to the host split: the same `hash_arrays % K` routing the
   ShuffleWriterExec applies, minus the files. Results are identical either
   way — bucket p always holds exactly the rows whose key hashes to p, in
   producer row order.

Byte parity with the per-partition path is by construction: the reader
orders bucket p's locations by map partition, so its row order is global
producer row order; the mesh path carries a row id through the exchange
and re-sorts, then re-splits batches at producer-partition boundaries so
even the consumer's chunking matches.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterator

import numpy as np
import pyarrow as pa

from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
from ballista_tpu.plan.physical import ExecutionPlan, TaskContext

log = logging.getLogger(__name__)


class UnsupportedExchangeType(Exception):
    """A producer output column cannot be encoded to int64 exchange lanes."""


# ---------------------------------------------------------------------------
# column <-> int64-lane codecs
# ---------------------------------------------------------------------------


def _encode_column(arr: pa.Array) -> tuple[list[np.ndarray], np.ndarray | None, dict]:
    """Arrow column -> (int64 lanes, validity bool[n] or None, decode meta).

    Every supported type round-trips EXACTLY: ints/dates widen to int64,
    floats travel as bit-cast int64 (f32 upcast to f64 first — exact), and
    strings ship as dictionary codes against a host-side dictionary built
    over the whole producer output (one table, so the dictionary is global
    by construction)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    if pa.types.is_dictionary(t):
        arr = arr.cast(t.value_type)
        t = arr.type
    valid = None
    if arr.null_count:
        valid = np.asarray(arr.is_valid())
    if pa.types.is_integer(t) or pa.types.is_boolean(t) or pa.types.is_date(t) \
            or pa.types.is_timestamp(t):
        lane_t = pa.int64()
        filled = arr.fill_null(0) if arr.null_count else arr
        lane = np.asarray(filled.cast(lane_t)).astype(np.int64)
        return [lane], valid, {"kind": "int"}
    if pa.types.is_floating(t):
        filled = arr.fill_null(0.0) if arr.null_count else arr
        f64 = np.asarray(filled.cast(pa.float64())).astype(np.float64)
        return [f64.view(np.int64)], valid, {"kind": "float"}
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        dict_arr = arr.dictionary_encode()
        codes = dict_arr.indices.fill_null(0) if dict_arr.indices.null_count else dict_arr.indices
        lane = np.asarray(codes.cast(pa.int64())).astype(np.int64)
        return [lane], valid, {"kind": "dict", "dictionary": dict_arr.dictionary}
    raise UnsupportedExchangeType(str(t))


def _decode_column(field_type: pa.DataType, lanes: list[np.ndarray],
                   valid: np.ndarray | None, meta: dict) -> pa.Array:
    kind = meta["kind"]
    if kind == "float":
        values = pa.array(lanes[0].view(np.float64))
    elif kind == "dict":
        values = meta["dictionary"].take(pa.array(lanes[0]))
    else:
        values = pa.array(lanes[0])
    if valid is not None:
        mask = pa.array(~valid)
        values = pa.compute.if_else(mask, pa.nulls(len(valid), values.type), values)
    out_type = field_type
    if pa.types.is_dictionary(out_type):
        out_type = out_type.value_type
    return values.cast(out_type) if values.type != out_type else values


# ---------------------------------------------------------------------------
# the plan node
# ---------------------------------------------------------------------------


class MeshExchangeExec(ExecutionPlan):
    """Fused hash exchange inside a merged mesh stage.

    Stands where the consumer's ShuffleReaderExec stood: `execute(p)`
    serves reduce bucket p of the producer's hash-partitioned output. The
    exchange itself runs ONCE (first execute) — on the device mesh when the
    ladder allows, on the host split otherwise — and every bucket serves
    from the cached result, so a single task must cover all K partitions
    (the planner marks the merged stage `mesh=True` and the graph hands it
    out as one mesh-wide task)."""

    def __init__(self, producer: ExecutionPlan, keys: list, file_partitions: int):
        super().__init__(producer.df_schema)
        self.producer = producer
        self.keys = keys
        self.file_partitions = max(1, int(file_partitions))
        self._lock = threading.Lock()
        self._buckets: list[list[pa.RecordBatch]] | None = None
        # set by AQE at stage resolution to veto the device path from
        # observed input sizes; also carried through with_children
        self.demote_reason: str | None = None

    def children(self):
        return [self.producer]

    def with_children(self, c):
        out = MeshExchangeExec(c[0], self.keys, self.file_partitions)
        out.demote_reason = self.demote_reason
        return out

    def with_file_partitions(self, k: int) -> "MeshExchangeExec":
        """Fresh exchange at a different bucket count — AQE's mesh bucket
        replan. Hash routing is count-parametric (`h % K` on both the
        device and host paths), so any K yields a valid partitioning; a
        fresh node (new lock, empty cache) keeps the replan from aliasing
        a prior resolution's buckets."""
        out = MeshExchangeExec(self.producer, self.keys, k)
        out.demote_reason = self.demote_reason
        return out

    def output_partition_count(self) -> int:
        return self.file_partitions

    def node_str(self) -> str:
        k = ", ".join(str(e) for e in self.keys)
        why = f", demoted={self.demote_reason}" if self.demote_reason else ""
        return f"MeshExchangeExec: keys=[{k}], partitions={self.file_partitions}{why}"

    # ------------------------------------------------------------------

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        with self._lock:
            if self._buckets is None:
                self._buckets = self._exchange(ctx)
        yield from self._buckets[partition]

    # ------------------------------------------------------------------

    def _exchange(self, ctx: TaskContext) -> list[list[pa.RecordBatch]]:
        from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

        routed = self._daemon_exchange(ctx)
        if routed is not None:
            return routed

        part_tables: list[pa.Table] = []
        schema = self.producer.schema()
        for p in range(self.producer.output_partition_count()):
            batches = [b for b in self.producer.execute(p, ctx) if b.num_rows]
            part_tables.append(
                pa.Table.from_batches(batches, schema=schema) if batches
                else pa.table({f.name: pa.array([], f.type) for f in schema}, schema=schema)
            )

        with RUN_STATS.run("mesh_exchange") as rec:
            reason, buckets = self._try_device_exchange(part_tables, ctx, rec)
            if buckets is None:
                log.info("mesh exchange demoted to per-partition host split: %s", reason)
                buckets = self._host_split(part_tables)
            RUN_STATS.set("mesh_mode_reason", reason, rec=rec)
        return buckets

    def _daemon_exchange(self, ctx: TaskContext):
        """Route the whole mesh-wide stage (producer partitions + fused
        exchange) through the device daemon, which owns the device span
        the mesh pins. The request tag stays "mesh_exchange" so the
        daemon's mirrored rec — mesh_mode_reason included, capacity/dtype
        demotions and all — lands under the SAME stage key local runs
        use. None = run locally (daemon off, crashed out, quarantined, or
        an AQE veto already demoted the exchange)."""
        if self.demote_reason:
            return None
        from ballista_tpu.ops.tpu import daemon_route

        fp = f"{self.node_str()}|{self.producer.node_str()}"
        results = daemon_route.run_via_daemon(
            ctx.config,
            plan_builder=lambda: self,
            partitions=list(range(self.file_partitions)),
            tag="mesh_exchange",
            fingerprint=fp)
        if results is None:
            return None
        return [results.get(p, []) for p in range(self.file_partitions)]

    # -- demotion ladder -------------------------------------------------

    def _try_device_exchange(self, part_tables, ctx, rec):
        """Returns (reason, buckets-or-None). None buckets = take the host
        path; the reason string says which rung of the ladder failed."""
        from ballista_tpu.config import (
            TPU_MESH_DEVICES,
            TPU_MESH_EXCHANGE_CAPACITY,
            TPU_MESH_MIN_ROWS,
        )
        from ballista_tpu.parallel.exchange import ExchangeCapacityExceeded

        if self.demote_reason:
            return f"demoted:{self.demote_reason}", None
        total_rows = sum(t.num_rows for t in part_tables)
        if total_rows < int(ctx.config.get(TPU_MESH_MIN_ROWS)):
            return "demoted:small-input", None
        try:
            from ballista_tpu.parallel.exchange import make_mesh

            want = int(ctx.config.get(TPU_MESH_DEVICES)) or None
            mesh = make_mesh(want)
        except Exception as e:  # noqa: BLE001 — no jax / no devices
            return f"demoted:no-mesh({type(e).__name__})", None
        if mesh.devices.size < 2:
            return "demoted:single-device", None
        cap_limit = int(ctx.config.get(TPU_MESH_EXCHANGE_CAPACITY))
        try:
            buckets = self._device_exchange(part_tables, mesh, cap_limit, rec)
            return "mesh", buckets
        except ExchangeCapacityExceeded as e:
            log.warning("mesh exchange capacity overflow: %s", e)
            return "demoted:capacity", None
        except UnsupportedExchangeType as e:
            return f"demoted:dtype:{e}", None

    # -- the host (per-partition) path -----------------------------------

    def _row_hashes(self, tbl: pa.Table) -> np.ndarray:
        from ballista_tpu.ops.hashing import hash_arrays

        if tbl.num_rows == 0:
            return np.zeros(0, dtype=np.uint64)
        batch = tbl.combine_chunks().to_batches()[0]
        bound = [bind_expr(k, self.df_schema) for k in self.keys]
        return hash_arrays([evaluate_to_array(b, batch) for b in bound])

    def _host_split(self, part_tables) -> list[list[pa.RecordBatch]]:
        """ShuffleWriterExec's routing without the files: per producer
        partition, in order, rows split by hash % K — location order and row
        order both match what the reader would have served."""
        k = self.file_partitions
        buckets: list[list[pa.RecordBatch]] = [[] for _ in range(k)]
        for tbl in part_tables:
            if tbl.num_rows == 0:
                continue
            h = self._row_hashes(tbl)
            pids = (h % np.uint64(k)).astype(np.int64)
            batch = tbl.combine_chunks().to_batches()[0]
            for p in range(k):
                sel = np.nonzero(pids == p)[0]
                if len(sel):
                    buckets[p].append(batch.take(pa.array(sel)))
        return buckets

    # -- the device (collective) path ------------------------------------

    def _device_exchange(self, part_tables, mesh, cap_limit, rec) -> list[list[pa.RecordBatch]]:
        from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS
        from ballista_tpu.parallel.exchange import (
            hash_exchange_table,
            require_exchange_capacity,
        )

        n = mesh.devices.size
        schema = self.producer.schema()
        combined = pa.concat_tables(part_tables).combine_chunks()
        rows = combined.num_rows
        hashes = self._row_hashes(combined)

        # encode every column to int64 lanes (raises UnsupportedExchangeType
        # before anything touches the device)
        col_lanes: list[list[np.ndarray]] = []
        col_valid: list[np.ndarray | None] = []
        col_meta: list[dict] = []
        for name in combined.column_names:
            lanes, valid, meta = _encode_column(combined.column(name))
            col_lanes.append(lanes)
            col_valid.append(valid)
            col_meta.append(meta)

        # pad to a multiple of the device count; padding rows are dead
        padded = rows + (-rows) % n
        local_rows = padded // n

        def _pad(a: np.ndarray, fill=0) -> np.ndarray:
            if len(a) == padded:
                return a
            out = np.full(padded, fill, dtype=a.dtype)
            out[: len(a)] = a
            return out

        live = _pad(np.ones(rows, dtype=bool), False)
        h_lane = _pad(hashes.view(np.int64))
        rowid = _pad(np.arange(rows, dtype=np.int64))

        # host gate BEFORE dispatch: per-sender shards are the contiguous
        # row ranges the mesh sharding assigns
        shards = [hashes[d * local_rows:(d + 1) * local_rows] for d in range(n)]
        required = require_exchange_capacity(shards, n, cap_limit, prehashed=True)
        cap = max(1, required)

        flat_lanes = [rowid]
        for lanes, valid in zip(col_lanes, col_valid):
            flat_lanes.extend(_pad(l) for l in lanes)
            if valid is not None:
                flat_lanes.append(_pad(valid.astype(np.int64)))

        t0 = time.time()
        h_out, lanes_out, valid_out = hash_exchange_table(
            h_lane, flat_lanes, live, mesh, capacity=cap)
        h_out = np.asarray(h_out)
        lanes_out = [np.asarray(l) for l in lanes_out]
        ok = np.asarray(valid_out)
        RUN_STATS.set("exchange_s", round(time.time() - t0, 4), rec=rec)
        RUN_STATS.set("mesh_devices", n, rec=rec)
        RUN_STATS.set(
            "exchange_bytes_on_device",
            int(ok.sum()) * 8 * (len(flat_lanes) + 1) + int(ok.sum()),
            rec=rec,
        )

        if int(ok.sum()) != rows:
            # the gate above makes this unreachable; never trade silence
            # for speed if it ever regresses
            raise RuntimeError(
                f"mesh exchange lost rows: sent {rows}, received {int(ok.sum())}")

        # decode: valid rows only, restored to global producer row order so
        # bucket contents are byte-identical to the file-shuffle reader
        h_recv = h_out[ok].view(np.uint64)
        recv = [l[ok] for l in lanes_out]
        order = np.argsort(recv[0], kind="stable")  # recv[0] is rowid
        h_recv = h_recv[order]
        recv = [l[order] for l in recv]

        k = self.file_partitions
        pids = (h_recv % np.uint64(k)).astype(np.int64)
        # producer-partition boundaries: split each bucket into one batch
        # per map partition, mirroring the reader's per-location batches
        offsets = np.cumsum([0] + [t.num_rows for t in part_tables])
        map_of_row = np.searchsorted(offsets, recv[0], side="right") - 1

        buckets: list[list[pa.RecordBatch]] = [[] for _ in range(k)]
        n_parts = len(part_tables)
        for p in range(k):
            in_p = pids == p
            for m in range(n_parts):
                sel = np.nonzero(in_p & (map_of_row == m))[0]
                if not len(sel):
                    continue
                arrays = []
                cursor = 1  # lane 0 is rowid
                for field, lanes, valid, meta in zip(
                        schema, col_lanes, col_valid, col_meta):
                    col_recv = [recv[cursor + i][sel] for i in range(len(lanes))]
                    cursor += len(lanes)
                    v = None
                    if valid is not None:
                        v = recv[cursor][sel].astype(bool)
                        cursor += 1
                    arrays.append(_decode_column(field.type, col_recv, v, meta))
                buckets[p].append(pa.RecordBatch.from_arrays(arrays, schema=schema))
        return buckets


def contains_mesh_exchange(plan: ExecutionPlan) -> bool:
    if isinstance(plan, MeshExchangeExec):
        return True
    return any(contains_mesh_exchange(c) for c in plan.children())
