"""Shared daemon-routing ladder for device stages.

Every stage family that can ship work to the warm device-runtime daemon
(TpuStageExec partials, TpuFinalStageExec merges, MeshExchangeExec
exchanges) goes through `run_via_daemon`, which owns the whole failure
domain (docs/device_daemon.md#failure-domain):

1. quarantine check — a stage fingerprint that already killed two daemon
   incarnations is demoted straight to the in-process ladder;
2. serialize the RAW subtree (device wrappers unwrapped via
   `unwrap_device_stages`; the daemon recompiles through the same
   maybe_compile_tpu entry, so results are byte-identical and the
   fingerprints — hence the daemon's compile cache keys — are stable);
3. execute with a deadline derived from the stage's byte estimate
   (protocol.derive_execute_timeout_s) that the daemon-side watchdog
   enforces too;
4. on a typed DaemonCrashed: count it, classify a watchdog kill from the
   <socket>.crash.json post-mortem, respawn-and-retry ONCE, and poison
   the fingerprint on the second crash so nothing crash-loops.

Outcomes land in RunStats as daemon_failover / daemon_failover_reason,
and the process-lifetime failure counters (daemon_restarts,
daemon_crashes_detected, watchdog_kills, poisoned_stages) are mirrored
into the merged stats so they ride the executor heartbeat.

Like the client module, this file must stay importable without jax.
"""

from __future__ import annotations

import logging
import zlib

from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS
from ballista_tpu.plan.physical import ExecutionPlan

log = logging.getLogger(__name__)


def stage_tag(prefix: str, fingerprint: str) -> str:
    """The daemon-visible identity of a stage: stable across processes
    (quarantine entries must outlive the client that wrote them) and
    short enough for a JSON header."""
    return f"{prefix}_{zlib.crc32(fingerprint.encode()):08x}"


def unwrap_device_stages(plan: ExecutionPlan) -> ExecutionPlan:
    """Replace every compiled device wrapper in `plan` with the raw
    subtree it stands for, so serde can encode the tree. The daemon's
    maybe_compile_tpu re-derives the SAME wrappers from the raw shape —
    unwrap + recompile is identity up to process boundary."""
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec
    from ballista_tpu.ops.tpu.sort_window import (
        TpuSortStageExec,
        TpuWindowStageExec,
    )
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec
    from ballista_tpu.plan.physical import (
        CoalescePartitionsExec,
        SortExec,
        WindowExec,
    )

    if isinstance(plan, TpuStageExec):
        raw = plan.partial_agg.with_children([plan._raw_chain()])
        return unwrap_device_stages(raw) if raw is not plan else raw
    if isinstance(plan, TpuFinalStageExec):
        node = unwrap_device_stages(plan.child)
        if plan.coalesce:
            # match_final_stage consumed a CoalescePartitionsExec to set
            # coalesce=True; re-add it so the daemon re-derives the shape
            node = CoalescePartitionsExec(node)
        node = plan.agg.with_children([node])
        for op in reversed(plan.post_ops):
            node = op.with_children([node])
        if plan.sort is not None:
            node = plan.sort.with_children([node])
        return node
    if isinstance(plan, TpuSortStageExec):
        return SortExec(unwrap_device_stages(plan.input), plan.keys, plan.fetch)
    if isinstance(plan, TpuWindowStageExec):
        return WindowExec(unwrap_device_stages(plan.input), plan.window_exprs,
                          plan.df_schema)
    kids = plan.children()
    if not kids:
        return plan
    new_kids = [unwrap_device_stages(c) for c in kids]
    if all(a is b for a, b in zip(new_kids, kids)):
        return plan
    return plan.with_children(new_kids)


def _mirror_counters() -> None:
    """Publish the process-lifetime failure counters into the merged
    RunStats view (literal keys — the analysis stats-sync pass reads
    these call sites)."""
    from ballista_tpu.device_daemon import client as dclient

    c = dclient.failure_counters()
    RUN_STATS.set("daemon_restarts", float(c.get("daemon_restarts", 0)))
    RUN_STATS.set("daemon_crashes_detected",
                  float(c.get("daemon_crashes_detected", 0)))
    RUN_STATS.set("watchdog_kills", float(c.get("watchdog_kills", 0)))
    RUN_STATS.set("poisoned_stages", float(c.get("poisoned_stages", 0)))


def _note_local(mode_reason: str, failover: str = "",
                failover_reason: str = "") -> None:
    RUN_STATS.set("daemon_mode", "in_process")
    RUN_STATS.set("daemon_mode_reason", mode_reason[:300])
    RUN_STATS.set("daemon_attached", 0.0)
    if failover:
        RUN_STATS.set("daemon_failover", failover)
        RUN_STATS.set("daemon_failover_reason", failover_reason[:300])
    _mirror_counters()


def run_via_daemon(config, *, plan_builder, partitions, tag: str,
                   fingerprint: str, emit_pid=None, est_bytes: int = 0):
    """Ship one stage through the daemon's failure-domain ladder.

    Returns {partition: [batches]} on success, None to mean 'run it
    locally' — with the reason in RunStats daemon_mode_reason and, for
    crash-driven demotions, daemon_failover / daemon_failover_reason.
    `plan_builder` is called lazily (only when the daemon is enabled and
    the stage is not quarantined) and must return the raw subtree; device
    wrappers in it are unwrapped here. Never raises.
    """
    from ballista_tpu.config import TPU_DAEMON_ENABLED, TPU_DAEMON_POISON_TTL_S

    if not bool(config.get(TPU_DAEMON_ENABLED)):
        return None
    from ballista_tpu.device_daemon import client as dclient

    path = dclient.resolve_socket(config)
    ttl = float(config.get(TPU_DAEMON_POISON_TTL_S))
    if dclient.is_poisoned(path, tag, ttl):
        _note_local(f"poisoned: {tag} quarantined after repeated daemon "
                    "crashes", failover="poisoned",
                    failover_reason=f"{tag} in quarantine (ttl {ttl:.0f}s)")
        log.warning("stage %s is quarantined; running in-process", tag)
        return None
    try:
        from ballista_tpu import serde

        raw = unwrap_device_stages(plan_builder())
        plan_bytes = serde.plan_to_bytes(raw)
    except Exception as e:  # noqa: BLE001 — a shape serde can't carry yet
        _note_local(f"serde_failed: {e}")
        log.info("stage %s not daemon-serializable (%s); running in-process",
                 tag, e)
        return None
    deadline_s = protocol_deadline(config, est_bytes)

    for attempt in (0, 1):
        client, mode, reason = dclient.attach(config)
        if client is None:
            _note_local(reason)
            log.info("daemon unavailable (%s); running stage in-process",
                     reason)
            return None
        if attempt > 0:
            # the ladder brought a daemon back after a crash (respawned,
            # or a supervisor's replacement answered) — a recovery event
            dclient.bump_counter("daemon_restarts")
        crashed_gen = client.generation
        try:
            results, resp = client.execute(
                plan_bytes, config.to_key_value_pairs(), partitions,
                emit_pid=emit_pid, tag=tag, deadline_s=deadline_s)
        except dclient.DaemonCrashed as e:
            dclient.bump_counter("daemon_crashes_detected")
            dclient.drop_attached(path)
            # classify: a diagnosed watchdog kill leaves a post-mortem for
            # THIS incarnation next to the socket (fresh binds remove
            # stale ones, so generation can only match the latest corpse)
            report = dclient.read_crash_report(path)
            if (report is not None and report.get("kind") == "watchdog"
                    and (not crashed_gen
                         or report.get("generation") == crashed_gen)):
                dclient.bump_counter("watchdog_kills")
            count = dclient.record_stage_crash(path, tag, fingerprint, ttl)
            log.warning("daemon crashed running %s (%s; crash %d/%d)",
                        tag, e.reason, count, dclient.POISON_CRASH_THRESHOLD)
            if count >= dclient.POISON_CRASH_THRESHOLD:
                dclient.bump_counter("poisoned_stages")
                _note_local(
                    f"poisoned: {tag} crashed {count} daemons",
                    failover="poisoned",
                    failover_reason=f"crash ({e.reason}) x{count}; quarantined")
                return None
            if attempt == 0:
                # respawn-and-retry ONCE: attach() reruns its ladder (the
                # spawn knob governs whether a dead daemon is restarted)
                continue
            _note_local(f"daemon_crashed: {e}", failover="crashed",
                        failover_reason=f"crash ({e.reason}) after retry")
            return None
        except RuntimeError as e:
            if getattr(e, "poisoned", False):
                # a respawned daemon refusing a quarantined stage: clean
                # demotion, not a new crash against the fingerprint
                _note_local(f"poisoned: {e}", failover="poisoned",
                            failover_reason="daemon refused quarantined stage")
                return None
            _note_local(f"execute_failed: {e}")
            log.warning("daemon execute failed; running stage in-process: %s",
                        e)
            return None
        except Exception as e:  # noqa: BLE001 — the daemon must never fail
            # a query the in-process engine can run
            _note_local(f"execute_failed: {e}")
            log.warning("daemon execute failed; running stage in-process",
                        exc_info=True)
            return None
        _mirror_success(tag, resp, reason, retried=attempt > 0)
        return results
    return None  # unreachable; the loop always returns


def protocol_deadline(config, est_bytes: int) -> float:
    from ballista_tpu.config import TPU_DAEMON_EXECUTE_TIMEOUT_S
    from ballista_tpu.device_daemon import protocol

    return protocol.derive_execute_timeout_s(
        float(config.get(TPU_DAEMON_EXECUTE_TIMEOUT_S)), est_bytes)


def _mirror_success(tag: str, resp: dict, reason: str, retried: bool) -> None:
    """Publish the daemon's mirrored engine stats under this stage's tag:
    the client's RUN_STATS (heartbeat, bench events) reports the device
    work even though it happened in the daemon process."""
    with RUN_STATS.run(tag) as rec:
        for k, v in resp.get("stats", {}).items():
            if isinstance(v, (int, float, str, bool)):
                rec[k] = v
        rec["daemon_mode"] = "attached"
        rec["daemon_mode_reason"] = reason
        rec["daemon_attached"] = 1.0
        rec["daemon_sessions"] = float(resp.get("sessions", 0))
        rec["daemon_queue_depth"] = float(resp.get("queue_depth", 0))
        if retried:
            rec["daemon_failover"] = "daemon_restarted"
            rec["daemon_failover_reason"] = "crash recovered by respawn+retry"
        init_s = resp.get("init_phase_s", {})
        if "platform_probe" in init_s:
            rec["init_platform_probe_s"] = float(init_s["platform_probe"])
        if "jax_devices" in init_s:
            rec["init_jax_devices_s"] = float(init_s["jax_devices"])
        if "first_compile" in init_s:
            rec["init_first_compile_s"] = float(init_s["first_compile"])
    _mirror_counters()
