"""Arrow → device columnar encoding.

The central TPU-native design problem (SURVEY.md §7 hard-part #1/#2): Arrow
batches are ragged and typed for CPUs; XLA wants fixed shapes and hardware
lanes. Encoding rules:

- integers            → int64 device lanes (jax x64 enabled by the engine)
- date32              → int32 day counts (comparisons become int compares)
- float64 that proves to be N-decimal fixed-point (TPC-H money) → int64
  scaled integers: exact on-device arithmetic and overflow-safe to ~9.2e18
  scale units — beyond the SF1000 aggregate range at scale 1e6
- other float64       → float64 (XLA emulates f64 on TPU; correctness first,
  the money path is the fast path)
- strings             → dictionary codes (int32) + host-side dictionary; all
  string predicates become host-computed boolean LUTs over the dictionary,
  gathered on device (predicates never touch bytes on the TPU)
- booleans            → bool lanes
- NULLs               → per-column validity masks are NOT yet lowered; any
  nullable data falls back to the CPU engine at runtime

Rows are padded to the session's shape buckets with a row-validity mask so
one XLA compilation serves every batch in the bucket
(`ballista.tpu.shape.buckets`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


@dataclass
class DeviceCol:
    kind: str  # i64 | f64 | money | date | code | bool
    data: Any  # np/jnp array, padded
    dictionary: Optional[list] = None  # for kind == "code"
    scale: int = 0  # for kind == "money": value = data / 10**scale
    valid: Optional[np.ndarray] = None  # bool validity plane; None = no nulls


@dataclass
class DeviceBatch:
    n_rows: int  # valid rows (<= padded length)
    columns: dict[str, DeviceCol]
    mask: Any  # bool[n_padded] row validity


def next_bucket(n: int, buckets: list[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    # beyond the largest bucket: round up to a multiple of it
    top = buckets[-1]
    return ((n + top - 1) // top) * top


def _is_fixed_point(vals: np.ndarray, scale: int = 2) -> bool:
    if len(vals) == 0:
        return True
    m = 10**scale
    scaled = vals * m
    return bool(np.all(np.abs(scaled - np.rint(scaled)) < 1e-6))


def _narrow_int(vals: np.ndarray) -> np.ndarray:
    """Transfer-dtype narrowing: the PCIe/tunnel link is the bottleneck, so
    ship the smallest int that holds the range; device readers upcast to
    int64 in HBM (free relative to the link)."""
    if len(vals) == 0:
        return vals.astype(np.int32)
    lo, hi = int(vals.min()), int(vals.max())
    if -(2**15) <= lo and hi < 2**15:
        return vals.astype(np.int16)
    if -(2**31) <= lo and hi < 2**31:
        return vals.astype(np.int32)
    return vals.astype(np.int64)


def encode_column(arr: pa.Array) -> Optional[DeviceCol]:
    """Encode one Arrow column; None = not encodable (fallback to CPU).

    Nullable columns encode with a boolean VALIDITY PLANE riding alongside
    the value lane: null slots are filled with a type default (the plane,
    not the fill value, is what kernels consult) so stages over NULL-bearing
    data stay on device instead of falling back to the CPU engine."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = arr.type
    valid = np.asarray(arr.is_valid()) if arr.null_count else None

    def v(col: DeviceCol) -> DeviceCol:
        col.valid = valid
        return col

    if pa.types.is_dictionary(t):
        idx = arr.indices
        if idx.null_count:
            idx = pc.fill_null(idx, 0)
        codes = idx.to_numpy(zero_copy_only=False)
        return v(DeviceCol("code", _narrow_int(codes), dictionary=arr.dictionary.to_pylist()))
    if arr.null_count:
        if pa.types.is_boolean(t):
            arr = pc.fill_null(arr, False)
        elif pa.types.is_date(t):
            filled = pc.fill_null(arr.cast(pa.int32() if pa.types.is_date32(t) else pa.int64(),
                                           safe=False), 0)
            days = filled.to_numpy(zero_copy_only=False)
            if pa.types.is_date64(t):
                days = days // 86_400_000  # ms → days
            return v(DeviceCol("date", days.astype(np.int32)))
        elif pa.types.is_string(t) or pa.types.is_large_string(t):
            pass  # dictionary_encode keeps nulls in the index; filled below
        else:
            arr = pc.fill_null(arr, 0)
    if pa.types.is_integer(t):
        vals = arr.cast(pa.int64(), safe=False).to_numpy(zero_copy_only=False)
        return v(DeviceCol("i64", _narrow_int(vals.astype(np.int64, copy=False))))
    if pa.types.is_date(t):
        if pa.types.is_date64(t):
            ms = arr.cast(pa.int64(), safe=False).to_numpy(zero_copy_only=False)
            return v(DeviceCol("date", (ms // 86_400_000).astype(np.int32)))
        return v(DeviceCol("date", arr.cast(pa.int32(), safe=False).to_numpy(zero_copy_only=False)))
    if pa.types.is_boolean(t):
        return v(DeviceCol("bool", arr.to_numpy(zero_copy_only=False)))
    if pa.types.is_decimal(t):
        # exact decimal policy: unscaled int64 goes straight to the device
        # money lane — no float sniffing, the scale is declared. Wide or
        # deep-scaled decimals fall to f64 (lossy only past 2^53).
        s = t.scale
        if pa.types.is_decimal128(t) and 0 <= s <= 4 and t.precision - s <= 14:
            scaled = pc.multiply(arr, pa.scalar(10 ** s, pa.int64())) if s else arr
            vals = pc.cast(scaled, pa.int64()).to_numpy(zero_copy_only=False)
            return v(DeviceCol("money", _narrow_int(vals), scale=s))
        vals = arr.cast(pa.float64()).to_numpy(zero_copy_only=False)
        return v(DeviceCol("f64", vals))
    if pa.types.is_floating(t):
        vals = arr.cast(pa.float64()).to_numpy(zero_copy_only=False)
        if _is_fixed_point(vals, 2):
            return v(DeviceCol("money", _narrow_int(np.rint(vals * 100)), scale=2))
        return v(DeviceCol("f64", vals))
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        enc = pc.dictionary_encode(arr)
        if isinstance(enc, pa.ChunkedArray):
            enc = enc.combine_chunks()
        idx = enc.indices
        if idx.null_count:
            idx = pc.fill_null(idx, 0)
        codes = idx.to_numpy(zero_copy_only=False)
        return v(DeviceCol("code", _narrow_int(codes), dictionary=enc.dictionary.to_pylist()))
    return None


def encode_stacked(arr: pa.Array, part_rows: list[int], n_padded: int) -> Optional[DeviceCol]:
    """Encode one whole-scan column and lay it out as a [P, N] partition
    stack (row `off:off+part_rows[p]` of the flat encoding → `stack[p, :r]`,
    zero-padded). The single code path shared by the serial and pipelined
    device fills, so both are byte-identical by construction. The flat
    encoding is dropped before returning: peak host memory per column is
    one flat copy + one stack, not both for the table's lifetime."""
    dc = encode_column(arr)
    if dc is None:
        return None
    P = len(part_rows)
    stack = np.zeros((P, n_padded), dtype=dc.data.dtype)
    off = 0
    for p, r in enumerate(part_rows):
        stack[p, :r] = dc.data[off : off + r]
        off += r
    vstack = None
    if dc.valid is not None:
        vstack = np.zeros((P, n_padded), dtype=bool)
        off = 0
        for p, r in enumerate(part_rows):
            vstack[p, :r] = dc.valid[off : off + r]
            off += r
    return DeviceCol(dc.kind, stack, dictionary=dc.dictionary, scale=dc.scale,
                     valid=vstack)


def encode_table(tbl: pa.Table, buckets: list[int]) -> Optional[DeviceBatch]:
    n = tbl.num_rows
    padded = next_bucket(max(n, 1), buckets)
    cols: dict[str, DeviceCol] = {}
    for name, col in zip(tbl.column_names, tbl.columns):
        dc = encode_column(col)
        if dc is None:
            return None
        dc.data = _pad(dc.data, padded)
        cols[name] = dc
    mask = np.zeros(padded, dtype=bool)
    mask[:n] = True
    return DeviceBatch(n, cols, mask)


def _pad(a: np.ndarray, n: int) -> np.ndarray:
    if len(a) == n:
        return a
    out = np.zeros(n, dtype=a.dtype)
    out[: len(a)] = a
    return out


def decode_value(val: float | int, kind: str, scale: int):
    if kind == "money":
        return val / (10**scale)
    return val
