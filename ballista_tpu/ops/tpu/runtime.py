"""JAX runtime bootstrap for the TPU engine.

x64 is required: join keys are int64 and money arithmetic is int64 scaled
(ops/tpu/columnar.py). On TPU, f64 falls back to XLA software emulation —
acceptable because the hot paths (masks, money, codes) are integer.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_ready = False


def ensure_jax():
    global _ready
    with _lock:
        if _ready:
            import jax

            return jax
        import jax

        # honor JAX_PLATFORMS even when a site hook pre-imported jax with a
        # different platform baked in (env vars are read at import time);
        # without this, JAX_PLATFORMS=cpu can still dial a dead TPU plugin
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        jax.config.update("jax_enable_x64", True)
        _ready = True
        return jax


def device_kind() -> str:
    jax = ensure_jax()
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"
