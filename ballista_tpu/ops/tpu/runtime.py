"""JAX runtime bootstrap for the TPU engine.

x64 is required: join keys are int64 and money arithmetic is int64 scaled
(ops/tpu/columnar.py). On TPU, f64 falls back to XLA software emulation —
acceptable because the hot paths (masks, money, codes) are integer.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_ready = False

# -------------------------------------------------- persistent compile cache
# JAX's on-disk compilation cache: compiled XLA programs keyed by (HLO,
# compile options, backend) survive process restarts, so a re-admitted or
# redeployed executor skips recompiles entirely. Hits/misses are observed
# through jax's monitoring events (the cache itself never surfaces them).
_cc_lock = threading.Lock()
_cc_dir: str | None = None
_cc_listener_on = False
_cc_env_checked = False
_cc_counts = {"requests": 0, "hits": 0}


def ensure_jax():
    global _ready
    with _lock:
        if _ready:
            import jax

            return jax
        import jax

        # honor JAX_PLATFORMS even when a site hook pre-imported jax with a
        # different platform baked in (env vars are read at import time);
        # without this, JAX_PLATFORMS=cpu can still dial a dead TPU plugin
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        jax.config.update("jax_enable_x64", True)
        _ready = True
    # env-only activation path: daemons (or bare runtime users) that never
    # consult a session config still get the persistent cache via the env
    # var; session configs re-call init_compile_cache with their own value.
    # One-shot (init_compile_cache re-enters ensure_jax).
    global _cc_env_checked
    with _cc_lock:
        check_env = not _cc_env_checked
        _cc_env_checked = True
    if check_env:
        env_dir = os.environ.get("BALLISTA_TPU_COMPILE_CACHE")
        if env_dir:
            init_compile_cache(env_dir)
    import jax

    return jax


def _cc_on_event(event: str, **kwargs) -> None:
    # recorded by jax._src.compiler around every backend_compile: one
    # *_use_cache request per compilation attempt, one cache_hits when the
    # persistent entry was found (misses = requests - hits)
    if event == "/jax/compilation_cache/compile_requests_use_cache":
        with _cc_lock:
            _cc_counts["requests"] += 1
    elif event == "/jax/compilation_cache/cache_hits":
        with _cc_lock:
            _cc_counts["hits"] += 1


def init_compile_cache(cache_dir: str | None) -> str | None:
    """Enable the persistent XLA compilation cache under `cache_dir`.
    Idempotent; returns the active directory (None = disabled). Thresholds
    are zeroed so even sub-second stage compiles persist — a query engine's
    compile population is small and every warm-start second counts."""
    global _cc_dir, _cc_listener_on
    if not cache_dir:
        return _cc_dir
    with _cc_lock:
        if _cc_dir == cache_dir:
            return _cc_dir
    jax = ensure_jax()
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 — knob name drifts across jax versions
        pass
    try:
        # jax latches cache initialization on the FIRST backend compile: a
        # compile that ran before the dir was configured leaves the cache
        # permanently off for the process. Reset so the new dir takes.
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except Exception:  # noqa: BLE001 — private module; best effort
        pass
    with _cc_lock:
        _cc_dir = cache_dir
        if not _cc_listener_on:
            try:
                from jax._src import monitoring

                monitoring.register_event_listener(_cc_on_event)
                _cc_listener_on = True
            except Exception:  # noqa: BLE001 — stats only, cache still works
                pass
    return cache_dir


def compile_cache_dir() -> str | None:
    """Active persistent-cache directory, or None when disabled."""
    with _cc_lock:
        return _cc_dir


def compile_cache_stats() -> dict:
    """Snapshot of persistent-cache effectiveness for this process."""
    with _cc_lock:
        return {
            "dir": _cc_dir,
            "requests": _cc_counts["requests"],
            "hits": _cc_counts["hits"],
            "misses": _cc_counts["requests"] - _cc_counts["hits"],
        }


def device_kind() -> str:
    jax = ensure_jax()
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def process_rusage() -> dict:
    """Resource-usage snapshot of THIS process for post-mortem artifacts
    (the device daemon's crash report): peak RSS and CPU split. jax-free
    and never raises — diagnostics must not add failure modes."""
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        return {
            "max_rss_kb": int(ru.ru_maxrss),
            "user_s": round(ru.ru_utime, 3),
            "system_s": round(ru.ru_stime, 3),
        }
    except Exception:  # noqa: BLE001 — platform without getrusage
        return {}


# ---------------------------------------------------------------- binding
# Per-chip executor pinning (SURVEY §7 step 7: one executor per chip,
# scheduler slot = chip; reference analog: the vcore slot model in
# executor/src/executor_process.rs:261). Two layers:
#
#  * process level — on real TPU hardware a chip is claimed exclusively at
#    backend init, so a pinned daemon must filter visibility BEFORE jax
#    initialises (bind_process_ordinal, called from executor_process.main);
#  * dispatch level — on shared-runtime platforms (CPU test mesh, an
#    in-process standalone cluster) every executor sees all devices, so
#    each stage commits its arrays via jax.default_device
#    (device_scope, threaded through TaskContext.device_ordinal).

def bind_process_ordinal(ordinal: int) -> bool:
    """Restrict this PROCESS to one TPU chip. Must run before jax's backend
    initialises; returns False (and binds nothing) when jax is already in."""
    import sys

    if ordinal is None or ordinal < 0:
        return False
    if "jax" in sys.modules:
        return False
    # libtpu / the PJRT TPU plugin read these at backend-init time; both
    # spellings are honored across runtime generations. Harmless on CPU.
    # The explicit --device-ordinal wins over any inherited host-wide value:
    # setdefault here would silently leave multiple daemons seeing (and on
    # real TPU, exclusively claiming) each other's chips.
    os.environ["TPU_VISIBLE_DEVICES"] = str(ordinal)
    os.environ["TPU_VISIBLE_CHIPS"] = str(ordinal)
    os.environ["TPU_PROCESS_BOUNDS"] = "1,1,1"
    return True


def bound_device(ordinal: int):
    """Resolve the jax.Device for an ordinal. After process-level filtering
    only one device is visible and it wins regardless of ordinal; otherwise
    index the local device list."""
    if ordinal is None or ordinal < 0:
        return None
    jax = ensure_jax()
    devs = jax.local_devices()
    if len(devs) == 1:
        return devs[0]
    if ordinal >= len(devs):
        # never alias a misconfigured ordinal onto someone else's chip: the
        # slot=chip model requires disjoint placement, so fail loudly (the
        # stage dispatcher logs this and falls back to CPU)
        raise ValueError(
            f"device ordinal {ordinal} out of range: {len(devs)} local devices")
    return devs[ordinal]


def device_scope(ordinal: int):
    """Context manager committing jax ops to the pinned device (no-op when
    unpinned). Wrap every device dispatch path in this."""
    import contextlib

    dev = bound_device(ordinal)
    if dev is None:
        return contextlib.nullcontext()
    jax = ensure_jax()
    return jax.default_device(dev)
