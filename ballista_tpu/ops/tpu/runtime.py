"""JAX runtime bootstrap for the TPU engine.

x64 is required: join keys are int64 and money arithmetic is int64 scaled
(ops/tpu/columnar.py). On TPU, f64 falls back to XLA software emulation —
acceptable because the hot paths (masks, money, codes) are integer.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_ready = False


def ensure_jax():
    global _ready
    with _lock:
        if _ready:
            import jax

            return jax
        import jax

        # honor JAX_PLATFORMS even when a site hook pre-imported jax with a
        # different platform baked in (env vars are read at import time);
        # without this, JAX_PLATFORMS=cpu can still dial a dead TPU plugin
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        jax.config.update("jax_enable_x64", True)
        _ready = True
        return jax


def device_kind() -> str:
    jax = ensure_jax()
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


# ---------------------------------------------------------------- binding
# Per-chip executor pinning (SURVEY §7 step 7: one executor per chip,
# scheduler slot = chip; reference analog: the vcore slot model in
# executor/src/executor_process.rs:261). Two layers:
#
#  * process level — on real TPU hardware a chip is claimed exclusively at
#    backend init, so a pinned daemon must filter visibility BEFORE jax
#    initialises (bind_process_ordinal, called from executor_process.main);
#  * dispatch level — on shared-runtime platforms (CPU test mesh, an
#    in-process standalone cluster) every executor sees all devices, so
#    each stage commits its arrays via jax.default_device
#    (device_scope, threaded through TaskContext.device_ordinal).

def bind_process_ordinal(ordinal: int) -> bool:
    """Restrict this PROCESS to one TPU chip. Must run before jax's backend
    initialises; returns False (and binds nothing) when jax is already in."""
    import sys

    if ordinal is None or ordinal < 0:
        return False
    if "jax" in sys.modules:
        return False
    # libtpu / the PJRT TPU plugin read these at backend-init time; both
    # spellings are honored across runtime generations. Harmless on CPU.
    # The explicit --device-ordinal wins over any inherited host-wide value:
    # setdefault here would silently leave multiple daemons seeing (and on
    # real TPU, exclusively claiming) each other's chips.
    os.environ["TPU_VISIBLE_DEVICES"] = str(ordinal)
    os.environ["TPU_VISIBLE_CHIPS"] = str(ordinal)
    os.environ["TPU_PROCESS_BOUNDS"] = "1,1,1"
    return True


def bound_device(ordinal: int):
    """Resolve the jax.Device for an ordinal. After process-level filtering
    only one device is visible and it wins regardless of ordinal; otherwise
    index the local device list."""
    if ordinal is None or ordinal < 0:
        return None
    jax = ensure_jax()
    devs = jax.local_devices()
    if len(devs) == 1:
        return devs[0]
    if ordinal >= len(devs):
        # never alias a misconfigured ordinal onto someone else's chip: the
        # slot=chip model requires disjoint placement, so fail loudly (the
        # stage dispatcher logs this and falls back to CPU)
        raise ValueError(
            f"device ordinal {ordinal} out of range: {len(devs)} local devices")
    return devs[ordinal]


def device_scope(ordinal: int):
    """Context manager committing jax ops to the pinned device (no-op when
    unpinned). Wrap every device dispatch path in this."""
    import contextlib

    dev = bound_device(ordinal)
    if dev is None:
        return contextlib.nullcontext()
    jax = ensure_jax()
    return jax.default_device(dev)
