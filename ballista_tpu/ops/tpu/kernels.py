"""JAX kernels: expression lowering, masked segment aggregation, row hash.

Design rules (pallas_guide / XLA-friendly):
- no data-dependent shapes: filters produce MASKS, never compaction; the
  aggregation consumes (value, mask) pairs with segment ops
- string work never reaches the device: predicates over dictionary columns
  are host-precomputed boolean LUTs, gathered by code on device
- money arithmetic stays in int64 scaled integers (exact); scale tracking
  happens at lowering time (static), not at runtime
- one jitted function per (stage fingerprint, shape bucket, dict sizes):
  the compile cache is keyed exactly on what changes the traced program

hash64/hash_combine are the bit-exact twins of ops/hashing.py — the wire
contract that lets device-side hash partitioning interoperate with host and
C++ shuffle readers.
"""

from __future__ import annotations

import datetime as _dt
import decimal as _decimal
import fnmatch
from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ballista_tpu.plan.expressions import (
    Alias,
    Between,
    BinaryExpr,
    Case,
    Cast,
    Column,
    Expr,
    InList,
    IsNotNull,
    IsNull,
    Like,
    Literal,
    Negative,
    Not,
    ScalarFunction,
)
from ballista_tpu.plan.schema import DFSchema


def _jnp():
    import jax.numpy as jnp

    return jnp


# -- device value with static (lowering-time) type info ---------------------


@dataclass
class DevVal:
    kind: str  # i64 | f64 | money | date | code | bool
    arr: Any  # jnp array
    scale: int = 0
    dictionary: list | None = None
    valid: Any = None  # jnp bool array; None = known non-null everywhere


class Unsupported(Exception):
    """Raised at lowering time → subtree falls back to the CPU engine."""


def vand(*valids):
    """Null-strict validity combine: result is null if ANY input is null
    (the SQL rule for comparisons, arithmetic, casts, function args)."""
    out = None
    for v in valids:
        if v is None:
            continue
        out = v if out is None else out & v
    return out


def true_mask(v: DevVal):
    """Project three-valued logic onto filtering: rows pass a WHERE clause
    only when the predicate is TRUE — unknown (NULL) behaves as false."""
    if v.valid is None:
        return v.arr
    return v.arr & v.valid


# -- bit-exact twin of ops/hashing.py ---------------------------------------


def hash64(x):
    """splitmix64 over uint64 lanes (jax)."""
    jnp = _jnp()
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def hash_combine_jax(h, v):
    jnp = _jnp()
    return h ^ (v + jnp.uint64(0x9E3779B97F4A7C15) + (h << jnp.uint64(6)) + (h >> jnp.uint64(2)))


# -- lowering ----------------------------------------------------------------


class Lowering:
    """Collects host-side LUT constants while lowering expressions into
    closures over (cols, luts). LUTs are padded to pow2 so jit keys are
    stable across partitions with slightly different dictionaries."""

    def __init__(self, schema: DFSchema, kinds: list[tuple[str, int]], dictionaries: list[list | None]):
        self.schema = schema
        self.kinds = kinds  # per-field (kind, scale)
        self.dictionaries = dictionaries
        # LUTs are registered as (source_slot, builder) so they can be
        # REBUILT for each partition's dictionaries without re-tracing: the
        # compiled function takes LUTs as traced args, only their contents
        # change across partitions (padded size is part of the jit key).
        self.lut_builders: list[tuple[int, Any]] = []
        self.slots: list[int] = list(range(len(kinds)))  # field → source slot
        # fused_pallas stages flip this on: dictionary-code predicates run
        # through the pallas dict_filter kernel (VMEM-resident LUT) instead
        # of a plain XLA gather
        self.pallas_dict_filter = False
        # env indirection (set by the stage compiler): field index → lowered
        # fn, so projections rebind what a Column reference means
        self.env_fns: list | None = None
        self.env_meta: list | None = None

    def add_lut(self, src_slot, builder) -> int:
        """src_slot: scan column index, or ('build', join_idx, col_idx) for
        dictionaries that live in a join's build table."""
        self.lut_builders.append((src_slot, builder))
        return len(self.lut_builders) - 1

    def build_luts(self, dictionaries_by_slot: list[list | None],
                   build_dicts: list[list[list | None]] | None = None) -> list[np.ndarray]:
        out = []
        for slot, builder in self.lut_builders:
            if isinstance(slot, tuple) and slot[0] == "build":
                dic = build_dicts[slot[1]][slot[2]] if build_dicts else None
            else:
                dic = dictionaries_by_slot[slot]
            vals = builder(dic)
            n = 1
            while n < max(len(vals), 1):
                n *= 2
            padded = np.zeros(n, dtype=vals.dtype)
            padded[: len(vals)] = vals
            out.append(padded)
        return out

    def col_index(self, c: Column) -> int:
        return self.schema.index_of(c.name, c.qualifier)


LoweredFn = Callable[[list, list], DevVal]  # (cols, luts) -> DevVal


def _string_expr_fn(e: Expr, ctx: "Lowering"):
    """Recognize pure string-function trees over ONE dictionary column
    (substr/upper/lower/trim with literal args). Returns (base Column,
    col_index, str→str fn) — predicates over such trees compose into the
    column's host-side dictionary LUT, so strings never reach the device
    (the substring(c_phone,..) IN (...) pattern of q22 and TPC-DS)."""
    if isinstance(e, Alias):
        return _string_expr_fn(e.expr, ctx)
    if isinstance(e, Column):
        i = ctx.col_index(e)
        if ctx.kinds[i][0] == "code":
            return e, i, lambda s: s
        return None
    if isinstance(e, ScalarFunction) and e.name in ("substr", "upper", "lower", "trim"):
        inner = _string_expr_fn(e.args[0], ctx) if e.args else None
        if inner is None:
            return None
        col, i, f = inner
        extra = e.args[1:]
        if not all(isinstance(a, Literal) for a in extra):
            return None
        vals = [a.value for a in extra]
        name = e.name
        if name in ("upper", "lower", "trim") and extra:
            # BTRIM(col, chars) etc. — semantics we don't model: stay on cpu
            return None
        if name == "substr":
            if not vals or not all(isinstance(v, int) for v in vals):
                return None
            if vals[0] < 1:
                return None  # SQL start<1 clamps; python would wrap
            start = vals[0] - 1
            end = start + vals[1] if len(vals) > 1 else None

            def g(s, f=f, start=start, end=end):
                t = f(s)
                return t[start:end] if end is not None else t[start:]
        elif name == "upper":
            def g(s, f=f):
                return f(s).upper()
        elif name == "lower":
            def g(s, f=f):
                return f(s).lower()
        else:  # trim
            def g(s, f=f):
                return f(s).strip()
        return col, i, g
    return None


def lower_expr(e: Expr, ctx: Lowering) -> LoweredFn:
    jnp_mod = None  # resolved lazily inside closures

    if isinstance(e, Alias):
        return lower_expr(e.expr, ctx)

    if isinstance(e, Column):
        i = ctx.col_index(e)
        if ctx.env_fns is not None:
            return ctx.env_fns[i]
        kind, scale = ctx.kinds[i]
        dic = ctx.dictionaries[i]
        return lambda cols, luts: DevVal(kind, cols[i], scale, dic)

    if isinstance(e, Literal):
        v = e.value
        if isinstance(v, bool):
            return lambda cols, luts: DevVal("bool", _jnp().asarray(v))
        if isinstance(v, int):
            return lambda cols, luts: DevVal("i64", _jnp().asarray(v, dtype=_jnp().int64))
        if isinstance(v, float):
            cents = v * 100
            if abs(cents - round(cents)) < 1e-9:
                c = int(round(cents))
                return lambda cols, luts: DevVal("money", _jnp().asarray(c, dtype=_jnp().int64), 2)
            return lambda cols, luts: DevVal("f64", _jnp().asarray(v, dtype=_jnp().float64))
        if isinstance(v, _decimal.Decimal):
            # exact-policy literal: the declared scale IS the fixed point
            exp = -v.as_tuple().exponent
            if 0 <= exp <= 4:
                c = int(v.scaleb(exp))
                return lambda cols, luts, c=c, exp=exp: DevVal(
                    "money", _jnp().asarray(c, dtype=_jnp().int64), exp)
            fv = float(v)
            return lambda cols, luts, fv=fv: DevVal(
                "f64", _jnp().asarray(fv, dtype=_jnp().float64))
        if isinstance(v, _dt.date):
            days = (v - _dt.date(1970, 1, 1)).days
            return lambda cols, luts: DevVal("date", _jnp().asarray(days, dtype=_jnp().int32))
        raise Unsupported(f"literal {v!r}")

    if isinstance(e, BinaryExpr):
        # string equality over dictionary columns → host LUT, device gather
        if e.op in ("=", "<>"):
            for a, b in ((e.left, e.right), (e.right, e.left)):
                if isinstance(b, Literal) and isinstance(b.value, str):
                    hit = _string_expr_fn(a, ctx)
                    if hit is not None:
                        col, i, sfn = hit
                        src = lower_expr(col, ctx)
                        val = b.value
                        li = ctx.add_lut(
                            ctx.slots[i],
                            lambda dic, val=val, sfn=sfn: np.array(
                                [sfn(x) == val for x in dic], dtype=bool
                            ),
                        )
                        neg = e.op == "<>"

                        def run(cols, luts, src=src, li=li, neg=neg, ctx=ctx):
                            v = src(cols, luts)
                            lut = luts[li]
                            if ctx.pallas_dict_filter and getattr(v.arr, "ndim", 0) == 2:
                                from ballista_tpu.ops.tpu.pallas_kernels import dict_filter

                                jnp = _jnp()
                                # the kernel conjoins validity in VMEM; under
                                # <> keep the raw gather (the valid plane
                                # handles nulls downstream either way)
                                mask = v.valid if (v.valid is not None and not neg) \
                                    else jnp.ones(v.arr.shape, bool)
                                out = dict_filter(v.arr, lut, mask)
                            else:
                                out = lut[v.arr]
                            return DevVal("bool", ~out if neg else out, valid=v.valid)

                        return run
        lf = lower_expr(e.left, ctx)
        rf = lower_expr(e.right, ctx)
        op = e.op

        def run(cols, luts):
            return _binop(lf(cols, luts), op, rf(cols, luts))

        return run

    if isinstance(e, Not):
        f = lower_expr(e.expr, ctx)

        def run(cols, luts):
            v = f(cols, luts)
            return DevVal("bool", ~v.arr, valid=v.valid)  # NOT NULL is NULL

        return run

    if isinstance(e, IsNull) or isinstance(e, IsNotNull):
        f = lower_expr(e.expr, ctx)
        want_null = isinstance(e, IsNull)

        def run(cols, luts):
            jnp = _jnp()
            v = f(cols, luts)
            if v.valid is None:
                out = jnp.zeros(jnp.shape(v.arr), bool) if want_null \
                    else jnp.ones(jnp.shape(v.arr), bool)
            else:
                out = ~v.valid if want_null else v.valid
            return DevVal("bool", out)  # IS [NOT] NULL is never null itself

        return run

    if isinstance(e, Negative):
        f = lower_expr(e.expr, ctx)

        def run(cols, luts):
            v = f(cols, luts)
            return DevVal(v.kind, -v.arr, v.scale, valid=v.valid)

        return run

    if isinstance(e, Between):
        vf = lower_expr(e.expr, ctx)
        lof = lower_expr(e.low, ctx)
        hif = lower_expr(e.high, ctx)
        neg = e.negated

        def run(cols, luts):
            v = vf(cols, luts)
            lo = _binop(v, ">=", lof(cols, luts))
            hi = _binop(v, "<=", hif(cols, luts))
            both = _binop(lo, "and", hi)
            return DevVal("bool", ~both.arr if neg else both.arr, valid=both.valid)

        return run

    if isinstance(e, InList):
        # string-fn trees over a code column compose into the dictionary LUT
        hit = _string_expr_fn(e.expr, ctx)
        if hit is not None and all(isinstance(v, str) for v in e.values):
            col, i, sfn = hit
            src = lower_expr(col, ctx)
            values = set(e.values)
            li = ctx.add_lut(
                ctx.slots[i],
                lambda dic, values=values, sfn=sfn: np.array(
                    [sfn(x) in values for x in dic], dtype=bool
                ),
            )
            neg = e.negated

            def run(cols, luts):
                v = src(cols, luts)
                out = luts[li][v.arr]
                return DevVal("bool", ~out if neg else out, valid=v.valid)

            return run
        inner = lower_expr(e.expr, ctx)
        if isinstance(e.expr, (Column, Alias)):
            col = e.expr.expr if isinstance(e.expr, Alias) else e.expr
            i = ctx.col_index(col)
            kind, _ = ctx.kinds[i]
            src = inner
            if kind in ("i64", "date"):
                vals = list(e.values)
                neg = e.negated

                def run(cols, luts):
                    jnp = _jnp()
                    v = src(cols, luts)
                    out = jnp.zeros(v.arr.shape, dtype=bool)
                    for lit in vals:
                        if isinstance(lit, _dt.date):
                            lit = (lit - _dt.date(1970, 1, 1)).days
                        out = out | (v.arr == lit)
                    # NULL IN (...) / NULL NOT IN (...) are both unknown
                    return DevVal("bool", ~out if neg else out, valid=v.valid)

                return run
        raise Unsupported(f"IN over {e.expr}")

    if isinstance(e, Like):
        if not isinstance(e.expr, Column):
            raise Unsupported("LIKE over non-column")
        i = ctx.col_index(e.expr)
        kind, _ = ctx.kinds[i]
        if kind != "code":
            raise Unsupported("LIKE over non-string")
        src = lower_expr(e.expr, ctx)
        pat = _like_to_fnmatch(e.pattern)
        li = ctx.add_lut(
            ctx.slots[i],
            lambda dic, pat=pat: np.array(
                [fnmatch.fnmatchcase(x, pat) for x in dic], dtype=bool
            ),
        )
        neg = e.negated

        def run(cols, luts, src=src, li=li, neg=neg, ctx=ctx):
            v = src(cols, luts)
            lut = luts[li]
            if ctx.pallas_dict_filter and getattr(v.arr, "ndim", 0) == 2:
                from ballista_tpu.ops.tpu.pallas_kernels import dict_filter
                jnp = _jnp()
                mask = (v.valid if (v.valid is not None and not neg)
                        else jnp.ones(v.arr.shape, bool))
                out = dict_filter(v.arr, lut, mask)
            else:
                out = lut[v.arr]
            return DevVal("bool", ~out if neg else out, valid=v.valid)

        return run

    if isinstance(e, Case):
        branch_fns = [(lower_expr(w, ctx), lower_expr(t, ctx)) for w, t in e.branches]
        else_fn = lower_expr(e.else_expr, ctx) if e.else_expr is not None else None

        has_else = else_fn is not None

        def run(cols, luts):
            jnp = _jnp()
            thens = [tf(cols, luts) for _, tf in branch_fns]
            whens = [wf(cols, luts) for wf, _ in branch_fns]
            # align all branch values to a common kind/scale
            target = thens[0]
            if has_else:
                evd = else_fn(cols, luts)
            else:
                # no ELSE: the fall-through value is NULL
                evd = DevVal(target.kind, jnp.zeros((), dtype=target.arr.dtype),
                             target.scale, valid=jnp.zeros((), dtype=bool))
            allv = thens + [evd]
            kind, scale = _common_kind([(v.kind, v.scale) for v in allv])
            allv = [_coerce(v, kind, scale) for v in allv]
            nullable = any(v.valid is not None for v in whens) or any(
                v.valid is not None for v in allv
            )
            out = allv[-1].arr
            out_valid = None
            if nullable:
                ev = allv[-1].valid
                out_valid = ev if ev is not None else jnp.ones((), dtype=bool)
            decided = jnp.zeros((), dtype=bool)
            for w, t in zip(whens, allv[:-1]):
                taken = true_mask(w)  # a NULL condition skips its branch
                cond = taken & ~decided
                out = jnp.where(cond, t.arr, out)
                if nullable:
                    tv = t.valid if t.valid is not None else True
                    out_valid = jnp.where(cond, tv, out_valid)
                decided = decided | taken
            return DevVal(kind, out, scale, valid=out_valid)

        return run

    if isinstance(e, Cast):
        f = lower_expr(e.expr, ctx)
        import pyarrow as pa

        to = e.to

        def run(cols, luts):
            jnp = _jnp()
            v = f(cols, luts)
            if pa.types.is_floating(to):
                return _coerce(v, "f64", 0)
            if pa.types.is_integer(to):
                if v.kind == "money":
                    return DevVal("i64", v.arr // (10**v.scale), valid=v.valid)
                return DevVal("i64", v.arr.astype(jnp.int64), valid=v.valid)
            raise Unsupported(f"cast to {to}")

        return run

    if isinstance(e, ScalarFunction):
        if e.name in ("extract_year", "extract_month"):
            f = lower_expr(e.args[0], ctx)
            part = e.name

            def run(cols, luts):
                jnp = _jnp()
                v = f(cols, luts)
                if v.kind != "date":
                    raise Unsupported("extract over non-date")
                days = v.arr.astype(jnp.int64)
                # civil-from-days (Howard Hinnant's algorithm, vectorized)
                z = days + 719468
                era = jnp.where(z >= 0, z, z - 146096) // 146097
                doe = z - era * 146097
                yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
                y = yoe + era * 400
                doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
                mp = (5 * doy + 2) // 153
                m = jnp.where(mp < 10, mp + 3, mp - 9)
                y = jnp.where(m <= 2, y + 1, y)
                if part == "extract_year":
                    return DevVal("i64", y.astype(jnp.int64), valid=v.valid)
                return DevVal("i64", m.astype(jnp.int64), valid=v.valid)

            return run
        raise Unsupported(f"scalar fn {e.name}")

    raise Unsupported(f"{type(e).__name__}")


def _like_to_fnmatch(pat: str) -> str:
    out = []
    for ch in pat:
        if ch == "%":
            out.append("*")
        elif ch == "_":
            out.append("?")
        elif ch in "*?[]":
            out.append(f"[{ch}]")
        else:
            out.append(ch)
    return "".join(out)


def _common_kind(pairs: list[tuple[str, int]]) -> tuple[str, int]:
    kinds = {k for k, _ in pairs}
    if "f64" in kinds:
        return "f64", 0
    if "money" in kinds:
        scale = max(s for k, s in pairs if k == "money")
        return "money", scale
    if kinds <= {"i64", "bool"}:
        return "i64", 0
    if kinds == {"date"}:
        return "date", 0
    if kinds == {"code"}:
        raise Unsupported("code-valued CASE")
    return "i64", 0


def _coerce(v: DevVal, kind: str, scale: int) -> DevVal:
    jnp = _jnp()
    if v.kind == kind and v.scale == scale:
        return v
    if kind == "f64":
        if v.kind == "money":
            return DevVal("f64", v.arr.astype(jnp.float64) / (10**v.scale), valid=v.valid)
        return DevVal("f64", v.arr.astype(jnp.float64), valid=v.valid)
    if kind == "money":
        if v.kind == "money":
            return DevVal("money", v.arr * (10 ** (scale - v.scale)), scale, valid=v.valid)
        if v.kind in ("i64", "bool"):
            return DevVal("money", v.arr.astype(jnp.int64) * (10**scale), scale, valid=v.valid)
    if kind == "i64":
        return DevVal("i64", v.arr.astype(jnp.int64), valid=v.valid)
    raise Unsupported(f"coerce {v.kind}->{kind}")


_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}


def _binop(l: DevVal, op: str, r: DevVal) -> DevVal:
    jnp = _jnp()
    if op in ("and", "or"):
        # Kleene three-valued logic. Null value slots are FILLED with False
        # at encode time, so the value lane of AND/OR is simply &/| — the
        # validity lane records where the result is actually known:
        #   x AND y known iff (both known) or (a known-FALSE side exists)
        #   x OR  y known iff (both known) or (a known-TRUE  side exists)
        if l.valid is None and r.valid is None:
            out = l.arr & r.arr if op == "and" else l.arr | r.arr
            return DevVal("bool", out)
        lv = l.valid if l.valid is not None else True
        rv = r.valid if r.valid is not None else True
        if op == "and":
            valid = (lv & rv) | (lv & ~l.arr) | (rv & ~r.arr)
            return DevVal("bool", l.arr & r.arr, valid=valid)
        valid = (lv & rv) | (lv & l.arr) | (rv & r.arr)
        return DevVal("bool", l.arr | r.arr, valid=valid)

    valid = vand(l.valid, r.valid)
    if op in _CMP_OPS:
        if l.kind == "code" or r.kind == "code":
            code, lit = (l, r) if l.kind == "code" else (r, l)
            raise Unsupported("code comparison must be pre-lowered via LUT")
        kind, scale = _common_kind([(l.kind, l.scale), (r.kind, r.scale)])
        a, b = _coerce(l, kind, scale).arr, _coerce(r, kind, scale).arr
        fn = {
            "=": lambda: a == b, "<>": lambda: a != b, "<": lambda: a < b,
            "<=": lambda: a <= b, ">": lambda: a > b, ">=": lambda: a >= b,
        }[op]
        return DevVal("bool", fn(), valid=valid)

    # arithmetic (null-strict: validity is the AND of input validities)
    if op == "/":
        a = _coerce(l, "f64", 0).arr
        b = _coerce(r, "f64", 0).arr
        return DevVal("f64", a / b, valid=valid)
    if op == "*":
        if l.kind == "money" and r.kind == "money":
            return DevVal("money", l.arr * r.arr, l.scale + r.scale, valid=valid)
        if l.kind == "money" and r.kind in ("i64", "bool"):
            return DevVal("money", l.arr * r.arr.astype(jnp.int64), l.scale, valid=valid)
        if r.kind == "money" and l.kind in ("i64", "bool"):
            return DevVal("money", r.arr * l.arr.astype(jnp.int64), r.scale, valid=valid)
        if "f64" in (l.kind, r.kind):
            return DevVal("f64", _coerce(l, "f64", 0).arr * _coerce(r, "f64", 0).arr, valid=valid)
        return DevVal("i64", l.arr.astype(jnp.int64) * r.arr.astype(jnp.int64), valid=valid)
    if op in ("+", "-"):
        if l.kind == "date" and r.kind == "i64":
            arr = l.arr + (r.arr if op == "+" else -r.arr).astype(l.arr.dtype)
            return DevVal("date", arr, valid=valid)
        kind, scale = _common_kind([(l.kind, l.scale), (r.kind, r.scale)])
        a, b = _coerce(l, kind, scale).arr, _coerce(r, kind, scale).arr
        return DevVal(kind, a + b if op == "+" else a - b, scale, valid=valid)
    raise Unsupported(f"binop {op}")


# -- aggregation -------------------------------------------------------------


def segment_aggregate(values: DevVal, mask, gids, num_segments: int, func: str):
    """Masked per-group aggregate; returns jnp array[num_segments]."""
    import jax

    jnp = _jnp()
    if func in ("count", "count_all"):
        return jax.ops.segment_sum(mask.astype(jnp.int64), gids, num_segments=num_segments)
    v = values.arr
    if func == "sum":
        zero = jnp.zeros((), dtype=v.dtype)
        return jax.ops.segment_sum(jnp.where(mask, v, zero), gids, num_segments=num_segments)
    if func == "min":
        big = _max_of(v.dtype)
        return jax.ops.segment_min(jnp.where(mask, v, big), gids, num_segments=num_segments)
    if func == "max":
        small = _min_of(v.dtype)
        return jax.ops.segment_max(jnp.where(mask, v, small), gids, num_segments=num_segments)
    raise Unsupported(f"agg {func}")


def _max_of(dtype):
    jnp = _jnp()
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.iinfo(dtype).max
    return jnp.inf


def _min_of(dtype):
    jnp = _jnp()
    if jnp.issubdtype(dtype, jnp.integer):
        return jnp.iinfo(dtype).min
    return -jnp.inf
