"""Compile physical-plan subtrees to jitted XLA stage functions.

TpuStageExec replaces a `HashAggregateExec(partial)` whose input chain is
Filter*/Projection*/CoalesceBatches* over a scan. The execution model is
built around two facts of TPU systems: HBM is fast, the host↔device link is
not (PCIe, or worse, a tunnel with ~70ms RTT), and XLA loves big static
shapes. So:

- the WHOLE table (all scan partitions) is encoded once with UNIFIED
  dictionaries and cached device-resident as [P, N] stacked columns
  (DeviceTableCache; LRU against ballista.tpu.max.device.bytes);
- scan filters and residual operators are lowered into ONE jitted kernel
  that processes all P partitions in a single dispatch: per-partition
  masked segment aggregation with global group ids p*G + g;
- per query the device round trips are O(1): upload LUTs (cached), one
  dispatch, one batched fetch — not O(partitions × outputs).

Output batches match the partial aggregate's schema exactly, so the
downstream repartition/final-aggregate machinery is engine-agnostic —
the per-subtree dispatch pattern of the reference's engine seam
(ballista/executor/src/execution_engine.rs:51,124-147) taken to XLA.

Fallback is runtime-adaptive: unencodable types, NULLs, oversized group
domains, or tiny inputs re-run the original subtree on the CPU engine.
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.config import TPU_MAX_DEVICE_BYTES, TPU_MIN_ROWS, BallistaConfig
from ballista_tpu.ops.tpu.columnar import encode_column, next_bucket
from ballista_tpu.ops.tpu.kernels import (
    DevVal,
    Lowering,
    Unsupported,
    lower_expr,
    segment_aggregate,
)
from ballista_tpu.ops.tpu.runtime import ensure_jax
from ballista_tpu.plan.expressions import Alias, Column, Expr
from ballista_tpu.plan.physical import (
    AggDesc,
    CoalesceBatchesExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    TaskContext,
    _concat,
    _empty_batch,
)
from ballista_tpu.plan.schema import DFSchema

log = logging.getLogger(__name__)

MAX_SEGMENTS = 1 << 16

_COMPILE_CACHE: dict = {}
_COMPILE_LOCK = threading.Lock()
_LUT_CACHE: dict = {}  # (table_key, lowering_id, lut_index) → device array


class DeviceTable:
    """All partitions of one scan, device-resident as [P, N] stacks."""

    def __init__(self, kinds, scales, dicts, cols, mask, part_rows, nbytes):
        self.kinds = kinds  # per column
        self.scales = scales
        self.dicts = dicts  # unified (global) dictionaries
        self.cols = cols  # list of jnp [P, N]
        self.mask = mask  # jnp bool [P, N]
        self.part_rows = part_rows
        self.nbytes = nbytes

    @property
    def shape(self):
        return self.mask.shape


class DeviceTableCache:
    def __init__(self):
        import collections

        self._cache: "collections.OrderedDict[tuple, DeviceTable]" = collections.OrderedDict()
        self._lock = threading.Lock()
        self._inflight: dict[tuple, threading.Event] = {}

    def get(self, scan, buckets: list[int], ctx, max_bytes: int) -> DeviceTable:
        key = self.key_of(scan)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                return hit
            ev = self._inflight.get(key)
            owner = ev is None
            if owner:
                ev = threading.Event()
                self._inflight[key] = ev
        if not owner:
            ev.wait()
            with self._lock:
                hit = self._cache.get(key)
            if hit is None:
                raise Unsupported("peer encode failed")
            return hit
        try:
            dt = self._load(scan, buckets, ctx)
            with self._lock:
                total = sum(v.nbytes for v in self._cache.values())
                while self._cache and total + dt.nbytes > max_bytes:
                    _, old = self._cache.popitem(last=False)
                    total -= old.nbytes
                self._cache[key] = dt
            return dt
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    def key_of(self, scan) -> tuple:
        if isinstance(scan, ParquetScanExec):
            files = tuple(
                tuple((f["file"], tuple(f.get("row_groups") or ())) for f in p.get("files", []))
                for p in scan.partitions
            )
            return (files, tuple(scan.projection))
        return (id(scan),)

    def _load(self, scan, buckets: list[int], ctx) -> DeviceTable:
        import concurrent.futures as fut

        jax = ensure_jax()
        jnp = jax.numpy
        if isinstance(scan, ParquetScanExec):
            raw = ParquetScanExec(scan.df_schema, scan.partitions, scan.projection, [], scan.table_name)
        else:
            raw = scan
        P = raw.output_partition_count()

        def read(p):
            return _concat([b for b in raw.execute(p, ctx) if b.num_rows], raw.schema())

        with fut.ThreadPoolExecutor(max_workers=min(P, 8)) as pool:
            tables = list(pool.map(read, range(P)))
        part_rows = [t.num_rows for t in tables]
        full = pa.concat_tables(tables)
        N = next_bucket(max(max(part_rows), 1), buckets)

        kinds, scales, dicts, cols_np = [], [], [], []
        for name in full.column_names:
            dc = encode_column(full.column(name))
            if dc is None:
                raise Unsupported(f"unencodable column {name}")
            kinds.append(dc.kind)
            scales.append(dc.scale)
            dicts.append(dc.dictionary)
            stack = np.zeros((P, N), dtype=dc.data.dtype)
            off = 0
            for p, r in enumerate(part_rows):
                stack[p, :r] = dc.data[off : off + r]
                off += r
            cols_np.append(stack)
        mask_np = np.zeros((P, N), dtype=bool)
        for p, r in enumerate(part_rows):
            mask_np[p, :r] = True

        cols = [jnp.asarray(c) for c in cols_np]
        mask = jnp.asarray(mask_np)
        nbytes = sum(c.nbytes for c in cols_np) + mask_np.nbytes
        return DeviceTable(kinds, scales, dicts, cols, mask, part_rows, nbytes)


DEVICE_CACHE = DeviceTableCache()


class TpuStageExec(ExecutionPlan):
    def __init__(self, partial_agg: HashAggregateExec, ops: list, scan: ExecutionPlan,
                 config: BallistaConfig):
        super().__init__(partial_agg.df_schema)
        self.partial_agg = partial_agg
        self.ops = ops  # dataflow-ordered FilterExec/ProjectionExec nodes
        self.scan = scan
        self.config = config
        self.min_rows = int(config.get(TPU_MIN_ROWS))
        self.buckets = config.shape_buckets()
        self.fallback_count = 0
        self.tpu_count = 0
        self._results: dict[int, list[pa.RecordBatch]] | None = None
        self._results_lock = threading.Lock()
        # structural fingerprint: identical stages across queries share XLA
        # compilations (plan objects are rebuilt per query, ids are not)
        self.fingerprint = "|".join(
            [partial_agg.node_str()]
            + [op.node_str() for op in ops]
            + [scan.node_str(), repr(scan.df_schema)]
        )

    def children(self) -> list[ExecutionPlan]:
        return [self.scan]

    def with_children(self, c):
        return TpuStageExec(self.partial_agg, self.ops, c[0], self.config)

    def output_partition_count(self) -> int:
        return self.scan.output_partition_count()

    def node_str(self) -> str:
        return f"TpuStageExec: [{self.partial_agg.node_str()}] ops={len(self.ops)}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(iter(self._run(partition, ctx)))

    # ------------------------------------------------------------------

    def _run(self, partition: int, ctx: TaskContext) -> list[pa.RecordBatch]:
        with self._results_lock:
            if self._results is None:
                try:
                    self._results = self._tpu_run_all(ctx)
                    self.tpu_count += 1
                except Unsupported as e:
                    log.info("tpu fallback (%s): %s", e, self.partial_agg.node_str())
                    self._results = {}
        if partition in self._results:
            return self._results.pop(partition)
        return self._fallback(partition, ctx)

    def _fallback(self, partition: int, ctx: TaskContext) -> list[pa.RecordBatch]:
        """Re-run the original CPU subtree (scan filters applied on host)."""
        self.fallback_count += 1
        node: ExecutionPlan = self.scan
        for op in self.ops:
            node = op.with_children([node])
        agg = self.partial_agg.with_children([node])
        return [b for b in agg.execute(partition, ctx)]

    # ------------------------------------------------------------------

    def _tpu_run_all(self, ctx: TaskContext) -> dict[int, list[pa.RecordBatch]]:
        """One dispatch + one fetch for every partition of this stage."""
        jax = ensure_jax()
        jnp = jax.numpy

        max_bytes = int(self.config.get(TPU_MAX_DEVICE_BYTES))
        dt = DEVICE_CACHE.get(self.scan, self.buckets, ctx, max_bytes)
        if sum(dt.part_rows) < self.min_rows:
            raise Unsupported(f"only {sum(dt.part_rows)} rows (< tpu min)")

        P, N = dt.shape
        kinds = list(zip(dt.kinds, dt.scales))
        dicts = dt.dicts
        dtypes = tuple(str(c.dtype) for c in dt.cols)

        key = (
            self.fingerprint, P, N, tuple(kinds), dtypes,
            tuple(_pow2(len(d)) if d else 0 for d in dicts),
        )
        with _COMPILE_LOCK:
            cached = _COMPILE_CACHE.get(key)
            if cached is None:
                cached = self._compile(dt, kinds, dicts, P, N)
                _COMPILE_CACHE[key] = cached
        fn, lowering, meta = cached

        # device LUTs cached per (table, stage): zero uploads when hot
        lut_key = (DEVICE_CACHE.key_of(self.scan), self.fingerprint)
        luts = _LUT_CACHE.get(lut_key)
        if luts is None:
            luts = [jnp.asarray(l) for l in lowering.build_luts(dicts)]
            _LUT_CACHE[lut_key] = luts

        outs = fn(dt.cols, luts, dt.mask)
        outs = jax.device_get(list(outs))  # ONE batched fetch
        return self._decode_all(outs, meta, P, dicts)

    # ------------------------------------------------------------------

    def _compile(self, dt: DeviceTable, kinds, dicts, P: int, N: int):
        jax = ensure_jax()
        jnp = jax.numpy
        agg = self.partial_agg
        scan_schema = self.scan.df_schema

        ctx = Lowering(scan_schema, kinds, dicts)
        env_fns = []
        for i, (kind, scale) in enumerate(kinds):
            env_fns.append(_mk_col_reader(i, kind, scale, dicts[i]))
        env_meta = [(k, s, d, i) for i, ((k, s), d) in enumerate(zip(kinds, dicts))]
        ctx.env_fns = env_fns
        ctx.env_meta = env_meta
        filter_fns = []

        cur_schema = scan_schema
        _bind_env(ctx, cur_schema)
        # scan-level predicates run ON DEVICE (cache holds raw columns)
        for f in getattr(self.scan, "filters", []):
            filter_fns.append(lower_expr(f, ctx))

        for op in self.ops:
            _bind_env(ctx, cur_schema)
            if isinstance(op, FilterExec):
                filter_fns.append(lower_expr(op.predicate, ctx))
            elif isinstance(op, ProjectionExec):
                new_fns, new_meta = [], []
                for e in op.exprs:
                    new_fns.append(lower_expr(e, ctx))
                    new_meta.append(_passthrough_meta(e, ctx, cur_schema))
                ctx.env_fns, ctx.env_meta = new_fns, new_meta
                cur_schema = op.df_schema
            elif isinstance(op, CoalesceBatchesExec):
                pass
            else:
                raise Unsupported(f"op {type(op).__name__}")
        _bind_env(ctx, cur_schema)

        group_src_slots = []
        group_fns = []
        pad_sizes = []
        for g in agg.group_exprs:
            gc = g.expr if isinstance(g, Alias) else g
            if not isinstance(gc, Column):
                raise Unsupported(f"non-column group key {g}")
            i = cur_schema.index_of(gc.name, gc.qualifier)
            meta = ctx.env_meta[i]
            if meta is None or meta[0] != "code" or meta[2] is None:
                raise Unsupported(f"group key {gc} is not a dictionary column")
            group_fns.append(ctx.env_fns[i])
            group_src_slots.append(meta[3])
            pad_sizes.append(_pow2(len(meta[2])))

        G = 1
        for p in pad_sizes:
            G *= p
        G = max(G, 1)
        if G * P > MAX_SEGMENTS * 16:
            raise Unsupported(f"group domain {G}x{P} too large")

        agg_fns = []
        for d in agg.aggs:
            if d.func not in ("sum", "min", "max", "count", "count_all"):
                raise Unsupported(f"agg {d.func}")
            agg_fns.append(lower_expr(d.expr, ctx) if d.expr is not None else None)

        if G > 64:
            # scatter-based segment ops are pathological on TPU; larger group
            # domains stay on the CPU engine until the sort-based device
            # aggregation lands
            raise Unsupported(f"group domain {G} > unrolled limit")

        meta_holder: dict = {}
        aggs = agg.aggs

        def raw(cols, luts, mask):
            # keep [P, N]: partitions are the leading axis, reductions run
            # over axis=1 — XLA fuses the per-group masked sums into single
            # VPU passes, no scatter anywhere
            m = mask
            for ff in filter_fns:
                m = m & ff(cols, luts).arr
            if group_fns:
                gid = None
                for gf, psz in zip(group_fns, pad_sizes):
                    codes = gf(cols, luts).arr.astype(jnp.int32)
                    gid = codes if gid is None else gid * psz + codes
                gmasks = [m & (gid == g) for g in range(G)]
            else:
                gmasks = [m]
            outs = []
            out_meta = []
            for d, af in zip(aggs, agg_fns):
                if af is None:
                    v = None
                    out_meta.append(("i64", 0))
                else:
                    v = af(cols, luts)
                    out_meta.append(("i64", 0) if d.func == "count" else (v.kind, v.scale))
                cols_out = []
                for gm in gmasks:
                    cols_out.append(_masked_reduce(jnp, v, gm, d.func))
                outs.append(jnp.stack(cols_out, axis=1))  # [P, G]
            presence = jnp.stack([gm.sum(axis=1) for gm in gmasks], axis=1)
            meta_holder["out"] = out_meta
            return tuple(outs) + (presence,)

        jitted = jax.jit(raw)
        cols_spec = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in dt.cols]
        luts0 = ctx.build_luts(dicts)
        luts_spec = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in luts0]
        mask_spec = jax.ShapeDtypeStruct(dt.mask.shape, np.bool_)
        jitted.lower(cols_spec, luts_spec, mask_spec)  # trace only → meta
        meta = {
            "out": meta_holder["out"],
            "group_src_slots": group_src_slots,
            "pad_sizes": pad_sizes,
            "G": G,
        }
        return jitted, ctx, meta

    # ------------------------------------------------------------------

    def _decode_all(self, outs: list[np.ndarray], meta: dict, P: int, dicts) -> dict[int, list[pa.RecordBatch]]:
        agg = self.partial_agg
        schema = self.schema()
        group_dicts = [dicts[s] for s in meta["group_src_slots"]]
        presence = outs[-1]  # [P, G]
        results: dict[int, list[pa.RecordBatch]] = {}
        n_group = len(agg.group_exprs)
        for p in range(P):
            sel = np.nonzero(presence[p] > 0)[0]
            if not len(sel):
                results[p] = [_empty_batch(schema)]
                continue
            arrays: list[pa.Array] = []
            gid = sel.astype(np.int64)
            comps = []
            for psz in reversed(meta["pad_sizes"]):
                comps.append(gid % psz)
                gid = gid // psz
            comps = list(reversed(comps))
            for comp, d, f in zip(comps, group_dicts, schema):
                arrays.append(pa.array([d[int(c)] for c in comp], f.type))
            for out, (kind, scale), f in zip(outs[:-1], meta["out"], list(schema)[n_group:]):
                vals = out[p][sel]
                if kind == "money":
                    arr = pa.array(vals.astype(np.float64) / (10**scale), pa.float64())
                elif kind == "date":
                    arr = pa.array(vals.astype(np.int32), pa.int32()).cast(pa.date32())
                else:
                    arr = pa.array(vals)
                if arr.type != f.type:
                    arr = arr.cast(f.type)
                arrays.append(arr)
            results[p] = [pa.RecordBatch.from_arrays(arrays, schema=schema)]
        return results


def _masked_reduce(jnp, v, gm, func: str):
    """One group's reduction over axis=1 of [P, N] lanes."""
    if func in ("count", "count_all"):
        return gm.sum(axis=1).astype(jnp.int64)
    arr = v.arr
    if func == "sum":
        zero = jnp.zeros((), dtype=arr.dtype)
        return jnp.where(gm, arr, zero).sum(axis=1)
    if func == "min":
        big = jnp.iinfo(arr.dtype).max if jnp.issubdtype(arr.dtype, jnp.integer) else jnp.inf
        return jnp.where(gm, arr, big).min(axis=1)
    if func == "max":
        small = jnp.iinfo(arr.dtype).min if jnp.issubdtype(arr.dtype, jnp.integer) else -jnp.inf
        return jnp.where(gm, arr, small).max(axis=1)
    raise Unsupported(f"agg {func}")


def _pow2(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _mk_col_reader(i: int, kind: str, scale: int, dictionary):
    """Column reader with device-side upcast: columns ship narrow (int16/32)
    to spare the link, then widen in HBM where bandwidth is cheap."""

    def run(cols, luts):
        import jax.numpy as jnp

        arr = cols[i]
        if kind in ("i64", "money") and arr.dtype != jnp.int64:
            arr = arr.astype(jnp.int64)
        elif kind == "code" and arr.dtype != jnp.int32:
            arr = arr.astype(jnp.int32)
        elif kind == "date" and arr.dtype != jnp.int32:
            arr = arr.astype(jnp.int32)
        return DevVal(kind, arr, scale, dictionary)

    return run


def _bind_env(ctx: Lowering, schema: DFSchema) -> None:
    """Point the Lowering at the current virtual schema: Column exprs now
    resolve through env_fns (projection rebinding) instead of raw columns."""
    ctx.schema = schema
    ctx.kinds = [
        (m[0], m[1]) if m is not None else ("?", 0) for m in ctx.env_meta
    ]
    ctx.dictionaries = [m[2] if m is not None else None for m in ctx.env_meta]
    ctx.slots = [m[3] if m is not None else -1 for m in ctx.env_meta]

    def col_index(c):
        return schema.index_of(c.name, c.qualifier)

    ctx.col_index = col_index  # type: ignore[assignment]


def _passthrough_meta(e: Expr, ctx: Lowering, schema: DFSchema):
    inner = e.expr if isinstance(e, Alias) else e
    if isinstance(inner, Column):
        i = schema.index_of(inner.name, inner.qualifier)
        return ctx.env_meta[i]
    return None
