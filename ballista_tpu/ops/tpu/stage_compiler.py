"""Compile physical-plan subtrees to jitted XLA stage functions.

TpuStageExec replaces a `HashAggregateExec(partial)` whose input chain is
Filter*/Projection*/CoalesceBatches* over a scan. The execution model is
built around two facts of TPU systems: HBM is fast, the host↔device link is
not (PCIe, or worse, a tunnel with ~70ms RTT), and XLA loves big static
shapes. So:

- the WHOLE table (all scan partitions) is encoded once with UNIFIED
  dictionaries and cached device-resident as [P, N] stacked columns
  (DeviceTableCache; LRU against ballista.tpu.max.device.bytes);
- scan filters and residual operators are lowered into ONE jitted kernel
  that processes all P partitions in a single dispatch: per-partition
  masked segment aggregation with global group ids p*G + g;
- per query the device round trips are O(1): upload LUTs (cached), one
  dispatch, one batched fetch — not O(partitions × outputs).

Output batches match the partial aggregate's schema exactly, so the
downstream repartition/final-aggregate machinery is engine-agnostic —
the per-subtree dispatch pattern of the reference's engine seam
(ballista/executor/src/execution_engine.rs:51,124-147) taken to XLA.

Fallback is runtime-adaptive: unencodable types, NULLs, oversized group
domains, or tiny inputs re-run the original subtree on the CPU engine.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import os
import threading
import time
import zlib
from collections.abc import Mapping
from typing import Iterator

import numpy as np
import pyarrow as pa

from ballista_tpu.config import (
    TPU_COMPILE_CACHE_DIR,
    TPU_COMPILE_OVERLAP,
    TPU_FILL_CHUNK_ROWS,
    TPU_FILL_THREADS,
    TPU_FUSION_PALLAS_MAX_GROUPS,
    TPU_FUSION_PALLAS_MAX_PROBE,
    TPU_HBM_GRACE_BUCKETS,
    TPU_HBM_GRACE_DEPTH,
    TPU_HBM_SPILL_DIR,
    TPU_HBM_SPILL_ENABLED,
    TPU_HBM_SPILL_HOST_BYTES,
    TPU_MAX_DEVICE_BYTES,
    TPU_MIN_ROWS,
    BallistaConfig,
    _env_int,
)
from ballista_tpu.ops.tpu import hbm
from ballista_tpu.ops.tpu.columnar import encode_column, encode_stacked, next_bucket
from ballista_tpu.ops.tpu.kernels import (
    DevVal,
    Lowering,
    Unsupported,
    lower_expr,
    true_mask,
)
from ballista_tpu.ops.tpu.runtime import ensure_jax
from ballista_tpu.plan.expressions import Alias, Column, Expr
from ballista_tpu.plan.physical import (
    CoalesceBatchesExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    ParquetScanExec,
    ProjectionExec,
    TaskContext,
    _concat,
    _empty_batch,
)
from ballista_tpu.plan.schema import DFSchema

log = logging.getLogger(__name__)

MAX_SEGMENTS = 1 << 16

# LruDict moved to utils/lru.py (PR 9) so CPU-side modules can bound their
# caches without importing this module — the executor heartbeat keys TPU
# gauges on `sys.modules` containing this module's name. Re-exported here
# for back-compat.
from ballista_tpu.utils.lru import LruDict  # noqa: E402


# Entry budgets (env-tunable; these are safety rails for long-lived daemons,
# not per-session knobs). Build tables also carry a byte budget: their
# payloads are device-resident and can dwarf the entry count.
_COMPILE_CACHE = LruDict(_env_int("BALLISTA_TPU_COMPILE_CACHE_ENTRIES", 64))
_COMPILE_LOCK = threading.Lock()
# (table_key, fingerprint, mesh, emit, ordinal) → device arrays
_LUT_CACHE = LruDict(_env_int("BALLISTA_TPU_LUT_CACHE_ENTRIES", 256))
# (table_key, fingerprint, join_idx, mesh, ordinal) → BuildTable
_BUILD_CACHE = LruDict(
    _env_int("BALLISTA_TPU_BUILD_CACHE_ENTRIES", 32),
    max_bytes=_env_int("BALLISTA_TPU_BUILD_CACHE_BYTES", 2 * 1024**3),
    sizer=lambda bt: sum(int(getattr(a, "nbytes", 0)) for a in bt.flat_arrays()),
)


class RunStats(Mapping):
    """Per-stage-run diagnostics for the bench/roofline harness and the
    executor heartbeat.

    Concurrent stages used to scribble over one bare module dict; now every
    `_tpu_run_all` opens a `run(tag)` scope that collects into a private
    per-run dict (helper threads write through an explicit `rec=` handle)
    and publishes atomically on exit: the merged view (`dict(RUN_STATS)`,
    `snapshot()`) is always a consistent most-recent-run-wins snapshot, and
    `stages()` keeps the last few per-stage records for overlap analysis.

    Keys: fill_s (whole device fill), encode_s (host encode wall),
    upload_s (device_put issue + flush), device_bytes, trace_s (python
    trace+lower), xla_compile_s (backend compile / persistent-cache fetch),
    compile_s (trace_s + xla_compile_s, the legacy total), compile_overlap_s
    (compile seconds hidden under the fill), exec_s (dispatch + fetch +
    decode), persist_cache_hits and persist_cache_misses (per-run deltas),
    fusion_mode
    (staged | fused_xla | fused_pallas — the mode that actually ran),
    fusion_reason (the cost model's stated rationale), fused_spans
    (operator spans compiled into the single kernel; 0 in staged mode),
    fused_kernel_s (device seconds of the fused dispatch, or the sum of
    per-span times in staged mode; span_s carries the per-span split),
    mesh_devices (devices participating in a mesh-fused exchange stage),
    exchange_bytes_on_device (bytes moved by the on-device all_to_all),
    exchange_s (wall seconds of the exchange collective), mesh_mode_reason
    (why the mesh merge pass did or did not fuse the exchange),
    hbm_budget_bytes (the resolved device budget the stage was admitted
    against), hbm_plan (run_whole | spill_colds | grace_split | cpu_demote)
    and hbm_plan_reason (the admission ladder's stated rationale),
    hbm_spill_bytes / hbm_spill_events / hbm_reupload_events (cumulative
    host-spill-pool counters), grace_splits (sub-buckets actually executed
    by a grace-partitioned join), hbm_oom_retries (cumulative stage re-runs
    after a caught RESOURCE_EXHAUSTED; the evict-spill-retry rung),
    sort_kernel_s (cumulative device seconds in the sort/window/top-k
    family), sort_invocations / topk_invocations / window_invocations
    (cumulative per-family kernel dispatch counts), topk_rows_kept
    (cumulative rows surviving fused top-k cuts), window_partitions
    (cumulative partitions swept by device window stages), and
    sort_full_materializations (ORDER BY ... LIMIT stages that fell back
    to a full sort instead of the fused top-k — nonzero means the top-k
    rung demoted). Warm-daemon routing (docs/device_daemon.md):
    daemon_mode ("attached" when the stage was shipped to the device
    daemon, "in_process" when the session opted in but execution stayed
    local) and daemon_mode_reason (why — "daemon disabled",
    "attach_failed: ...", "execute_failed: ..." or the socket attached
    to); the numeric twins daemon_attached / daemon_sessions /
    daemon_queue_depth and the daemon's per-phase init timings
    init_platform_probe_s / init_jax_devices_s / init_first_compile_s
    flow to the executor heartbeat as gauges. Daemon failure-domain
    outcomes (ops/tpu/daemon_route.py,
    docs/device_daemon.md#failure-domain): daemon_failover
    ("daemon_restarted" when a crash was recovered by respawn+retry,
    "crashed" when the retry also died, "poisoned" when the stage sits
    in — or just entered — the on-disk quarantine) with the narrative in
    daemon_failover_reason, plus the process-lifetime recovery counters
    daemon_restarts / daemon_crashes_detected / watchdog_kills /
    poisoned_stages mirrored from the daemon client into the merged view
    so they ride the heartbeat. AQE decision counters
    (ops/tpu/aqe_stats.py, docs/aqe.md): skew_splits (hot reduce
    partitions split into slice tasks), coalesced_partitions (reduce
    partitions merged away), broadcast_promotions / broadcast_demotions
    (runtime join mode switches in either direction), and
    aqe_mesh_replans (mesh stages whose bucket count was replanned or
    whose fused exchange was demoted on skew) — all cumulative, all
    forwarded to the heartbeat under their own names. Append ingestion
    (serving/incremental.py, docs/streaming.md): delta_fill_rows — rows
    a memory-backed (delta-grafted) scan filled onto the device, so the
    heartbeat shows ingested-delta volume reaching the TPU tier."""

    _MAX_STAGES = 32

    def __init__(self):
        self._lock = threading.Lock()
        self._merged: dict = {}
        import collections

        self._stages: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
        self._tls = threading.local()

    @contextlib.contextmanager
    def run(self, tag: str):
        rec: dict = {}
        prev = getattr(self._tls, "rec", None)
        self._tls.rec = rec
        try:
            yield rec
        finally:
            self._tls.rec = prev
            self._publish(tag, rec)

    def _publish(self, tag: str, rec: dict) -> None:
        if not rec:
            return
        with self._lock:
            self._merged.update(rec)
            self._stages.pop(tag, None)
            self._stages[tag] = dict(rec)
            while len(self._stages) > self._MAX_STAGES:
                self._stages.popitem(last=False)

    def set(self, key: str, value, rec: dict | None = None) -> None:
        """Record one stat. With `rec` (a run's private dict, threadable to
        helper threads) the write lands in that run; otherwise in the
        calling thread's open run scope, else directly in the merged view."""
        if rec is None:
            rec = getattr(self._tls, "rec", None)
        if rec is not None:
            rec[key] = value
        else:
            with self._lock:
                self._merged[key] = value

    def __setitem__(self, key: str, value) -> None:  # legacy write path
        self.set(key, value)

    def current(self) -> dict | None:
        return getattr(self._tls, "rec", None)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._merged)

    def stages(self) -> dict:
        with self._lock:
            return {t: dict(r) for t, r in self._stages.items()}

    def clear(self) -> None:
        with self._lock:
            self._merged.clear()
            self._stages.clear()

    # Mapping protocol over the merged snapshot (dict(RUN_STATS) keeps
    # working for bench.py and older tooling)
    def __getitem__(self, key):
        with self._lock:
            return self._merged[key]

    def __iter__(self):
        with self._lock:
            return iter(list(self._merged))

    def __len__(self) -> int:
        with self._lock:
            return len(self._merged)


RUN_STATS = RunStats()

KEY_SHIFT = 21  # multi-key combine: k = k1 << 21 | k2 (guarded ranges)


DIRECT_TABLE_MAX = 1 << 27  # 128M entries × int32 = 512 MB HBM ceiling

MAX_JOIN_DUP = 16  # expansion joins unroll this many match lanes at most


class BuildTable:
    """A join's build side, encoded for device probing.

    mode 'direct': keys are dense-enough ints → a [T] int32 lookup table
    (key → build row, -1 absent): ONE gather per probe. mode 'sorted':
    binary search over sorted keys (log B gathers) — the fallback for huge
    key ranges. Non-unique build keys (dup > 1, "expansion joins"): the
    payloads are laid out key-sorted and the lookup yields (first row,
    count); the probe pipeline unrolls dup match lanes (d < count masks)
    so each probe row can emit up to dup joined rows into the agg."""

    def __init__(self, mode, keys, payloads, kinds, scales, dicts, n_rows, device=False,
                 dup=1, cnt=None, pay_valids=None):
        self.mode = mode  # direct | sorted
        self.keys = keys  # direct: int32 [T] row/lo table; sorted: int64 [B] keys
        self.payloads = payloads  # per column, padded (unique direct: original order)
        self.kinds = kinds
        self.scales = scales
        self.dicts = dicts
        self.n_rows = n_rows
        self.device = device
        self.dup = dup  # max duplicates per key (1 = unique fast paths)
        self.cnt = cnt  # direct expansion mode: int32 [T] per-key match count
        self.shifts: list[int] = []  # multi-key combine shifts (per extra key)
        # per payload column: bool [B] validity plane or None; padding slots
        # are invalid, so an outer join's unmatched gathers decode as NULL
        self.pay_valids = pay_valids if pay_valids is not None else [None] * len(payloads)
        # build-schema field index → position in payloads (None = column was
        # not encodable and not uploaded; only legal for semi/anti filters)
        self.pay_pos: list = list(range(len(payloads)))

    def flat_arrays(self):
        """Device-arg layout: keys [, cnt] , payloads..., payload validity
        planes... (offset contract shared with the lowering closures)."""
        out = [self.keys]
        if self.cnt is not None:
            out.append(self.cnt)
        return out + list(self.payloads) + [v for v in self.pay_valids if v is not None]

    def pay_valid_flat_idx(self) -> list:
        """Per payload: index of its validity plane within flat_arrays()
        (relative to this build's block), or None."""
        out = []
        nxt = (2 if self.cnt is not None else 1) + len(self.payloads)
        for v in self.pay_valids:
            if v is None:
                out.append(None)
            else:
                out.append(nxt)
                nxt += 1
        return out

    def shape_key(self):
        return (
            self.mode, len(self.keys), tuple(self.shifts), self.dup,
            self.cnt is not None, self.padded_rows(),
            tuple(str(p.dtype) for p in self.payloads),
            tuple(v is not None for v in self.pay_valids),
            tuple(self.pay_pos),
            tuple(_pow2(len(d)) if d else 0 for d in self.dicts),
        )

    def padded_rows(self) -> int:
        """Padded payload length B — a compiled fn clips expansion-lane
        indices against it, so it must be part of the compile-cache key."""
        return self.payloads[0].shape[0] if self.payloads else _pow2(max(self.n_rows, 1))


class DeviceTable:
    """All partitions of one scan, device-resident as [P, N] stacks."""

    def __init__(self, kinds, scales, dicts, cols, mask, part_rows, nbytes,
                 valids=None):
        self.kinds = kinds  # per column
        self.scales = scales
        self.dicts = dicts  # unified (global) dictionaries
        self.cols = cols  # list of jnp [P, N]
        self.mask = mask  # jnp bool [P, N]
        self.part_rows = part_rows
        self.nbytes = nbytes
        # per column: jnp bool [P, N] validity plane, or None (no nulls);
        # value slots under an invalid plane hold type-default fills
        self.valids = valids if valids is not None else [None] * len(cols)

    @property
    def shape(self):
        return self.mask.shape

    def flat_cols(self):
        """Device-arg layout: data columns, then the validity planes of the
        nullable columns (offset contract shared with _mk_col_reader)."""
        return list(self.cols) + [v for v in self.valids if v is not None]

    def valid_flat_idx(self) -> list:
        """Per column: index of its validity plane in flat_cols(), or None."""
        out = []
        nxt = len(self.cols)
        for v in self.valids:
            if v is None:
                out.append(None)
            else:
                out.append(nxt)
                nxt += 1
        return out


class DeviceTableCache:
    def __init__(self):
        import collections

        self._cache: "collections.OrderedDict[tuple, DeviceTable]" = collections.OrderedDict()
        self._lock = threading.Lock()
        self._inflight: dict[tuple, threading.Event] = {}

    def table_key(self, scan, ctx, mesh=None) -> tuple:
        # device_ordinal in the key: an in-process cluster of differently
        # pinned executors must not share tables committed to one chip
        return (self.key_of(scan) + ((mesh.devices.size,) if mesh is not None else ())
                + (ctx.device_ordinal,))

    def get(self, scan, buckets: list[int], ctx, max_bytes: int,
            mesh=None, *, fill_threads: int = 0, chunk_rows: int = 0,
            stats: dict | None = None, on_spec=None,
            spill_pool=None) -> DeviceTable:
        key = self.table_key(scan, ctx, mesh)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                return hit
            ev = self._inflight.get(key)
            owner = ev is None
            if owner:
                ev = threading.Event()
                self._inflight[key] = ev
        if not owner:
            ev.wait()
            with self._lock:
                hit = self._cache.get(key)
            if hit is None:
                raise Unsupported("peer encode failed")
            return hit
        try:
            t0 = time.time()
            # spilled-entry fast path: a previously demoted table re-uploads
            # from its host (or disk) copy instead of re-running the whole
            # read+encode fill — the transparent-on-touch half of the spill
            # contract. on_spec still fires so compile/fill overlap holds.
            restored = spill_pool.pop(key) if spill_pool is not None else None
            if restored is not None:
                dt = _restore_device_table(restored, mesh)
                if on_spec is not None:
                    on_spec(dt)
            else:
                dt = self._load(scan, buckets, ctx, mesh, fill_threads=fill_threads,
                                chunk_rows=chunk_rows, stats=stats, on_spec=on_spec)
            RUN_STATS.set("fill_s", round(time.time() - t0, 3), rec=stats)
            RUN_STATS.set("device_bytes", dt.nbytes, rec=stats)
            if getattr(scan, "mem_token", None) is not None:
                # memory-backed fill = ingested delta rows riding a grafted
                # scan (serving/incremental.py) — surfaced so operators can
                # watch delta volume reach the device tier
                RUN_STATS.set("delta_fill_rows",
                              int(sum(int(r) for r in dt.part_rows)), rec=stats)
            with self._lock:
                total = sum(v.nbytes for v in self._cache.values())
                while self._cache and total + dt.nbytes > max_bytes:
                    old_key, old = self._cache.popitem(last=False)
                    total -= old.nbytes
                    if spill_pool is not None:
                        _spill_device_table(spill_pool, old_key, old)
                self._cache[key] = dt
            return dt
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    def resident_bytes(self, exclude_key: tuple | None = None) -> int:
        """Device bytes held by cached tables other than `exclude_key` —
        the admission planner's `resident_other` (cold residency that
        spill_colds can reclaim)."""
        with self._lock:
            return sum(v.nbytes for k, v in self._cache.items() if k != exclude_key)

    def ensure_headroom(self, max_bytes: int, keep_key: tuple | None,
                        spill_pool=None) -> int:
        """Demote cold entries (all but `keep_key`) until residency fits
        `max_bytes`. Returns bytes freed. The spill_colds admission rung."""
        freed = 0
        victims = []
        with self._lock:
            total = sum(v.nbytes for v in self._cache.values())
            for k in list(self._cache):
                if total <= max_bytes:
                    break
                if k == keep_key:
                    continue
                old = self._cache.pop(k)
                total -= old.nbytes
                freed += old.nbytes
                victims.append((k, old))
        for k, old in victims:
            if spill_pool is not None:
                _spill_device_table(spill_pool, k, old)
        return freed

    def spill_all(self, spill_pool=None) -> None:
        """Demote EVERY resident table — the runtime RESOURCE_EXHAUSTED
        rung frees the whole device before the one retry."""
        with self._lock:
            items = list(self._cache.items())
            self._cache.clear()
        for k, old in items:
            if spill_pool is not None:
                _spill_device_table(spill_pool, k, old)

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()

    def key_of(self, scan) -> tuple:
        if isinstance(scan, ParquetScanExec):
            files = tuple(
                tuple((f["file"], tuple(f.get("row_groups") or ())) for f in p.get("files", []))
                for p in scan.partitions
            )
            return (files, tuple(scan.projection))
        token = getattr(scan, "mem_token", None)
        if token is not None:
            return ("mem", token)  # monotonic: never aliases like id() does
        return ("obj", id(scan), id(type(scan)))

    def _load(self, scan, buckets: list[int], ctx, mesh=None, *,
              fill_threads: int = 0, chunk_rows: int = 0,
              stats: dict | None = None, on_spec=None) -> DeviceTable:
        """Read, encode and upload the whole scan as [P, N] stacks.

        Pipelined cold path: columns encode on a small host pool while the
        caller thread streams each finished stack to the device in column
        order, so encode of column k+1 overlaps the upload of column k.
        In-flight encoded stacks are bounded (lazy submission window) and
        every host intermediate — the partition tables, the concatenated
        arrow table, each column's flat encoding and its [P, N] stack — is
        released the moment it has been consumed, instead of all living
        until the end of the fill (~3× table bytes previously).

        `fill_threads` 0 = auto, 1 = strict serial (encode→upload one column
        at a time, the legacy order). `on_spec(spec_table)` fires on the
        encode worker that completes the LAST column: `spec_table` is a
        DeviceTable of ShapeDtypeStructs carrying everything the compile
        key needs (kinds, dtypes, dict sizes, P, N) while uploads are still
        streaming — the compile/fill overlap hook."""
        import concurrent.futures as fut

        jax = ensure_jax()
        if isinstance(scan, ParquetScanExec):
            raw = ParquetScanExec(scan.df_schema, scan.partitions, scan.projection, [], scan.table_name)
        else:
            raw = scan
        P = raw.output_partition_count()

        def read(p):
            return _concat([b for b in raw.execute(p, ctx) if b.num_rows], raw.schema())

        with fut.ThreadPoolExecutor(max_workers=min(P, 8)) as pool:
            tables = list(pool.map(read, range(P)))
        part_rows = [t.num_rows for t in tables]
        full = pa.concat_tables(tables)
        del tables  # concat is zero-copy; the chunks live on via `full`
        N = next_bucket(max(max(part_rows), 1), buckets)

        # multi-chip: shard the partition axis across the mesh — pad P to a
        # multiple of the device count with empty (all-masked) partitions
        if mesh is not None:
            nd = mesh.devices.size
            while len(part_rows) % nd:
                part_rows.append(0)
        P = len(part_rows)

        names = list(full.column_names)
        n_cols = len(names)
        # split the table into per-column references so each column's arrow
        # buffers can be dropped individually once encoded
        col_refs: list = [full.column(name) for name in names]
        del full

        if mesh is not None:
            from jax.sharding import PartitionSpec

            spec = PartitionSpec("part", None)
        else:
            spec = None

        threads = int(fill_threads)
        if threads <= 0:
            threads = min(8, max(2, (os.cpu_count() or 4) // 2), max(n_cols, 1))
        pipelined = threads > 1 and n_cols > 1

        kinds: list = [None] * n_cols
        scales: list = [0] * n_cols
        dicts: list = [None] * n_cols
        dtypes: list = [None] * n_cols
        has_valid = [False] * n_cols
        cols: list = [None] * n_cols
        valids: list = [None] * n_cols
        nbytes = 0
        meta_lock = threading.Lock()
        left = [n_cols]
        t_enc0 = time.time()

        def spec_table() -> DeviceTable:
            sds = jax.ShapeDtypeStruct
            scols = [sds((P, N), dtypes[i]) for i in range(n_cols)]
            svalids = [sds((P, N), np.bool_) if has_valid[i] else None
                       for i in range(n_cols)]
            return DeviceTable(list(kinds), list(scales), list(dicts), scols,
                               sds((P, N), np.bool_), list(part_rows), 0, svalids)

        def encode_one(i: int):
            dc = encode_stacked(col_refs[i], part_rows, N)
            col_refs[i] = None  # release the arrow buffers
            if dc is None:
                raise Unsupported(f"unencodable column {names[i]}")
            with meta_lock:
                kinds[i] = dc.kind
                scales[i] = dc.scale
                dicts[i] = dc.dictionary
                dtypes[i] = dc.data.dtype
                has_valid[i] = dc.valid is not None
                left[0] -= 1
                done = left[0] == 0
            if done:
                # the compile key (shapes, dtypes, kinds, dict sizes) is now
                # fully determined even though uploads are still streaming
                RUN_STATS.set("encode_s", round(time.time() - t_enc0, 3), rec=stats)
                if on_spec is not None:
                    on_spec(spec_table())
            return dc

        t_up = 0.0

        def upload(i: int, dc) -> None:
            nonlocal nbytes, t_up
            t0u = time.time()
            cols[i] = _put_chunked(mesh, dc.data, spec, chunk_rows)
            nbytes += dc.data.nbytes
            if dc.valid is not None:
                valids[i] = _put_chunked(mesh, dc.valid, spec, chunk_rows)
                nbytes += dc.valid.nbytes
            t_up += time.time() - t0u

        if pipelined:
            # lazy submission window: at most (threads + 2) encoded stacks
            # alive at once — double-buffering generalized, and the host-RSS
            # bound that replaces "hold every stack until the upload loop"
            window = threads + 2
            with fut.ThreadPoolExecutor(
                max_workers=threads, thread_name_prefix="tpu-fill"
            ) as pool:
                pending: dict[int, fut.Future] = {}
                nxt = 0
                for i in range(n_cols):
                    while nxt < n_cols and nxt < i + window:
                        pending[nxt] = pool.submit(encode_one, nxt)
                        nxt += 1
                    try:
                        dc = pending.pop(i).result()
                    except BaseException:
                        for f in pending.values():
                            f.cancel()
                        raise
                    upload(i, dc)
                    del dc  # host stack freed; the device copy is in flight
        else:
            for i in range(n_cols):
                upload(i, encode_one(i))

        mask_np = np.zeros((P, N), dtype=bool)
        for p, r in enumerate(part_rows):
            mask_np[p, :r] = True
        mask = _put(mesh, mask_np, spec)
        nbytes += mask_np.nbytes

        # drain the async transfers before publishing: fill_s must mean
        # "table resident", not "last copy enqueued"
        t0u = time.time()
        jax.block_until_ready([c for c in cols if c is not None]
                              + [v for v in valids if v is not None] + [mask])
        t_up += time.time() - t0u
        RUN_STATS.set("upload_s", round(t_up, 3), rec=stats)
        return DeviceTable(kinds, scales, dicts, cols, mask, part_rows, nbytes, valids)


def _record_spill_stats(rec: dict, spill_pool) -> None:
    """Mirror the host spill pool's cumulative counters into the run record
    (the RUN_STATS → heartbeat → /api/executors gauge path)."""
    if spill_pool is None:
        return
    st = spill_pool.stats()
    RUN_STATS.set("hbm_spill_bytes", st["spill_bytes"], rec=rec)
    RUN_STATS.set("hbm_spill_events", st["spill_events"], rec=rec)
    RUN_STATS.set("hbm_reupload_events", st["reupload_events"], rec=rec)
    RUN_STATS.set("hbm_oom_retries", hbm.oom_retry_count(), rec=rec)


def _spill_device_table(pool, key: tuple, dt: DeviceTable) -> None:
    """Demote one cached DeviceTable to the host spill pool: fetch every
    device plane back to numpy and hand the flat list (cols, mask, valids —
    None slots preserved) plus the encode metadata to the pool. The pool
    owns tiering (host buffers vs tmp+rename disk files)."""
    jax = ensure_jax()
    flat = ([np.asarray(jax.device_get(c)) for c in dt.cols]
            + [np.asarray(jax.device_get(dt.mask))]
            + [None if v is None else np.asarray(jax.device_get(v))
               for v in dt.valids])
    meta = (list(dt.kinds), list(dt.scales), list(dt.dicts),
            list(dt.part_rows), int(dt.nbytes))
    pool.put(key, meta, flat, int(dt.nbytes))


def _restore_device_table(restored, mesh) -> DeviceTable:
    """Re-upload a spilled table: the inverse of _spill_device_table, using
    the same placement chokepoint (_put) as the cold fill."""
    meta, flat = restored
    kinds, scales, dicts, part_rows, nbytes = meta
    n = len(kinds)
    if mesh is not None:
        from jax.sharding import PartitionSpec

        spec = PartitionSpec("part", None)
    else:
        spec = None
    cols = [_put(mesh, a, spec) for a in flat[:n]]
    mask = _put(mesh, flat[n], spec)
    valids = [None if a is None else _put(mesh, a, spec) for a in flat[n + 1:]]
    return DeviceTable(kinds, scales, dicts, cols, mask, part_rows, nbytes, valids)


DEVICE_CACHE = DeviceTableCache()


def clear_device_caches() -> None:
    """Release every module-level device cache: resident tables, compiled
    entries, string LUTs, and join build tables. Frees HBM (or host RAM
    under CPU-jax) between unrelated workloads; caches refill on demand.

    When this process is attached to a device daemon, the clear is also
    forwarded there: the state an attached executor actually uses is
    daemon-resident, so a purely local clear would free nothing but this
    process's cold twins while the daemon keeps serving from its caches.
    The forwarding is best-effort (a dead daemon has nothing resident)
    and a no-op inside the daemon itself."""
    DEVICE_CACHE.clear()
    _COMPILE_CACHE.clear()
    _LUT_CACHE.clear()
    _BUILD_CACHE.clear()
    hbm.SPILL_POOL.clear()
    from ballista_tpu.ops.tpu import final_stage

    final_stage.clear_compile_cache()
    from ballista_tpu.device_daemon import client as daemon_client

    daemon_client.clear_attached_caches()


class TpuStageExec(ExecutionPlan):
    def __init__(self, partial_agg: HashAggregateExec, ops: list, scan: ExecutionPlan,
                 config: BallistaConfig):
        super().__init__(partial_agg.df_schema)
        self.partial_agg = partial_agg
        self.ops = ops  # dataflow-ordered FilterExec/ProjectionExec nodes
        self.scan = scan
        self.config = config
        self.min_rows = int(config.get(TPU_MIN_ROWS))
        self.buckets = config.shape_buckets()
        self.fallback_count = 0
        self.tpu_count = 0
        # device-side shuffle routing: (output-schema key indices, K) set by
        # the engine when the parent shuffle writer hash-partitions on group
        # columns; the sorted path then emits a __pid column
        self.emit_pid: tuple[list[int], int] | None = None
        self.pid_emitted = 0
        self._results: dict[int, list[pa.RecordBatch]] | None = None
        self._results_lock = threading.Lock()
        # partitions served since the last (re-)dispatch: once every resident
        # result has been read at least once, the decoded host batches are
        # evicted instead of staying pinned for the stage's lifetime (a later
        # re-read just costs one more hot re-dispatch)
        self._served_since_dispatch: set[int] = set()
        self._device_ok = False
        # structural fingerprint: identical stages across queries share XLA
        # compilations (plan objects are rebuilt per query, ids are not).
        # Join ops must contribute their FULL build subtree: node_str()
        # alone prints only keys/type, so two joins against differently
        # FILTERED builds (q39's d_moy=1 vs d_moy=2 date_dim sides) would
        # collide in the build/LUT caches and reuse the wrong build table.
        def op_fp(op) -> str:
            from ballista_tpu.plan.physical import HashJoinExec

            if isinstance(op, HashJoinExec):
                return op.node_str() + "«" + op.left.display() + "»"
            return op.node_str()

        self.fingerprint = "|".join(
            [partial_agg.node_str()]
            + [op_fp(op) for op in ops]
            + [scan.node_str(), repr(scan.df_schema)]
        )

    def children(self) -> list[ExecutionPlan]:
        return [self.scan]

    def with_children(self, c):
        return TpuStageExec(self.partial_agg, self.ops, c[0], self.config)

    def output_partition_count(self) -> int:
        return self.scan.output_partition_count()

    def node_str(self) -> str:
        # live counters surface in EXPLAIN ANALYZE / stage metrics so
        # operators can SEE whether the device path ran or fell back
        extra = ""
        if self.tpu_count or self.fallback_count:
            extra = f" device_runs={self.tpu_count} cpu_fallbacks={self.fallback_count}"
        return f"TpuStageExec: [{self.partial_agg.node_str()}] ops={len(self.ops)}{extra}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(iter(self._run(partition, ctx)))

    # ------------------------------------------------------------------

    def _run(self, partition: int, ctx: TaskContext) -> list[pa.RecordBatch]:
        with self._results_lock:
            if self._results is None:
                try:
                    self._results = self._dispatch_all(ctx)
                    self.tpu_count += 1
                    self._device_ok = True
                except Unsupported as e:
                    log.info("tpu fallback (%s): %s", e, self.partial_agg.node_str())
                    self._results = {}
                except Exception:  # noqa: BLE001
                    # the device path must never fail a query the CPU engine
                    # can run: adaptive per-subtree dispatch, loudly
                    log.warning(
                        "tpu stage raised; falling back to cpu for %s",
                        self.partial_agg.node_str(), exc_info=True,
                    )
                    self._results = {}
            if partition not in self._results and self._device_ok:
                # a consumer re-executed a partition whose device result was
                # already popped (e.g. a parent's device attempt that later
                # fell back): the device table cache and compiled entry are
                # hot, so re-dispatching costs ~the exec time — never fall
                # through to a full host re-scan of the subtree
                try:
                    self._results.update(self._dispatch_all(ctx))
                    self.tpu_count += 1
                    self._served_since_dispatch = set()
                    # serve WITHOUT popping: a consumer that re-reads one
                    # partition tends to re-read them all — one re-dispatch
                    # must cover all K re-reads, not K re-dispatches
                    if partition in self._results:
                        out = list(self._results[partition])
                        self._note_served_locked(partition)
                        return out
                except Exception:  # noqa: BLE001
                    log.warning("tpu stage re-run failed; cpu fallback for %s",
                                self.partial_agg.node_str(), exc_info=True)
                    self._device_ok = False
            if partition in self._results:
                out = self._results.pop(partition)
                self._note_served_locked(partition)
                return out
        return self._fallback(partition, ctx)

    def _note_served_locked(self, partition: int) -> None:
        """Bound re-run retention (call under _results_lock): when every
        still-resident result has been served at least once since the last
        dispatch, drop them all — they only exist for re-read convenience."""
        self._served_since_dispatch.add(partition)
        if self._results and set(self._results) <= self._served_since_dispatch:
            self._results = {}

    def _dispatch_all(self, ctx: TaskContext) -> dict[int, list[pa.RecordBatch]]:
        """Route one whole-stage dispatch: warm device-runtime daemon first
        when the session opted in (docs/device_daemon.md), else the
        in-process engine pinned to the task's bound device."""
        from ballista_tpu.ops.tpu.runtime import device_scope

        out = self._daemon_run_all(ctx)
        if out is not None:
            return out
        # per-chip pinning: commit every upload/dispatch in this call tree
        # to the executor's bound device
        with device_scope(ctx.device_ordinal):
            return self._tpu_run_all(ctx)

    def _daemon_run_all(self, ctx: TaskContext) -> dict[int, list[pa.RecordBatch]] | None:
        """Ship this stage to the device daemon: the RAW rebuilt subtree
        (the same chain _fallback re-executes — this wrapper has no serde
        encoding, that chain round-trips) goes over the socket and the
        daemon runs it through the same maybe_compile_tpu entry, so an
        attached result is byte-identical to an in-process one by
        construction. The whole failure domain — derived execute deadline,
        crash detection, respawn-and-retry, poison quarantine — lives in
        daemon_route.run_via_daemon; None means 'run locally' with the
        reason in RUN_STATS daemon_mode/daemon_mode_reason."""
        from ballista_tpu.ops.tpu import daemon_route

        return daemon_route.run_via_daemon(
            self.config,
            plan_builder=lambda: self.partial_agg.with_children(
                [self._raw_chain()]),
            partitions=list(range(self.scan.output_partition_count())),
            tag=daemon_route.stage_tag("stage", self.fingerprint),
            fingerprint=self.fingerprint,
            emit_pid=self.emit_pid,
            est_bytes=int(getattr(self, "hbm_observed_input_bytes", 0) or 0))

    def _raw_chain(self) -> ExecutionPlan:
        """The original pre-aggregation subtree this wrapper replaced,
        rebuilt from its pieces: what _fallback re-executes on the host and
        what the daemon client serializes over the socket."""
        from ballista_tpu.plan.physical import HashJoinExec

        node: ExecutionPlan = self.scan
        for op in self.ops:
            if isinstance(op, HashJoinExec):
                node = op.with_children([op.left, node])
            else:
                node = op.with_children([node])
        return node

    def _fallback(self, partition: int, ctx: TaskContext) -> list[pa.RecordBatch]:
        """Re-run the original CPU subtree (scan filters applied on host)."""
        from ballista_tpu.plan.physical import CoalescePartitionsExec

        self.fallback_count += 1
        node = self._raw_chain()
        if self.emit_pid is not None:
            # device-routed layout contract: the device path ships EVERY
            # group through map task 0 (__pid routing) and empties the other
            # map outputs. Tasks decide device-vs-CPU independently (a
            # runtime OOM can demote ONE task after its peers served the
            # routed layout), so a classic partition-p partial here would
            # double-count surviving device outputs — or, demoting task 0,
            # silently drop every other partition's groups. Keep the shape:
            # task 0 aggregates the WHOLE input; the shuffle writer's host
            # hash is the device routing's bit-exact twin, so each group
            # still meets its partials in the same reduce partition.
            if partition != 0:
                return [_empty_batch(self.schema())]
            node = CoalescePartitionsExec(node)
        agg = self.partial_agg.with_children([node])
        return [b for b in agg.execute(partition, ctx)]

    # ------------------------------------------------------------------

    def _prepare_build(self, join, jidx: int, ctx: TaskContext, table_key,
                       mesh=None, grace: tuple[int, int] | None = None) -> BuildTable:
        """Collect + encode + sort a join's build side for device probing.

        `grace=(bucket, n_buckets)`: keep only the build rows whose combined
        key falls in the given secondary-hash sub-bucket (the grace-split
        path). Sub-builds carry their bucket in the cache key — a sub-build
        and the whole build must never alias."""
        import numpy as np

        from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
        from ballista_tpu.ops.tpu.columnar import encode_column

        jax = ensure_jax()
        jnp = jax.numpy
        cache_key = (table_key, self.fingerprint, jidx, mesh.devices.size if mesh else 0,
                     ctx.device_ordinal, grace)
        hit = _BUILD_CACHE.get(cache_key)
        if hit is not None:
            return hit

        batches = []
        for p in range(join.left.output_partition_count()):
            batches.extend(b for b in join.left.execute(p, ctx) if b.num_rows)
        tbl = _concat(batches, join.left.schema()).combine_chunks()
        if tbl.num_rows:
            # a build row whose key is NULL can never match any probe row
            # (inner/semi/anti/outer alike): drop it before encoding
            import pyarrow.compute as _pc

            keep = None
            for l_expr, _ in join.on:
                arr = evaluate_to_array(
                    bind_expr(l_expr, join.left.df_schema), tbl.to_batches()[0]
                )
                if arr.null_count:
                    va = arr.is_valid()
                    keep = va if keep is None else _pc.and_(keep, va)
            if keep is not None:
                tbl = tbl.filter(keep).combine_chunks()
        if tbl.num_rows == 0:
            raise Unsupported("empty build side (let CPU/AQE handle it)")
        batch = tbl.to_batches()[0]

        # combined int64 key, verified unique + range-guarded; each extra
        # key gets the smallest shift covering its build-side range (keeps
        # combined keys dense enough for direct addressing)
        key_np = None
        shifts: list[int] = []
        for l_expr, _ in join.on:
            arr = evaluate_to_array(bind_expr(l_expr, join.left.df_schema), batch)
            if arr.null_count:
                raise Unsupported("NULL build keys survived the pre-filter")
            import pyarrow as _pa

            t = arr.type
            if _pa.types.is_date(t):
                vals = arr.cast(_pa.int32()).cast(_pa.int64()).to_numpy(zero_copy_only=False)
            elif _pa.types.is_integer(t):
                vals = arr.cast(_pa.int64(), safe=False).to_numpy(zero_copy_only=False)
            else:
                raise Unsupported(f"non-integer join key {t}")
            vals = vals.astype(np.int64)
            if key_np is None:
                key_np = vals
            else:
                if (vals < 0).any():
                    raise Unsupported("negative secondary join key")
                shift = max(1, int(vals.max()).bit_length())
                if (key_np < 0).any() or (int(key_np.max()) >> (62 - shift)) > 0:
                    raise Unsupported("primary join key out of combine range")
                key_np = (key_np << shift) | vals
                shifts.append(shift)
        if grace is not None:
            bucket, n_buckets = grace
            sel = hbm.grace_bucket_of(key_np, n_buckets) == bucket
            if not sel.any():
                raise Unsupported(
                    f"empty grace sub-bucket {bucket}/{n_buckets}")
            key_np = key_np[sel]
            tbl = tbl.filter(pa.array(sel)).combine_chunks()
            batch = tbl.to_batches()[0]
        uniq, counts = np.unique(key_np, return_counts=True)
        dup = int(counts.max())
        membership_only = join.join_type in ("right_semi", "right_anti") and join.filter is None
        cba = _mult_shape_check(self.partial_agg, self.ops, join)
        # mirror _compile's activation exactly (counted build columns must be
        # non-null): a looser exemption here would pay the full build
        # collect/encode/upload only to fall back at compile time anyway
        mult_shaped = cba is not None and all(
            tbl.column(fi).null_count == 0 for fi in cba.values()
        )
        if dup > MAX_JOIN_DUP and not membership_only and not mult_shaped:
            # filterless semi/anti probes only test membership, and
            # aggregate-through-join stages consume match COUNTS — neither
            # unrolls lanes, so any dup is fine there; inner/outer gathers
            # and semi/anti FILTERS unroll dup lanes and are budgeted
            raise Unsupported(f"build key multiplicity {dup} > {MAX_JOIN_DUP}")

        max_key = int(key_np.max())
        min_key = int(key_np.min())
        direct = min_key >= 0 and max_key + 1 <= DIRECT_TABLE_MAX
        cnt_dev = None
        if dup == 1 and direct:
            T = _pow2(max_key + 1)
            table = np.full(T, -1, dtype=np.int32)
            table[key_np] = np.arange(len(key_np), dtype=np.int32)
            keys_dev = table
            order = np.arange(len(key_np))
            B = _pow2(len(key_np))
            mode = "direct"
        elif direct:
            # expansion layout: payloads key-sorted; lo/cnt tables give each
            # probe its first matching row and its match count
            order = np.argsort(key_np, kind="stable")
            sorted_keys = key_np[order]
            B = _pow2(len(sorted_keys))
            T = _pow2(max_key + 1)
            lo_table = np.zeros(T, dtype=np.int32)
            cnt_table = np.zeros(T, dtype=np.int32)
            firsts = np.searchsorted(sorted_keys, uniq)
            lo_table[uniq] = firsts.astype(np.int32)
            cnt_table[uniq] = counts.astype(np.int32)
            keys_dev = lo_table
            cnt_dev = cnt_table
            mode = "direct"
        else:
            order = np.argsort(key_np, kind="stable")
            sorted_keys = key_np[order]
            B = _pow2(len(sorted_keys))
            keys_dev = np.full(B, np.iinfo(np.int64).max, dtype=np.int64)
            keys_dev[: len(sorted_keys)] = sorted_keys
            mode = "sorted"

        kinds, scales, dicts, payloads, pay_valids, pay_pos = [], [], [], [], [], []
        if membership_only:
            # membership-only joins never gather build columns: skip payload
            # encode/upload entirely (an unencodable non-key column must not
            # knock a semi join off the device)
            pass
        else:
            # semi/anti WITH a join filter only gather the columns the
            # filter touches: tolerate unencodable columns with a None
            # payload slot (lowering raises only if the filter uses one)
            tolerate = join.join_type in ("right_semi", "right_anti")
            for name in batch.schema.names:
                dc = encode_column(batch.column(batch.schema.get_field_index(name)))
                if dc is None:
                    if not tolerate:
                        raise Unsupported(f"unencodable build column {name}")
                    kinds.append("?")
                    scales.append(0)
                    dicts.append(None)
                    pay_pos.append(None)
                    continue
                kinds.append(dc.kind)
                scales.append(dc.scale)
                dicts.append(dc.dictionary)
                padded = np.zeros(B, dtype=dc.data.dtype)
                padded[: len(order)] = dc.data[order]
                pay_pos.append(len(payloads))
                payloads.append(padded)
                if dc.valid is None:
                    pay_valids.append(None)
                else:
                    pv = np.zeros(B, dtype=bool)  # padding slots stay invalid
                    pv[: len(order)] = dc.valid[order]
                    pay_valids.append(pv)

        bt = BuildTable(
            mode, _put(mesh, keys_dev), [_put(mesh, p) for p in payloads],
            kinds, scales, dicts, len(order), device=True, dup=dup,
            cnt=None if cnt_dev is None else _put(mesh, cnt_dev),
            pay_valids=[None if v is None else _put(mesh, v) for v in pay_valids],
        )
        bt.pay_pos = pay_pos
        bt.shifts = shifts
        _BUILD_CACHE[cache_key] = bt
        return bt

    def _tpu_run_all(self, ctx: TaskContext) -> dict[int, list[pa.RecordBatch]]:
        tag = f"stage_{zlib.crc32(self.fingerprint.encode()):08x}"
        with RUN_STATS.run(tag) as rec:
            try:
                return self._tpu_run_all_inner(ctx, rec)
            except Unsupported:
                raise
            except Exception as e:  # noqa: BLE001 — classified below
                if not hbm.is_resource_exhausted(e):
                    raise
                # runtime OOM rung: the estimate said fit and the device
                # disagreed. Free everything (spilling residents to host so
                # their fills aren't lost), hint the planner to pre-plan
                # grace for this fingerprint, and retry ONCE; a second OOM
                # demotes to the CPU engine via the Unsupported ladder.
                log.warning("device RESOURCE_EXHAUSTED; spilling + retrying "
                            "stage once: %s", e)
                spill_pool = (hbm.SPILL_POOL
                              if bool(self.config.get(TPU_HBM_SPILL_ENABLED))
                              else None)
                DEVICE_CACHE.spill_all(spill_pool)
                _LUT_CACHE.clear()
                _BUILD_CACHE.clear()
                hbm.note_oom(self.fingerprint)
                rec["hbm_oom_retries"] = hbm.oom_retry_count()
                try:
                    return self._tpu_run_all_inner(ctx, rec)
                except Exception as e2:  # noqa: BLE001
                    if hbm.is_resource_exhausted(e2):
                        raise Unsupported(
                            f"device OOM persisted after spill+retry: {e2}"
                        ) from e2
                    raise

    def _compile_key(self, dt: DeviceTable, builds: list[BuildTable],
                     mode_req: str = "fused_xla") -> tuple:
        """The compile-cache key. Derivable from a spec DeviceTable (the
        encode metadata alone), which is what makes compile/fill overlap
        possible: tracing starts before the uploads finish."""
        P, N = dt.shape
        emit_key = (tuple(self.emit_pid[0]), self.emit_pid[1]) if self.emit_pid else None
        return (
            self.fingerprint, P, N, tuple(zip(dt.kinds, dt.scales)),
            tuple(str(c.dtype) for c in dt.cols),
            tuple(v is not None for v in dt.valids),
            tuple(_pow2(len(d)) if d else 0 for d in dt.dicts),
            tuple(b.shape_key() for b in builds), emit_key, mode_req,
        )

    def _fusion_decision(self, dt: DeviceTable, builds: list[BuildTable]):
        """Run the fusion cost model over compile-time stage facts. Pure
        host logic over encode metadata, so the overlap worker and the main
        thread compute the SAME decision from a spec table and the real
        table respectively (same kinds/dicts/part_rows/builds/config)."""
        from ballista_tpu.ops.tpu import fusion

        est = fusion.estimate_stage(self.scan, self.ops, self.partial_agg, dt, builds)
        cm = fusion.CostModel.from_config(self.config)
        try:
            cm.platform = ensure_jax().devices()[0].platform
        except Exception:  # noqa: BLE001
            cm.platform = "cpu"
        dec = cm.choose(est)
        if dec.mode == "fused_pallas" and _stage_mesh(self.config) is not None:
            # pallas kernels are single-device (no shard_map wrapping yet)
            dec = fusion.FusionDecision(
                "fused_xla", dec.reason + "; clamped: collective-exchange mesh")
        return dec, est

    def _compile_with_fallback(self, dt: DeviceTable, builds: list[BuildTable],
                               rec: dict | None, mode_req: str):
        """The fallback ladder's top rung: a fused_pallas request whose
        stage turns out kernel-ineligible at trace time (f64-only sums over
        money columns, validity planes, G past the lane budget) raises
        Unsupported — retry once as fused_xla instead of knocking the whole
        stage off the device."""
        try:
            return self._compile_locked(dt, builds, rec, mode_req)
        except Unsupported:
            if mode_req != "fused_pallas":
                raise
            log.info("fused_pallas ineligible at trace time; retrying fused_xla")
            return self._compile_locked(dt, builds, rec, "fused_xla")

    def _compile_locked(self, dt: DeviceTable, builds: list[BuildTable],
                        rec: dict | None, mode_req: str = "fused_xla"):
        """Look up or create the compiled entry. `dt` may be a spec table
        (ShapeDtypeStruct columns): _compile only consults shapes, dtypes,
        kinds and dictionaries. Returns (entry, fresh, lowered) — `lowered`
        (the jax Lowered, pre-backend-compile) only for fresh entries."""
        key = self._compile_key(dt, builds, mode_req)
        P, N = dt.shape
        kinds = list(zip(dt.kinds, dt.scales))
        with _COMPILE_LOCK:
            cached = _COMPILE_CACHE.get(key)
            if cached is not None:
                return cached, False, None
            t0 = time.time()
            fn, lowering, meta, lowered = self._compile(
                dt, kinds, dt.dicts, P, N, builds, mode_req=mode_req)
            RUN_STATS.set("trace_s", round(time.time() - t0, 3), rec=rec)
            # the dispatched flag lives with the entry: the FIRST call of a
            # jitted fn runs the backend compile, so the first dispatcher
            # attributes that wall time to xla_compile_s, not exec_s
            cached = (fn, lowering, meta, {"dispatched": False})
            _COMPILE_CACHE[key] = cached
            return cached, True, lowered

    def _tpu_run_all_inner(self, ctx: TaskContext,
                           rec: dict) -> dict[int, list[pa.RecordBatch]]:
        """One dispatch + one fetch for every partition of this stage."""
        from ballista_tpu.plan.physical import HashJoinExec
        from ballista_tpu.ops.tpu import runtime
        from ballista_tpu.ops.tpu.runtime import device_scope

        jax = ensure_jax()

        max_bytes = int(self.config.get(TPU_MAX_DEVICE_BYTES))
        budget = hbm.resolve_hbm_budget(self.config)
        if budget > 0:
            # the cache cap never exceeds the admission budget: a chaos- or
            # knob-shrunk budget drives real evictions (and thus spills)
            max_bytes = min(max_bytes, budget)
        spill_pool = None
        if bool(self.config.get(TPU_HBM_SPILL_ENABLED)):
            import tempfile

            from ballista_tpu.executor import disk as _disk

            spill_pool = hbm.SPILL_POOL
            sdir = str(self.config.get(TPU_HBM_SPILL_DIR) or "")
            cfg = self.config
            spill_pool.configure(
                int(self.config.get(TPU_HBM_SPILL_HOST_BYTES)), sdir,
                # low-watermark shed: under disk pressure demotions stay in
                # the host tier (docs/lifecycle.md#watermark-ladder)
                spill_gate=lambda: _disk.spill_allowed(
                    cfg, sdir or tempfile.gettempdir()))
        mesh = _stage_mesh(self.config)
        cc_dir = str(self.config.get(TPU_COMPILE_CACHE_DIR) or "")
        if cc_dir:
            runtime.init_compile_cache(cc_dir)
        cc0 = runtime.compile_cache_stats()
        overlap = bool(self.config.get(TPU_COMPILE_OVERLAP))
        fill_threads = int(self.config.get(TPU_FILL_THREADS))
        chunk_rows = int(self.config.get(TPU_FILL_CHUNK_ROWS))

        table_key = DEVICE_CACHE.key_of(self.scan)
        join_ops = [o for o in self.ops if isinstance(o, HashJoinExec)]
        cached = None
        holder: dict = {}

        if overlap:
            # Cold-path pipeline: build sides collect/encode concurrently
            # with the probe fill (independent subtrees), and the compile
            # worker starts tracing the moment the fill's encode phase
            # determines the compile key — all before the uploads drain.
            import concurrent.futures as cf

            spec_ev = threading.Event()

            def on_spec(sdt: DeviceTable) -> None:
                holder.setdefault("spec", sdt)
                spec_ev.set()

            pool = cf.ThreadPoolExecutor(max_workers=1 + len(join_ops),
                                         thread_name_prefix="tpu-cold")
            try:
                def prep(op, jidx):
                    # jax.default_device is thread-local config state: every
                    # helper thread re-enters the executor's chip pin
                    with device_scope(ctx.device_ordinal):
                        return self._prepare_build(op, jidx, ctx, table_key, mesh)

                build_futs = [pool.submit(prep, op, jidx)
                              for jidx, op in enumerate(join_ops)]

                def compile_ahead():
                    if not spec_ev.wait(timeout=900):
                        return None
                    sdt = holder.get("spec")
                    if sdt is None:
                        return None  # fill failed; main thread raises
                    bts = [f.result() for f in build_futs]
                    t0 = time.time()
                    with device_scope(ctx.device_ordinal):
                        dec, _ = self._fusion_decision(sdt, bts)
                        entry, fresh, lowered = self._compile_with_fallback(
                            sdt, bts, rec, dec.mode)
                        if fresh and lowered is not None and mesh is None \
                                and runtime.compile_cache_dir():
                            # AOT-compile here: backend_compile writes the
                            # binary into the persistent cache, so the main
                            # thread's dispatch-time compile becomes a disk
                            # fetch — the seconds-long XLA phase overlaps
                            # the fill instead of serializing after it
                            t1 = time.time()
                            try:
                                lowered.compile()
                                holder["xla_s"] = time.time() - t1
                            except Exception:  # noqa: BLE001 — warm-up only
                                log.debug("background XLA precompile failed",
                                          exc_info=True)
                    holder["compile_t0"] = t0
                    holder["compile_t1"] = time.time()
                    return entry

                compile_fut = pool.submit(compile_ahead)
                dt = DEVICE_CACHE.get(
                    self.scan, self.buckets, ctx, max_bytes, mesh,
                    fill_threads=fill_threads, chunk_rows=chunk_rows,
                    stats=rec, on_spec=on_spec, spill_pool=spill_pool)
                fill_end = time.time()
                if not spec_ev.is_set():
                    # device-cache hit: the fill never ran, so the spec never
                    # fired — the resident table IS the spec
                    on_spec(dt)
                if sum(dt.part_rows) < self.min_rows:
                    raise Unsupported(f"only {sum(dt.part_rows)} rows (< tpu min)")
                builds = [f.result() for f in build_futs]
                cached = compile_fut.result()
                c0, c1 = holder.get("compile_t0"), holder.get("compile_t1")
                if cached is not None and c0 is not None:
                    ov = max(0.0, min(c1, fill_end) - c0)
                    if ov > 0:
                        rec["compile_overlap_s"] = round(ov, 6)
            finally:
                spec_ev.set()  # never strand the compile worker
                pool.shutdown(wait=False)
        else:
            dt = DEVICE_CACHE.get(self.scan, self.buckets, ctx, max_bytes, mesh,
                                  fill_threads=fill_threads,
                                  chunk_rows=chunk_rows, stats=rec,
                                  spill_pool=spill_pool)
            if sum(dt.part_rows) < self.min_rows:
                raise Unsupported(f"only {sum(dt.part_rows)} rows (< tpu min)")
            builds = [self._prepare_build(op, jidx, ctx, table_key, mesh)
                      for jidx, op in enumerate(join_ops)]

        dec, est = self._fusion_decision(dt, builds)
        rec["fusion_reason"] = dec.reason

        # ---- HBM admission: every stage states its memory plan before the
        # dispatch, in the demotion-ladder style of fusion_reason. Splitting
        # is only sound for an INNER join's build: a probe row's whole match
        # set shares its key's sub-bucket, so wrong-bucket runs mask it like
        # any unmatched probe; outer/anti would re-emit it per bucket.
        grace_fanout = int(self.config.get(TPU_HBM_GRACE_BUCKETS))
        grace_depth_cap = int(self.config.get(TPU_HBM_GRACE_DEPTH))
        grace_eligible = (
            not est.has_mult
            and 0 <= est.max_build_jidx < len(join_ops)
            and join_ops[est.max_build_jidx].join_type == "inner"
        )
        my_key = DEVICE_CACHE.table_key(self.scan, ctx, mesh)
        plan = hbm.plan_stage(
            est, budget,
            grace_eligible=grace_eligible,
            grace_fanout=grace_fanout,
            grace_max_depth=grace_depth_cap,
            resident_other=DEVICE_CACHE.resident_bytes(exclude_key=my_key),
            observed_bytes=int(getattr(self, "hbm_observed_input_bytes", 0) or 0),
            force_grace=hbm.consume_oom_hint(self.fingerprint),
        )
        rec["hbm_budget_bytes"] = budget
        rec["hbm_plan"] = plan.decision
        rec["hbm_plan_reason"] = plan.reason
        mp = getattr(ctx, "memory_pool", None)
        if mp is not None and hasattr(mp, "sync_device_reserved"):
            # device vs host split-accounting: the session pool's device
            # ledger mirrors the cache residency; host `pressure()` (the
            # CPU sort-spill budget) never sees HBM bytes
            mp.set_device_capacity(budget)
            mp.sync_device_reserved(DEVICE_CACHE.resident_bytes())
        if plan.decision == hbm.CPU_DEMOTE:
            _record_spill_stats(rec, spill_pool)
            raise Unsupported(f"hbm plan: {plan.reason}")
        if plan.decision == hbm.SPILL_COLDS:
            DEVICE_CACHE.ensure_headroom(
                max(budget - plan.working_set, 0), my_key, spill_pool)
        if plan.decision == hbm.GRACE_SPLIT:
            try:
                return self._grace_run(ctx, rec, dt, join_ops, builds, plan,
                                       grace_fanout, grace_depth_cap, mesh,
                                       table_key, dec)
            finally:
                _record_spill_stats(rec, spill_pool)

        if cached is None:
            cached, _, _ = self._compile_with_fallback(dt, builds, rec, dec.mode)
        fn, lowering, meta, state = cached
        rec["fusion_mode"] = meta.get("fusion_mode", "fused_xla")
        rec["fused_spans"] = meta.get("fused_spans", 0)
        dicts = dt.dicts
        P, N = dt.shape

        emit_key = (tuple(self.emit_pid[0]), self.emit_pid[1]) if self.emit_pid else None
        # device LUTs cached per (table, stage): zero uploads when hot;
        # replicated across the mesh so probe gathers stay local
        lut_key = (table_key, self.fingerprint, mesh.devices.size if mesh else 0, emit_key,
                   ctx.device_ordinal)
        luts = _LUT_CACHE.get(lut_key)
        if luts is None:
            raw_luts = lowering.build_luts(dicts, [b.dicts for b in builds])
            luts = [_put(mesh, l) for l in raw_luts]
            _LUT_CACHE[lut_key] = luts

        build_args = [b.flat_arrays() for b in builds]
        first_dispatch = not state["dispatched"]
        state["dispatched"] = True
        span_s: dict[str, float] = {}
        t0 = time.time()
        if meta.get("exec") == "staged":
            outs = fn(dt.flat_cols(), luts, dt.mask, build_args, span_s)
        else:
            outs = fn(dt.flat_cols(), luts, dt.mask, build_args)
            jax.block_until_ready(list(outs))
        t_call = time.time() - t0
        # device seconds of the stage kernel(s): the fused dispatch (synced)
        # or the per-span sum. The cold call folds the backend compile in;
        # xla_compile_s below carries the honest attribution
        rec["fused_kernel_s"] = round(sum(span_s.values()) or t_call, 6)
        if span_s:
            rec["span_s"] = {k: round(v, 6) for k, v in span_s.items()}
        if first_dispatch:
            # jit compiles (or fetches from the persistent cache) inside the
            # first call; when the overlap worker already AOT-compiled, the
            # honest figure is ITS compile time (which ran under the fill)
            rec["xla_compile_s"] = round(holder.get("xla_s", t_call), 6)
        if meta["mode"] == "sorted":
            res = self._decode_sorted(outs, meta, P, dicts, [b.dicts for b in builds])
        else:
            outs = jax.device_get(list(outs))  # ONE batched fetch
            res = self._decode_all(outs, meta, P, dicts, [b.dicts for b in builds])
        exec_s = time.time() - t0
        if first_dispatch and "xla_s" not in holder:
            exec_s = max(0.0, exec_s - t_call)  # compile time isn't exec time
        rec["exec_s"] = round(exec_s, 6)
        if "trace_s" in rec or "xla_compile_s" in rec:
            rec["compile_s"] = round(
                rec.get("trace_s", 0.0) + rec.get("xla_compile_s", 0.0), 6)
        cc1 = runtime.compile_cache_stats()
        if cc1["requests"] > cc0["requests"]:
            rec["persist_cache_hits"] = cc1["hits"] - cc0["hits"]
            rec["persist_cache_misses"] = (
                (cc1["requests"] - cc0["requests"]) - (cc1["hits"] - cc0["hits"]))
        _record_spill_stats(rec, spill_pool)
        return res

    def _grace_run(self, ctx: TaskContext, rec: dict, dt: DeviceTable,
                   join_ops: list, builds: list[BuildTable], plan,
                   fanout: int, depth_cap: int, mesh, table_key,
                   dec) -> dict[int, list[pa.RecordBatch]]:
        """Grace-partitioned execution of a budget-breaking hash-join stage.

        The split join's build side re-splits by a secondary hash of the
        combined int64 key (hbm.grace_bucket_of — the splitmix64 lane
        encoding lineage of the PR 7 exchange, salted so it is independent
        of the routing hash) into `plan.grace_buckets` sub-buckets, each
        executed sequentially on device as the SAME compiled stage shape
        over the full probe table. Probe rows are never re-ordered: a row
        whose key lives in bucket b matches only in run b and is masked (an
        ordinary unmatched probe) in every other run, so concatenating the
        per-partition partial-aggregate batches in bucket order reunifies
        in producer row order and the downstream final aggregate merges
        them exactly as it merges multi-partition partials — byte-identical
        to the unconstrained run. Empty sub-builds are skipped; the
        GraceReport postconditions are checked before results are served."""
        jax = ensure_jax()
        dicts = dt.dicts
        P, _N = dt.shape
        n_buckets = int(plan.grace_buckets)
        jsplit = int(plan.split_jidx)
        merged: dict[int, list[pa.RecordBatch]] = {p: [] for p in range(P)}
        buckets_run: list[int] = []
        buckets_empty: list[int] = []
        for b in range(n_buckets):
            try:
                sub_builds = [
                    self._prepare_build(op, j, ctx, table_key, mesh,
                                        grace=(b, n_buckets))
                    if j == jsplit else builds[j]
                    for j, op in enumerate(join_ops)
                ]
            except Unsupported as e:
                if "empty grace sub-bucket" in str(e):
                    buckets_empty.append(b)
                    continue
                raise
            cached, _, _ = self._compile_with_fallback(dt, sub_builds, rec, dec.mode)
            fn, lowering, meta, state = cached
            state["dispatched"] = True
            # LUT cache bypass: sub-build dictionaries are bucket-dependent,
            # and the (table, stage) LUT key has no bucket component
            luts = [_put(mesh, l)
                    for l in lowering.build_luts(dicts, [sb.dicts for sb in sub_builds])]
            build_args = [sb.flat_arrays() for sb in sub_builds]
            span_s: dict[str, float] = {}
            if meta.get("exec") == "staged":
                outs = fn(dt.flat_cols(), luts, dt.mask, build_args, span_s)
            else:
                outs = fn(dt.flat_cols(), luts, dt.mask, build_args)
                jax.block_until_ready(list(outs))
            if meta["mode"] == "sorted":
                res = self._decode_sorted(outs, meta, P, dicts,
                                          [sb.dicts for sb in sub_builds])
            else:
                outs = jax.device_get(list(outs))
                res = self._decode_all(outs, meta, P, dicts,
                                       [sb.dicts for sb in sub_builds])
            for p, bl in res.items():
                merged[p].extend(x for x in bl if x.num_rows)
            buckets_run.append(b)

        report = hbm.GraceReport(
            stage_tag=f"stage_{zlib.crc32(self.fingerprint.encode()):08x}",
            n_buckets=n_buckets, fanout=max(2, int(fanout)),
            depth=int(plan.grace_depth), max_depth=int(depth_cap),
            buckets_run=buckets_run, buckets_empty=buckets_empty)
        from ballista_tpu.analysis.plan_check import check_grace

        violations = check_grace(report)
        if violations:
            # a postcondition miss means the merged output cannot be trusted:
            # demote to the always-correct CPU rung instead of serving it
            raise Unsupported("grace postcondition violated: "
                              + "; ".join(v.message for v in violations))
        rec["grace_splits"] = len(buckets_run)
        schema = self.schema()
        return {p: (bl if bl else [_empty_batch(schema)])
                for p, bl in merged.items()}

    # ------------------------------------------------------------------

    def _compile(self, dt: DeviceTable, kinds, dicts, P: int, N: int,
                 builds: list[BuildTable] | None = None,
                 mode_req: str = "fused_xla"):
        from ballista_tpu.plan.physical import HashJoinExec
        from ballista_tpu.ops.tpu import fusion as _fusion
        from ballista_tpu.ops.tpu.pallas_kernels import MAX_GROUPS as _PALLAS_MAX_G

        jax = ensure_jax()
        jnp = jax.numpy
        agg = self.partial_agg
        scan_schema = self.scan.df_schema
        builds = builds or []
        spans = _fusion.plan_spans(
            len(getattr(self.scan, "filters", []) or []), self.ops, agg)
        span_meta = [(s.kind, s.ops) for s in spans]
        # the pallas kernels are single-device (no shard_map wrapping yet):
        # under a collective-exchange mesh the XLA path handles sharding
        use_pallas = mode_req == "fused_pallas" and _stage_mesh(self.config) is None
        pallas_g_cap = min(int(self.config.get(TPU_FUSION_PALLAS_MAX_GROUPS)),
                           _PALLAS_MAX_G)
        pallas_probe_max = int(self.config.get(TPU_FUSION_PALLAS_MAX_PROBE))

        ctx = Lowering(scan_schema, kinds, dicts)
        ctx.pallas_dict_filter = use_pallas
        valid_idx = dt.valid_flat_idx()
        n_flat_cols = len(dt.cols) + sum(1 for v in dt.valids if v is not None)
        env_fns = []
        for i, (kind, scale) in enumerate(kinds):
            env_fns.append(_mk_col_reader(i, kind, scale, dicts[i], valid_idx[i]))
        env_meta = [(k, s, d, i) for i, ((k, s), d) in enumerate(zip(kinds, dicts))]
        ctx.env_fns = env_fns
        ctx.env_meta = env_meta
        filter_fns = []

        cur_schema = scan_schema
        _bind_env(ctx, cur_schema)
        # scan-level predicates run ON DEVICE (cache holds raw columns)
        for f in getattr(self.scan, "filters", []):
            filter_fns.append(lower_expr(f, ctx))

        lane_cells = [{"d": 0} for _ in builds]
        lane_dups: list[int] = []  # per build: lanes to unroll (1 for semi/anti)
        outer_jidx: set[int] = set()  # joins whose build gathers are nullable-by-miss

        # Aggregate-through-join pre-scan: when the LAST op is an inner/right
        # join whose build columns appear ONLY as count(col) arguments (and
        # group keys are probe-side), the stage aggregates THROUGH the join
        # with per-row match counts — no dup-lane unrolling, no MAX_JOIN_DUP
        # ceiling (the q13 shape: count(o_orderkey) group by c_custkey).
        mult_jidx = None
        mult_outer = False
        count_build_aggs: dict[int, int] = {}  # agg idx → build field idx
        join_ops = [o for o in self.ops if isinstance(o, HashJoinExec)]
        if builds and join_ops:
            jop = join_ops[-1]
            bt_last = builds[-1]
            cba = _mult_shape_check(agg, self.ops, jop)
            if cba is not None and bt_last.dup > 1:
                ok = True
                for fi in cba.values():
                    pp = bt_last.pay_pos[fi] if fi < len(bt_last.pay_pos) else None
                    if pp is None or bt_last.pay_valids[pp] is not None:
                        ok = False  # nullable build col: match count ≠ count(col)
                if ok:
                    mult_jidx = len(builds) - 1
                    mult_outer = jop.join_type == "right"
                    count_build_aggs = cba
        mult_weight_fn = None
        jidx = 0
        for op in self.ops:
            _bind_env(ctx, cur_schema)
            if isinstance(op, FilterExec):
                filter_fns.append(lower_expr(op.predicate, ctx))
            elif isinstance(op, HashJoinExec):
                bt = builds[jidx]
                # build arrays ride at the tail of the flattened cols list
                # (after the scan columns AND their validity planes)
                off = n_flat_cols + sum(len(builds[i].flat_arrays()) for i in range(jidx))
                pay_off = off + (2 if bt.cnt is not None else 1)
                probe_fns = [lower_expr(r, ctx) for (_, r) in op.on]
                probe_pallas = (
                    use_pallas and bt.mode == "direct" and bt.cnt is None
                    and bt.dup == 1
                    and int(bt.keys.shape[0]) <= pallas_probe_max
                )
                finder = _mk_join_finder(off, probe_fns, bt, lane_cells[jidx],
                                         pallas=probe_pallas)
                pv_idx = bt.pay_valid_flat_idx()
                if op.join_type in ("right_semi", "right_anti"):
                    neg = op.join_type == "right_anti"
                    if op.filter is None:
                        # membership only: the match mask filters probe rows
                        # (EXISTS / NOT IN after decorrelation) — no build
                        # columns, no expansion lanes, schema unchanged
                        filter_fns.append(
                            lambda cols, luts, _f=finder, _n=neg:
                            DevVal("bool", ~_f(cols, luts)[1].arr if _n else _f(cols, luts)[1].arr)
                        )
                    else:
                        # EXISTS with a correlated residual predicate (q21's
                        # l2.l_suppkey <> l1.l_suppkey): OR the filtered
                        # match across all dup lanes of the build key
                        if bt.dup > MAX_JOIN_DUP:
                            raise Unsupported(
                                f"semi/anti join filter over dup {bt.dup} > {MAX_JOIN_DUP}"
                            )
                        lane_preds = []
                        saved_fns, saved_meta = list(ctx.env_fns), list(ctx.env_meta)
                        combined_schema = op.left.df_schema.merge(cur_schema)
                        for d in range(bt.dup):
                            finder_d = _mk_join_finder(off, probe_fns, bt, {"d": d})
                            gfns, gmeta = [], []
                            for ci, pp in enumerate(bt.pay_pos):
                                if pp is None:
                                    gfns.append(_mk_raising(
                                        f"unencodable build column {ci} in join filter"))
                                    gmeta.append(None)
                                else:
                                    gfns.append(_mk_build_gather(
                                        pay_off, pp, bt.kinds[ci], bt.scales[ci],
                                        bt.dicts[ci], finder_d,
                                        None if pv_idx[pp] is None else off + pv_idx[pp]))
                                    gmeta.append((bt.kinds[ci], bt.scales[ci],
                                                  bt.dicts[ci], ("build", jidx, ci)))
                            ctx.env_fns = gfns + saved_fns
                            ctx.env_meta = gmeta + saved_meta
                            _bind_env(ctx, combined_schema)
                            lane_preds.append((finder_d, lower_expr(op.filter, ctx)))
                        ctx.env_fns, ctx.env_meta = saved_fns, saved_meta
                        _bind_env(ctx, cur_schema)

                        def run(cols, luts, _lp=lane_preds, _n=neg):
                            any_m = None
                            for fd, pf in _lp:
                                _, matched = fd(cols, luts)
                                md = true_mask(matched) & true_mask(pf(cols, luts))
                                any_m = md if any_m is None else any_m | md
                            return DevVal("bool", ~any_m if _n else any_m)

                        filter_fns.append(run)
                    lane_dups.append(1)
                    jidx += 1
                    continue
                if jidx == mult_jidx:
                    # aggregate-through-join: ONE count gather replaces all
                    # dup match lanes; build columns are never materialized
                    counter = _mk_join_counter(off, probe_fns, bt)
                    if op.join_type == "inner":
                        filter_fns.append(
                            lambda cols, luts, _c=counter:
                            DevVal("bool", _c(cols, luts) > 0)
                        )
                    mult_weight_fn = counter
                    n_bf = len(op.left.df_schema)
                    ctx.env_fns = [
                        _mk_raising("build column consumed as a value in an "
                                    "aggregate-through-join stage")
                    ] * n_bf + list(ctx.env_fns)
                    ctx.env_meta = [None] * n_bf + list(ctx.env_meta)
                    cur_schema = op.df_schema
                    lane_dups.append(1)
                    jidx += 1
                    continue
                outer = op.join_type == "right"
                if outer:
                    outer_jidx.add(jidx)
                    # right outer: every probe row emits — on lane 0
                    # unconditionally (unmatched rows ride lane 0 with NULL
                    # build gathers), on later lanes only when matched
                    def emit(cols, luts, _f=finder, _cell=lane_cells[jidx]):
                        jnp = ensure_jax().numpy
                        _, matched = _f(cols, luts)
                        if _cell["d"] == 0:
                            return DevVal("bool", jnp.ones_like(matched.arr))
                        return matched

                    filter_fns.append(emit)
                else:
                    filter_fns.append(lambda cols, luts, _f=finder: _f(cols, luts)[1])
                lane_dups.append(bt.dup)
                build_fns = [
                    _mk_build_gather(pay_off, ci, bt.kinds[ci], bt.scales[ci], bt.dicts[ci],
                                     finder,
                                     None if pv_idx[ci] is None else off + pv_idx[ci],
                                     outer=outer)
                    for ci in range(len(bt.payloads))
                ]
                build_meta = [
                    (bt.kinds[ci], bt.scales[ci], bt.dicts[ci], ("build", jidx, ci))
                    for ci in range(len(bt.payloads))
                ]
                # exec output order: build fields then probe fields
                ctx.env_fns = build_fns + list(ctx.env_fns)
                ctx.env_meta = build_meta + list(ctx.env_meta)
                cur_schema = op.df_schema
                jidx += 1
            elif isinstance(op, ProjectionExec):
                new_fns, new_meta = [], []
                for e in op.exprs:
                    new_fns.append(lower_expr(e, ctx))
                    new_meta.append(_passthrough_meta(e, ctx, cur_schema))
                ctx.env_fns, ctx.env_meta = new_fns, new_meta
                cur_schema = op.df_schema
            elif isinstance(op, CoalesceBatchesExec):
                pass
            else:
                raise Unsupported(f"op {type(op).__name__}")
        _bind_env(ctx, cur_schema)
        ctx.stage_filter_fns = filter_fns  # shared with the sorted path
        lane_sets = list(itertools.product(*[range(d) for d in lane_dups]))
        if len(lane_sets) > MAX_JOIN_DUP:
            raise Unsupported(f"{len(lane_sets)} expansion-join lanes > {MAX_JOIN_DUP}")
        ctx.lane_sets = lane_sets
        ctx.lane_cells = lane_cells

        # Group-key strategy: small dictionary domains unroll into per-group
        # masked reductions (pure VPU, no scatter/sort). Everything else —
        # int64 keys like l_orderkey, composite keys, big dictionaries —
        # goes through the sort-based segmented reduction below.
        def _slot_nullable(slot) -> bool:
            if isinstance(slot, tuple) and slot[0] == "build":
                if slot[1] in outer_jidx:
                    return True  # unmatched outer gathers are NULL
                pp = builds[slot[1]].pay_pos[slot[2]]
                return pp is None or builds[slot[1]].pay_valids[pp] is not None
            return dt.valids[slot] is not None

        unrolled = True
        group_src_slots: list = []
        group_fns: list = []
        pad_sizes: list = []
        for g in agg.group_exprs:
            gc = g.expr if isinstance(g, Alias) else g
            if not isinstance(gc, Column):
                unrolled = False
                break
            i = cur_schema.index_of(gc.name, gc.qualifier)
            gmeta = ctx.env_meta[i]
            if gmeta is None or gmeta[0] != "code" or gmeta[2] is None:
                unrolled = False
                break
            if _slot_nullable(gmeta[3]):
                # a NULL group key needs its own group: the sorted path
                # carries validity as an extra sort operand; the unrolled
                # code-domain form cannot distinguish null from code 0
                unrolled = False
                break
            group_fns.append(ctx.env_fns[i])
            group_src_slots.append(gmeta[3])
            pad_sizes.append(_pow2(len(gmeta[2])))

        G = 1
        for p in pad_sizes:
            G *= p
        G = max(G, 1)
        n_lanes = len(ctx.lane_sets)
        if unrolled and agg.group_exprs and (
            G * n_lanes > 64 or G * n_lanes * P > MAX_SEGMENTS * 16
        ):
            # the unrolled form materializes G masked reductions PER
            # expansion lane; beyond this budget the sorted form wins (and
            # scatter-free unrolling stops scaling) — UNLESS the Pallas
            # hash-aggregate was requested and the stage fits the kernel
            # family: its one-hot matmul accumulation carries all G lanes
            # without per-group unrolling, so the 64-group budget lifts to
            # the kernel ceiling. If the value lanes turn out ineligible at
            # trace time (money int64 sums, validity planes), raw() raises
            # Unsupported and the fallback ladder retries as fused_xla,
            # landing here again with use_pallas off → sorted path.
            pallas_agg_ok = (
                use_pallas and n_lanes == 1 and mult_weight_fn is None
                and G <= pallas_g_cap and G * P <= 1 << 22
                and all(d.func in ("sum", "count", "count_all")
                        for d in agg.aggs)
            )
            if not pallas_agg_ok:
                unrolled = False

        agg_fns = []
        agg_modes = []  # "row" | "build_cnt" (count of a mult-join build col)
        for ai, d in enumerate(agg.aggs):
            if d.func in ("welford_mean", "welford_m2"):
                # mean/M2 partials are not additive across expansion lanes and
                # have no weighted form: only plain (single-lane, unweighted)
                # stages carry variance on device; others re-run on cpu
                if mult_weight_fn is not None or len(ctx.lane_sets) != 1:
                    raise Unsupported("welford through expansion join")
            elif d.func not in ("sum", "min", "max", "count", "count_all"):
                raise Unsupported(f"agg {d.func}")
            if ai in count_build_aggs:
                agg_fns.append(None)
                agg_modes.append("build_cnt")
            else:
                agg_fns.append(lower_expr(d.expr, ctx) if d.expr is not None else None)
                agg_modes.append("row")
        mult = (mult_weight_fn, mult_outer) if mult_weight_fn is not None else None

        if not unrolled:
            group_fns = [lower_expr(g, ctx) for g in agg.group_exprs]
            # live-dictionary slots for decode (compilations are shared
            # across tables with equal shapes/dict sizes; dict CONTENTS are
            # resolved at decode time, never baked into the cached meta)
            key_slots: list = []
            key_premeta: list = []  # (kind, scale, dict, slot) | None, PRE-trace
            for g in agg.group_exprs:
                gc = g.expr if isinstance(g, Alias) else g
                slot = None
                gmeta = None
                if isinstance(gc, Column):
                    i = cur_schema.index_of(gc.name, gc.qualifier)
                    gmeta = ctx.env_meta[i]
                    if gmeta is not None:
                        slot = gmeta[3]
                key_slots.append(slot)
                key_premeta.append(gmeta)
            fn_s, ctx_s, meta_s, lowered_s = self._compile_sorted(
                dt, ctx, P, N, builds, group_fns, agg_fns, key_slots, key_premeta,
                agg_modes=agg_modes, mult=mult,
            )
            meta_s["fusion_mode"] = "fused_xla"
            meta_s["fused_spans"] = len(spans)
            meta_s["spans"] = span_meta
            return fn_s, ctx_s, meta_s, lowered_s

        meta_holder: dict = {}
        aggs = agg.aggs

        lane_sets = ctx.lane_sets
        lane_cells = ctx.lane_cells

        # --- span closures, shared by the fused and staged executions -----
        # Fused mode composes these into ONE traced function; staged mode
        # jits each span separately with HBM intermediates between them.
        # Either way the SAME jnp expressions run over the same inputs,
        # which is what makes fused-vs-staged outputs byte-identical.

        def eval_pred(cols, luts, mask):
            """predicate span: scan filters, FilterExec predicates, semi/
            anti membership masks, join match masks — one fused [P, N]
            boolean."""
            m = mask
            for ff in filter_fns:
                m = m & true_mask(ff(cols, luts))
            return m

        def eval_proj(cols, luts):
            """project/probe span: group-id composition and agg value lanes
            (join-probe gathers ride inside the lowered column closures)."""
            if group_fns:
                gid = None
                for gf, psz in zip(group_fns, pad_sizes):
                    codes = gf(cols, luts).arr.astype(jnp.int32)
                    gid = codes if gid is None else gid * psz + codes
            else:
                gid = None
            vs = [af(cols, luts) if af is not None else None for af in agg_fns]
            return gid, vs

        def aggregate_lane(m, gid, vs, w, m_eff):
            """aggregate span, one expansion lane: per-group masked
            reductions (the XLA form — pure VPU, no scatter)."""
            gmasks = [m & (gid == g) for g in range(G)] if gid is not None else [m]
            outs_lane = []
            out_meta = []
            nullcnt_lane = []
            nullcnt_map: dict[int, int] = {}
            for ai, (d, v) in enumerate(zip(aggs, vs)):
                if v is None:
                    out_meta.append(("i64", 0))
                else:
                    out_meta.append(("i64", 0) if d.func == "count" else (v.kind, v.scale))
                cols_out = []
                for gm in gmasks:
                    if agg_modes[ai] == "build_cnt":
                        cols_out.append(
                            jnp.where(gm, w, 0).astype(jnp.int64).sum(axis=1))
                    elif m_eff is None:
                        cols_out.append(_masked_reduce(jnp, v, gm, d.func))
                    else:
                        cols_out.append(_masked_reduce_w(jnp, v, gm, d.func, m_eff))
                outs_lane.append(jnp.stack(cols_out, axis=1))  # [P, G]
                if (v is not None and v.valid is not None
                        and d.func in ("sum", "min", "max",
                                       "welford_mean", "welford_m2")):
                    # valid-count companion: a group whose inputs are all
                    # NULL must decode to NULL, not 0 / ±inf
                    nullcnt_map[ai] = len(nullcnt_lane)
                    nullcnt_lane.append(jnp.stack(
                        [(gm & v.valid).sum(axis=1) for gm in gmasks], axis=1
                    ))
            presence_lane = jnp.stack([gm.sum(axis=1) for gm in gmasks], axis=1)
            meta_holder["out"] = out_meta
            meta_holder["nullcnt_map"] = nullcnt_map
            return outs_lane, nullcnt_lane, presence_lane

        def pallas_lane(m, gid, vs):
            """aggregate span, Pallas form: the multi-tile one-hot hash
            aggregate computes ALL G masked sums + counts in one VMEM pass
            per float value lane (exact int64 money stays on the XLA
            reductions in aggregate_lane)."""
            from ballista_tpu.ops.tpu.pallas_kernels import masked_group_reduce

            # sums first: every sum's kernel call also yields the counts,
            # so count aggs never need a dedicated pass
            sum_results: dict[int, object] = {}
            counts = None
            for i_, (d, v) in enumerate(zip(aggs, vs)):
                if d.func == "sum":
                    arr = jnp.broadcast_to(v.arr, m.shape)
                    s, c = masked_group_reduce(arr, gid, m, G)
                    sum_results[i_] = s
                    counts = c if counts is None else counts
            if counts is None:  # count-only aggregation
                _, counts = masked_group_reduce(
                    jnp.zeros(m.shape, jnp.float32), gid, m, G
                )
            outs_lane = []
            out_meta = []
            for i_, d in enumerate(aggs):
                if d.func in ("count", "count_all"):
                    outs_lane.append(counts.astype(jnp.int64))
                    out_meta.append(("i64", 0))
                else:
                    outs_lane.append(sum_results[i_].astype(jnp.float64))
                    out_meta.append(("f64", 0))
            meta_holder["out"] = out_meta
            meta_holder["nullcnt_map"] = {}
            meta_holder["pallas_used"] = True
            return outs_lane, counts

        staged_ok = (
            mode_req == "staged" and len(lane_sets) == 1
            and mult_weight_fn is None
        )
        if staged_ok:
            return self._compile_staged(
                dt, ctx, dicts, builds, eval_pred, eval_proj, aggregate_lane,
                meta_holder, span_meta, group_src_slots, pad_sizes, G,
            )

        def raw(cols, luts, mask, build_args):
            # keep [P, N]: partitions are the leading axis, reductions run
            # over axis=1 — XLA fuses the per-group masked sums into single
            # VPU passes, no scatter anywhere. Join-probe gathers hit the
            # build arrays appended after the scan columns. Expansion joins
            # unroll match lanes: the full pipeline is traced once per lane
            # combination (XLA CSEs lane-invariant work) and reductions
            # accumulate across lanes.
            cols = list(cols) + [a for b in build_args for a in b]
            outs = None
            presence = None
            nullcnts: list = []
            for lane in lane_sets:
                for cell, d_ in zip(lane_cells, lane):
                    cell["d"] = d_
                m = eval_pred(cols, luts, mask)
                gid, vs = eval_proj(cols, luts)
                w = m_eff = None
                if mult_weight_fn is not None:
                    w = jnp.broadcast_to(mult_weight_fn(cols, luts), mask.shape)
                    m_eff = jnp.maximum(w, 1) if mult_outer else w
                pallas_ok = (
                    use_pallas and gid is not None and aggs
                    and G <= pallas_g_cap and mult_weight_fn is None
                    and all(v is None or v.valid is None for v in vs)
                    and all(
                        d.func in ("count", "count_all")
                        or (d.func == "sum" and v is not None and v.kind == "f64")
                        for d, v in zip(aggs, vs)
                    )
                )
                if pallas_ok:
                    outs_lane, presence_lane = pallas_lane(m, gid, vs)
                    nullcnt_lane = []
                else:
                    if use_pallas and (
                        G * len(lane_sets) > 64
                        or G * len(lane_sets) * m.shape[0] > MAX_SEGMENTS * 16
                    ):
                        # this stage only kept the unrolled form because the
                        # relaxed Pallas budget admitted it; its value lanes
                        # turned out kernel-ineligible (money int64 sums,
                        # validity planes) — refuse the G-wide XLA unroll
                        # and let the fallback ladder retry as fused_xla
                        raise Unsupported(
                            f"pallas-ineligible aggregation at G={G}")
                    outs_lane, nullcnt_lane, presence_lane = aggregate_lane(
                        m, gid, vs, w, m_eff)
                if outs is None:
                    outs, presence, nullcnts = outs_lane, presence_lane, nullcnt_lane
                else:
                    merged = []
                    for d, prev, cur in zip(aggs, outs, outs_lane):
                        if d.func == "min":
                            merged.append(jnp.minimum(prev, cur))
                        elif d.func == "max":
                            merged.append(jnp.maximum(prev, cur))
                        else:  # sum / count: additive across lanes
                            merged.append(prev + cur)
                    outs = merged
                    presence = presence + presence_lane
                    nullcnts = [p_ + c_ for p_, c_ in zip(nullcnts, nullcnt_lane)]
            return tuple(outs) + tuple(nullcnts) + (presence,)

        jitted = jax.jit(raw)
        cols_spec = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in dt.flat_cols()]
        luts0 = ctx.build_luts(dicts, [b.dicts for b in builds])
        luts_spec = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in luts0]
        mask_spec = jax.ShapeDtypeStruct(dt.mask.shape, np.bool_)
        builds_spec = [
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in b.flat_arrays()]
            for b in builds
        ]
        # trace → meta; the Lowered also feeds the overlap worker's optional
        # AOT backend compile (which warms the persistent cache)
        lowered = jitted.lower(cols_spec, luts_spec, mask_spec, builds_spec)
        meta = {
            "mode": "unrolled",
            "fusion_mode": (
                "fused_pallas" if meta_holder.get("pallas_used") else "fused_xla"
            ),
            "fused_spans": len(spans),
            "spans": span_meta,
            "out": meta_holder["out"],
            "nullcnt_map": meta_holder.get("nullcnt_map", {}),
            "group_src_slots": group_src_slots,
            "pad_sizes": pad_sizes,
            "G": G,
        }
        return jitted, ctx, meta, lowered

    def _compile_staged(self, dt: DeviceTable, ctx: Lowering, dicts, builds,
                        eval_pred, eval_proj, aggregate_lane, meta_holder,
                        span_meta, group_src_slots, pad_sizes, G: int):
        """Per-span sub-kernels with HBM intermediates — the always-available
        fallback mode and the roofline instrument.

        Each span (predicate → project → aggregate) is its own jitted
        function, dispatched with a device sync in between, so `span_s`
        in RunStats shows where a stage's time actually goes. The spans
        trace the SAME closures the fused path composes (eval_pred /
        eval_proj / aggregate_lane), so staged and fused_xla results are
        byte-identical; the price is materializing the predicate mask and
        every projected value lane in HBM between dispatches."""
        jax = ensure_jax()
        jnp = jax.numpy
        proj_info: dict = {}

        def pred_raw(cols, luts, mask, build_args):
            cols = list(cols) + [a for b in build_args for a in b]
            return eval_pred(cols, luts, mask)

        def proj_raw(cols, luts, mask, build_args):
            cols = list(cols) + [a for b in build_args for a in b]
            gid, vs = eval_proj(cols, luts)
            out = {}
            if gid is not None:
                out["gid"] = jnp.broadcast_to(gid, mask.shape)
            vmeta = []
            for ai, v in enumerate(vs):
                if v is None:
                    vmeta.append(None)
                    continue
                out[f"a{ai}"] = jnp.broadcast_to(v.arr, mask.shape)
                if v.valid is not None:
                    out[f"v{ai}"] = jnp.broadcast_to(v.valid, mask.shape)
                vmeta.append((v.kind, v.scale))
            proj_info["vmeta"] = vmeta
            return out

        def agg_raw(m, pv):
            vs = []
            for ai, vm in enumerate(proj_info["vmeta"]):
                if vm is None:
                    vs.append(None)
                else:
                    kind, scale = vm
                    vs.append(DevVal(kind, pv[f"a{ai}"], scale,
                                     valid=pv.get(f"v{ai}")))
            outs_lane, nullcnt_lane, presence_lane = aggregate_lane(
                m, pv.get("gid"), vs, None, None)
            return tuple(outs_lane) + tuple(nullcnt_lane) + (presence_lane,)

        # single expansion lane (the staged gate): pin the lane cells once
        for cell, d_ in zip(ctx.lane_cells, ctx.lane_sets[0]):
            cell["d"] = d_
        jp = jax.jit(pred_raw)
        jproj = jax.jit(proj_raw)
        jagg = jax.jit(agg_raw)

        cols_spec = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in dt.flat_cols()]
        luts0 = ctx.build_luts(dicts, [b.dicts for b in builds])
        luts_spec = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in luts0]
        mask_spec = jax.ShapeDtypeStruct(dt.mask.shape, np.bool_)
        builds_spec = [
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in b.flat_arrays()]
            for b in builds
        ]
        # trace now (Unsupported must surface at compile time, where the
        # fallback ladder lives): proj fills vmeta, agg fills meta_holder
        # (out / nullcnt_map) — the same metadata the fused trace produces
        jax.eval_shape(pred_raw, cols_spec, luts_spec, mask_spec, builds_spec)
        pv_spec = jax.eval_shape(proj_raw, cols_spec, luts_spec, mask_spec,
                                 builds_spec)
        jax.eval_shape(agg_raw, mask_spec, pv_spec)

        def staged_fn(cols, luts, mask, build_args, span_s=None):
            t0 = time.time()
            m = jp(cols, luts, mask, build_args)
            jax.block_until_ready(m)
            t1 = time.time()
            pv = jproj(cols, luts, mask, build_args)
            jax.block_until_ready(pv)
            t2 = time.time()
            outs = jagg(m, pv)
            jax.block_until_ready(list(outs))
            t3 = time.time()
            if span_s is not None:
                span_s["predicate"] = t1 - t0
                span_s["project"] = t2 - t1
                span_s["aggregate"] = t3 - t2
            return outs

        meta = {
            "mode": "unrolled",
            "exec": "staged",
            "fusion_mode": "staged",
            "fused_spans": 0,
            "spans": span_meta,
            "out": meta_holder["out"],
            "nullcnt_map": meta_holder.get("nullcnt_map", {}),
            "group_src_slots": group_src_slots,
            "pad_sizes": pad_sizes,
            "G": G,
        }
        return staged_fn, ctx, meta, None

    def _compile_sorted(self, dt: DeviceTable, ctx: Lowering, P: int, N: int,
                        builds: list[BuildTable], group_fns, agg_fns, key_slots,
                        key_premeta, agg_modes=None, mult=None):
        """Sort-based segmented reduction for large/int group domains.

        The TPU has no fast random scatter, so hash aggregation is out; the
        device-native plan for arbitrary group keys is: lexicographic
        `lax.sort` over (validity, key...) with agg inputs as payload,
        segment boundaries from adjacent-key diffs, per-segment totals via
        cumsum-subtract (sum/count: exact int64) or a segmented associative
        scan (min/max), then ONE unique-index scatter per output column to
        compact segment results into a static [C] capacity. The fetch is
        sliced to pow2(actual segment count), so a 4M-slot capacity costs
        nothing when a query yields 10k groups. Overflow (> C distinct
        groups) raises and the stage re-runs on the CPU engine.
        """
        jax = ensure_jax()
        jnp = jax.numpy
        agg = self.partial_agg
        aggs = agg.aggs
        filter_fns = ctx.stage_filter_fns
        lane_sets = ctx.lane_sets
        lane_cells = ctx.lane_cells
        M = P * N * len(lane_sets)
        C = min(_pow2(M), 1 << 22)
        meta_holder: dict = {}
        # device-side shuffle routing: emit a __pid column over the
        # compacted output rows (bit-exact twin of ops/hashing.py — string
        # keys hash via per-dictionary FNV LUTs)
        emit_keys: list[int] | None = None
        emit_k = 0
        emit_luts: dict[int, int] = {}
        if self.emit_pid is not None:
            idxs, emit_k = self.emit_pid
            if all(0 <= i < len(group_fns) for i in idxs) and emit_k > 0:
                emit_keys = list(idxs)
                # LUTs MUST register before tracing: lut specs are frozen
                # when the jitted fn lowers, so trace-time add_lut would
                # index past the traced argument list
                from ballista_tpu.ops.hashing import fnv1a_str

                for ki in emit_keys:
                    pm = key_premeta[ki]
                    if pm is None:
                        emit_keys = None
                        break
                    if pm[0] == "code":
                        emit_luts[ki] = ctx.add_lut(
                            pm[3],
                            lambda dic: np.array(
                                [fnv1a_str(x) for x in (dic or [])], dtype=np.uint64
                            ),
                        )

        def raw(cols, luts, mask, build_args):
            cols = list(cols) + [a for b in build_args for a in b]
            # per expansion-join match lane: (valid, key operands, payloads);
            # lanes concatenate into one row set feeding a single sort.
            # A NULLABLE group key contributes TWO sort operands — a null
            # marker then the (filled) value — so NULL forms its own group
            # (SQL GROUP BY treats NULLs as equal) without sentinel values.
            lane_valid, lane_keyops, lane_pays = [], [], []
            for lane in lane_sets:
                for cell, d_ in zip(lane_cells, lane):
                    cell["d"] = d_
                m = mask
                for ff in filter_fns:
                    m = m & true_mask(ff(cols, luts))
                lane_valid.append(m.reshape(-1))
                keyops = []  # flat key operand list
                key_meta = []  # per key: (kind, scale, slot, has_null)
                for gf, slot in zip(group_fns, key_slots):
                    v = gf(cols, luts)
                    if v.kind == "f64":
                        raise Unsupported("f64 group key")
                    if v.kind == "code" and slot is None:
                        raise Unsupported("code group key without a dictionary slot")
                    arr = v.arr
                    if arr.dtype == jnp.bool_:
                        arr = arr.astype(jnp.int32)
                    has_null = v.valid is not None
                    if has_null:
                        marker = jnp.broadcast_to(~v.valid, mask.shape).reshape(-1)
                        keyops.append(marker.astype(jnp.int32))
                    keyops.append(jnp.broadcast_to(arr, mask.shape).reshape(-1))
                    key_meta.append((v.kind, v.scale, slot, has_null))
                meta_holder["key_meta"] = key_meta
                lane_keyops.append(keyops)
                w_b = m_eff = None
                if mult is not None:
                    wfn, mouter = mult
                    w_b = jnp.broadcast_to(wfn(cols, luts), mask.shape)
                    m_eff = jnp.maximum(w_b, 1) if mouter else w_b
                # payload plan: per agg → (pay_idx|None, ncnt_idx|None)
                pays = []
                pay_plan = []
                out_meta = []
                # the welford (mean, m2) pair shares one Cast expr object:
                # ship its value/validity lanes through the sort ONCE
                welford_pay: dict[int, tuple] = {}
                for ai, (d, af) in enumerate(zip(aggs, agg_fns)):
                    if agg_modes is not None and agg_modes[ai] == "build_cnt":
                        # count of a mult-join build column == match count
                        out_meta.append(("i64", 0))
                        pays.append(w_b.reshape(-1).astype(jnp.int64))
                        pay_plan.append((len(pays) - 1, None))
                        continue
                    v = af(cols, luts) if af is not None else None
                    if d.func in ("count", "count_all"):
                        out_meta.append(("i64", 0))
                        if v is None or v.valid is None:
                            if m_eff is None:
                                pay_plan.append((None, None))  # segment length
                            else:
                                pays.append(m_eff.reshape(-1).astype(jnp.int64))
                                pay_plan.append((len(pays) - 1, None))
                        else:
                            # count(x): number of non-null x per group (each
                            # probe row weighted by its join multiplicity)
                            vb = jnp.broadcast_to(v.valid, mask.shape)
                            cnt1 = m_eff if m_eff is not None else 1
                            pays.append(jnp.where(vb, cnt1, 0)
                                        .reshape(-1).astype(jnp.int64))
                            pay_plan.append((len(pays) - 1, None))
                        continue
                    if (d.func in ("welford_mean", "welford_m2")
                            and id(d.expr) in welford_pay):
                        out_meta.append(("f64", 0))
                        pay_plan.append(welford_pay[id(d.expr)])
                        continue
                    out_meta.append((v.kind, v.scale))
                    arr = v.arr
                    if m_eff is not None and d.func == "sum":
                        arr = arr * m_eff.astype(arr.dtype)
                    ncnt_idx = None
                    if v.valid is not None:
                        # null-skip: neutralize invalid slots for the reduce,
                        # and carry a valid-count so all-NULL groups decode
                        # to NULL rather than 0 / ±inf
                        if d.func in ("sum", "welford_mean", "welford_m2"):
                            neutral = jnp.zeros((), dtype=arr.dtype)
                        elif d.func == "min":
                            neutral = (jnp.iinfo(arr.dtype).max
                                       if jnp.issubdtype(arr.dtype, jnp.integer) else jnp.inf)
                        else:
                            neutral = (jnp.iinfo(arr.dtype).min
                                       if jnp.issubdtype(arr.dtype, jnp.integer) else -jnp.inf)
                        arr = jnp.where(v.valid, arr, neutral)
                        pays.append(jnp.broadcast_to(
                            v.valid, mask.shape).reshape(-1).astype(jnp.int64))
                        ncnt_idx = len(pays) - 1
                    pays.append(jnp.broadcast_to(arr, mask.shape).reshape(-1))
                    pay_plan.append((len(pays) - 1, ncnt_idx))
                    if d.func in ("welford_mean", "welford_m2"):
                        welford_pay[id(d.expr)] = pay_plan[-1]
                meta_holder["out"] = out_meta
                meta_holder["pay_plan"] = pay_plan
                lane_pays.append(pays)

            valid = jnp.concatenate(lane_valid)
            n_keyops = len(lane_keyops[0])
            cat_keys = [
                jnp.concatenate([lk[i] for lk in lane_keyops]) for i in range(n_keyops)
            ]
            cat_pays = [
                jnp.concatenate([lp[i] for lp in lane_pays])
                for i in range(len(lane_pays[0]))
            ]
            operands = [(~valid).astype(jnp.int32)] + cat_keys + cat_pays
            sorted_ = jax.lax.sort(tuple(operands), num_keys=1 + n_keyops)
            svalid = sorted_[0] == 0
            skeys = sorted_[1 : 1 + n_keyops]
            spays = list(sorted_[1 + n_keyops :])

            diff = jnp.zeros((M,), bool).at[0].set(True)
            for k in skeys:
                diff = diff | jnp.concatenate([jnp.ones((1,), bool), k[1:] != k[:-1]])
            boundary = svalid & diff
            seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
            bor_inv = boundary | ~svalid
            is_end = svalid & jnp.concatenate([bor_inv[1:], jnp.ones((1,), bool)])
            n_seg = boundary.sum().astype(jnp.int32)

            arange = jnp.arange(M, dtype=jnp.int32)
            # segment-start position of each row's segment, via one scatter
            # + gather (indices unique: one boundary row per segment)
            spos = (
                jnp.zeros((C,), jnp.int32)
                .at[jnp.where(boundary, seg, C)]
                .set(arange, mode="drop", unique_indices=True)
            )
            start = spos[jnp.clip(seg, 0, C - 1)]
            end_idx = jnp.where(is_end, seg, C)

            def compact(src):
                return (
                    jnp.zeros((C,), src.dtype)
                    .at[end_idx]
                    .set(src, mode="drop", unique_indices=True)
                )

            def int_segsum(sv):
                # exact int64: global cumsum minus prefix-at-segment-start
                w = sv.astype(jnp.int64)
                csum = jnp.cumsum(w)
                presum = csum - w  # exclusive
                return compact(csum - presum[start])

            key_outs = [compact(k) for k in skeys]
            agg_outs = []
            ncnt_outs = []
            ncnt_map: dict[int, int] = {}
            welford_stats: dict[int, tuple] = {}  # pay_idx → (c_c, mean_c, ncnt_pos)
            for ai, (d, (pay_idx, ncnt_idx)) in enumerate(
                zip(aggs, meta_holder["pay_plan"])
            ):
                if pay_idx is None:
                    agg_outs.append(compact((arange - start + 1).astype(jnp.int64)))
                    continue
                sv = spays[pay_idx]
                if d.func in ("welford_mean", "welford_m2"):
                    # two-pass variance partial over sorted segments: segment
                    # mean via float segscan, then gather the mean back per
                    # row (seg indexes the compacted [C] space) for the
                    # centered square sum — stable, no cancellation. The
                    # (mean, m2) pair shares payload lanes and stats.
                    if pay_idx in welford_stats:
                        c_c, mean_c, ncnt_pos = welford_stats[pay_idx]
                    else:
                        if ncnt_idx is not None:
                            c_c = int_segsum(spays[ncnt_idx])
                        else:
                            c_c = compact((arange - start + 1).astype(jnp.int64))
                        s1_c = compact(_segscan(jnp, sv, boundary, "sum"))
                        mean_c = s1_c / jnp.maximum(c_c, 1).astype(sv.dtype)
                        ncnt_pos = None
                        if ncnt_idx is not None:
                            ncnt_pos = len(ncnt_outs)
                            ncnt_outs.append(c_c)
                        welford_stats[pay_idx] = (c_c, mean_c, ncnt_pos)
                    if d.func == "welford_mean":
                        agg_outs.append(mean_c)
                    else:
                        mean_row = mean_c[jnp.clip(seg, 0, C - 1)]
                        d2 = (sv - mean_row) ** 2
                        if ncnt_idx is not None:
                            # null x slots were sum-neutralized to 0; keep
                            # them out of the square sum too
                            d2 = jnp.where(spays[ncnt_idx] > 0, d2, 0.0)
                        agg_outs.append(compact(_segscan(jnp, d2, boundary, "sum")))
                    if ncnt_pos is not None:
                        ncnt_map[ai] = ncnt_pos
                    continue
                fname = "sum" if d.func in ("count", "count_all") else d.func
                if fname == "sum" and jnp.issubdtype(sv.dtype, jnp.integer):
                    agg_outs.append(int_segsum(sv))
                else:
                    # float sums use the segmented scan too: cumsum-subtract
                    # would difference two near-equal whole-table totals
                    # (catastrophic cancellation for small late segments)
                    agg_outs.append(compact(_segscan(jnp, sv, boundary, fname)))
                if ncnt_idx is not None:
                    ncnt_map[ai] = len(ncnt_outs)
                    ncnt_outs.append(int_segsum(spays[ncnt_idx]))
            meta_holder["nullcnt_map"] = ncnt_map

            if emit_keys is not None:
                from ballista_tpu.ops.tpu.kernels import hash64, hash_combine_jax

                # key_outs layout: optional marker precedes each nullable
                # key's value — build a key→(marker, value) position map
                pos = 0
                key_pos = []
                for (_k, _s, _slot, hn) in meta_holder["key_meta"]:
                    key_pos.append((pos if hn else None, pos + (1 if hn else 0)))
                    pos += 2 if hn else 1
                _NULL_TAG = jnp.uint64(0x9E3779B97F4A7C15)
                h = jnp.zeros((C,), jnp.uint64)
                for ki in emit_keys:
                    kind, scale, slot, _hn = meta_holder["key_meta"][ki]
                    mpos, vpos = key_pos[ki]
                    arr = key_outs[vpos]
                    if kind == "code":
                        enc = luts[emit_luts[ki]][arr]
                    elif kind == "money":
                        f = arr.astype(jnp.float64) / (10.0 ** scale)
                        f = jnp.where(f == 0.0, 0.0, f)  # -0.0 normalizes
                        enc = jax.lax.bitcast_convert_type(f, jnp.uint64)
                    else:  # i64 / date / bool — value-preserving int64 bits
                        enc = arr.astype(jnp.int64).astype(jnp.uint64)
                    hv = hash64(enc)
                    if mpos is not None:
                        hv = jnp.where(key_outs[mpos] != 0, _NULL_TAG, hv)
                    h = hash_combine_jax(h, hv)
                pid = (h % jnp.uint64(emit_k)).astype(jnp.int32)
                return tuple(key_outs) + tuple(agg_outs) + tuple(ncnt_outs) + (pid, n_seg)
            return tuple(key_outs) + tuple(agg_outs) + tuple(ncnt_outs) + (n_seg,)

        jitted = jax.jit(raw)
        cols_spec = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in dt.flat_cols()]
        luts0 = ctx.build_luts(dt.dicts, [b.dicts for b in builds])
        luts_spec = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in luts0]
        mask_spec = jax.ShapeDtypeStruct(dt.mask.shape, np.bool_)
        builds_spec = [
            [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in b.flat_arrays()]
            for b in builds
        ]
        lowered = jitted.lower(cols_spec, luts_spec, mask_spec, builds_spec)  # trace → meta
        meta = {
            "mode": "sorted",
            "out": meta_holder["out"],
            "key_meta": meta_holder["key_meta"],
            "nullcnt_map": meta_holder.get("nullcnt_map", {}),
            "emit_pid": emit_keys is not None,
            "C": C,
        }
        return jitted, ctx, meta, lowered

    # ------------------------------------------------------------------

    def _decode_sorted(self, outs, meta: dict, P: int, dicts,
                       build_dicts: list) -> dict[int, list[pa.RecordBatch]]:
        """Decode the sorted-path compacted outputs. Partial-agg results are
        mergeable, so all segments land in output partition 0 (globally
        deduplicated across input partitions — strictly better reduction
        than per-partition partials); other partitions emit empty."""
        jax = ensure_jax()
        schema = self.schema()
        key_meta = meta["key_meta"]
        n_keys = len(key_meta)
        n_keyops = sum(2 if km[3] else 1 for km in key_meta)
        C = meta["C"]
        n = int(jax.device_get(outs[-1]))
        if n > C:
            raise Unsupported(f"group capacity overflow ({n} > {C})")
        results = {p: [_empty_batch(schema)] for p in range(P)}
        if n == 0:
            return results
        pid_out = None
        data_outs = outs[:-1]
        if meta.get("emit_pid"):
            pid_out = data_outs[-1]
            data_outs = data_outs[:-1]
        cp = min(_pow2(n), C)  # sliced fetch: pay for actual groups only
        host = jax.device_get([o[:cp] for o in data_outs])
        pid_host = jax.device_get(pid_out[:cp]) if pid_out is not None else None
        nullcnt_map = meta.get("nullcnt_map", {})
        n_aggs = len(meta["out"])
        ncnt_host = host[n_keyops + n_aggs:]
        arrays: list[pa.Array] = []
        pos = 0
        for (kind, scale, slot, has_null), f in zip(key_meta, schema):
            null_mask = None
            if has_null:
                null_mask = host[pos][:n] != 0
                pos += 1
            kv = host[pos]
            pos += 1
            vals = kv[:n]
            if kind == "code":
                # resolve the LIVE dictionary (compilations are shared across
                # tables with equal shapes; contents are per-table)
                if isinstance(slot, tuple) and slot[0] == "build":
                    dic = build_dicts[slot[1]][slot[2]]
                else:
                    dic = dicts[slot]
                py = [None if (null_mask is not None and null_mask[j]) else dic[int(c)]
                      for j, c in enumerate(vals)]
                arr = pa.array(py, f.type)
            elif kind == "date":
                arr = pa.array(vals.astype(np.int32), pa.int32(), mask=null_mask).cast(pa.date32())
            elif kind == "money":
                arr = pa.array(vals.astype(np.float64) / (10**scale), pa.float64(),
                               mask=null_mask)
            else:
                arr = pa.array(vals, mask=null_mask)
            if arr.type != f.type:
                arr = arr.cast(f.type)
            arrays.append(arr)
        for ai, (out, (kind, scale), f) in enumerate(
            zip(host[n_keyops:n_keyops + n_aggs], meta["out"], list(schema)[n_keys:])
        ):
            vals = out[:n]
            null_mask = None
            if ai in nullcnt_map:
                # all of the group's agg inputs were NULL → the agg is NULL
                null_mask = ncnt_host[nullcnt_map[ai]][:n] == 0
            if kind == "money":
                arr = pa.array(vals.astype(np.float64) / (10**scale), pa.float64(),
                               mask=null_mask)
            elif kind == "date":
                arr = pa.array(vals.astype(np.int32), pa.int32(), mask=null_mask).cast(pa.date32())
            else:
                arr = pa.array(vals, mask=null_mask)
            if arr.type != f.type:
                arr = arr.cast(f.type)
            arrays.append(arr)
        if pid_host is not None:
            # device-routed shuffle: ship the partition ids alongside; the
            # shuffle writer consumes and drops the __pid column
            arrays.append(pa.array(pid_host[:n].astype(np.int32), pa.int32()))
            out_schema = pa.schema(list(schema) + [pa.field("__pid", pa.int32())])
            self.pid_emitted += 1
            results[0] = [pa.RecordBatch.from_arrays(arrays, schema=out_schema)]
            return results
        results[0] = [pa.RecordBatch.from_arrays(arrays, schema=schema)]
        return results

    def _decode_all(self, outs: list[np.ndarray], meta: dict, P: int, dicts,
                    build_dicts: list | None = None) -> dict[int, list[pa.RecordBatch]]:
        agg = self.partial_agg
        schema = self.schema()
        group_dicts = []
        for s in meta["group_src_slots"]:
            if isinstance(s, tuple) and s[0] == "build":
                group_dicts.append(build_dicts[s[1]][s[2]])
            else:
                group_dicts.append(dicts[s])
        presence = outs[-1]  # [P, G]
        n_aggs = len(meta["out"])
        nullcnt_map = meta.get("nullcnt_map", {})
        nullcnt_outs = outs[n_aggs:-1]
        results: dict[int, list[pa.RecordBatch]] = {}
        n_group = len(agg.group_exprs)
        for p in range(P):
            sel = np.nonzero(presence[p] > 0)[0]
            if not len(sel):
                results[p] = [_empty_batch(schema)]
                continue
            arrays: list[pa.Array] = []
            gid = sel.astype(np.int64)
            comps = []
            for psz in reversed(meta["pad_sizes"]):
                comps.append(gid % psz)
                gid = gid // psz
            comps = list(reversed(comps))
            for comp, d, f in zip(comps, group_dicts, schema):
                arrays.append(pa.array([d[int(c)] for c in comp], f.type))
            for ai, (out, (kind, scale), f) in enumerate(
                zip(outs[:n_aggs], meta["out"], list(schema)[n_group:])
            ):
                vals = out[p][sel]
                null_mask = None
                if ai in nullcnt_map:
                    # all agg inputs in the group were NULL → the agg is NULL
                    null_mask = nullcnt_outs[nullcnt_map[ai]][p][sel] == 0
                if kind == "money":
                    arr = pa.array(vals.astype(np.float64) / (10**scale), pa.float64(),
                                   mask=null_mask)
                elif kind == "date":
                    arr = pa.array(vals.astype(np.int32), pa.int32(), mask=null_mask).cast(pa.date32())
                else:
                    arr = pa.array(vals, mask=null_mask)
                if arr.type != f.type:
                    arr = arr.cast(f.type)
                arrays.append(arr)
            results[p] = [pa.RecordBatch.from_arrays(arrays, schema=schema)]
        return results


def _put(mesh, arr, spec=None):
    """Place an array for stage execution: mesh-sharded/replicated under a
    mesh, plain device array otherwise. The single place that decides
    placement (memory kind, donation would go here) — which makes it the
    single place chaos hbm_oom can fault an upload."""
    hbm.maybe_chaos_oom()
    jax = ensure_jax()
    if mesh is None:
        return jax.numpy.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.device_put(arr, NamedSharding(mesh, spec if spec is not None else PartitionSpec()))


def _put_chunked(mesh, arr, spec=None, chunk_rows: int = 0):
    """Upload a [P, N] stack in row chunks along N. Each device_put is
    async, so chunk k+1's host slice is cut while chunk k streams — the
    double-buffered form of the column upload; the device-side concatenate
    reassembles the full stack in HBM where bandwidth is cheap. Mesh-sharded
    puts stay whole (GSPMD owns their layout), as do 1-D arrays and columns
    smaller than one chunk."""
    if (mesh is not None or chunk_rows <= 0 or getattr(arr, "ndim", 0) != 2
            or arr.shape[1] <= chunk_rows):
        return _put(mesh, arr, spec)
    jax = ensure_jax()
    parts = [
        jax.device_put(np.ascontiguousarray(arr[:, o:o + chunk_rows]))
        for o in range(0, arr.shape[1], chunk_rows)
    ]
    return jax.numpy.concatenate(parts, axis=1)


def _stage_mesh(config: BallistaConfig):
    """1-D mesh over the partition axis when collective exchange is on and
    more than one accelerator is visible: the stage kernel's inputs shard
    by partition and XLA/GSPMD inserts the ICI collectives (psum-style
    merges, gather for the compacted outputs) — the collective form of the
    file shuffle for co-scheduled stages (SURVEY.md §2.5 TPU-native
    equivalent). One executor process drives the whole slice."""
    from ballista_tpu.config import TPU_COLLECTIVE_EXCHANGE

    if not bool(config.get(TPU_COLLECTIVE_EXCHANGE)):
        return None
    jax = ensure_jax()
    devs = jax.devices()
    if len(devs) < 2:
        return None
    from jax.sharding import Mesh

    return Mesh(np.array(devs), ("part",))


def _segscan(jnp, values, boundary, func: str):
    """Inclusive segmented sum/min/max scan: resets at boundary rows. The
    combine is the classic segmented-scan monoid — associative, so XLA
    lowers it to a log-depth scan."""
    import jax

    op = {"min": jnp.minimum, "max": jnp.maximum, "sum": jnp.add}[func]

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, op(av, bv)), af | bf

    out, _ = jax.lax.associative_scan(combine, (values, boundary))
    return out


def _masked_reduce_w(jnp, v, gm, func: str, m_eff):
    """Weighted reduction for aggregate-through-join: each probe row stands
    in for m_eff joined rows (match count; max(count, 1) under outer)."""
    if func == "count_all":
        return jnp.where(gm, m_eff, 0).astype(jnp.int64).sum(axis=1)
    if func == "count":
        m2 = gm if (v is None or v.valid is None) else gm & v.valid
        return jnp.where(m2, m_eff, 0).astype(jnp.int64).sum(axis=1)
    if func == "sum":
        arr = v.arr
        if v.valid is not None:
            gm = gm & v.valid
        scaled = arr * m_eff.astype(arr.dtype)
        zero = jnp.zeros((), dtype=arr.dtype)
        return jnp.where(gm, scaled, zero).sum(axis=1)
    # min/max are multiplicity-invariant (w==0 rows are filtered for inner
    # joins; under outer every probe row legitimately appears)
    return _masked_reduce(jnp, v, gm, func)


def _masked_reduce(jnp, v, gm, func: str):
    """One group's reduction over axis=1 of [P, N] lanes. SQL null-skipping:
    an agg input's validity plane joins the group mask — count(x) counts
    only non-null x, sum/min/max ignore null slots."""
    if func == "count_all" or (func == "count" and (v is None or v.valid is None)):
        return gm.sum(axis=1).astype(jnp.int64)
    if func == "count":
        return (gm & v.valid).sum(axis=1).astype(jnp.int64)
    arr = v.arr
    if v.valid is not None:
        gm = gm & v.valid
    if func == "sum":
        zero = jnp.zeros((), dtype=arr.dtype)
        return jnp.where(gm, arr, zero).sum(axis=1)
    if func in ("welford_mean", "welford_m2"):
        # variance partials (physical_planner's (cnt, mean, M2) triple): the
        # true two-pass form — group mean first, then the mean-centered
        # square sum — numerically stable at f64 with no Welford recurrence
        # (which would serialize; this stays two fused VPU passes)
        c = gm.sum(axis=1)
        s = jnp.where(gm, arr, 0.0).sum(axis=1)
        mean = s / jnp.maximum(c, 1)
        if func == "welford_mean":
            return mean
        d2 = (arr - mean[:, None]) ** 2
        return jnp.where(gm, d2, 0.0).sum(axis=1)
    if func == "min":
        big = jnp.iinfo(arr.dtype).max if jnp.issubdtype(arr.dtype, jnp.integer) else jnp.inf
        return jnp.where(gm, arr, big).min(axis=1)
    if func == "max":
        small = jnp.iinfo(arr.dtype).min if jnp.issubdtype(arr.dtype, jnp.integer) else -jnp.inf
        return jnp.where(gm, arr, small).max(axis=1)
    raise Unsupported(f"agg {func}")


def _pow2(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


def _mk_col_reader(i: int, kind: str, scale: int, dictionary, valid_idx=None):
    """Column reader with device-side upcast: columns ship narrow (int16/32)
    to spare the link, then widen in HBM where bandwidth is cheap. Nullable
    columns read their validity plane from the flattened arg tail."""

    def run(cols, luts):
        import jax.numpy as jnp

        arr = cols[i]
        if kind in ("i64", "money") and arr.dtype != jnp.int64:
            arr = arr.astype(jnp.int64)
        elif kind == "code" and arr.dtype != jnp.int32:
            arr = arr.astype(jnp.int32)
        elif kind == "date" and arr.dtype != jnp.int32:
            arr = arr.astype(jnp.int32)
        valid = cols[valid_idx] if valid_idx is not None else None
        return DevVal(kind, arr, scale, dictionary, valid=valid)

    return run


def _mk_join_finder(off: int, probe_fns, bt: BuildTable, cell: dict,
                    pallas: bool = False):
    """Closure computing (clamped build index, matched mask) for one join.

    'direct' unique mode: the build shipped a dense key→row int32 table —
    ONE gather per probe (the TPU-friendly hash table: identity hash, no
    collisions by construction). 'direct' expansion mode (dup > 1): lo/cnt
    tables; the probe's match lane d (`cell["d"]`, set by the lane loop at
    trace time) selects row lo+d, matched iff d < cnt. 'sorted' mode:
    binary search over sorted keys with an int64.max tail (two searches
    when expansion). Multi-key probes combine as k1 << shift | k2 with
    device range guards mirroring the host-side guards, so out-of-range
    keys can never alias a real build key. XLA CSEs the duplicate lookups
    issued by the per-column gathers.

    `pallas=True` (direct unique mode only) routes the lookup through the
    tiled `hash_probe` kernel: table VMEM-resident, gather + match mask
    fused. Every build-column gather closure re-invokes the finder, and
    XLA does not CSE custom calls the way it CSEs gathers — so the kernel
    result is memoized per trace, keyed by the identity of the traced
    `cols` list (a strong ref pins the list so its id cannot be recycled;
    the identity check makes a stale hit impossible).
    """
    mode, shifts, dup = bt.mode, bt.shifts, bt.dup
    has_cnt = bt.cnt is not None
    b_static = bt.padded_rows()  # in shape_key, so cache hits can't go stale
    _probe_memo: dict = {}

    def run(cols, luts):
        import jax.numpy as jnp

        keys_arr = cols[off]
        valid = None
        k = None
        for i, pf in enumerate(probe_fns):
            v = pf(cols, luts)
            if v.kind not in ("i64", "date"):
                raise Unsupported(f"non-integer probe key kind {v.kind}")
            ki = v.arr.astype(jnp.int64)
            if i == 0:
                k = ki
                valid = ki >= 0
            else:
                shift = shifts[i - 1]
                valid = valid & (ki >= 0) & (ki < (1 << shift))
                k = (k << shift) | ki
            if v.valid is not None:
                valid = valid & v.valid  # a NULL probe key matches nothing
        d = cell["d"]
        if mode == "direct" and not has_cnt:
            T = keys_arr.shape[0]
            in_range = valid & (k >= 0) & (k < T)
            if pallas:
                from ballista_tpu.ops.tpu.pallas_kernels import hash_probe

                hit = _probe_memo.get(id(cols))
                if hit is None or hit[0] is not cols:
                    kq = jnp.where(in_range, k, 0).astype(jnp.int32)
                    rows, matched = hash_probe(kq, keys_arr, in_range)
                    if len(_probe_memo) > 4:
                        _probe_memo.clear()
                    hit = (cols, rows, matched)
                    _probe_memo[id(cols)] = hit
                return hit[1], DevVal("bool", hit[2])
            row = keys_arr[jnp.where(in_range, k, 0)]
            matched = in_range & (row >= 0)
            idxc = jnp.clip(row, 0, None).astype(jnp.int32)
            return idxc, DevVal("bool", matched)
        if mode == "direct":
            T = keys_arr.shape[0]
            in_range = valid & (k >= 0) & (k < T)
            kc = jnp.where(in_range, k, 0)
            lo = keys_arr[kc]
            c = cols[off + 1][kc]
            matched = in_range & (d < c)
            idxc = jnp.clip(lo + d, 0, b_static - 1).astype(jnp.int32)
            return idxc, DevVal("bool", matched)
        if dup == 1:
            idx = jnp.searchsorted(keys_arr, k)
            idxc = jnp.clip(idx, 0, keys_arr.shape[0] - 1)
            matched = (keys_arr[idxc] == k) & valid
            return idxc, DevVal("bool", matched)
        lo = jnp.searchsorted(keys_arr, k, side="left")
        hi = jnp.searchsorted(keys_arr, k, side="right")
        matched = valid & (lo + d < hi)
        idxc = jnp.clip(lo + d, 0, keys_arr.shape[0] - 1).astype(jnp.int32)
        return idxc, DevVal("bool", matched)

    return run


def _mult_shape_check(partial_agg, ops, join) -> dict | None:
    """Structural eligibility for aggregate-through-join: `join` must be the
    stage's LAST join (only pass-through projections may follow), inner or
    right with no residual filter, group keys probe-side, and every
    build-column use a bare count(col). Returns {agg index → build field
    index} (may be empty) or None if ineligible. Shared by _prepare_build
    (to exempt such joins from the dup-lane cap) and _compile (to activate
    the weight path)."""
    from ballista_tpu.plan.physical import HashJoinExec, ProjectionExec

    real_ops = [o for o in ops if not isinstance(o, CoalesceBatchesExec)]
    if join not in real_ops:
        return None
    if join.join_type not in ("inner", "right") or join.filter is not None:
        return None
    k = real_ops.index(join)
    n_build = len(join.left.df_schema)
    schema = join.df_schema
    # per current-schema field: originating build field index, or None
    build_of: list = [i if i < n_build else None for i in range(len(schema))]

    def refs_build(e) -> list[int]:
        refs: list[int] = []

        def walk(x):
            if isinstance(x, Column):
                i = schema.maybe_index_of(x.name, x.qualifier)
                if i is not None and build_of[i] is not None:
                    refs.append(build_of[i])
            for c in x.children():
                walk(c)

        walk(e)
        return refs

    for op in real_ops[k + 1:]:
        if not isinstance(op, ProjectionExec):
            return None  # a later join/filter may consume build values
        new_build: list = []
        for e in op.exprs:
            inner = e.expr if isinstance(e, Alias) else e
            if isinstance(inner, Column):
                i = schema.maybe_index_of(inner.name, inner.qualifier)
                if i is None:
                    return None
                new_build.append(build_of[i])
            else:
                if refs_build(inner):
                    return None  # computed expr over a build column
                new_build.append(None)
        schema = op.df_schema
        build_of = new_build

    for g in partial_agg.group_exprs:
        if refs_build(g.expr if isinstance(g, Alias) else g):
            return None
    out: dict[int, int] = {}
    for ai, d in enumerate(partial_agg.aggs):
        if d.expr is None:
            continue
        brefs = refs_build(d.expr)
        if not brefs:
            continue
        inner_e = d.expr.expr if isinstance(d.expr, Alias) else d.expr
        if d.func == "count" and isinstance(inner_e, Column) and len(brefs) == 1:
            out[ai] = brefs[0]
        else:
            return None
    return out


def _mk_join_counter(off: int, probe_fns, bt: BuildTable):
    """Closure computing each probe row's MATCH COUNT against the build —
    the aggregate-through-join weight. Where every build-column use in the
    stage is multiplicity-shaped (count(col), count(*), probe-side sums),
    gathering the count replaces dup-lane unrolling entirely: one gather
    instead of dup traced pipelines, and no MAX_JOIN_DUP ceiling."""
    mode, shifts = bt.mode, bt.shifts
    has_cnt = bt.cnt is not None

    def run(cols, luts):
        import jax.numpy as jnp

        keys_arr = cols[off]
        valid = None
        k = None
        for i, pf in enumerate(probe_fns):
            v = pf(cols, luts)
            if v.kind not in ("i64", "date"):
                raise Unsupported(f"non-integer probe key kind {v.kind}")
            ki = v.arr.astype(jnp.int64)
            if i == 0:
                k = ki
                valid = ki >= 0
            else:
                shift = shifts[i - 1]
                valid = valid & (ki >= 0) & (ki < (1 << shift))
                k = (k << shift) | ki
            if v.valid is not None:
                valid = valid & v.valid
        zero = jnp.zeros((), jnp.int32)
        if mode == "direct" and has_cnt:
            T = keys_arr.shape[0]
            in_range = valid & (k >= 0) & (k < T)
            kc = jnp.where(in_range, k, 0)
            return jnp.where(in_range, cols[off + 1][kc], zero)
        if mode == "direct":
            T = keys_arr.shape[0]
            in_range = valid & (k >= 0) & (k < T)
            row = keys_arr[jnp.where(in_range, k, 0)]
            return jnp.where(in_range & (row >= 0), 1, zero).astype(jnp.int32)
        lo = jnp.searchsorted(keys_arr, k, side="left")
        hi = jnp.searchsorted(keys_arr, k, side="right")
        return jnp.where(valid, (hi - lo).astype(jnp.int32), zero)

    return run


def _mk_raising(msg: str):
    def run(cols, luts):
        raise Unsupported(msg)

    return run


def _mk_build_gather(pay_off: int, ci: int, kind: str, scale: int, dictionary, finder,
                     valid_abs_idx=None, outer=False):
    """Gather one build-payload column through the join finder. Nullable
    payloads gather their validity plane too; under an outer join the gather
    of an UNMATCHED probe row is NULL (valid = matched & payload-valid)."""

    def run(cols, luts):
        import jax.numpy as jnp

        idxc, matched = finder(cols, luts)
        arr = cols[pay_off + ci][idxc]
        if kind in ("i64", "money") and arr.dtype != jnp.int64:
            arr = arr.astype(jnp.int64)
        elif kind in ("code", "date") and arr.dtype != jnp.int32:
            arr = arr.astype(jnp.int32)
        valid = cols[valid_abs_idx][idxc] if valid_abs_idx is not None else None
        if outer:
            m = true_mask(matched)
            valid = m if valid is None else valid & m
        return DevVal(kind, arr, scale, dictionary, valid=valid)

    return run


def _bind_env(ctx: Lowering, schema: DFSchema) -> None:
    """Point the Lowering at the current virtual schema: Column exprs now
    resolve through env_fns (projection rebinding) instead of raw columns."""
    ctx.schema = schema
    ctx.kinds = [
        (m[0], m[1]) if m is not None else ("?", 0) for m in ctx.env_meta
    ]
    ctx.dictionaries = [m[2] if m is not None else None for m in ctx.env_meta]
    ctx.slots = [m[3] if m is not None else -1 for m in ctx.env_meta]

    def col_index(c):
        return schema.index_of(c.name, c.qualifier)

    ctx.col_index = col_index  # type: ignore[assignment]


def _passthrough_meta(e: Expr, ctx: Lowering, schema: DFSchema):
    inner = e.expr if isinstance(e, Alias) else e
    if isinstance(inner, Column):
        i = schema.index_of(inner.name, inner.qualifier)
        return ctx.env_meta[i]
    return None
