"""Device execution for final-aggregation / sort / top-K stage shapes.

The reference engine executes EVERY stage of a query
(ballista/executor/src/execution_engine.rs:51); round 2 of this build
lowered only partial-aggregation chains to the device. This module lowers
the stage class that sits ABOVE the shuffle: merge the hash-partitioned
partial accumulators in HBM, apply the post-aggregation projections and
HAVING filters, and run ORDER BY (+ LIMIT) with one lexicographic
`lax.sort` — so a q3-class stage fetches 10 rows back to the host instead
of millions.

Stage shape handled (top-down):

    [SortExec(fetch?)]  [ProjectionExec|FilterExec]*  HashAggregateExec(final)
        [CoalesceBatchesExec|CoalescePartitionsExec]*  <child>

Execution model (same contract as TpuStageExec): the whole stage — all
partitions — runs as ONE device dispatch. Input partitions stack to a
[P, N] device layout; partition id rides as the leading sort key so
per-partition grouping and per-partition top-K happen inside a single
compiled program; the fetch returns only surviving rows. The final-mode
merge semantics mirror HashAggregateExec (plan/physical.py:535): sum/count
partials add, min/max partials re-reduce, NULL accumulators are skipped
and an all-NULL group decodes to NULL.

Fallback is runtime-adaptive like the partial path: unencodable inputs,
welford triples, capacity overflow, or tiny inputs re-run the original
CPU subtree; `match_final_stage` pre-lowers every expression at plan time
with static kinds so stages that CANNOT lower are never wrapped (the
device/fallback counters in EXPLAIN ANALYZE stay honest).
"""

from __future__ import annotations

import threading
from typing import Iterator

import numpy as np
import pyarrow as pa

from ballista_tpu.config import TPU_MAX_DEVICE_BYTES, TPU_MIN_ROWS, BallistaConfig, _env_int
from ballista_tpu.ops.tpu.columnar import encode_column, next_bucket
from ballista_tpu.ops.tpu.stage_compiler import LruDict
from ballista_tpu.ops.tpu.kernels import DevVal, Lowering, Unsupported, lower_expr, true_mask
from ballista_tpu.ops.tpu.runtime import ensure_jax
from ballista_tpu.plan.expressions import Alias, Column, SortKey
from ballista_tpu.plan.physical import (
    CoalesceBatchesExec,
    CoalescePartitionsExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    ProjectionExec,
    SortExec,
    TaskContext,
    _concat,
    _empty_batch,
)

MAX_CAPACITY = 1 << 22

# bounded: long-lived executors see one entry per (stage fingerprint, shape)
# and would otherwise grow without limit (stage_compiler's LruDict is
# import-safe here: stage_compiler only imports this module lazily)
_FINAL_COMPILE_CACHE = LruDict(_env_int("BALLISTA_TPU_FINAL_CACHE_ENTRIES", 64))
_FINAL_COMPILE_LOCK = threading.Lock()


def clear_compile_cache() -> None:
    with _FINAL_COMPILE_LOCK:
        _FINAL_COMPILE_CACHE.clear()


def match_final_stage(node: ExecutionPlan):
    """Match the final-stage shape rooted at `node`; return
    (sort, post_ops top-down, agg, child, coalesce) or None. Conservative:
    only matches when every expression trial-lowers with static kinds, so a
    wrapped stage falls back only on genuinely runtime conditions."""
    sort = None
    cur = node
    if isinstance(cur, SortExec):
        sort = cur
        cur = cur.input
    post_ops: list[ExecutionPlan] = []
    while isinstance(cur, (ProjectionExec, FilterExec, CoalesceBatchesExec)):
        post_ops.append(cur)
        cur = cur.children()[0]
    if not isinstance(cur, HashAggregateExec) or cur.mode != "final":
        return None
    agg = cur
    if not agg.group_exprs:
        # global merges are a handful of rows — nothing for the device
        return None
    child = agg.input
    coalesce = False
    while isinstance(child, (CoalesceBatchesExec, CoalescePartitionsExec)):
        if isinstance(child, CoalescePartitionsExec):
            coalesce = True
        child = child.children()[0]
    if not _trial_lowerable(sort, post_ops, agg):
        return None
    return sort, post_ops, agg, child, coalesce


def _static_kind(t: pa.DataType):
    """Conservative (kind, scale) for trial lowering from an Arrow type.
    float64 is guessed f64 — the money refinement only changes arithmetic
    scales at runtime, never lowerability."""
    if pa.types.is_integer(t):
        return ("i64", 0)
    if pa.types.is_date(t):
        return ("date", 0)
    if pa.types.is_boolean(t):
        return ("bool", 0)
    if pa.types.is_floating(t):
        return ("f64", 0)
    if pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_dictionary(t):
        return ("code", 0)
    return None


def _lower_chain(ctx: Lowering, sort, post_ops):
    """The ONE lowering walk shared by the plan-time matcher and the
    runtime compiler (so they cannot drift): rebinds the env through
    projections, collects filter predicates, and lowers the sort keys with
    their code→lexicographic-rank LUTs. Raises Unsupported when any piece
    cannot lower. Returns (keep_fns, sort_specs)."""
    from ballista_tpu.ops.tpu.stage_compiler import _bind_env, _passthrough_meta

    cur_schema = ctx.schema
    keep_fns: list = []
    for op in reversed(post_ops):
        if isinstance(op, ProjectionExec):
            new_fns, new_meta = [], []
            for e in op.exprs:
                new_fns.append(lower_expr(e, ctx))
                new_meta.append(_passthrough_meta(e, ctx, cur_schema))
            ctx.env_fns, ctx.env_meta = new_fns, new_meta
            cur_schema = op.df_schema
            _bind_env(ctx, cur_schema)
        elif isinstance(op, FilterExec):
            keep_fns.append(lower_expr(op.predicate, ctx))
        # CoalesceBatchesExec: no-op

    sort_specs: list = []  # (fn, ascending, nulls_first, rank_lut_idx|None)
    if sort is not None:
        for k in sort.keys:
            kf = lower_expr(k.expr, ctx)
            m = _passthrough_meta(k.expr, ctx, cur_schema)
            lut_idx = None
            if m is not None and m[0] == "code":
                # dictionary codes are appearance-ordered, not collated:
                # sort through a host-built code→lexicographic-rank LUT
                if m[3] is None or not isinstance(m[3], int) or m[3] < 0:
                    raise Unsupported("string sort key without a slot")

                def rank_builder(dic):
                    ranks = np.zeros(max(len(dic or []), 1), dtype=np.int32)
                    if dic:
                        order = sorted(range(len(dic)), key=lambda j: dic[j])
                        for r, j in enumerate(order):
                            ranks[j] = r
                    return ranks

                lut_idx = ctx.add_lut(m[3], rank_builder)
            sort_specs.append((kf, k.ascending, k.nulls_first, lut_idx))
    return keep_fns, sort_specs


def _trial_lowerable(sort, post_ops, agg) -> bool:
    """Dry-run the shared lowering walk with static kinds. Lowered closures
    are never CALLED, so dummy readers suffice; Unsupported → False."""
    for d in agg.aggs:
        if d.func not in ("sum", "min", "max", "count", "count_all"):
            return False  # welford triples merge on cpu (round-3 scope)
    kinds: list = []
    for f in agg.df_schema:
        k = _static_kind(f.dtype)
        if k is None:
            return False
        # float group keys are allowed statically: TPC money columns refine
        # to exact scaled-int "money" at encode time; a key that stays true
        # f64 is rejected at runtime (falls back, honestly counted)
        kinds.append(k)
    try:
        ctx = Lowering(agg.df_schema, kinds, [[] if k[0] == "code" else None for k in kinds])
        ctx.env_fns = [lambda cols, luts: None] * len(kinds)
        ctx.env_meta = [
            (k[0], k[1], [] if k[0] == "code" else None, i) for i, k in enumerate(kinds)
        ]
        from ballista_tpu.ops.tpu.stage_compiler import _bind_env

        _bind_env(ctx, agg.df_schema)
        _lower_chain(ctx, sort, post_ops)
    except Unsupported:
        return False
    return True


class TpuFinalStageExec(ExecutionPlan):
    """One-dispatch device execution of a final-agg/sort stage (see module
    docstring). Counters (device_runs / cpu_fallbacks) surface in EXPLAIN
    ANALYZE exactly like TpuStageExec's."""

    def __init__(self, sort, post_ops: list, agg: HashAggregateExec,
                 child: ExecutionPlan, config: BallistaConfig, coalesce: bool = False):
        top = sort if sort is not None else (post_ops[0] if post_ops else agg)
        super().__init__(top.df_schema)
        self.sort = sort
        self.post_ops = post_ops  # top-down Projection/Filter/CoalesceBatches
        self.agg = agg
        self.child = child
        self.config = config
        self.coalesce = coalesce  # True: all input partitions merge into one
        self.min_rows = int(config.get(TPU_MIN_ROWS))
        self.buckets = config.shape_buckets()
        self.tpu_count = 0
        self.fallback_count = 0
        self._results: dict[int, list[pa.RecordBatch]] | None = None
        self._results_lock = threading.Lock()
        self._device_ok = False
        # child output materialized by a device attempt that then declined:
        # (tables, child df_schema, merged?) — the CPU fallback aggregates
        # THESE instead of re-executing the whole child subtree
        self._mat_input: tuple | None = None
        self._mat_node = None
        # fallback partitions already served off the materialized copy; once
        # every expected partition has been read the copy is dropped (it can
        # pin the stage's whole input on the host otherwise)
        self._mat_served: set[int] = set()
        self._mat_released_merged = False
        # partitions served since the last (re-)dispatch — see
        # _note_served_locked for the re-run retention bound
        self._served_since_dispatch: set[int] = set()
        parts = [op.node_str() for op in ([sort] if sort else []) + post_ops]
        self.fingerprint = "|".join(
            parts + [agg.node_str(), repr(agg.input.df_schema), f"coalesce={coalesce}"]
        )

    def children(self) -> list[ExecutionPlan]:
        return [self.child]

    def with_children(self, c):
        return TpuFinalStageExec(self.sort, self.post_ops, self.agg, c[0],
                                 self.config, self.coalesce)

    def output_partition_count(self) -> int:
        return 1 if self.coalesce else self.child.output_partition_count()

    def node_str(self) -> str:
        extra = ""
        if self.tpu_count or self.fallback_count:
            extra = f" device_runs={self.tpu_count} cpu_fallbacks={self.fallback_count}"
        s = f" sort={self.sort.node_str()}" if self.sort is not None else ""
        return (f"TpuFinalStageExec: [{self.agg.node_str()}]"
                f" post_ops={len(self.post_ops)}{s}{extra}")

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(iter(self._run(partition, ctx)))

    # ------------------------------------------------------------------

    def _run(self, partition: int, ctx: TaskContext) -> list[pa.RecordBatch]:
        import logging

        from ballista_tpu.ops.tpu.runtime import device_scope

        with self._results_lock:
            if self._results is None:
                # protected-surface routing (docs/device_daemon.md): ship
                # the whole final-merge stage to the warm daemon first; the
                # route's failure domain (crash retry, poison quarantine)
                # demotes to the local attempt below by returning None
                routed = self._daemon_run_all(ctx)
                if routed is not None:
                    self._results = routed
                    self.tpu_count += 1
                    self._device_ok = True
                    self._mat_input = None
            if self._results is None:
                try:
                    with device_scope(ctx.device_ordinal):
                        self._results = self._tpu_run_all(ctx)
                    self.tpu_count += 1
                    self._device_ok = True
                    self._mat_input = None  # success: release the host copy
                except Unsupported as e:
                    logging.getLogger(__name__).info(
                        "tpu final-stage fallback (%s): %s", e, self.agg.node_str())
                    self._results = {}
                except Exception as e:  # noqa: BLE001 — classified below
                    self._results = {}
                    from ballista_tpu.config import TPU_HBM_SPILL_ENABLED
                    from ballista_tpu.ops.tpu import hbm
                    from ballista_tpu.ops.tpu import stage_compiler as _sc

                    if hbm.is_resource_exhausted(e):
                        # runtime OOM rung, final-stage edition: free the
                        # device (spilling residents to host) and retry ONCE
                        # on device before the CPU demotion — the retry
                        # re-reads the child, which the decline contract
                        # already permits (see _fallback's re-read branch)
                        logging.getLogger(__name__).warning(
                            "final stage RESOURCE_EXHAUSTED; spilling + "
                            "retrying once: %s", e)
                        spill_pool = (
                            hbm.SPILL_POOL
                            if bool(self.config.get(TPU_HBM_SPILL_ENABLED))
                            else None)
                        _sc.DEVICE_CACHE.spill_all(spill_pool)
                        hbm.note_oom(self.fingerprint)
                        hbm.consume_oom_hint(self.fingerprint)  # no grace rung here
                        try:
                            with device_scope(ctx.device_ordinal):
                                self._results = self._tpu_run_all(ctx)
                            self.tpu_count += 1
                            self._device_ok = True
                            self._mat_input = None
                            _sc.RUN_STATS.set("hbm_oom_retries",
                                              hbm.oom_retry_count())
                        except Exception:  # noqa: BLE001
                            logging.getLogger(__name__).warning(
                                "final stage OOM persisted after spill+retry; "
                                "falling back to cpu for %s",
                                self.agg.node_str(), exc_info=True)
                            self._results = {}
                    else:
                        logging.getLogger(__name__).warning(
                            "tpu final stage raised; falling back to cpu for %s",
                            self.agg.node_str(), exc_info=True,
                        )
            if partition not in self._results and self._device_ok:
                # results were already consumed (a consumer re-executed this
                # partition); caches are hot, so re-running the device path
                # costs ~one dispatch — never a host re-aggregation
                try:
                    with device_scope(ctx.device_ordinal):
                        self._results.update(self._tpu_run_all(ctx))
                    self.tpu_count += 1
                    self._mat_input = None
                    self._served_since_dispatch = set()
                    # serve WITHOUT popping: one re-dispatch covers all K
                    # re-reads of an already-consumed result
                    if partition in self._results:
                        out = list(self._results[partition])
                        self._note_served_locked(partition)
                        return out
                except Exception:  # noqa: BLE001
                    logging.getLogger(__name__).warning(
                        "tpu final-stage re-run failed; cpu fallback for %s",
                        self.agg.node_str(), exc_info=True)
                    self._device_ok = False
            if partition in self._results:
                out = self._results.pop(partition)
                self._note_served_locked(partition)
                return out
        return self._fallback(partition, ctx)

    def _note_served_locked(self, partition: int) -> None:
        """Bound re-run retention (call under _results_lock): when every
        still-resident result has been served at least once since the last
        dispatch, drop them all — they only exist for re-read convenience."""
        self._served_since_dispatch.add(partition)
        if self._results and set(self._results) <= self._served_since_dispatch:
            self._results = {}

    def _daemon_run_all(self, ctx: TaskContext):
        """Route the final-merge stage through the device daemon.
        unwrap_device_stages rebuilds the raw sort/post_ops/agg subtree
        from this wrapper (re-adding the CoalescePartitionsExec the
        matcher consumed), so the daemon re-derives the identical stage —
        byte parity and stable compile-cache keys by construction."""
        from ballista_tpu.ops.tpu import daemon_route

        return daemon_route.run_via_daemon(
            self.config,
            plan_builder=lambda: self,
            partitions=list(range(self.output_partition_count())),
            tag=daemon_route.stage_tag("final", self.fingerprint),
            fingerprint=self.fingerprint,
            est_bytes=int(getattr(self, "hbm_observed_input_bytes", 0) or 0))

    def _materialized_scan(self):
        """Build (once) a MemoryScanExec over the child output a declined
        device attempt already read, so the CPU fallback never re-executes
        the child subtree. Returns (scan, merged?) or None."""
        with self._results_lock:
            if self._mat_node is None and self._mat_input is not None:
                from ballista_tpu.plan.physical import MemoryScanExec

                tables, dfs, merged = self._mat_input
                batches = []
                for t in tables:
                    bs = t.combine_chunks().to_batches()
                    batches.append(bs[0] if bs else _empty_batch(t.schema))
                self._mat_node = (
                    MemoryScanExec(dfs, batches, partitions=len(batches)), merged)
                self._mat_input = None  # don't retain a second full copy
            return self._mat_node

    def _note_mat_served(self, partition: int, merged: bool) -> None:
        """Drop the materialized child copy once the LAST expected fallback
        partition has been served: merged/coalesced stages only ever serve
        partition 0; hash-placed stages serve every output partition."""
        with self._results_lock:
            if self._mat_node is None:
                return
            self._mat_served.add(partition)
            expected = ({0} if (merged or self.coalesce)
                        else set(range(self.output_partition_count())))
            if self._mat_served >= expected:
                self._mat_node = None
                self._mat_served.clear()
                self._mat_released_merged = merged

    def _fallback(self, partition: int, ctx: TaskContext) -> list[pa.RecordBatch]:
        self.fallback_count += 1
        mat = self._materialized_scan()
        merged_mat = False
        if mat is not None:
            node, merged = mat
            merged_mat = merged
            if merged:
                # bypass-read input is NOT hash-placed: merge globally and
                # emit on partition 0 (the device bypass contract)
                if partition != 0 and not self.coalesce:
                    return []
                node = CoalescePartitionsExec(node)
                partition = 0
            elif self.coalesce:
                node = CoalescePartitionsExec(node)
        else:
            node = self.child
            if self._mat_released_merged:
                # the merged host copy was served and released; bypass-read
                # input is not hash-placed, so a late re-read must re-merge
                # the child globally and still emit only on partition 0
                if partition != 0 and not self.coalesce:
                    return []
                node = CoalescePartitionsExec(node)
                partition = 0
            elif self.coalesce:
                node = CoalescePartitionsExec(node)
        node = self.agg.with_children([node])
        for op in reversed(self.post_ops):
            node = op.with_children([node])
        if self.sort is not None:
            node = self.sort.with_children([node])
        out = [b for b in node.execute(partition, ctx)]
        if mat is not None:
            self._note_mat_served(partition, merged_mat)
        return out

    # ------------------------------------------------------------------

    def _tpu_run_all(self, ctx: TaskContext) -> dict[int, list[pa.RecordBatch]]:
        import concurrent.futures as fut

        from ballista_tpu.ops.tpu.stage_compiler import _pow2, _put
        from ballista_tpu.plan.physical import RepartitionExec

        child = self.child
        P_result = self.output_partition_count()
        bypass = False
        if isinstance(child, RepartitionExec) and child.scheme == "hash":
            # the host hash-radix between partial and final agg is pure
            # overhead for this kernel: it re-groups globally anyway. Read
            # the repartition's input directly and emit the merged result
            # on output partition 0 (others empty) — the in-process form of
            # replacing the exchange with a device-side merge.
            #
            # CONTRACT (pinned by test_tpu_final_stage.py::
            # test_bypass_partitioning_contract): output_partition_count()
            # still advertises K, but rows do NOT follow the hash scheme —
            # they all land on partition 0. This is sound because no
            # consumer in this engine trusts declared hash placement:
            # partition-sensitive consumers (partitioned joins, repartition
            # writers) always get a FRESH RepartitionExec inserted above
            # them by the physical planner (physical_planner.py:556-558),
            # and everything else merges/concatenates partitions. A future
            # partitioning-property optimization that elides "redundant"
            # repartitions MUST exclude TpuFinalStageExec outputs.
            child = child.input
            bypass = True
        P_in = child.output_partition_count()

        # the session quota is thread-local (one-handler-thread-per-request
        # in the daemon); re-scope it on the pool threads or a daemon-routed
        # final stage would run its inner partials with no ceiling
        from ballista_tpu.ops.tpu import hbm
        quota = hbm.active_session_quota()

        def read(p):
            with hbm.session_quota(quota):
                return _concat([b for b in child.execute(p, ctx) if b.num_rows],
                               child.schema())

        with fut.ThreadPoolExecutor(max_workers=min(max(P_in, 1), 8)) as pool:
            tables = list(pool.map(read, range(P_in)))
        # from here on the child's output is in hand: any decline below must
        # aggregate THESE tables on the CPU, not re-execute the child (whose
        # device results this read just consumed — re-deriving them on the
        # host is the 100x overhead the profile pinned)
        self._mat_input = (tables, child.df_schema, bypass)
        self._mat_node = None
        part_rows = [t.num_rows for t in tables]
        total = sum(part_rows)
        if total < max(self.min_rows, 1):
            # declined BEFORE ensure_jax(): a daemon-attached client whose
            # final merge is tiny (the common shape — partials did the heavy
            # lifting device-side) never pays a platform init of its own
            raise Unsupported(f"only {total} rows (< tpu min)")
        jax = ensure_jax()

        full = pa.concat_tables(tables)
        N = next_bucket(max(max(part_rows), 1), self.buckets)
        P = len(part_rows)

        # encode first (cheap dtype/validity info), then enforce the HBM
        # budget BEFORE any host stacking or device upload: the partial
        # path's discipline (stage_compiler.py:586) — a stage the budget
        # rejects falls back cleanly instead of relying on catching a
        # device OOM that can wedge the client on real runtimes
        encoded = []
        for name in full.column_names:
            dc = encode_column(full.column(name))
            if dc is None:
                raise Unsupported(f"unencodable column {name}")
            encoded.append(dc)
        cell_bytes = P * N
        proj_bytes = cell_bytes  # [P, N] bool row mask
        for dc in encoded:
            proj_bytes += cell_bytes * dc.data.dtype.itemsize
            if dc.valid is not None:
                proj_bytes += cell_bytes  # bool validity plane
        max_bytes = int(self.config.get(TPU_MAX_DEVICE_BYTES))
        # fold the HBM admission budget into the pre-upload cap: the final
        # stage has no build side to grace-split, so the ladder here is just
        # run-whole vs CPU demotion — but the decision still lands in
        # RunStats so /api/executors sees WHY a final stage left the device
        from ballista_tpu.ops.tpu import hbm
        from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

        budget = hbm.resolve_hbm_budget(self.config)
        if budget > 0:
            max_bytes = min(max_bytes, budget)
        RUN_STATS.set("hbm_budget_bytes", budget)
        if proj_bytes > max_bytes:
            RUN_STATS.set("hbm_plan", hbm.CPU_DEMOTE)
            RUN_STATS.set(
                "hbm_plan_reason",
                f"final stage needs {proj_bytes} B > budget {max_bytes} B")
            raise Unsupported(
                f"final stage needs {proj_bytes} device bytes (> cap {max_bytes})")
        RUN_STATS.set("hbm_plan", hbm.RUN_WHOLE)
        RUN_STATS.set("hbm_plan_reason",
                      f"final stage fits: {proj_bytes} B <= {max_bytes} B")

        kinds, scales, dicts, cols_np, valids_np = [], [], [], [], []
        for dc in encoded:
            kinds.append(dc.kind)
            scales.append(dc.scale)
            dicts.append(dc.dictionary)
            stack = np.zeros((P, N), dtype=dc.data.dtype)
            off = 0
            for p, r in enumerate(part_rows):
                stack[p, :r] = dc.data[off:off + r]
                off += r
            cols_np.append(stack)
            if dc.valid is None:
                valids_np.append(None)
            else:
                vstack = np.zeros((P, N), dtype=bool)
                off = 0
                for p, r in enumerate(part_rows):
                    vstack[p, :r] = dc.valid[off:off + r]
                    off += r
                valids_np.append(vstack)
        mask_np = np.zeros((P, N), dtype=bool)
        for p, r in enumerate(part_rows):
            mask_np[p, :r] = True

        key = (
            self.fingerprint, P, N, bypass,
            tuple(zip(kinds, scales)),
            tuple(str(c.dtype) for c in cols_np),
            tuple(v is not None for v in valids_np),
            tuple(_pow2(len(d)) if d else 0 for d in dicts),
        )
        with _FINAL_COMPILE_LOCK:
            cached = _FINAL_COMPILE_CACHE.get(key)
            if cached is None:
                fn, lowering, meta = self._compile(
                    kinds, scales, dicts, valids_np, cols_np, P, N,
                    merge_all=bypass)
                # per-entry run lock: the jitted closure mutates its shared
                # trace-time `cell` dict if jax ever retraces it (e.g. jit
                # cache eviction); serializing execution of THIS entry keeps
                # any retrace single-threaded without a global choke point
                cached = (fn, lowering, meta, threading.Lock())
                _FINAL_COMPILE_CACHE[key] = cached
        fn, lowering, meta, run_lock = cached

        luts = [_put(None, l) for l in lowering.build_luts(dicts)]
        flat = [_put(None, c) for c in cols_np] + [
            _put(None, v) for v in valids_np if v is not None
        ]
        mask = _put(None, mask_np)
        with run_lock:
            outs = fn(flat, luts, mask)
        return self._decode(outs, meta, P_result, dicts)

    # ------------------------------------------------------------------

    def _compile(self, kinds, scales, dicts, valids_np, cols_np, P: int, N: int,
                 merge_all: bool = False):
        from ballista_tpu.ops.tpu.stage_compiler import _bind_env, _pow2, _segscan

        jax = ensure_jax()
        jnp = jax.numpy
        agg = self.agg
        n_group = len(agg.group_exprs)
        n_aggs = len(agg.aggs)
        if len(kinds) != n_group + n_aggs:
            raise Unsupported("final input is not [groups..., accumulators...]")
        for d in agg.aggs:
            if d.func not in ("sum", "min", "max", "count", "count_all"):
                raise Unsupported(f"final merge of {d.func}")
        for i in range(n_group):
            if kinds[i] == "f64":
                raise Unsupported("f64 group key")

        # flat-arg layout mirrors DeviceTable.flat_cols(): data cols, then
        # validity planes of nullable cols
        valid_idx: list = []
        nxt = len(cols_np)
        for v in valids_np:
            if v is None:
                valid_idx.append(None)
            else:
                valid_idx.append(nxt)
                nxt += 1

        M = P * N
        C = min(_pow2(M), MAX_CAPACITY)

        # ---- compacted-space env: post-op closures read segment results
        # from this cell, populated inside raw before they run
        cell: dict = {}

        def mk_key_reader(i):
            def run(cols, luts):
                return DevVal(kinds[i], cell["keys"][i], scales[i], dicts[i],
                              valid=cell["key_valid"][i])
            return run

        def mk_acc_reader(ai):
            def run(cols, luts):
                return DevVal(cell["acc_kind"][ai], cell["accs"][ai],
                              cell["acc_scale"][ai], None,
                              valid=cell["acc_valid"][ai])
            return run

        ctx = Lowering(agg.df_schema, list(zip(kinds, scales)), dicts)
        env_fns: list = []
        env_meta: list = []
        for i in range(n_group):
            env_fns.append(mk_key_reader(i))
            env_meta.append((kinds[i], scales[i], dicts[i], i))
        for ai, d in enumerate(agg.aggs):
            src = n_group + ai
            if d.func in ("count", "count_all"):
                k, s = "i64", 0
            else:
                k, s = kinds[src], scales[src]
            env_fns.append(mk_acc_reader(ai))
            env_meta.append((k, s, dicts[src], src))
        ctx.env_fns = env_fns
        ctx.env_meta = env_meta
        _bind_env(ctx, agg.df_schema)

        keep_fns, sort_specs = _lower_chain(ctx, self.sort, self.post_ops)
        out_fns = list(ctx.env_fns)
        out_slots = [m[3] if m is not None else None for m in ctx.env_meta]
        fetch = self.sort.fetch if self.sort is not None else None

        agg_descs = list(agg.aggs)
        coalesce = self.coalesce or merge_all
        P_out = 1 if coalesce else P
        meta_holder: dict = {}

        def raw(cols, luts, mask):
            arangeM = jnp.arange(M, dtype=jnp.int32)
            if coalesce:
                pid = jnp.zeros((M,), jnp.int32)
            else:
                pid = jnp.broadcast_to(
                    jnp.arange(P, dtype=jnp.int32)[:, None], (P, N)).reshape(-1)
            valid = mask.reshape(-1)

            def read_col(i):
                arr = cols[i]
                if kinds[i] in ("i64", "money") and arr.dtype != jnp.int64:
                    arr = arr.astype(jnp.int64)
                elif kinds[i] in ("code", "date") and arr.dtype not in (jnp.int32,):
                    arr = arr.astype(jnp.int32)
                vplane = cols[valid_idx[i]] if valid_idx[i] is not None else None
                return arr.reshape(-1), (None if vplane is None else vplane.reshape(-1))

            # ---- phase 1 sort: (invalid, pid, group keys) --------------
            keyops: list = []
            key_layout: list = []  # per group key: (marker_pos|None, value_pos)
            for i in range(n_group):
                arr, vplane = read_col(i)
                mpos = None
                if vplane is not None:
                    mpos = len(keyops)
                    keyops.append((~vplane).astype(jnp.int32))
                key_layout.append((mpos, len(keyops)))
                keyops.append(arr)
            meta_holder["key_layout"] = key_layout

            pays: list = []
            pay_plan: list = []  # per agg: (pay_idx, ncnt_idx|None)
            for ai, d in enumerate(agg_descs):
                arr, vplane = read_col(n_group + ai)
                if d.func in ("count", "count_all"):
                    # partial counts are non-null; sum them exactly
                    a = arr.astype(jnp.int64)
                    if vplane is not None:
                        a = jnp.where(vplane, a, 0)
                    pays.append(a)
                    pay_plan.append((len(pays) - 1, None))
                    continue
                ncnt_idx = None
                if vplane is not None:
                    if d.func == "sum":
                        neutral = jnp.zeros((), dtype=arr.dtype)
                    elif d.func == "min":
                        neutral = (jnp.iinfo(arr.dtype).max
                                   if jnp.issubdtype(arr.dtype, jnp.integer) else jnp.inf)
                    else:
                        neutral = (jnp.iinfo(arr.dtype).min
                                   if jnp.issubdtype(arr.dtype, jnp.integer) else -jnp.inf)
                    arr = jnp.where(vplane, arr, neutral)
                    pays.append(vplane.astype(jnp.int64))
                    ncnt_idx = len(pays) - 1
                pays.append(arr)
                pay_plan.append((len(pays) - 1, ncnt_idx))

            operands = [(~valid).astype(jnp.int32), pid] + keyops + pays
            n_sortkeys = 2 + len(keyops)
            sorted_ = jax.lax.sort(tuple(operands), num_keys=n_sortkeys)
            svalid = sorted_[0] == 0
            spid = sorted_[1]
            skeys = sorted_[2:2 + len(keyops)]
            spays = list(sorted_[2 + len(keyops):])

            diff = jnp.zeros((M,), bool).at[0].set(True)
            diff = diff | jnp.concatenate(
                [jnp.ones((1,), bool), spid[1:] != spid[:-1]])
            for k in skeys:
                diff = diff | jnp.concatenate(
                    [jnp.ones((1,), bool), k[1:] != k[:-1]])
            boundary = svalid & diff
            seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1
            bor_inv = boundary | ~svalid
            is_end = svalid & jnp.concatenate([bor_inv[1:], jnp.ones((1,), bool)])
            n_seg = boundary.sum().astype(jnp.int32)

            spos = (
                jnp.zeros((C,), jnp.int32)
                .at[jnp.where(boundary, seg, C)]
                .set(arangeM, mode="drop", unique_indices=True)
            )
            start = spos[jnp.clip(seg, 0, C - 1)]
            end_idx = jnp.where(is_end, seg, C)

            def compact(src):
                return (
                    jnp.zeros((C,), src.dtype)
                    .at[end_idx]
                    .set(src, mode="drop", unique_indices=True)
                )

            def int_segsum(sv):
                w = sv.astype(jnp.int64)
                csum = jnp.cumsum(w)
                presum = csum - w
                return compact(csum - presum[start])

            pid_c = compact(spid)
            key_vals: list = []
            key_valid: list = []
            for (mpos, vpos) in key_layout:
                key_vals.append(compact(skeys[vpos]))
                if mpos is None:
                    key_valid.append(None)
                else:
                    key_valid.append(compact(skeys[mpos]) == 0)

            accs: list = []
            acc_valid: list = []
            acc_kind: list = []
            acc_scale: list = []
            for ai, (d, (pay_idx, ncnt_idx)) in enumerate(zip(agg_descs, pay_plan)):
                sv = spays[pay_idx]
                if d.func in ("count", "count_all"):
                    accs.append(int_segsum(sv))
                    acc_valid.append(None)
                    acc_kind.append("i64")
                    acc_scale.append(0)
                    continue
                src = n_group + ai
                fname = d.func
                if fname == "sum" and jnp.issubdtype(sv.dtype, jnp.integer):
                    accs.append(int_segsum(sv))
                elif fname == "sum":
                    accs.append(compact(_segscan(jnp, sv, boundary, "sum")))
                else:
                    out = compact(_segscan(jnp, sv, boundary, fname))
                    if kinds[src] in ("i64", "money") and out.dtype != jnp.int64:
                        out = out.astype(jnp.int64)
                    accs.append(out)
                if ncnt_idx is not None:
                    acc_valid.append(int_segsum(spays[ncnt_idx]) > 0)
                else:
                    acc_valid.append(None)
                acc_kind.append(kinds[src])
                acc_scale.append(scales[src])
            cell["keys"] = key_vals
            cell["key_valid"] = key_valid
            cell["accs"] = accs
            cell["acc_valid"] = acc_valid
            cell["acc_kind"] = acc_kind
            cell["acc_scale"] = acc_scale

            arangeC = jnp.arange(C, dtype=jnp.int32)
            alive = arangeC < n_seg
            for kf in keep_fns:
                alive = alive & true_mask(kf(cols, luts))

            out_vals = [f(cols, luts) for f in out_fns]
            out_meta = []
            for v, slot in zip(out_vals, out_slots):
                if v.kind == "code" and (slot is None or not isinstance(slot, int)):
                    raise Unsupported("computed string output")
                out_meta.append((v.kind, v.scale, slot,
                                 v.valid is not None))
            meta_holder["out"] = out_meta

            # ---- phase 2 sort: (dead, pid, user keys...) + perm --------
            ops2: list = [(~alive).astype(jnp.int32), pid_c]
            for (kf, asc, nf, lut_idx) in sort_specs:
                v = kf(cols, luts)
                arr = v.arr
                if v.kind == "code":
                    if lut_idx is None:
                        raise Unsupported("unranked string sort key")
                    arr = luts[lut_idx][arr]
                if arr.dtype == jnp.bool_:
                    arr = arr.astype(jnp.int32)
                arr = jnp.broadcast_to(arr, (C,))
                if not asc:
                    arr = -arr
                if v.valid is not None:
                    marker = jnp.broadcast_to(~v.valid, (C,)).astype(jnp.int32)
                    ops2.append(-marker if nf else marker)  # nulls first → ahead
                ops2.append(arr)
            ops2.append(arangeC)
            sorted2 = jax.lax.sort(tuple(ops2), num_keys=len(ops2) - 1)
            alive_s = sorted2[0] == 0
            spid2 = sorted2[1]
            perm = sorted2[-1]

            b2 = alive_s & jnp.concatenate(
                [jnp.ones((1,), bool), spid2[1:] != spid2[:-1]])
            spos_pid = (
                jnp.zeros((P_out,), jnp.int32)
                .at[jnp.where(b2, spid2, P_out)]
                .set(arangeC, mode="drop", unique_indices=True)
            )
            rank = arangeC - spos_pid[jnp.clip(spid2, 0, P_out - 1)]
            keep_out = alive_s
            if fetch is not None:
                keep_out = keep_out & (rank < fetch)
            out_pos = jnp.cumsum(keep_out.astype(jnp.int32)) - 1
            n_out = keep_out.sum().astype(jnp.int32)
            scatter_idx = jnp.where(keep_out, out_pos, C)
            row_src = (
                jnp.zeros((C,), jnp.int32)
                .at[scatter_idx].set(perm, mode="drop", unique_indices=True)
            )
            pid_final = (
                jnp.zeros((C,), jnp.int32)
                .at[scatter_idx].set(spid2, mode="drop", unique_indices=True)
            )

            outs: list = []
            for v in out_vals:
                arr = jnp.broadcast_to(v.arr, (C,))
                outs.append(arr[row_src])
            for v in out_vals:
                if v.valid is not None:
                    outs.append(jnp.broadcast_to(v.valid, (C,))[row_src])
            return tuple(outs) + (pid_final, n_seg, n_out)

        jitted = jax.jit(raw)
        cols_spec = [jax.ShapeDtypeStruct(c.shape, c.dtype) for c in cols_np] + [
            jax.ShapeDtypeStruct(v.shape, np.bool_) for v in valids_np if v is not None
        ]
        luts0 = ctx.build_luts(dicts)
        luts_spec = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in luts0]
        mask_spec = jax.ShapeDtypeStruct((P, N), np.bool_)
        jitted.lower(cols_spec, luts_spec, mask_spec)  # trace only → meta
        meta = {
            "out": meta_holder["out"],
            "C": C,
            "P_out": P_out,
        }
        return jitted, ctx, meta

    # ------------------------------------------------------------------

    def _decode(self, outs, meta: dict, P_result: int, dicts) -> dict[int, list[pa.RecordBatch]]:
        from ballista_tpu.ops.tpu.stage_compiler import _pow2

        jax = ensure_jax()
        schema = self.schema()
        C = meta["C"]
        P_out = meta["P_out"]  # kernel pid space; ≤ P_result under bypass
        n_seg, n_out = (int(x) for x in jax.device_get(outs[-2:]))
        if n_seg > C:
            raise Unsupported(f"group capacity overflow ({n_seg} > {C})")
        if self.sort is not None and self.sort.fetch is not None:
            from ballista_tpu.ops.tpu.sort_window import _count

            _count("topk_rows_kept", n_out)
        results = {p: [_empty_batch(schema)] for p in range(P_result)}
        if n_out == 0:
            return results
        cp = min(_pow2(n_out), C)
        data = jax.device_get([o[:cp] for o in outs[:-2]])
        out_meta = meta["out"]
        n_cols = len(out_meta)
        vals = data[:n_cols]
        valid_planes = data[n_cols:-1]
        pid = data[-1][:n_out]
        vi = 0
        arrays: list[pa.Array] = []
        for (kind, scale, slot, has_valid), f in zip(out_meta, schema):
            v = vals[len(arrays)][:n_out]
            null_mask = None
            if has_valid:
                null_mask = ~valid_planes[vi][:n_out]
                vi += 1
            if kind == "code":
                dic = dicts[slot]
                py = [None if (null_mask is not None and null_mask[j]) else dic[int(c)]
                      for j, c in enumerate(v)]
                arr = pa.array(py, f.type)
            elif kind == "date":
                arr = pa.array(v.astype(np.int32), pa.int32(),
                               mask=null_mask).cast(pa.date32())
            elif kind == "money":
                arr = pa.array(v.astype(np.float64) / (10 ** scale), pa.float64(),
                               mask=null_mask)
            elif kind == "bool":
                arr = pa.array(v.astype(bool), mask=null_mask)
            else:
                arr = pa.array(v, mask=null_mask)
            if arr.type != f.type:
                arr = arr.cast(f.type)
            arrays.append(arr)
        for p in range(P_out):
            sel = np.nonzero(pid == p)[0]
            if not len(sel):
                continue
            # np.take preserves order: rows are already (pid, sort-key) ordered
            cols_p = [a.take(pa.array(sel, pa.int32())) for a in arrays]
            results[p] = [pa.RecordBatch.from_arrays(cols_p, schema=schema)]
        return results
