"""AQE decision counters: RunStats emissions from the replanning seam.

The scheduler's adaptive rules (scheduler/aqe/) run outside any RUN_STATS
run scope, so each helper here writes straight into the merged gauges —
the same store the executor heartbeat ships to /api/executors
(executor_process._tpu_metrics). Standalone mode runs the scheduler
in-process, so these scheduler-side decisions surface in the exact same
gauge pipeline as device-side stats.

Every helper is deliberately best-effort: a stats failure must never turn
a replan into a scheduling error. Keys stay literal per function so the
stats-sync analysis pass can match emissions against the consumer list.
"""

from __future__ import annotations


def _stats():
    try:
        from ballista_tpu.ops.tpu import stage_compiler

        return stage_compiler.RUN_STATS
    except Exception:  # pragma: no cover — stats must never break scheduling
        return None


def note_skew_splits(n: int = 1) -> None:
    """Hot reduce partitions split into slice tasks at stage resolution."""
    stats = _stats()
    if stats is None:
        return
    try:
        stats.set("skew_splits", int(stats.snapshot().get("skew_splits", 0) or 0) + n)
    except Exception:
        pass


def note_coalesced_partitions(n: int) -> None:
    """Reduce partitions merged away by AQE coalescing (old count - new)."""
    stats = _stats()
    if stats is None or n <= 0:
        return
    try:
        stats.set("coalesced_partitions",
                  int(stats.snapshot().get("coalesced_partitions", 0) or 0) + n)
    except Exception:
        pass


def note_broadcast_promotion(n: int = 1) -> None:
    """Hash joins promoted to broadcast from observed build-side size."""
    stats = _stats()
    if stats is None:
        return
    try:
        stats.set("broadcast_promotions",
                  int(stats.snapshot().get("broadcast_promotions", 0) or 0) + n)
    except Exception:
        pass


def note_broadcast_demotion(n: int = 1) -> None:
    """Planned broadcasts demoted to partitioned joins (build oversized)."""
    stats = _stats()
    if stats is None:
        return
    try:
        stats.set("broadcast_demotions",
                  int(stats.snapshot().get("broadcast_demotions", 0) or 0) + n)
    except Exception:
        pass


def note_mesh_replan(n: int = 1) -> None:
    """Mesh stages AQE acted on: bucket-count replan or skew demotion."""
    stats = _stats()
    if stats is None:
        return
    try:
        stats.set("aqe_mesh_replans",
                  int(stats.snapshot().get("aqe_mesh_replans", 0) or 0) + n)
    except Exception:
        pass
