"""Pallas TPU kernel family for the fused stage-execution hot path.

Two kernels back `fusion_mode=fused_pallas` in stage_compiler.py:

- `masked_group_reduce`: per-(partition, group) masked (sum, count) over
  [P, N] value lanes. The per-group reduction is VECTORIZED inside the
  kernel as a one-hot matmul — each row block builds a [block_n, 128]
  one-hot membership tile (group id == lane, AND the stage mask) and a
  single `jnp.dot` yields all 128 group sums at once on the MXU, instead
  of the old O(G) static Python unroll that emitted two VPU reductions
  per group. Group domains beyond one 128-lane tile run on a multi-tile
  grid axis (G up to MAX_GROUPS), so compile time and kernel size no
  longer grow linearly with the group count.
- `hash_probe`: tiled direct-mode join probe. The build side's dense
  key→row int32 table stays VMEM-resident per block while probe-key
  blocks stream through; the gather and the downstream predicate mask
  (in-range AND probe-valid AND row-present) fuse into one kernel so the
  match mask never round-trips through HBM.

Grid = (partition, [group tile,] row block); reduction outputs are
revisited across row blocks and accumulated in place (the standard
Pallas reduction pattern, pallas_guide.md).

Scope follows TPU arithmetic reality: f32 sums + i32 counts (the VPU's
native widths). The exact int64-cents money path stays on the XLA
reduction. Mode selection lives in ops/tpu/fusion.py (cost model); on
CPU backends both kernels run in interpreter mode so tier-1 tests cover
the exact same code path.
"""

from __future__ import annotations

import functools

GROUP_LANES = 128  # output tile width (one VPU lane row)
MAX_GROUP_TILES = 32
MAX_GROUPS = GROUP_LANES * MAX_GROUP_TILES  # multi-tile grid ceiling


def _on_cpu() -> bool:
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()
    try:
        return jax.devices()[0].platform == "cpu"
    except Exception:  # noqa: BLE001
        return True


@functools.lru_cache(maxsize=32)
def _build_group_reduce(P: int, N: int, block_n: int, G: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_tiles = -(-G // GROUP_LANES)

    def kernel(vals_ref, gid_ref, mask_ref, sums_ref, cnts_ref):
        gt = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            sums_ref[...] = jnp.zeros_like(sums_ref)
            cnts_ref[...] = jnp.zeros_like(cnts_ref)

        v = vals_ref[...]  # [1, block_n]
        g = gid_ref[0, :]
        m = mask_ref[0, :] != 0
        # one-hot membership tile for this kernel's 128 group lanes:
        # [block_n, GROUP_LANES], mask folded in — ONE matmul then computes
        # every lane's masked sum (MXU), no per-group unroll
        lanes = gt * GROUP_LANES + jax.lax.broadcasted_iota(
            jnp.int32, (1, GROUP_LANES), 1
        )
        oh = ((g[:, None] == lanes) & m[:, None]).astype(jnp.float32)
        sums_ref[...] += jnp.dot(v, oh, preferred_element_type=jnp.float32)
        ones = jnp.ones((1, block_n), jnp.float32)
        # block_n ≤ 2048 < 2^24: per-block f32 counts are exact
        cnts_ref[...] += jnp.dot(
            ones, oh, preferred_element_type=jnp.float32
        ).astype(jnp.int32)

    grid = (P, n_tiles, N // block_n)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n), lambda i, gt, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, gt, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, gt, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, GROUP_LANES), lambda i, gt, j: (i, gt)),
            pl.BlockSpec((1, GROUP_LANES), lambda i, gt, j: (i, gt)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((P, n_tiles * GROUP_LANES), jnp.float32),
            jax.ShapeDtypeStruct((P, n_tiles * GROUP_LANES), jnp.int32),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def masked_group_reduce(vals, gid, mask, num_groups: int, block_n: int = 2048):
    """Per-(partition, group) masked (sum, count) over [P, N] lanes.

    vals: f32 [P, N]; gid: i32 [P, N]; mask: bool [P, N].
    Returns (sums f32 [P, G], counts i32 [P, G]).
    """
    import jax.numpy as jnp

    if num_groups > MAX_GROUPS:
        raise ValueError(f"num_groups {num_groups} > {MAX_GROUPS}")
    P, N = vals.shape
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    fn = _build_group_reduce(P, N, bn, num_groups, interpret=_on_cpu())
    sums, cnts = fn(
        vals.astype(jnp.float32), gid.astype(jnp.int32), mask.astype(jnp.int32)
    )
    return sums[:, :num_groups], cnts[:, :num_groups]


@functools.lru_cache(maxsize=32)
def _build_hash_probe(P: int, N: int, block_n: int, T: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(keys_ref, mask_ref, table_ref, row_ref, match_ref):
        k = keys_ref[0, :]
        m = mask_ref[0, :] != 0
        table = table_ref[...]  # full [T] lookup table, VMEM-resident
        rows = table[k]
        matched = m & (rows >= 0)
        # fused downstream predicate mask: unmatched probes clamp to row 0
        # (the gather index contract of the XLA finder, bit-for-bit)
        row_ref[0, :] = jnp.where(matched, rows, 0)
        match_ref[0, :] = matched.astype(jnp.int8)

    grid = (P, N // block_n)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((T,), lambda i, j: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((P, N), jnp.int32),
            jax.ShapeDtypeStruct((P, N), jnp.int8),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def hash_probe(keys, table, mask, block_n: int = 2048):
    """Direct-mode join probe: rows = table[keys], fused with the probe
    predicate mask.

    keys: i32 [P, N], pre-clamped into [0, T); table: i32 [T] (key → build
    row, -1 absent); mask: bool [P, N] (in-range AND probe-key-valid).
    Returns (rows i32 [P, N] — 0 where unmatched, matching the XLA
    finder's clamped gather index — and matched bool [P, N]).
    """
    import jax.numpy as jnp

    P, N = keys.shape
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    fn = _build_hash_probe(P, N, bn, int(table.shape[0]), interpret=_on_cpu())
    rows, matched = fn(
        keys.astype(jnp.int32), mask.astype(jnp.int32), table.astype(jnp.int32)
    )
    return rows, matched != 0
