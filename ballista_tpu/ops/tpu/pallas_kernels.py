"""Pallas TPU kernels for the hot aggregation op.

`masked_group_reduce`: the fused form of the unrolled aggregation path in
stage_compiler.py — one pass over each [P, N] value lane computing ALL G
per-group masked sums and counts from VMEM tiles, instead of materializing
G masked copies for XLA to reduce. Grid = (partition, row-block); output
blocks are revisited across row-blocks and accumulated in place (the
standard Pallas reduction pattern, pallas_guide.md).

Scope follows TPU arithmetic reality: f32 sums + i32 counts (the VPU's
native widths). The exact int64-cents money path stays on the XLA
reduction; this kernel serves float aggregates and the lossy
`ballista.tpu.allow.f32.money` mode. Gated by
`ballista.tpu.pallas.enabled`; on CPU backends the kernel runs in
interpreter mode so tests cover the exact same code path.
"""

from __future__ import annotations

import functools

GROUP_LANES = 128  # output tile width (one VPU lane row); G must fit


def _on_cpu() -> bool:
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()
    try:
        return jax.devices()[0].platform == "cpu"
    except Exception:  # noqa: BLE001
        return True


@functools.lru_cache(maxsize=32)
def _build(P: int, N: int, block_n: int, G: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(vals_ref, gid_ref, mask_ref, sums_ref, cnts_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            sums_ref[...] = jnp.zeros_like(sums_ref)
            cnts_ref[...] = jnp.zeros_like(cnts_ref)

        v = vals_ref[0, :]
        g = gid_ref[0, :]
        m = mask_ref[0, :] != 0
        # static unroll over groups: each iteration is one VPU masked
        # reduction; XLA-in-pallas fuses the compares with the sums
        sums = jnp.stack(
            [jnp.sum(jnp.where(m & (g == gg), v, 0.0)) for gg in range(G)]
        )
        cnts = jnp.stack(
            [jnp.sum((m & (g == gg)).astype(jnp.int32)) for gg in range(G)]
        )
        pad = GROUP_LANES - G
        sums_ref[0, :] += jnp.pad(sums, (0, pad))
        cnts_ref[0, :] += jnp.pad(cnts, (0, pad))

    grid = (P, N // block_n)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, GROUP_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((1, GROUP_LANES), lambda i, j: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((P, GROUP_LANES), jnp.float32),
            jax.ShapeDtypeStruct((P, GROUP_LANES), jnp.int32),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def masked_group_reduce(vals, gid, mask, num_groups: int, block_n: int = 2048):
    """Per-(partition, group) masked (sum, count) over [P, N] lanes.

    vals: f32 [P, N]; gid: i32 [P, N]; mask: bool [P, N].
    Returns (sums f32 [P, G], counts i32 [P, G]).
    """
    import jax.numpy as jnp

    if num_groups > GROUP_LANES:
        raise ValueError(f"num_groups {num_groups} > {GROUP_LANES}")
    P, N = vals.shape
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    fn = _build(P, N, bn, num_groups, interpret=_on_cpu())
    sums, cnts = fn(
        vals.astype(jnp.float32), gid.astype(jnp.int32), mask.astype(jnp.int32)
    )
    return sums[:, :num_groups], cnts[:, :num_groups]
