"""Pallas TPU kernel family for the fused stage-execution hot path.

Four kernel groups back `fusion_mode=fused_pallas`:

- `masked_group_reduce`: per-(partition, group) masked (sum, count) over
  [P, N] value lanes. The per-group reduction is VECTORIZED inside the
  kernel as a one-hot matmul — each row block builds a [block_n, 128]
  one-hot membership tile (group id == lane, AND the stage mask) and a
  single `jnp.dot` yields all 128 group sums at once on the MXU, instead
  of the old O(G) static Python unroll that emitted two VPU reductions
  per group. Group domains beyond one 128-lane tile run on a multi-tile
  grid axis (G up to MAX_GROUPS), so compile time and kernel size no
  longer grow linearly with the group count.
- `hash_probe`: tiled direct-mode join probe. The build side's dense
  key→row int32 table stays VMEM-resident per block while probe-key
  blocks stream through; the gather and the downstream predicate mask
  (in-range AND probe-valid AND row-present) fuse into one kernel so the
  match mask never round-trips through HBM.

- `segmented_sort` / `topk_select`: the ORDER BY family over the int64
  lane encoding (ints/dates widened, floats bit-twiddled order-preserving,
  strings as lexicographic-rank dictionary codes, validity as a leading
  null-rank operand). Each [P, N] row sorts independently with a bitonic
  network expressed as static reshape + compare-exchange passes (no
  gathers), over the lexicographic triple (key, tiebreak, position) — the
  position operand makes the network's output identical to a STABLE sort
  by (key, tiebreak). `topk_select` never materializes the full sort:
  chunks of C = pow2(≥k) lanes sort locally, then pairs fold with the
  elementwise-min bitonic trick (keep the C smallest of 2C, re-merge),
  log2(N/C) rounds down to one sorted chunk.
- `segmented_scan`: inclusive segmented sum/min/max over [P, N] lanes with
  boundary resets — the window-aggregate primitive (Hillis-Steele with
  flag propagation, log2(N) shift passes).
- `dict_filter`: string predicates (eq / prefix / LIKE-literal) as a
  VMEM-resident boolean LUT gather over dictionary codes, fused with the
  incoming predicate mask — the hash_probe pattern applied to the host-
  compiled predicate LUTs.

Grid = (partition, [group tile,] row block); reduction outputs are
revisited across row blocks and accumulated in place (the standard
Pallas reduction pattern, pallas_guide.md).

Scope follows TPU arithmetic reality: f32 sums + i32 counts (the VPU's
native widths). The exact int64-cents money path stays on the XLA
reduction. Mode selection lives in ops/tpu/fusion.py (cost model); on
CPU backends both kernels run in interpreter mode so tier-1 tests cover
the exact same code path.
"""

from __future__ import annotations

import functools

GROUP_LANES = 128  # output tile width (one VPU lane row)
MAX_GROUP_TILES = 32
MAX_GROUPS = GROUP_LANES * MAX_GROUP_TILES  # multi-tile grid ceiling


def _on_cpu() -> bool:
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()
    try:
        return jax.devices()[0].platform == "cpu"
    except Exception:  # noqa: BLE001
        return True


@functools.lru_cache(maxsize=32)
def _build_group_reduce(P: int, N: int, block_n: int, G: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_tiles = -(-G // GROUP_LANES)

    def kernel(vals_ref, gid_ref, mask_ref, sums_ref, cnts_ref):
        gt = pl.program_id(1)
        j = pl.program_id(2)

        @pl.when(j == 0)
        def _init():
            sums_ref[...] = jnp.zeros_like(sums_ref)
            cnts_ref[...] = jnp.zeros_like(cnts_ref)

        v = vals_ref[...]  # [1, block_n]
        g = gid_ref[0, :]
        m = mask_ref[0, :] != 0
        # one-hot membership tile for this kernel's 128 group lanes:
        # [block_n, GROUP_LANES], mask folded in — ONE matmul then computes
        # every lane's masked sum (MXU), no per-group unroll
        lanes = gt * GROUP_LANES + jax.lax.broadcasted_iota(
            jnp.int32, (1, GROUP_LANES), 1
        )
        oh = ((g[:, None] == lanes) & m[:, None]).astype(jnp.float32)
        sums_ref[...] += jnp.dot(v, oh, preferred_element_type=jnp.float32)
        ones = jnp.ones((1, block_n), jnp.float32)
        # block_n ≤ 2048 < 2^24: per-block f32 counts are exact
        cnts_ref[...] += jnp.dot(
            ones, oh, preferred_element_type=jnp.float32
        ).astype(jnp.int32)

    grid = (P, n_tiles, N // block_n)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n), lambda i, gt, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, gt, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, gt, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, GROUP_LANES), lambda i, gt, j: (i, gt)),
            pl.BlockSpec((1, GROUP_LANES), lambda i, gt, j: (i, gt)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((P, n_tiles * GROUP_LANES), jnp.float32),
            jax.ShapeDtypeStruct((P, n_tiles * GROUP_LANES), jnp.int32),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def masked_group_reduce(vals, gid, mask, num_groups: int, block_n: int = 2048):
    """Per-(partition, group) masked (sum, count) over [P, N] lanes.

    vals: f32 [P, N]; gid: i32 [P, N]; mask: bool [P, N].
    Returns (sums f32 [P, G], counts i32 [P, G]).
    """
    import jax.numpy as jnp

    if num_groups > MAX_GROUPS:
        raise ValueError(f"num_groups {num_groups} > {MAX_GROUPS}")
    P, N = vals.shape
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    fn = _build_group_reduce(P, N, bn, num_groups, interpret=_on_cpu())
    sums, cnts = fn(
        vals.astype(jnp.float32), gid.astype(jnp.int32), mask.astype(jnp.int32)
    )
    return sums[:, :num_groups], cnts[:, :num_groups]


@functools.lru_cache(maxsize=32)
def _build_hash_probe(P: int, N: int, block_n: int, T: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(keys_ref, mask_ref, table_ref, row_ref, match_ref):
        k = keys_ref[0, :]
        m = mask_ref[0, :] != 0
        table = table_ref[...]  # full [T] lookup table, VMEM-resident
        rows = table[k]
        matched = m & (rows >= 0)
        # fused downstream predicate mask: unmatched probes clamp to row 0
        # (the gather index contract of the XLA finder, bit-for-bit)
        row_ref[0, :] = jnp.where(matched, rows, 0)
        match_ref[0, :] = matched.astype(jnp.int8)

    grid = (P, N // block_n)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((T,), lambda i, j: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((P, N), jnp.int32),
            jax.ShapeDtypeStruct((P, N), jnp.int8),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def hash_probe(keys, table, mask, block_n: int = 2048):
    """Direct-mode join probe: rows = table[keys], fused with the probe
    predicate mask.

    keys: i32 [P, N], pre-clamped into [0, T); table: i32 [T] (key → build
    row, -1 absent); mask: bool [P, N] (in-range AND probe-key-valid).
    Returns (rows i32 [P, N] — 0 where unmatched, matching the XLA
    finder's clamped gather index — and matched bool [P, N]).
    """
    import jax.numpy as jnp

    P, N = keys.shape
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    fn = _build_hash_probe(P, N, bn, int(table.shape[0]), interpret=_on_cpu())
    rows, matched = fn(
        keys.astype(jnp.int32), mask.astype(jnp.int32), table.astype(jnp.int32)
    )
    return rows, matched != 0


# ---------------------------------------------------------------------------
# segmented sort / top-k (ORDER BY family)
# ---------------------------------------------------------------------------

MAX_SORT_LANES = 1 << 20  # absolute ceiling; the cost model caps lower


def _cx3(jnp, lax, a, b, p, k: int, j: int):
    """One bitonic compare-exchange pass over the last axis (length n,
    pow2) of the lexicographic triple (a, b, p). Partner pairs at XOR
    distance j are materialized by a reshape to [..., n/(2j), 2, j] — no
    gathers, so the pass is pure VPU select traffic. Direction follows the
    classic (index & k) == 0 rule; with k == n this is the all-ascending
    merge of a bitonic sequence."""
    sh = a.shape
    n = sh[-1]
    m = n // (2 * j)
    s3 = sh[:-1] + (m, 2, j)
    a3, b3, p3 = a.reshape(s3), b.reshape(s3), p.reshape(s3)
    la, ha = a3[..., 0, :], a3[..., 1, :]
    lb, hb = b3[..., 0, :], b3[..., 1, :]
    lp, hp = p3[..., 0, :], p3[..., 1, :]
    blk = lax.broadcasted_iota(jnp.int32, (m, j), 0)
    up = ((blk * (2 * j)) & k) == 0
    gt = (la > ha) | ((la == ha) & ((lb > hb) | ((lb == hb) & (lp > hp))))
    sw = jnp.where(up, gt, ~gt)

    def put(lo, hi):
        return jnp.stack([jnp.where(sw, hi, lo), jnp.where(sw, lo, hi)],
                         axis=-2).reshape(sh)

    return put(la, ha), put(lb, hb), put(lp, hp)


def _bitonic_sort3(jnp, lax, a, b, p):
    """Full bitonic sort of each last-axis row, ascending by (a, b, p)."""
    n = a.shape[-1]
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            a, b, p = _cx3(jnp, lax, a, b, p, k, j)
            j //= 2
        k *= 2
    return a, b, p


@functools.lru_cache(maxsize=32)
def _build_segmented_sort(P: int, N: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    def kernel(a_ref, b_ref, p_ref, oa_ref, ob_ref, op_ref):
        a, b, p = a_ref[0, :], b_ref[0, :], p_ref[0, :]
        a, b, p = _bitonic_sort3(jnp, lax, a, b, p)
        oa_ref[0, :] = a
        ob_ref[0, :] = b
        op_ref[0, :] = p

    spec = pl.BlockSpec((1, N), lambda i: (i, 0))
    fn = pl.pallas_call(
        kernel,
        grid=(P,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec, spec),
        out_shape=(
            jax.ShapeDtypeStruct((P, N), jnp.int64),
            jax.ShapeDtypeStruct((P, N), jnp.int64),
            jax.ShapeDtypeStruct((P, N), jnp.int32),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def segmented_sort(a, b, pos):
    """Sort each row of [P, N] ascending by the triple (a, b, pos).

    a, b: i64 lanes (b is the tiebreak operand — zeros for single-key
    sorts, the null-rank plane for nullable keys); pos: i32 original
    positions. N must be a power of two; pad with (i64 max, i64 max,
    i32 max) sentinels, which sort strictly after every real row.
    Returns the sorted triple; the permutation is the pos output.
    """
    import jax.numpy as jnp

    P, N = a.shape
    if N & (N - 1):
        raise ValueError(f"segmented_sort needs pow2 lanes, got {N}")
    fn = _build_segmented_sort(P, N, interpret=_on_cpu())
    return fn(a.astype(jnp.int64), b.astype(jnp.int64), pos.astype(jnp.int32))


@functools.lru_cache(maxsize=32)
def _build_topk(P: int, N: int, C: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    def kernel(a_ref, b_ref, p_ref, oa_ref, ob_ref, op_ref):
        nc = N // C
        a = a_ref[0, :].reshape(nc, C)
        b = b_ref[0, :].reshape(nc, C)
        p = p_ref[0, :].reshape(nc, C)
        # round 0: every C-lane chunk sorts locally (ascending)
        a, b, p = _bitonic_sort3(jnp, lax, a, b, p)
        # fold rounds: pair chunks, keep the C smallest of each 2C via the
        # elementwise-min bitonic trick, re-merge (k=C ascending merge) —
        # the full N-lane sort is never materialized
        while a.shape[0] > 1:
            ea, eb, ep = a[0::2], b[0::2], p[0::2]
            oa, ob, op = a[1::2, ::-1], b[1::2, ::-1], p[1::2, ::-1]
            lt = (ea < oa) | ((ea == oa) & ((eb < ob) | ((eb == ob) & (ep < op))))
            a = jnp.where(lt, ea, oa)
            b = jnp.where(lt, eb, ob)
            p = jnp.where(lt, ep, op)
            j = C // 2
            while j >= 1:
                a, b, p = _cx3(jnp, lax, a, b, p, C, j)
                j //= 2
        oa_ref[0, :] = a.reshape(C)
        ob_ref[0, :] = b.reshape(C)
        op_ref[0, :] = p.reshape(C)

    in_spec = pl.BlockSpec((1, N), lambda i: (i, 0))
    out_spec = pl.BlockSpec((1, C), lambda i: (i, 0))
    fn = pl.pallas_call(
        kernel,
        grid=(P,),
        in_specs=[in_spec, in_spec, in_spec],
        out_specs=(out_spec, out_spec, out_spec),
        out_shape=(
            jax.ShapeDtypeStruct((P, C), jnp.int64),
            jax.ShapeDtypeStruct((P, C), jnp.int64),
            jax.ShapeDtypeStruct((P, C), jnp.int32),
        ),
        interpret=interpret,
    )
    return jax.jit(fn)


def topk_select(a, b, pos, k: int):
    """Per-row k smallest triples of [P, N] lanes, in sorted order —
    ORDER BY ... LIMIT without the full sort. Same operand contract and
    sentinel padding as segmented_sort. Returns [P, k] triples."""
    import jax.numpy as jnp

    P, N = a.shape
    if N & (N - 1):
        raise ValueError(f"topk_select needs pow2 lanes, got {N}")
    C = 1
    while C < max(k, 1):
        C *= 2
    C = min(max(C, 128), N)  # chunk floor keeps the fold shallow
    fn = _build_topk(P, N, C, interpret=_on_cpu())
    sa, sb, sp = fn(a.astype(jnp.int64), b.astype(jnp.int64),
                    pos.astype(jnp.int32))
    return sa[:, :k], sb[:, :k], sp[:, :k]


# ---------------------------------------------------------------------------
# segmented scans (window-aggregate primitive)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _build_seg_scan(P: int, N: int, func: str, dtype_name: str, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    dtype = jnp.dtype(dtype_name)
    floating = jnp.issubdtype(dtype, jnp.floating)
    # python scalars, not jnp arrays: the kernel must not capture tracers
    if func == "sum":
        ident = 0
        op = jnp.add
    elif func == "min":
        ident = float("inf") if floating else int(jnp.iinfo(dtype).max)
        op = jnp.minimum
    else:  # max
        ident = float("-inf") if floating else int(jnp.iinfo(dtype).min)
        op = jnp.maximum

    def kernel(v_ref, f_ref, o_ref):
        v = v_ref[0, :]
        f = f_ref[0, :] != 0
        d = 1
        # Hillis-Steele with boundary-flag OR-propagation: shifted-out
        # positions read the identity under a True flag (the implicit
        # segment boundary at lane 0)
        while d < N:
            pv = jnp.concatenate([jnp.full((d,), ident, dtype), v[:-d]])
            pf = jnp.concatenate([jnp.ones((d,), jnp.bool_), f[:-d]])
            v = jnp.where(f, v, op(v, pv))
            f = f | pf
            d *= 2
        o_ref[0, :] = v

    spec = pl.BlockSpec((1, N), lambda i: (i, 0))
    fn = pl.pallas_call(
        kernel,
        grid=(P,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((P, N), dtype),
        interpret=interpret,
    )
    return jax.jit(fn)


def segmented_scan(vals, boundary, func: str):
    """Inclusive segmented sum/min/max over each [P, N] row: the scan
    resets wherever boundary is True (row 0 is an implicit boundary).
    N must be a power of two; pad the tail with boundary=True lanes."""
    import jax.numpy as jnp

    P, N = vals.shape
    if N & (N - 1):
        raise ValueError(f"segmented_scan needs pow2 lanes, got {N}")
    fn = _build_seg_scan(P, N, func, str(vals.dtype), interpret=_on_cpu())
    return fn(vals, boundary.astype(jnp.int32))


# ---------------------------------------------------------------------------
# dictionary-code string predicates
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _build_dict_filter(P: int, N: int, block_n: int, T: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(codes_ref, mask_ref, lut_ref, keep_ref):
        c = codes_ref[0, :]
        m = mask_ref[0, :] != 0
        lut = lut_ref[...]  # full [T] boolean LUT, VMEM-resident
        keep_ref[0, :] = (m & (lut[c] != 0)).astype(jnp.int8)

    grid = (P, N // block_n)
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
            pl.BlockSpec((T,), lambda i, j: (0,)),
        ],
        out_specs=pl.BlockSpec((1, block_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((P, N), jnp.int8),
        interpret=interpret,
    )
    return jax.jit(fn)


def dict_filter(codes, lut, mask, block_n: int = 2048):
    """String predicate over dictionary codes: keep = mask & lut[codes].

    codes: i32 [P, N] dictionary indices (pre-clamped into [0, T));
    lut: bool [T] host-compiled predicate truth table (eq / prefix /
    LIKE-literal evaluated per dictionary entry, pow2-padded); mask:
    bool [P, N]. Returns keep bool [P, N] — the gather and the mask
    conjunction never round-trip through HBM."""
    import jax.numpy as jnp

    P, N = codes.shape
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    fn = _build_dict_filter(P, N, bn, int(lut.shape[0]), interpret=_on_cpu())
    keep = fn(codes.astype(jnp.int32), mask.astype(jnp.int32),
              lut.astype(jnp.int8))
    return keep != 0
