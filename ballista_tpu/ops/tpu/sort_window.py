"""On-device ORDER BY / window / top-k stage family.

`maybe_compile_tpu` wraps eligible SortExec / WindowExec subtrees in
TpuSortStageExec / TpuWindowStageExec (`ballista.tpu.sort.enabled`). The
split of labor is the parity contract:

- The HOST evaluates the sort-key expressions with the exact same
  `bind_expr`/`evaluate_to_array` calls the CPU oracle sorts, encodes them
  to order-preserving int64 lanes (ints/dates widened, floats bit-twiddled,
  strings as lexicographic-rank dictionary codes, NULLS FIRST/LAST as a
  leading null-rank operand), and applies the resulting PERMUTATION with
  `pa.Table.take` — payload columns never leave the host, so the output
  bytes are the CPU engine's bytes by construction.
- The DEVICE computes only the permutation (and, for windows, the
  segmented scans): `fused_pallas` runs the bitonic `segmented_sort` /
  `topk_select` / `segmented_scan` kernels, `fused_xla` one `lax.sort`
  over all key operands, `staged` one stable `lax.sort` per key (LSD
  passes). `CostModel.choose_sort` picks per shape with the demotion
  ladder; an ineligible shape raises Unsupported and the operator falls
  back to the CPU oracle over the SAME materialized input (never
  re-executing the child).

Order-preserving int64 encoding per key kind:

  i64 / date / money / bool  value (or unscaled cents) as int64 — exact
  f64                        -0.0 canonicalized to +0.0, NaN to INT64_MAX
                             (pyarrow sorts NaN greatest), then the
                             sign-fold bit twiddle: b >= 0 → b, else
                             ~b | sign bit — total order == float order
  code                       host-ranked dictionary codes; equal strings
                             under duplicate dictionary entries share one
                             rank so ties fall through to stability
  DESC                       bitwise NOT of the ascending lane (no
                             INT64_MIN negation overflow)
  NULLS FIRST/LAST           leading operand: nulls_first → 1 - is_valid
                             complement trick below keeps nulls ahead;
                             always sorted ascending

Window aggregates keep the CPU oracle's skeleton (ops/cpu/window.py):
boundary flags and peer-last sharing are computed with the oracle's own
`_changes`/`_peer_last` over the device permutation, the per-segment
cumulative state runs as device segmented scans, and the oracle's
`_emit_agg`/`_decimal_prepare` build the output arrays — so NULL masks,
decimal reconstruction, and NaN peer-splitting are shared code, not
reimplementations.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterator, Optional

import numpy as np
import pyarrow as pa

from ballista_tpu.config import (
    BallistaConfig,
    TPU_MIN_ROWS,
    TPU_SORT_ENABLED,
    TPU_TOPK_ENABLED,
)
from ballista_tpu.ops.phys_expr import bind_expr, evaluate_to_array
from ballista_tpu.ops.tpu.columnar import encode_column
from ballista_tpu.ops.tpu.kernels import Unsupported
from ballista_tpu.ops.tpu.runtime import device_scope, ensure_jax
from ballista_tpu.plan.expressions import SortKey, WindowFunction
from ballista_tpu.plan.physical import (
    ExecutionPlan,
    TaskContext,
    _concat,
    _empty_batch,
    _sort_table,
)
from ballista_tpu.plan.schema import DFSchema

log = logging.getLogger(__name__)

_I64_MAX = (1 << 63) - 1
_I64_MIN = -(1 << 63)
_SIGN = 1 << 63

_WINDOW_DEVICE_FUNCS = ("row_number", "rank", "count", "sum", "min", "max")


# ---------------------------------------------------------------------------
# cumulative kernel counters (heartbeat gauges; the hbm spill-counter
# pattern — later clean runs must not erase earlier evidence)

_CTR_LOCK = threading.Lock()
_COUNTERS = {
    "sort_invocations": 0,
    "topk_invocations": 0,
    "window_invocations": 0,
    "topk_rows_kept": 0,
    "window_partitions": 0,
    "sort_full_materializations": 0,
}
_KERNEL_S = [0.0]


def _count(key: str, delta: int = 1) -> int:
    with _CTR_LOCK:
        _COUNTERS[key] += int(delta)
        val = _COUNTERS[key]
    _publish_counters()
    return val


def _publish_counters() -> None:
    """Mirror the cumulative counters into RUN_STATS (literal keys — the
    stats-sync pass matches emit sites by string constant)."""
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    with _CTR_LOCK:
        snap = dict(_COUNTERS)
    RUN_STATS.set("sort_invocations", snap["sort_invocations"])
    RUN_STATS.set("topk_invocations", snap["topk_invocations"])
    RUN_STATS.set("window_invocations", snap["window_invocations"])
    RUN_STATS.set("topk_rows_kept", snap["topk_rows_kept"])
    RUN_STATS.set("window_partitions", snap["window_partitions"])
    RUN_STATS.set("sort_full_materializations",
                  snap["sort_full_materializations"])


def _note_kernel_s(dt: float) -> None:
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    with _CTR_LOCK:
        _KERNEL_S[0] += dt
        val = round(_KERNEL_S[0], 4)
    RUN_STATS.set("sort_kernel_s", val)


def counters_snapshot() -> dict:
    with _CTR_LOCK:
        return dict(_COUNTERS, sort_kernel_s=round(_KERNEL_S[0], 4))


# ---------------------------------------------------------------------------
# host-side key encoding


def _dict_ranks(dictionary: list) -> np.ndarray:
    """code → lexicographic rank; duplicate dictionary values (legal in
    user-supplied dictionary arrays) share one rank so equal strings tie
    exactly like the CPU comparator and fall through to the next key."""
    if any(v is None for v in dictionary):
        raise Unsupported("null entry in sort-key dictionary")
    ranks = np.zeros(max(len(dictionary), 1), dtype=np.int64)
    order = sorted(range(len(dictionary)), key=lambda j: dictionary[j])
    r = -1
    prev = object()
    for j in order:
        if dictionary[j] != prev:
            r += 1
            prev = dictionary[j]
        ranks[j] = r
    return ranks


def _order_lane(arr: pa.Array):
    """Encode one evaluated key column as an order-preserving int64 lane.
    Returns (lane i64[n], is_valid bool[n] | None, nan bool[n] | None,
    kind)."""
    dc = encode_column(arr)
    if dc is None:
        raise Unsupported(f"unencodable sort key type {arr.type}")
    nan = None
    if dc.kind in ("i64", "date", "money"):
        lane = dc.data.astype(np.int64, copy=False)
    elif dc.kind == "bool":
        lane = dc.data.astype(np.int64)
    elif dc.kind == "code":
        lane = _dict_ranks(dc.dictionary)[dc.data.astype(np.int64, copy=False)]
    elif dc.kind == "f64":
        v = dc.data + 0.0  # canonicalize -0.0 → +0.0
        bits = v.view(np.int64)
        lane = np.where(bits >= 0, bits, (~bits) | np.int64(-_SIGN))
        nan = np.isnan(v)  # placed after the direction flip, see caller
    else:
        raise Unsupported(f"sort key kind {dc.kind}")
    return np.ascontiguousarray(lane), dc.valid, nan, dc.kind


def _encode_key_arrays(arrays: list, orders: list) -> tuple[list, list]:
    """Encode evaluated key arrays into device sort operands.

    `orders` is [(ascending, nulls_first)] per array. Returns
    (key_ops, key_meta): key_ops is [(null_rank i64[n] | None, lane
    i64[n])] to be sorted ASCENDING lexicographically with a trailing
    position tiebreak; key_meta is [(kind, nullable)] for the estimate."""
    key_ops: list = []
    key_meta: list = []
    for arr, (asc, nulls_first) in zip(arrays, orders):
        lane, valid, nan, kind = _order_lane(arr)
        if not asc:
            lane = ~lane
        if nan is not None and nan.any():
            # pyarrow sorts NaN at the END of the non-null block in BOTH
            # directions (placement, not magnitude), so the override goes
            # on top of the flipped lane. I64_MAX-1 needs float bits of a
            # NaN payload to reach → no real value collides, and it stays
            # strictly below the I64_MAX pad sentinel of the pallas rung.
            lane = np.where(nan, np.int64(_I64_MAX - 1), lane)
        nrank = None
        if valid is not None:
            is_null = (~valid).astype(np.int64)
            nrank = (1 - is_null) if nulls_first else is_null
            nrank = np.ascontiguousarray(nrank)
        key_ops.append((nrank, lane))
        key_meta.append((kind, valid is not None))
    return key_ops, key_meta


# ---------------------------------------------------------------------------
# device permutation


def _sort_cost_model(config: BallistaConfig):
    from ballista_tpu.ops.tpu import fusion

    cm = fusion.CostModel.from_config(config)
    try:
        cm.platform = ensure_jax().devices()[0].platform
    except Exception:  # noqa: BLE001
        cm.platform = "cpu"
    return cm


def _admit(est, config: BallistaConfig) -> None:
    """HBM admission for a sort/window stage: no splittable build side, so
    the ladder is run-whole vs CPU demotion, reason recorded."""
    from ballista_tpu.ops.tpu import hbm
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    budget = hbm.resolve_hbm_budget(config)
    plan = hbm.plan_stage(est, budget, grace_eligible=False, grace_fanout=2,
                          grace_max_depth=0)
    RUN_STATS.set("hbm_budget_bytes", budget)
    RUN_STATS.set("hbm_plan", plan.decision)
    RUN_STATS.set("hbm_plan_reason", plan.reason)
    if plan.decision == hbm.CPU_DEMOTE:
        raise Unsupported(f"hbm admission: {plan.reason}")


class _Uploads:
    """Tracks actual device bytes of every operand shipped for a stage, so
    the fill test can assert estimate >= actual (RUN_STATS device_bytes)."""

    def __init__(self):
        self.bytes = 0

    def put(self, arr: np.ndarray):
        jax = ensure_jax()
        self.bytes += int(arr.nbytes)
        return jax.numpy.asarray(arr)


def _perm_full(key_ops: list, n: int, mode: str, up: _Uploads) -> np.ndarray:
    """Full ordering permutation of n rows by the encoded key operands."""
    jax = ensure_jax()
    jnp = jax.numpy
    if mode == "fused_pallas":
        from ballista_tpu.ops.tpu.pallas_kernels import segmented_sort

        L = _pow2(n)
        pos = jnp.arange(L, dtype=jnp.int32)
        perm = pos
        # LSD passes, least-significant key first: the kernel's position
        # operand makes each pass a stable sort by (null rank, lane), so
        # earlier passes' order survives ties. Sentinel lanes (i64 max on
        # BOTH operands) sort strictly after every real row because real
        # null-rank operands are 0/1.
        for nrank, lane in reversed(key_ops):
            a = up.put(_pad_i64(nrank if nrank is not None else
                                np.zeros(n, np.int64), L))
            b = up.put(_pad_i64(lane, L))
            _, _, p = segmented_sort(a[perm][None, :], b[perm][None, :],
                                     pos[None, :])
            perm = perm[p[0]]
        return np.asarray(jax.device_get(perm))[:n]
    flat: list = []
    for nrank, lane in key_ops:
        if nrank is not None:
            flat.append(up.put(nrank))
        flat.append(up.put(lane))
    pos = jnp.arange(n, dtype=jnp.int32)
    up.bytes += n * 4
    if mode == "staged":
        # one stable lax.sort per key, least-significant first
        perm = pos
        i = len(flat)
        for nrank, lane in reversed(key_ops):
            w = 2 if nrank is not None else 1
            i -= w
            ops = tuple(o[perm] for o in flat[i:i + w]) + (perm,)
            perm = jax.lax.sort(ops, num_keys=w, is_stable=True)[-1]
        return np.asarray(jax.device_get(perm))
    # fused_xla: one sort over every operand; the position operand is the
    # final key, so the result is the stable lexicographic order
    res = jax.lax.sort(tuple(flat) + (pos,), num_keys=len(flat) + 1)
    return np.asarray(jax.device_get(res[-1]))


def _perm_topk(key_ops: list, n: int, k: int, up: _Uploads) -> np.ndarray:
    """First-k permutation via the fused top-k kernel (single key only;
    the full sort is never materialized)."""
    jax = ensure_jax()
    jnp = jax.numpy
    from ballista_tpu.ops.tpu.pallas_kernels import topk_select

    (nrank, lane), = key_ops
    L = _pow2(n)
    a = up.put(_pad_i64(nrank if nrank is not None else np.zeros(n, np.int64), L))
    b = up.put(_pad_i64(lane, L))
    pos = jnp.arange(L, dtype=jnp.int32)
    up.bytes += L * 4
    kk = min(int(k), n)
    _, _, sp = topk_select(a[None, :], b[None, :], pos[None, :], kk)
    return np.asarray(jax.device_get(sp[0]))[:kk]


def _pad_i64(a: np.ndarray, L: int) -> np.ndarray:
    if len(a) == L:
        return np.ascontiguousarray(a, dtype=np.int64)
    out = np.full(L, _I64_MAX, dtype=np.int64)
    out[: len(a)] = a
    return out


def _pow2(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


# ---------------------------------------------------------------------------
# static eligibility (plan-time; keeps ineligible stages unwrapped)


def _sortable_type(t: pa.DataType) -> bool:
    if (pa.types.is_integer(t) or pa.types.is_date(t) or pa.types.is_boolean(t)
            or pa.types.is_floating(t) or pa.types.is_string(t)
            or pa.types.is_large_string(t) or pa.types.is_dictionary(t)):
        return True
    if pa.types.is_decimal128(t):
        # the exact money lane; wide decimals would round through f64 and
        # could mis-order near-ties — those stay on the host comparator
        return 0 <= t.scale <= 4 and t.precision - t.scale <= 14
    return False


def sort_static_ok(keys: list, schema: DFSchema) -> bool:
    try:
        return all(_sortable_type(k.expr.data_type(schema)) for k in keys)
    except Exception:  # noqa: BLE001 — unresolvable expr: not ours to run
        return False


def _int_like(t: pa.DataType) -> bool:
    if pa.types.is_integer(t) or pa.types.is_boolean(t):
        return True
    return (pa.types.is_decimal128(t)
            and 0 <= t.scale <= 4 and t.precision - t.scale <= 14)


def window_static_ok(window_exprs: list, schema: DFSchema) -> bool:
    try:
        for w in window_exprs:
            if w.frame is not None or w.func not in _WINDOW_DEVICE_FUNCS:
                return False
            if not sort_static_ok(list(w.order_by), schema):
                return False
            if not all(_sortable_type(e.data_type(schema)) for e in w.partition_by):
                return False
            if w.func == "sum":
                # float sums take the oracle's sequential f64 cumsum; a
                # log-depth device scan would round differently — demote
                if not w.args or not _int_like(w.args[0].data_type(schema)):
                    return False
            elif w.func in ("min", "max"):
                t = w.args[0].data_type(schema) if w.args else None
                if t is None or not (_int_like(t) or pa.types.is_floating(t)):
                    return False
        return True
    except Exception:  # noqa: BLE001
        return False


# ---------------------------------------------------------------------------
# ORDER BY [LIMIT]


def _device_sort(tbl: pa.Table, df_schema: DFSchema, keys: list,
                 fetch: Optional[int], config: BallistaConfig) -> pa.Table:
    from ballista_tpu.ops.tpu import fusion
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    n = tbl.num_rows
    if n == 0:
        return tbl
    if n < max(int(config.get(TPU_MIN_ROWS)), 1):
        raise Unsupported(f"only {n} rows (< tpu min)")
    batch = tbl.combine_chunks().to_batches()[0]
    arrays = [evaluate_to_array(bind_expr(k.expr, df_schema), batch)
              for k in keys]
    orders = [(k.ascending, k.nulls_first) for k in keys]
    key_ops, key_meta = _encode_key_arrays(arrays, orders)

    topk_wanted = fetch is not None and bool(config.get(TPU_TOPK_ENABLED))
    est = fusion.estimate_sort_stage(
        n, key_meta, fetch=fetch if topk_wanted else None)
    _admit(est, config)
    cm = _sort_cost_model(config)
    dec = cm.choose_sort(est)
    RUN_STATS.set("fusion_mode", dec.mode)
    RUN_STATS.set("fusion_reason", dec.reason)

    up = _Uploads()
    t0 = time.time()
    if dec.mode == "fused_pallas" and topk_wanted:
        # choose_sort only keeps topk_k on the pallas rung when the kernel
        # can take it (single key, k under the ceiling)
        perm = _perm_topk(key_ops, n, int(fetch), up)
        _count("topk_invocations")
        _count("topk_rows_kept", len(perm))
    else:
        perm = _perm_full(key_ops, n, dec.mode, up)
        _count("sort_invocations")
        if fetch is not None:
            _count("sort_full_materializations")
    _note_kernel_s(time.time() - t0)
    RUN_STATS.set("device_bytes", up.bytes)

    out = tbl.take(pa.array(perm))
    if fetch is not None:
        out = out.slice(0, int(fetch))
    return out


class TpuSortStageExec(ExecutionPlan):
    """SortExec on the device: materialize the child once, compute the
    ordering permutation on device, take on the host. Unsupported shapes
    host-sort the SAME materialized table (no child re-execution)."""

    def __init__(self, input: ExecutionPlan, keys: list[SortKey],
                 fetch: Optional[int], config: BallistaConfig):
        super().__init__(input.df_schema)
        self.input = input
        self.keys = keys
        self.fetch = fetch
        self.config = config
        self.tpu_count = 0
        self.fallback_count = 0

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_children(self, c):
        return TpuSortStageExec(c[0], self.keys, self.fetch, self.config)

    def output_partition_count(self) -> int:
        return self.input.output_partition_count()

    def node_str(self) -> str:
        k = ", ".join(str(x) for x in self.keys)
        f = f", fetch={self.fetch}" if self.fetch is not None else ""
        extra = ""
        if self.tpu_count or self.fallback_count:
            extra = (f" device_runs={self.tpu_count}"
                     f" cpu_fallbacks={self.fallback_count}")
        return f"TpuSortStageExec: [{k}]{f}{extra}"

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(iter(self._run(partition, ctx)))

    def _run(self, partition: int, ctx: TaskContext):
        batches = [b for b in self.input.execute(partition, ctx) if b.num_rows]
        tbl = _concat(batches, self.schema())
        try:
            with device_scope(ctx.device_ordinal):
                out = _device_sort(tbl, self.df_schema, self.keys, self.fetch,
                                   self.config)
            self.tpu_count += 1
        except Unsupported as e:
            log.info("tpu sort fallback (%s)", e)
            out = self._host_sort(tbl)
        except Exception:  # noqa: BLE001 — device trouble never fails the query
            log.warning("tpu sort raised; falling back to cpu", exc_info=True)
            out = self._host_sort(tbl)
        if out.num_rows == 0:
            yield _empty_batch(self.schema())
            return
        for b in out.combine_chunks().to_batches(max_chunksize=ctx.batch_size):
            yield b

    def _host_sort(self, tbl: pa.Table) -> pa.Table:
        self.fallback_count += 1
        out = _sort_table(tbl, self.df_schema, self.keys)
        if self.fetch is not None:
            out = out.slice(0, self.fetch)
        return out


# ---------------------------------------------------------------------------
# window aggregates


def _device_frame(batch: pa.RecordBatch, w: WindowFunction, schema: DFSchema,
                  config: BallistaConfig, window_funcs: int, up: "_Uploads"):
    """The oracle's _Frame, with the sort permutation computed on device.
    Boundary flags reuse the oracle's `_changes` (nulls equal, NaN splits
    peers) so peer semantics cannot drift. Returns (_Frame, mode)."""
    from ballista_tpu.ops.cpu.window import _Frame, _changes, _first_only
    from ballista_tpu.ops.tpu import fusion
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    n = batch.num_rows
    part_arrays = [evaluate_to_array(bind_expr(e, schema), batch)
                   for e in w.partition_by]
    order_arrays = [evaluate_to_array(bind_expr(k.expr, schema), batch)
                    for k in w.order_by]
    arrays = part_arrays + order_arrays
    orders = [(True, False)] * len(part_arrays) + [
        (k.ascending, k.nulls_first) for k in w.order_by
    ]
    key_ops, key_meta = _encode_key_arrays(arrays, orders)
    est = fusion.estimate_sort_stage(n, key_meta or [("i64", False)],
                                     window_funcs=max(window_funcs, 1))
    _admit(est, config)
    dec = _sort_cost_model(config).choose_sort(est)
    RUN_STATS.set("fusion_mode", dec.mode)
    RUN_STATS.set("fusion_reason", dec.reason)

    t0 = time.time()
    if key_ops:
        idx = _perm_full(key_ops, n, dec.mode, up).astype(np.int64)
    else:
        idx = np.arange(n, dtype=np.int64)
    _note_kernel_s(time.time() - t0)

    inv = np.empty(n, dtype=np.int64)
    inv[idx] = np.arange(n, dtype=np.int64)
    new_part = _changes(part_arrays, idx) if part_arrays else _first_only(n)
    new_peer = new_part | (_changes(order_arrays, idx) if order_arrays
                           else np.zeros(n, bool))
    arange = np.arange(n, dtype=np.int64)
    seg_start = np.maximum.accumulate(np.where(new_part, arange, 0))
    starts = np.flatnonzero(new_part)
    ends = np.r_[starts[1:] - 1, n - 1] if len(starts) else np.array([], np.int64)
    counts = ends - starts + 1 if len(starts) else np.array([], np.int64)
    seg_end = np.repeat(ends, counts) if len(starts) else np.zeros(n, np.int64)
    _count("window_partitions", int(len(starts)))
    return _Frame(idx, inv, new_part, new_peer, seg_start, seg_end), dec.mode


def _seg_scan(vals: np.ndarray, boundary: np.ndarray, func: str, mode: str,
              up: _Uploads) -> np.ndarray:
    """Device inclusive segmented scan (reset at boundary lanes)."""
    jax = ensure_jax()
    jnp = jax.numpy
    n = len(vals)
    if mode == "fused_pallas":
        from ballista_tpu.ops.tpu.pallas_kernels import segmented_scan

        L = _pow2(n)
        v = np.zeros(L, dtype=vals.dtype)
        v[:n] = vals
        f = np.ones(L, dtype=bool)  # padding lanes self-reset
        f[:n] = boundary
        out = segmented_scan(up.put(v)[None, :], up.put(f)[None, :], func)
        return np.asarray(jax.device_get(out[0]))[:n]
    from ballista_tpu.ops.tpu.stage_compiler import _segscan

    out = _segscan(jnp, up.put(vals), up.put(boundary), func)
    return np.asarray(jax.device_get(out))


def _device_compute_one(batch: pa.RecordBatch, w: WindowFunction,
                        schema: DFSchema, fr, mode: str,
                        up: _Uploads) -> pa.Array:
    """One window expression over a shared frame: device segmented scans
    inside the oracle's gather/scatter/emit skeleton."""
    from ballista_tpu.ops.cpu.window import _decimal_prepare, _emit_agg, _peer_last

    n = batch.num_rows
    out_type = w.data_type(schema)
    if n == 0:
        return pa.array([], out_type)
    t0 = time.time()
    boundary = fr.new_part.copy()
    boundary[0] = True
    arange = np.arange(n, dtype=np.int64)

    if w.func == "row_number":
        out_sorted = _seg_scan(np.ones(n, np.int64), boundary, "sum", mode, up)
    elif w.func == "rank":
        marked = np.where(fr.new_peer, arange, np.int64(_I64_MIN))
        peer_start = _seg_scan(marked, boundary, "max", mode, up)
        out_sorted = peer_start - fr.seg_start + 1
    else:
        arr = _emit_scan_agg(batch, w, schema, fr, mode, boundary, up,
                             out_type, _decimal_prepare, _emit_agg,
                             _peer_last, n)
        _note_kernel_s(time.time() - t0)
        return arr
    _note_kernel_s(time.time() - t0)
    out = np.empty(n, dtype=np.int64)
    out[fr.idx] = out_sorted
    return pa.array(out, out_type)


def _emit_scan_agg(batch, w, schema, fr, mode, boundary, up, out_type,
                   _decimal_prepare, _emit_agg, _peer_last, n):
    import pyarrow.compute as pc  # noqa: F401 — _decimal_prepare path

    dec_scale = None
    if w.args:
        arr = evaluate_to_array(bind_expr(w.args[0], schema),
                                batch).take(pa.array(fr.idx))
        valid = arr.is_valid().to_numpy(zero_copy_only=False).astype(bool)
        if pa.types.is_decimal(arr.type):
            arr, dec_scale = _decimal_prepare(arr, w, out_type)
    else:  # count(*)
        arr = None
        valid = np.ones(n, dtype=bool)
    last = _peer_last(fr.new_peer, n)

    seg_cnt = _seg_scan(valid.astype(np.int64), boundary, "sum", mode, up)
    if w.func == "count":
        out = np.empty(n, dtype=np.int64)
        out[fr.idx] = seg_cnt[last]
        return pa.array(out, out_type)

    vals = arr.to_numpy(zero_copy_only=False)
    if w.func == "sum":
        # nullable ints come back from to_numpy as float64-with-NaN, and
        # the oracle then runs its cumsum in float64 — recover the exact
        # ints via fill_null and bound the magnitude so the float path is
        # exact too (every prefix sum < 2^53 → the two agree bit-for-bit)
        import pyarrow.compute as pc

        if pa.types.is_integer(arr.type) or pa.types.is_boolean(arr.type):
            v = pc.fill_null(arr, 0).cast(pa.int64()).to_numpy(
                zero_copy_only=False).astype(np.int64, copy=False)
        elif np.issubdtype(np.asarray(vals).dtype, np.integer):
            v = np.where(valid, np.asarray(vals, dtype=np.int64), 0)
        else:
            raise Unsupported("float window sum (sequential-cumsum parity)")
        if arr.null_count and n:
            m = int(np.abs(v).max())
            if m and m * n >= (1 << 53):
                raise Unsupported("window sum magnitude beyond exact-f64")
        out_sorted = _seg_scan(v, boundary, "sum", mode, up)[last]
    else:  # min / max
        is_f = (np.issubdtype(np.asarray(vals).dtype, np.floating)
                or pa.types.is_floating(out_type))
        v = np.asarray(vals, dtype=np.float64 if is_f else np.int64)
        if is_f:
            sentinel = np.inf if w.func == "min" else -np.inf
        else:
            sentinel = (np.iinfo(np.int64).max if w.func == "min"
                        else np.iinfo(np.int64).min)
        v = np.where(valid, v, sentinel)
        out_sorted = _seg_scan(v, boundary, w.func, mode, up)[last]
    mask_sorted = seg_cnt[last] == 0  # SQL: aggregate over zero rows is NULL

    out = np.empty(n, dtype=out_sorted.dtype)
    out[fr.idx] = out_sorted
    mask = np.empty(n, dtype=bool)
    mask[fr.idx] = mask_sorted
    return _emit_agg(out, out_type, mask, dec_scale)


def _device_windows(batch: pa.RecordBatch, window_exprs: list,
                    schema: DFSchema, config: BallistaConfig) -> list[pa.Array]:
    n = batch.num_rows
    if n < max(int(config.get(TPU_MIN_ROWS)), 1):
        raise Unsupported(f"only {n} rows (< tpu min)")
    if not window_static_ok(window_exprs, schema):
        raise Unsupported("window shape not device-eligible")
    groups: dict[tuple, int] = {}
    for w in window_exprs:
        key = (tuple(str(e) for e in w.partition_by),
               tuple(str(k) for k in w.order_by))
        groups[key] = groups.get(key, 0) + 1
    frames: dict[tuple, tuple] = {}
    out = []
    up = _Uploads()  # stage-total device bytes: sorts + scans (fill test)
    for w in window_exprs:
        key = (tuple(str(e) for e in w.partition_by),
               tuple(str(k) for k in w.order_by))
        if key not in frames:
            frames[key] = _device_frame(batch, w, schema, config,
                                        groups[key], up)
        fr, mode = frames[key]
        out.append(_device_compute_one(batch, w, schema, fr, mode, up))
    from ballista_tpu.ops.tpu.stage_compiler import RUN_STATS

    RUN_STATS.set("device_bytes", up.bytes)
    _count("window_invocations")
    return out


class TpuWindowStageExec(ExecutionPlan):
    """WindowExec on the device: sort permutation + segmented scans on
    device, boundary/emit logic shared with the CPU oracle. Ineligible
    shapes run `compute_windows` over the SAME materialized batch."""

    def __init__(self, input: ExecutionPlan, window_exprs: list,
                 df_schema: DFSchema, config: BallistaConfig):
        super().__init__(df_schema)
        self.input = input
        self.window_exprs = window_exprs
        self.config = config
        self.tpu_count = 0
        self.fallback_count = 0

    def children(self) -> list[ExecutionPlan]:
        return [self.input]

    def with_children(self, c):
        return TpuWindowStageExec(c[0], self.window_exprs, self.df_schema,
                                  self.config)

    def output_partition_count(self) -> int:
        return self.input.output_partition_count()

    def node_str(self) -> str:
        extra = ""
        if self.tpu_count or self.fallback_count:
            extra = (f" device_runs={self.tpu_count}"
                     f" cpu_fallbacks={self.fallback_count}")
        return (f"TpuWindowStageExec: "
                f"[{', '.join(map(str, self.window_exprs))}]{extra}")

    def execute(self, partition: int, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
        return self._timed(iter(self._run(partition, ctx)))

    def _run(self, partition: int, ctx: TaskContext):
        batches = [b for b in self.input.execute(partition, ctx) if b.num_rows]
        if not batches:
            yield _empty_batch(self.schema())
            return
        tbl = _concat(batches, self.input.schema())
        batch = tbl.combine_chunks().to_batches()[0] if tbl.num_rows else None
        if batch is None:
            yield _empty_batch(self.schema())
            return
        try:
            with device_scope(ctx.device_ordinal):
                wins = _device_windows(batch, self.window_exprs,
                                       self.input.df_schema, self.config)
            self.tpu_count += 1
        except Unsupported as e:
            log.info("tpu window fallback (%s)", e)
            wins = self._host_windows(batch)
        except Exception:  # noqa: BLE001 — device trouble never fails the query
            log.warning("tpu window raised; falling back to cpu", exc_info=True)
            wins = self._host_windows(batch)
        arrays = [batch.column(i) for i in range(batch.num_columns)] + wins
        out = pa.RecordBatch.from_arrays(arrays, schema=self.schema())
        for off in range(0, out.num_rows, ctx.batch_size):
            yield out.slice(off, min(ctx.batch_size, out.num_rows - off))

    def _host_windows(self, batch: pa.RecordBatch) -> list[pa.Array]:
        from ballista_tpu.ops.cpu.window import compute_windows

        self.fallback_count += 1
        return compute_windows(batch, self.window_exprs, self.input.df_schema)


def sort_family_enabled(config: BallistaConfig) -> bool:
    return bool(config.get(TPU_SORT_ENABLED))
