"""Out-of-core TPU execution: the per-stage HBM memory plan.

Theseus-style discipline (arXiv:2508.05029) transplanted onto the TPU
path: device memory is a *planned* resource, not a crash surface. Three
rungs, every decision recorded in RUN_STATS as `hbm_plan` /
`hbm_plan_reason` in the demotion-ladder style of `mesh_mode_reason`:

- **admission** (`plan_stage`): before dispatch, the stage's working-set
  bytes — probe table + dictionary LUTs + join build tables, all
  derivable from `fusion.estimate_stage`'s encode metadata — are checked
  against a configurable budget (`ballista.tpu.hbm.budget.bytes`,
  default a fraction of detected device memory). Outcomes: `run_whole`,
  `spill_colds` (the stage fits but cold cache residents must demote
  first), `grace_split`, or `cpu_demote`.
- **spill** (`HostSpillPool`): cold `DeviceTableCache` entries demote to
  host buffers instead of being dropped, re-uploading transparently on
  the next touch; past the host budget they demote again to disk files
  written with the CPU spill pool's attempt-unique tmp+rename discipline
  (shuffle/writer.py). A runtime `RESOURCE_EXHAUSTED` from XLA evicts +
  spills and retries the stage ONCE before demoting.
- **grace fallback**: a hash-join working set over budget re-splits the
  build side by a secondary hash (a re-mixed splitmix64 of the combined
  join key — independent of the PR 7 exchange routing hash, which routes
  on the UN-mixed key) into `buckets^depth` sub-buckets executed
  sequentially on device. Probe rows are never permuted: each sub-run
  sees the full [P, N] stacks in producer row order and a probe row
  matches only in the sub-bucket its key hashes to, so the concatenated
  partial-aggregate outputs are exactly the unconstrained run's partials
  re-bucketed — the downstream final aggregate merges them identically.
  Recursion depth is bounded; past the cap the stage demotes to the CPU
  engine, the always-correct final rung.

Everything here is pure host logic: jax is imported lazily inside the
few functions that need it, so the module can be imported by chaos
injection and the analysis passes without pulling in a backend.
"""

from __future__ import annotations

import contextlib
import logging
import os
import tempfile
import threading
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

# secondary-hash salt for grace sub-bucketing. The PR 7 exchange routes on
# `hash_arrays(keys) % n_devices`; grace buckets on a re-mixed image of the
# combined int64 join key so the two splits stay independent (a partition
# that landed on this chip BY key hash still spreads across sub-buckets).
GRACE_SALT = 0xA5A5_5A5A_C3C3_3C3C

RUN_WHOLE = "run_whole"
SPILL_COLDS = "spill_colds"
GRACE_SPLIT = "grace_split"
CPU_DEMOTE = "cpu_demote"


class InjectedResourceExhausted(RuntimeError):
    """Chaos mode hbm_oom's synthetic device OOM. The message carries the
    literal RESOURCE_EXHAUSTED tag so `is_resource_exhausted` classifies it
    exactly like the real XlaRuntimeError."""


def is_resource_exhausted(exc: BaseException) -> bool:
    """Classify a device-path exception as an out-of-memory condition.
    XLA surfaces HBM exhaustion as XlaRuntimeError with a
    RESOURCE_EXHAUSTED status string; chaos injects the same tag."""
    if isinstance(exc, InjectedResourceExhausted):
        return True
    return "RESOURCE_EXHAUSTED" in f"{type(exc).__name__}: {exc}"


# ---------------------------------------------------------------------------
# chaos arming (executor-local; see ballista.chaos.mode = hbm_oom)

_CHAOS_LOCK = threading.Lock()
_CHAOS = {"armed": False, "budget": 0, "oom_n": 0, "puts": 0}


def arm_chaos(budget_bytes: int, oom_n: int = 0) -> None:
    """Arm the hbm_oom chaos override: the resolved budget shrinks to
    `budget_bytes`, and (oom_n > 0) the oom_n-th device upload raises a
    synthetic RESOURCE_EXHAUSTED — once, so the spill+retry rung can be
    observed converging."""
    with _CHAOS_LOCK:
        _CHAOS["armed"] = True
        _CHAOS["budget"] = int(budget_bytes)
        _CHAOS["oom_n"] = int(oom_n)
        _CHAOS["puts"] = 0


def disarm_chaos() -> None:
    with _CHAOS_LOCK:
        _CHAOS["armed"] = False
        _CHAOS["budget"] = 0
        _CHAOS["oom_n"] = 0
        _CHAOS["puts"] = 0


def chaos_budget() -> int:
    """The armed chaos budget, or 0 when chaos is not steering the plan."""
    with _CHAOS_LOCK:
        return _CHAOS["budget"] if _CHAOS["armed"] else 0


def maybe_chaos_oom() -> None:
    """Call on every device upload. When armed with oom_n > 0, the N-th
    upload raises a synthetic RESOURCE_EXHAUSTED exactly once."""
    with _CHAOS_LOCK:
        if not _CHAOS["armed"] or _CHAOS["oom_n"] <= 0:
            return
        _CHAOS["puts"] += 1
        if _CHAOS["puts"] < _CHAOS["oom_n"]:
            return
        _CHAOS["oom_n"] = 0  # fire once: the retry after spill must succeed
    raise InjectedResourceExhausted(
        "RESOURCE_EXHAUSTED: chaos hbm_oom injected device OOM on upload")


# ---------------------------------------------------------------------------
# budget resolution

def detect_device_memory_bytes() -> int:
    """Bytes of device memory on the executing chip via jax memory_stats
    (0 when the backend does not report — CPU-jax, interpret mode)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats() or {}
        return int(stats.get("bytes_limit", 0) or 0)
    except Exception:  # noqa: BLE001 — detection is best-effort by design
        return 0


# per-session HBM quota (device daemon multi-tenancy): the daemon wraps
# each attached session's stage execution in session_quota(q), and the
# budget resolver clamps to it — every downstream admission decision
# (plan_stage's spill/grace/demote ladder) becomes quota-aware without
# the ladder itself knowing sessions exist.
_QUOTA_TLS = threading.local()


@contextlib.contextmanager
def session_quota(quota_bytes: int):
    """Scope a per-session ceiling over resolve_hbm_budget(). 0 = no
    ceiling. Nests (inner scope wins); thread-local, matching the daemon's
    one-handler-thread-per-request execution model."""
    prev = getattr(_QUOTA_TLS, "quota", 0)
    _QUOTA_TLS.quota = int(quota_bytes)
    try:
        yield
    finally:
        _QUOTA_TLS.quota = prev


def active_session_quota() -> int:
    return int(getattr(_QUOTA_TLS, "quota", 0) or 0)


def resolve_hbm_budget(config) -> int:
    """The per-stage HBM budget in bytes. Precedence: armed chaos override,
    then the explicit knob, then fraction x detected device memory, then
    fraction x ballista.tpu.max.device.bytes (CPU-jax fallback). An active
    session_quota() clamps whatever the ladder produced (chaos included:
    a quota-ed tenant must not dodge its ceiling via a chaos knob)."""
    from ballista_tpu.config import (
        TPU_HBM_BUDGET_BYTES,
        TPU_HBM_BUDGET_FRACTION,
        TPU_MAX_DEVICE_BYTES,
    )

    def _clamp(budget: int) -> int:
        quota = active_session_quota()
        return max(1, min(budget, quota)) if quota > 0 else budget

    forced = chaos_budget()
    if forced > 0:
        return _clamp(forced)
    explicit = int(config.get(TPU_HBM_BUDGET_BYTES))
    if explicit > 0:
        return _clamp(explicit)
    frac = float(config.get(TPU_HBM_BUDGET_FRACTION))
    base = detect_device_memory_bytes() or int(config.get(TPU_MAX_DEVICE_BYTES))
    return _clamp(max(1, int(base * frac)))


# ---------------------------------------------------------------------------
# OOM hints: a stage that hit RESOURCE_EXHAUSTED pre-plans grace on retry

_HINT_LOCK = threading.Lock()
# analysis: ignore[bounded-cache] self-draining: consume_oom_hint discards on read; one entry per in-flight OOM-retried stage
_OOM_HINTS: set[str] = set()


_OOM_RETRIES = [0]  # cumulative, process-wide (mirrored into RUN_STATS like
#                     the spill counters: a later clean re-run of the same
#                     stage tag must not erase the evidence that a retry ran)


def note_oom(fingerprint: str) -> None:
    with _HINT_LOCK:
        _OOM_HINTS.add(fingerprint)
        _OOM_RETRIES[0] += 1


def oom_retry_count() -> int:
    with _HINT_LOCK:
        return _OOM_RETRIES[0]


def consume_oom_hint(fingerprint: str) -> bool:
    with _HINT_LOCK:
        return fingerprint in _OOM_HINTS and (_OOM_HINTS.discard(fingerprint) or True)


# ---------------------------------------------------------------------------
# admission

@dataclass(frozen=True)
class HbmPlan:
    """One stage's admission decision (RUN_STATS hbm_plan/_reason)."""

    decision: str  # run_whole | spill_colds | grace_split | cpu_demote
    reason: str
    budget: int
    working_set: int
    grace_buckets: int = 0  # total sub-buckets (fanout ** depth)
    grace_depth: int = 0
    split_jidx: int = -1  # which join's build side the grace split targets


def plan_stage(est, budget: int, *, grace_eligible: bool, grace_fanout: int,
               grace_max_depth: int, resident_other: int = 0,
               observed_bytes: int = 0, force_grace: bool = False) -> HbmPlan:
    """Admission: check the stage's working-set estimate against the budget.

    `est` is a fusion.StageEstimate carrying table_bytes / dict_bytes /
    build_bytes (all derivable from encode metadata, so the decision is
    computable from a spec table during compile/fill overlap).
    `resident_other` is the device-cache residency NOT owned by this stage
    (cold entries spillable to make room). `observed_bytes` is the AQE
    seam's observed input volume for a resolved/retried stage — a floor
    under the build estimate. `force_grace` is the post-OOM hint: the
    estimate said "fits" once already and the device disagreed."""
    working = int(est.table_bytes) + int(est.dict_bytes) + int(est.build_bytes)
    observed_extra = 0
    if observed_bytes > 0:
        floored = int(est.table_bytes) + int(est.dict_bytes) + int(observed_bytes)
        if floored > working:
            # the AQE seam observed more input volume than the estimate
            # priced: the excess is build-side data the grace split can
            # partition, so it rides the splittable term, not the fixed one
            observed_extra = floored - working
            working = floored
    if budget <= 0:
        return HbmPlan(RUN_WHOLE, "unbudgeted (hbm budget <= 0)", budget, working)
    over = working > budget or force_grace
    if not over:
        if resident_other > 0 and resident_other + working > budget:
            return HbmPlan(
                SPILL_COLDS,
                f"stage fits ({working} <= {budget} B) but {resident_other} B "
                f"of cold residents must spill to host first",
                budget, working)
        return HbmPlan(RUN_WHOLE, f"working set {working} B <= budget {budget} B",
                       budget, working)
    # over budget: try the grace rung, then the CPU rung. A stage that is
    # only "over" because of the post-OOM hint (its estimate fits; the
    # device disagreed once) prefers grace but falls back to re-running
    # whole when no grace rung exists — the evict+spill freed the device,
    # and that retry is the contract; a SECOND runtime OOM demotes for real.
    nominally_fits = working <= budget
    why = (f"post-OOM pre-plan (estimate {working} B, budget {budget} B)"
           if force_grace and nominally_fits
           else f"working set {working} B > budget {budget} B")
    split = int(est.max_build_bytes)
    if split > 0 and est.max_build_jidx >= 0:
        split += observed_extra
    if not grace_eligible or est.max_build_jidx < 0 or split <= 0:
        if nominally_fits:
            return HbmPlan(RUN_WHOLE, why + "; no grace-splittable inner-join "
                           "build — re-running whole after spill", budget, working)
        return HbmPlan(CPU_DEMOTE, why + "; no grace-splittable inner-join build",
                       budget, working)
    if grace_max_depth <= 0:
        if nominally_fits:
            return HbmPlan(RUN_WHOLE, why + "; grace disabled (max depth 0) — "
                           "re-running whole after spill", budget, working)
        return HbmPlan(CPU_DEMOTE, why + "; grace disabled (max depth 0)",
                       budget, working)
    fixed = working - split
    if fixed > budget:
        return HbmPlan(
            CPU_DEMOTE,
            why + f"; non-splittable bytes ({fixed} B) alone exceed the budget",
            budget, working)
    fanout = max(2, int(grace_fanout))
    for depth in range(1, int(grace_max_depth) + 1):
        buckets = fanout ** depth
        if fixed + -(-split // buckets) <= budget:
            return HbmPlan(
                GRACE_SPLIT,
                why + f"; grace-splitting build {est.max_build_jidx} "
                f"({split} B) into {buckets} sub-buckets (depth {depth})",
                budget, working, grace_buckets=buckets, grace_depth=depth,
                split_jidx=int(est.max_build_jidx))
    return HbmPlan(
        CPU_DEMOTE,
        why + f"; grace depth cap {grace_max_depth} (fanout {fanout}) still "
        f"over budget — demoting to the CPU engine",
        budget, working)


def grace_bucket_of(key_np, n_buckets: int):
    """Secondary-hash sub-bucket of each combined int64 join key: the
    splitmix64 finalizer (ops/hashing.py — the bit-exact twin of the
    device hash64) over the salted key. Deterministic, engine-independent,
    and independent of the exchange's primary routing hash."""
    import numpy as np

    from ballista_tpu.ops.hashing import splitmix64

    salted = (key_np.astype(np.int64).view(np.uint64)
              ^ np.uint64(GRACE_SALT))
    return (splitmix64(salted) % np.uint64(n_buckets)).astype(np.int64)


# ---------------------------------------------------------------------------
# grace verification record (consumed by analysis/plan_check.py)

@dataclass
class GraceReport:
    """What a grace-split execution actually did — checked by
    plan_check.verify_grace after every grace run (the postconditions the
    static verifier owns: sub-buckets cover the partition, the merge kept
    producer row order, recursion stayed under the cap)."""

    stage_tag: str
    n_buckets: int
    fanout: int
    depth: int
    max_depth: int
    buckets_run: list = field(default_factory=list)
    buckets_empty: list = field(default_factory=list)  # empty sub-build: no-op
    # how sub-runs merged: "producer-order" = probe rows were never permuted
    # (each sub-run masks non-bucket matches in place) and per-partition
    # outputs concatenate in bucket order
    merge: str = "producer-order"


# ---------------------------------------------------------------------------
# host spill pool

_SEQ_LOCK = threading.Lock()
_SEQ = [0]


def _next_seq() -> int:
    with _SEQ_LOCK:
        _SEQ[0] += 1
        return _SEQ[0]


class SpilledEntry:
    """One demoted cache entry: metadata + either host numpy arrays or a
    disk-tier npz path (never both)."""

    def __init__(self, meta, arrays, nbytes: int, path: str | None = None):
        self.meta = meta  # opaque to the pool; the cache reconstructs from it
        self.arrays = arrays  # list[np.ndarray | None] | None when on disk
        self.nbytes = int(nbytes)
        self.path = path

    @property
    def on_disk(self) -> bool:
        return self.path is not None


class HostSpillPool:
    """Demotion target for cold device-cache entries.

    Two tiers: host buffers up to `max_host_bytes` (LRU), then disk files
    under `spill_dir` written with the shuffle writer's attempt-unique
    tmp+rename discipline (write `<name>.tmp`, fsync-free `os.replace`;
    a crashed writer leaves only a .tmp that never shadows a committed
    file). Counters are cumulative gauges mirrored into RUN_STATS by the
    stage compiler: spill_bytes / spill_events / reupload_events."""

    def __init__(self, max_host_bytes: int = 2 * 1024**3, spill_dir: str = ""):
        import collections

        self.max_host_bytes = int(max_host_bytes)
        self.spill_dir = spill_dir
        self._entries: "collections.OrderedDict[tuple, SpilledEntry]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.host_bytes = 0
        self.spill_bytes = 0  # cumulative bytes demoted (host + disk tiers)
        self.spill_events = 0
        self.reupload_events = 0
        # disk-pressure gate (docs/lifecycle.md#watermark-ladder): a
        # callable returning False sheds DISK demotions — cold entries stay
        # in the host tier (overcommitting it) instead of filling the last
        # of the disk. None = disk always allowed.
        self.spill_gate = None

    def configure(self, max_host_bytes: int, spill_dir: str, spill_gate=None) -> None:
        with self._lock:
            self.max_host_bytes = int(max_host_bytes)
            self.spill_dir = spill_dir
            self.spill_gate = spill_gate

    def _disk_tier_allowed(self) -> bool:
        gate = self.spill_gate
        if gate is None:
            return True
        try:
            return bool(gate())
        except Exception:  # noqa: BLE001 — a broken gate must not block demotion
            return True

    def _dir(self) -> str:
        d = self.spill_dir or os.path.join(tempfile.gettempdir(), "ballista-hbm-spill")
        os.makedirs(d, exist_ok=True)
        return d

    def put(self, key: tuple, meta, arrays, nbytes: int) -> None:
        """Demote one entry (host numpy arrays). Entries past the host
        budget immediately take the disk tier; host-tier overflow demotes
        the coldest host entries to disk too."""
        entry = SpilledEntry(meta, arrays, nbytes)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._drop_locked(old)
            if nbytes > self.max_host_bytes and self._disk_tier_allowed():
                self._to_disk_locked(key, entry)
            else:
                self.host_bytes += entry.nbytes
                while (self.host_bytes > self.max_host_bytes and
                       self._disk_tier_allowed() and
                       any(not e.on_disk and e is not entry
                           for e in self._entries.values())):
                    ck, cold = next((k, e) for k, e in self._entries.items()
                                    if not e.on_disk)
                    self._entries.pop(ck)
                    self.host_bytes -= cold.nbytes
                    self._to_disk_locked(ck, cold)
            self._entries[key] = entry
            self.spill_bytes += entry.nbytes
            self.spill_events += 1

    def _to_disk_locked(self, key: tuple, entry: SpilledEntry) -> None:
        import numpy as np

        name = f"hbm-{os.getpid()}-{_next_seq()}-{abs(hash(key)) & 0xFFFFFFFF:08x}.npz"
        path = os.path.join(self._dir(), name)
        live = {f"a{i}": a for i, a in enumerate(entry.arrays) if a is not None}
        mask = [a is not None for a in entry.arrays]
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, __mask__=np.asarray(mask, dtype=bool), **live)
            os.replace(tmp, path)
        except OSError as e:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            from ballista_tpu.executor.disk import wrap_enospc

            typed = wrap_enospc(e, "hbm spill demotion")
            if typed is not None:
                raise typed from e
            raise
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._entries[key] = entry
        self._entries[key].path = path
        self._entries[key].arrays = None

    def pop(self, key: tuple):
        """Take a demoted entry for re-upload: returns (meta, arrays) or
        None. The entry (and any disk file) is consumed."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return None
            if not entry.on_disk:
                self.host_bytes -= entry.nbytes
            self.reupload_events += 1
        if not entry.on_disk:
            return entry.meta, entry.arrays
        import numpy as np

        try:
            with np.load(entry.path) as z:
                mask = z["__mask__"]
                arrays = [z[f"a{i}"] if present else None
                          for i, present in enumerate(mask)]
        finally:
            try:
                os.unlink(entry.path)
            except OSError:
                pass
        return entry.meta, arrays

    def _drop_locked(self, entry: SpilledEntry) -> None:
        if entry.on_disk:
            try:
                os.unlink(entry.path)
            except OSError:
                pass
        else:
            self.host_bytes -= entry.nbytes

    def drop(self, key: tuple) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._drop_locked(entry)

    def clear(self) -> None:
        with self._lock:
            for entry in self._entries.values():
                self._drop_locked(entry)
            self._entries.clear()
            self.host_bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "host_bytes": self.host_bytes,
                "spill_bytes": self.spill_bytes,
                "spill_events": self.spill_events,
                "reupload_events": self.reupload_events,
            }


SPILL_POOL = HostSpillPool()
