"""Whole-stage fusion planning for the TPU stage compiler.

Three pieces, all pure host-side logic (no jax imports at module scope):

- `plan_spans`: walk a stage's operator chain and group it into fusible
  SPANS — predicate (scan filters + FilterExec + join match masks),
  project (ProjectionExec rebinding), probe (HashJoinExec lookup+gather),
  aggregate (the partial agg). Consecutive ops of the same span kind
  merge; the span list is what `fused_spans` counts and what the staged
  path materializes one HBM intermediate per.
- `estimate_stage`: derive a `StageEstimate` from compile-time facts only
  (DeviceTable encode metadata + prepared BuildTables + the plan), so the
  estimate is computable from a spec table during compile/fill overlap:
  rows, group-domain cardinality (product of pow2 dictionary sizes, None
  when unbounded), expansion-lane count, aggregate-through-join shape,
  operator mix, agg function set.
- `CostModel.choose`: pick `staged` / `fused_xla` / `fused_pallas` for a
  stage. The choice is a REQUEST: `_compile` clamps it to what the stage
  actually supports (the fallback ladder — fused_pallas degrades to
  fused_xla at trace time, staged-ineligible stages compile fused) and
  RUN_STATS `fusion_mode` reports what ran.

Decision rules (auto mode):
  forced mode knob          → that mode (still clamped by the compiler)
  fusion disabled           → staged (per-span sub-kernels, the
                              always-available fallback)
  legacy pallas knob        → fused_pallas when kernel-eligible
  rows < fusion.min.rows
    and staged-eligible     → staged (dispatch overhead is noise; span
                              timings feed the roofline taps)
  pallas-eligible on a real
    TPU backend             → fused_pallas
  otherwise                 → fused_xla (one jitted kernel, intermediates
                              fused by XLA)

Pallas eligibility = grouped aggregation over a bounded code domain
(1 < G ≤ pallas.max.groups), single expansion lane, no
aggregate-through-join weights, and only sum/count/count_all aggregates
(the kernel accumulates f32 sums + i32 counts). `fused_pallas` is never
auto-picked on CPU backends: the interpreter-mode kernel is for test
parity, not speed.
"""

from __future__ import annotations

from dataclasses import dataclass, field


def _pow2(n: int) -> int:
    p = 1
    while p < max(n, 1):
        p *= 2
    return p


PREDICATE = "predicate"
PROJECT = "project"
PROBE = "probe"
AGGREGATE = "aggregate"
SORT = "sort"
WINDOW = "window"
TOPK = "topk"


@dataclass
class Span:
    """One fusible span of the operator chain."""

    kind: str  # predicate | project | probe | aggregate
    ops: int = 1  # plan nodes merged into this span


@dataclass
class StageEstimate:
    """Compile-time stage facts feeding the cost model (derivable from a
    spec DeviceTable, so the decision can run during compile/fill
    overlap)."""

    rows: int  # total input rows across partitions
    partitions: int
    group_domain: int | None  # product of pow2 dict sizes; None = unbounded
    n_group_keys: int
    lanes: int  # expansion-join lane product (1 = no dup unroll)
    has_mult: bool  # aggregate-through-join weight path active
    n_filters: int
    n_projections: int
    n_joins: int
    max_probe_table: int  # largest direct build table (entries), 0 if none
    agg_funcs: tuple = ()
    spans: list = field(default_factory=list)
    # HBM working-set bytes (admission inputs for the out-of-core planner).
    # table_bytes reproduces DeviceTable.nbytes exactly — data stacks +
    # validity planes + row mask, all [P, N] — so it is computable from a
    # spec table before the uploads drain. dict_bytes prices the string
    # LUTs the stage uploads per dictionary column (the undercount this
    # field fixes: codes were budgeted, their dictionaries were not).
    table_bytes: int = 0
    dict_bytes: int = 0
    build_bytes: int = 0  # all join build sides, device layout
    max_build_bytes: int = 0  # largest single build (the grace-split target)
    max_build_jidx: int = -1  # its join index, -1 when no builds
    # ORDER BY / window family (estimate_sort_stage): key count, padded
    # lane width (pow2 for the bitonic network), LIMIT fetch, window
    # function count. Zero everywhere for aggregate stages.
    sort_keys: int = 0
    sort_lanes: int = 0
    topk_k: int = 0
    window_funcs: int = 0


@dataclass
class FusionDecision:
    mode: str  # staged | fused_xla | fused_pallas
    reason: str


def plan_spans(n_scan_filters: int, ops, agg, *, sort_keys: int = 0,
               fetch=None, window_funcs: int = 0) -> list[Span]:
    """Group the stage's op chain into fusible spans, dataflow order.

    The ORDER BY family rides the keyword tail: `sort_keys` > 0 appends a
    SORT span (or a TOPK span when `fetch` bounds the output — the fused
    top-k never materializes the full sort), and `window_funcs` > 0
    appends a WINDOW span (segmented scans over the sorted layout)."""
    from ballista_tpu.plan.physical import (
        CoalesceBatchesExec,
        FilterExec,
        HashJoinExec,
        ProjectionExec,
    )

    spans: list[Span] = []

    def add(kind: str) -> None:
        if spans and spans[-1].kind == kind:
            spans[-1].ops += 1
        else:
            spans.append(Span(kind))

    for _ in range(max(0, int(n_scan_filters))):
        add(PREDICATE)
    for op in ops:
        if isinstance(op, CoalesceBatchesExec):
            continue
        if isinstance(op, FilterExec):
            add(PREDICATE)
        elif isinstance(op, HashJoinExec):
            add(PROBE)
        elif isinstance(op, ProjectionExec):
            add(PROJECT)
        else:
            add(PROJECT)  # unknown residuals lower like projections or raise later
    if agg is not None:
        add(AGGREGATE)
    if sort_keys > 0:
        spans.append(Span(TOPK if fetch is not None else SORT,
                          max(1, int(sort_keys))))
    if window_funcs > 0:
        spans.append(Span(WINDOW, max(1, int(window_funcs))))
    return spans


def estimate_stage(scan, ops, agg, dt, builds) -> StageEstimate:
    """Build a StageEstimate from encode metadata + prepared builds.

    The group-domain walk mirrors _compile's unrolled-eligibility scan: a
    provenance environment maps each current-schema slot to its (kind,
    dictionary) origin; projections rebind Columns, joins prepend build
    slots. Any group key that is not a dictionary-coded Column makes the
    domain unbounded (None)."""
    from ballista_tpu.plan.expressions import Alias, Column
    from ballista_tpu.plan.physical import (
        CoalesceBatchesExec,
        FilterExec,
        HashJoinExec,
        ProjectionExec,
    )

    scan_filters = len(getattr(scan, "filters", []) or [])
    spans = plan_spans(scan_filters, ops, agg)

    # provenance env: per current-schema slot, (kind, dictionary) or None
    env: list = [(k, d) for k, d in zip(dt.kinds, dt.dicts)]
    cur_schema = scan.df_schema
    n_filters = scan_filters
    n_projections = 0
    n_joins = 0
    lanes = 1
    has_mult = False
    max_probe_table = 0

    join_ops = [o for o in ops if isinstance(o, HashJoinExec)]
    if builds and join_ops:
        try:
            from ballista_tpu.ops.tpu.stage_compiler import _mult_shape_check

            cba = _mult_shape_check(agg, ops, join_ops[-1])
            has_mult = cba is not None and builds[-1].dup > 1
        except Exception:  # noqa: BLE001 — estimate only, never fail a stage
            has_mult = False

    jidx = 0
    for op in ops:
        if isinstance(op, CoalesceBatchesExec):
            continue
        if isinstance(op, FilterExec):
            n_filters += 1
        elif isinstance(op, HashJoinExec):
            n_joins += 1
            bt = builds[jidx] if jidx < len(builds) else None
            membership = op.join_type in ("right_semi", "right_anti")
            is_mult = has_mult and jidx == len(builds) - 1
            if bt is not None:
                if bt.mode == "direct":
                    try:
                        max_probe_table = max(max_probe_table, int(bt.keys.shape[0]))
                    except Exception:  # noqa: BLE001
                        pass
                if not membership and not is_mult:
                    lanes *= max(1, int(bt.dup))
            if not membership and not is_mult and bt is not None:
                # build fields prepend, like _compile's env rebinding
                env = [
                    (k, d) for k, d in zip(bt.kinds, bt.dicts)
                ] + env
                cur_schema = op.df_schema
            elif is_mult:
                env = [None] * len(op.left.df_schema) + env
                cur_schema = op.df_schema
            jidx += 1
        elif isinstance(op, ProjectionExec):
            n_projections += 1
            new_env: list = []
            for e in op.exprs:
                inner = e.expr if isinstance(e, Alias) else e
                slot = None
                if isinstance(inner, Column):
                    i = cur_schema.maybe_index_of(inner.name, inner.qualifier)
                    if i is not None and i < len(env):
                        slot = env[i]
                new_env.append(slot)
            env = new_env
            cur_schema = op.df_schema

    group_domain: int | None = 1
    n_group_keys = len(agg.group_exprs) if agg is not None else 0
    if agg is not None:
        for g in agg.group_exprs:
            gc = g.expr if isinstance(g, Alias) else g
            slot = None
            if isinstance(gc, Column):
                i = cur_schema.maybe_index_of(gc.name, gc.qualifier)
                if i is not None and i < len(env):
                    slot = env[i]
            if slot is None or slot[0] != "code" or slot[1] is None:
                group_domain = None
                break
            group_domain *= _pow2(len(slot[1]))

    import numpy as np

    P, N = dt.shape
    # mirror _load's nbytes accumulation term for term: data stacks,
    # validity planes of nullable columns, then the [P, N] row mask
    table_bytes = sum(P * N * np.dtype(c.dtype).itemsize for c in dt.cols)
    table_bytes += sum(P * N for v in dt.valids if v is not None)
    table_bytes += P * N
    # each dictionary column uploads a pow2-padded LUT; 8 B/slot covers the
    # widest remap target (int64 combined keys / i64 decode tables)
    dict_bytes = sum(_pow2(len(d)) * 8 for d in dt.dicts if d)
    build_bytes = 0
    max_build_bytes = 0
    max_build_jidx = -1
    for j, bt in enumerate(builds or []):
        b = sum(int(getattr(a, "nbytes", 0)) for a in bt.flat_arrays())
        dict_bytes += sum(_pow2(len(d)) * 8 for d in bt.dicts if d)
        build_bytes += b
        if b > max_build_bytes:
            max_build_bytes, max_build_jidx = b, j

    agg_funcs = tuple(d.func for d in agg.aggs) if agg is not None else ()
    return StageEstimate(
        rows=sum(dt.part_rows),
        partitions=len(dt.part_rows),
        group_domain=group_domain,
        n_group_keys=n_group_keys,
        lanes=lanes,
        has_mult=has_mult,
        n_filters=n_filters,
        n_projections=n_projections,
        n_joins=n_joins,
        max_probe_table=max_probe_table,
        agg_funcs=agg_funcs,
        spans=spans,
        table_bytes=table_bytes,
        dict_bytes=dict_bytes,
        build_bytes=build_bytes,
        max_build_bytes=max_build_bytes,
        max_build_jidx=max_build_jidx,
    )


def estimate_sort_stage(n_rows: int, key_meta, fetch=None,
                        window_funcs: int = 0) -> StageEstimate:
    """StageEstimate for an ORDER BY / window stage (the device-permutation
    layout: only key lanes upload; payload columns stay host-side and are
    gathered by the returned permutation).

    `key_meta` is a sequence of (kind, nullable) per sort key — kind from
    the lane encoding (i64/date/money/f64/code/bool). Priced per padded
    lane (pow2 for the bitonic network):

      per key: 8 B transformed i64 + 8 B null-rank tiebreak operand
               (+ 1 B NaN-disambiguation plane for f64 keys)
      fixed:   4 B position + 4 B permutation output
      scans:   per window function, 8 B value lanes + 8 B scan state
               + 4 B partition-boundary flags + 4 B peer-boundary flags
               (boundary planes ship as int32 lanes)

    The total lands in table_bytes so `hbm.plan_stage` admits the stage
    through the same ladder as aggregate stages (no grace rung: sorts
    have no splittable build side, so over-budget demotes to the CPU
    engine with the reason recorded)."""
    key_meta = list(key_meta)
    lanes = _pow2(max(int(n_rows), 1))
    per_key = 0
    for kind, nullable in key_meta:
        per_key += 8 + 8  # transformed key + tiebreak operand
        if kind == "f64":
            per_key += 1
        if nullable:
            per_key += 1
    scratch = lanes * (per_key + 4 + 4)
    scratch += int(window_funcs) * lanes * (8 + 8 + 4 + 4)
    return StageEstimate(
        rows=int(n_rows),
        partitions=1,
        group_domain=None,
        n_group_keys=0,
        lanes=1,
        has_mult=False,
        n_filters=0,
        n_projections=0,
        n_joins=0,
        max_probe_table=0,
        spans=plan_spans(0, (), None, sort_keys=len(key_meta),
                         fetch=fetch, window_funcs=window_funcs),
        table_bytes=scratch,
        sort_keys=len(key_meta),
        sort_lanes=lanes,
        topk_k=int(fetch) if fetch is not None else 0,
        window_funcs=int(window_funcs),
    )


@dataclass
class CostModel:
    """Fuse-vs-stage chooser. All inputs are compile-time facts; the
    platform string keeps auto mode honest (interpreter-mode Pallas on
    CPU is a correctness rig, not a fast path)."""

    enabled: bool = True
    mode: str = "auto"
    min_fused_rows: int = 4096
    pallas_max_groups: int = 4096
    pallas_max_probe: int = 1 << 18
    force_pallas: bool = False  # legacy ballista.tpu.pallas.enabled
    platform: str = "cpu"
    sort_max_rows: int = 1 << 17  # pallas bitonic lane ceiling (padded)
    topk_max_k: int = 1024  # above this, ORDER BY...LIMIT full-sorts

    @classmethod
    def from_config(cls, config) -> "CostModel":
        from ballista_tpu.config import (
            TPU_FUSION_ENABLED,
            TPU_FUSION_MIN_ROWS,
            TPU_FUSION_MODE,
            TPU_FUSION_PALLAS_MAX_GROUPS,
            TPU_FUSION_PALLAS_MAX_PROBE,
            TPU_PALLAS,
            TPU_SORT_PALLAS_MAX_ROWS,
            TPU_TOPK_MAX_K,
        )

        return cls(
            enabled=bool(config.get(TPU_FUSION_ENABLED)),
            mode=str(config.get(TPU_FUSION_MODE)),
            min_fused_rows=int(config.get(TPU_FUSION_MIN_ROWS)),
            pallas_max_groups=int(config.get(TPU_FUSION_PALLAS_MAX_GROUPS)),
            pallas_max_probe=int(config.get(TPU_FUSION_PALLAS_MAX_PROBE)),
            force_pallas=bool(config.get(TPU_PALLAS)),
            sort_max_rows=int(config.get(TPU_SORT_PALLAS_MAX_ROWS)),
            topk_max_k=int(config.get(TPU_TOPK_MAX_K)),
        )

    def _pallas_eligible(self, est: StageEstimate) -> bool:
        from ballista_tpu.ops.tpu.pallas_kernels import MAX_GROUPS

        cap = min(self.pallas_max_groups, MAX_GROUPS)
        return (
            est.n_group_keys > 0
            and est.group_domain is not None
            and 1 < est.group_domain <= cap
            and est.lanes == 1
            and not est.has_mult
            and bool(est.agg_funcs)
            and all(f in ("sum", "count", "count_all") for f in est.agg_funcs)
        )

    def _staged_eligible(self, est: StageEstimate) -> bool:
        # mirrors _compile's staged gate: single lane, no mult weights,
        # bounded group domain small enough for the unrolled form
        return (
            est.lanes == 1
            and not est.has_mult
            and est.group_domain is not None
            and est.group_domain <= 64
        )

    def choose(self, est: StageEstimate) -> FusionDecision:
        if self.mode in ("staged", "fused_xla", "fused_pallas"):
            return FusionDecision(
                self.mode, f"forced by ballista.tpu.fusion.mode={self.mode}"
            )
        if not self.enabled:
            return FusionDecision(
                "staged", "fusion disabled; staged per-span fallback"
            )
        if self.force_pallas and self._pallas_eligible(est):
            return FusionDecision(
                "fused_pallas", "legacy ballista.tpu.pallas.enabled"
            )
        if est.rows < self.min_fused_rows and self._staged_eligible(est):
            return FusionDecision(
                "staged",
                f"{est.rows} rows < fusion.min.rows={self.min_fused_rows}",
            )
        if self.platform == "tpu" and self._pallas_eligible(est):
            return FusionDecision(
                "fused_pallas",
                f"grouped agg, G={est.group_domain} fits the kernel family",
            )
        why = []
        if est.group_domain is None:
            why.append("unbounded group domain")
        elif est.group_domain > self.pallas_max_groups:
            why.append(f"G={est.group_domain} > pallas ceiling")
        if est.lanes > 1:
            why.append(f"{est.lanes} expansion lanes")
        if est.has_mult:
            why.append("aggregate-through-join weights")
        if self.platform != "tpu":
            why.append(f"platform={self.platform}")
        return FusionDecision(
            "fused_xla", "whole-chain XLA fusion (" + "; ".join(why) + ")"
        )

    def _sort_pallas_eligible(self, est: StageEstimate) -> tuple[bool, str]:
        from ballista_tpu.ops.tpu.pallas_kernels import MAX_SORT_LANES

        cap = min(self.sort_max_rows, MAX_SORT_LANES)
        if est.sort_lanes > cap:
            return False, f"{est.sort_lanes} padded lanes > sort ceiling {cap}"
        if est.topk_k and est.topk_k > self.topk_max_k:
            return False, (f"fetch {est.topk_k} > topk.max.k {self.topk_max_k}"
                           " — full sort + slice")
        if est.topk_k and est.sort_keys > 1:
            return False, (f"{est.sort_keys} sort keys — the top-k kernel "
                           "takes one composite key; full sort + slice")
        return True, ""

    def choose_sort(self, est: StageEstimate) -> FusionDecision:
        """Mode choice for the ORDER BY / window stage family. Same ladder
        shape as `choose`: forced knob > disabled→staged > pallas on a
        real TPU backend > fused_xla, every demotion with its reason."""
        kinds = {s.kind for s in est.spans}
        what = "window" if WINDOW in kinds else ("topk" if TOPK in kinds else "sort")
        ok, why = self._sort_pallas_eligible(est)
        if self.mode in ("staged", "fused_xla", "fused_pallas"):
            if self.mode == "fused_pallas" and not ok:
                return FusionDecision(
                    "fused_xla", f"forced fused_pallas but {why}")
            return FusionDecision(
                self.mode, f"forced by ballista.tpu.fusion.mode={self.mode}")
        if not self.enabled:
            return FusionDecision(
                "staged", "fusion disabled; per-pass lax.sort fallback")
        if (self.platform == "tpu" or self.force_pallas) and ok:
            return FusionDecision(
                "fused_pallas",
                f"{what} stage, {est.sort_lanes} lanes fit the kernel family")
        parts = [why] if why else []
        if self.platform != "tpu" and not self.force_pallas:
            parts.append(f"platform={self.platform}")
        return FusionDecision(
            "fused_xla", f"{what} via whole-chain XLA sort ("
                         + "; ".join(parts) + ")")
