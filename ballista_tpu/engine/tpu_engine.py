"""TPU engine: rewrite supported subtrees of a physical plan to XLA stages.

The seam the reference exposes as `ExecutionEngine`
(ballista/executor/src/execution_engine.rs:51): given a query stage's
physical plan, produce the executor that runs it. `ballista.executor.engine
= tpu` routes stages through here; unsupported subtrees keep their CPU
operators (per-subtree dispatch like execution_engine.rs:124-147).

v1 lowers Filter*/Projection* → HashAggregateExec(partial) pipelines over a
scan (the FLOP/bandwidth-dominant part of aggregation queries). Joins and
large-domain aggregations stay on the CPU engine this round; the device
join kernel lands with the on-device shuffle path.
"""

from __future__ import annotations

from ballista_tpu.config import BallistaConfig
from ballista_tpu.plan.physical import (
    CoalesceBatchesExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
)


def _concretize_dynamic_joins(node: ExecutionPlan) -> ExecutionPlan:
    """Rewrite every DynamicJoinSelectionExec into its planned HashJoinExec
    before device compilation. The deferral exists so the CPU engine can
    promote to a collected broadcast at first-batch time — but a deferred
    node is opaque to the stage compiler, which silently pushes the whole
    join chain back to the host (measured round 5: q3/q5/q9/q14/q19 hot
    ran at ~1x the CPU engine while q1/q6 ran 40-100x). The device join
    (direct-table gathers against an HBM-resident build) is what the
    deferral would be deciding toward anyway; subtrees the device rejects
    still fall back per-subtree, where the CPU join runs as planned."""
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec
    from ballista_tpu.plan.physical import HashJoinExec

    kids = node.children()
    new_kids = [_concretize_dynamic_joins(c) for c in kids]
    if any(a is not b for a, b in zip(new_kids, kids)):
        node = node.with_children(new_kids)
    if isinstance(node, DynamicJoinSelectionExec):
        node = HashJoinExec(node.left, node.right, node.on, node.join_type,
                            node.filter, node.mode, node.df_schema)
    return node


def maybe_compile_tpu(physical: ExecutionPlan, config: BallistaConfig) -> ExecutionPlan:
    from ballista_tpu.config import TPU_COMPILE_CACHE_DIR
    from ballista_tpu.ops.tpu import runtime
    from ballista_tpu.ops.tpu.final_stage import TpuFinalStageExec, match_final_stage
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec

    # activate the persistent XLA cache before any stage compiles, so even
    # the first stage of a restarted process can hit on-disk artifacts
    cc_dir = str(config.get(TPU_COMPILE_CACHE_DIR) or "")
    if cc_dir:
        runtime.init_compile_cache(cc_dir)

    # capture the AQE resolve-time stamp before any rewrite rebuilds the
    # root node (with_children does not carry ad-hoc attributes)
    observed_bytes = int(getattr(physical, "hbm_observed_input_bytes", 0) or 0)
    physical = _concretize_dynamic_joins(physical)

    from ballista_tpu.ops.tpu.sort_window import (
        TpuSortStageExec,
        TpuWindowStageExec,
        sort_family_enabled,
        sort_static_ok,
        window_static_ok,
    )
    from ballista_tpu.plan.physical import SortExec, WindowExec

    sort_on = sort_family_enabled(config)

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        fs = match_final_stage(node)
        if fs is not None:
            # final-agg/sort stage shape: merge partials + ORDER BY/LIMIT in
            # HBM; the child (shuffle reader, or repartition in local plans)
            # keeps its own device opportunities
            sort, post_ops, agg, child, coalesce = fs
            return TpuFinalStageExec(sort, post_ops, agg, walk(child), config, coalesce)
        if isinstance(node, HashAggregateExec) and node.mode == "partial":
            chain = _match_chain(node.input)
            if chain is not None:
                ops, scan = chain
                if _static_ok(node):
                    return TpuStageExec(node, ops, scan, config)
                hoisted = _hoist_expr_group_keys(node)
                if hoisted is not None and _static_ok(hoisted.input):
                    inner = TpuStageExec(hoisted.input, ops, scan, config)
                    return hoisted.with_children([inner])
            elif _static_ok(node):
                # a UNION on the probe chain (TPC-DS cross-channel shapes:
                # q2/q5/q71/q75/q76) blocks the single-scan stage form —
                # push the partial agg through the union so each branch
                # compiles its own device chain. Per-partition outputs are
                # identical: union partitions map 1:1 onto branch
                # partitions, and partials merge downstream either way.
                pushed = _push_agg_through_union(node)
                if pushed is not None:
                    return walk(pushed)
        if (sort_on and isinstance(node, SortExec)
                and sort_static_ok(node.keys, node.input.df_schema)):
            # standalone ORDER BY [LIMIT] (final-stage shapes were claimed
            # above): device permutation, host take — cost model picks the
            # rung per shape at run time
            return TpuSortStageExec(walk(node.input), node.keys, node.fetch,
                                    config)
        if (sort_on and isinstance(node, WindowExec)
                and window_static_ok(node.window_exprs, node.input.df_schema)):
            return TpuWindowStageExec(walk(node.input), node.window_exprs,
                                      node.df_schema, config)
        kids = node.children()
        if not kids:
            return node
        new_kids = [walk(c) for c in kids]
        if all(a is b for a, b in zip(new_kids, kids)):
            return node
        return node.with_children(new_kids)

    out = walk(physical)
    _wire_device_routing(out)
    _wire_observed_bytes(observed_bytes, out)
    return out


def _wire_observed_bytes(observed: int, out: ExecutionPlan) -> None:
    """Propagate the AQE resolve-time stamp (HbmPrePlanRule's
    `hbm_observed_input_bytes`, ground-truth input volume from the finished
    producers) from the stage root onto every compiled device stage, where
    HBM admission uses it as a floor under the build-size estimate. Plain
    attributes both sides — executor-local by design (the serde note:
    sub-plans never cross the wire)."""
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec

    if observed <= 0:
        return

    def walk(node: ExecutionPlan) -> None:
        if isinstance(node, TpuStageExec):
            node.hbm_observed_input_bytes = observed
        for c in node.children():
            walk(c)

    walk(out)


def _wire_device_routing(root: ExecutionPlan) -> None:
    """When a stage's root shuffle writer hash-partitions on columns of a
    TpuStageExec's output, tell the stage to emit a device-computed __pid
    column (the writer consumes it and skips host hashing). Sorted-path
    stages honor it; others ignore it."""
    from ballista_tpu.plan.expressions import Alias as _Alias
    from ballista_tpu.plan.expressions import Column as _Column
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    if not isinstance(root, ShuffleWriterExec) or root.output_partitions <= 0:
        return
    # the stage must feed the writer DIRECTLY: an intervening operator
    # (CoalesceBatches etc.) re-asserts its declared schema and would choke
    # on the extra __pid column
    node = root.input
    if not isinstance(node, TpuStageExec):
        return
    schema = node.df_schema
    if any(f.name == "__pid" for f in schema):
        return  # never shadow a user column
    n_group = len(node.partial_agg.group_exprs)
    idxs: list[int] = []
    for k in root.keys:
        kc = k.expr if isinstance(k, _Alias) else k
        if not isinstance(kc, _Column):
            return
        i = schema.maybe_index_of(kc.name, kc.qualifier)
        if i is None:
            i = schema.maybe_index_of(kc.name, None)
        if i is None or i >= n_group:
            return  # key is not a group output column
        idxs.append(i)
    if idxs:
        node.emit_pid = (idxs, root.output_partitions)
        root.device_routed = True  # writer honors __pid only when flagged


def _match_chain(node: ExecutionPlan):
    """Descend the PROBE path through Filter/Projection/CoalesceBatches and
    CollectLeft inner hash joins to a scan; return (dataflow-ordered op
    list, scan) or None. Join build sides stay CPU-side subplans executed
    at stage start; probe-side rows never leave the device."""
    from ballista_tpu.plan.physical import HashJoinExec

    ops: list[ExecutionPlan] = []
    cur = node
    while True:
        if isinstance(cur, (ParquetScanExec, MemoryScanExec)):
            ops.reverse()
            return ops, cur
        if isinstance(cur, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
            ops.append(cur)
            cur = cur.children()[0]
            continue
        if (
            isinstance(cur, HashJoinExec)
            and cur.mode == "collect_left"
            and (
                (cur.join_type in ("inner", "right") and cur.filter is None)
                or cur.join_type in ("right_semi", "right_anti")
            )
        ):
            # inner: build-column gathers join the chain; right (outer):
            # every probe row emits, unmatched gathers are NULL (validity
            # planes); right_semi/right_anti emit probe rows only — the
            # match mask IS the filter, and a join filter (e.g. q21's
            # l_suppkey <> l1.l_suppkey) ORs across build match lanes
            ops.append(cur)
            cur = cur.right  # probe side continues the device chain
            continue
        return None


def _hoist_expr_group_keys(agg: HashAggregateExec):
    """Rewrite a partial agg whose group keys are single-column expressions
    (TPC-DS q62/q99's `substr(w_warehouse_name, 1, 20)`) so the DEVICE
    groups by the raw column — a strict refinement — and the expression is
    applied by a tiny CPU projection over the (few) partial group rows.
    Correct because the FINAL aggregation re-groups by the expression's
    value and every partial accumulator (sum/min/max/count and the Welford
    triple) merges across the finer groups. Returns the projection node
    (child = the rewritten partial agg) or None."""
    from ballista_tpu.plan.expressions import Alias, Column, transform_expr
    from ballista_tpu.plan.schema import DFField, DFSchema

    in_schema = agg.input.df_schema
    new_groups = []
    post_exprs = []
    group_fields = []
    changed = False
    for i, g in enumerate(agg.group_exprs):
        out_name = g.output_name()
        out_field = agg.df_schema.field(i)
        inner = g.expr if isinstance(g, Alias) else g
        if isinstance(inner, Column):
            new_groups.append(g)
            group_fields.append(out_field)
            post_exprs.append(Alias(Column(out_name), out_name))
            continue
        cols = [e for e in _walk_exprs(inner) if isinstance(e, Column)]
        if len({(c.name, c.qualifier) for c in cols}) != 1:
            return None  # multi-column or constant group expr: no raw key
        raw = cols[0]
        raw_field = in_schema.field(in_schema.index_of(raw.name, raw.qualifier))
        gk = f"__gk{i}"
        new_groups.append(Alias(Column(raw.name, raw.qualifier), gk))
        group_fields.append(DFField(gk, raw_field.dtype, raw_field.nullable))
        rewritten = transform_expr(
            inner, lambda e: Column(gk) if isinstance(e, Column) else e)
        post_exprs.append(Alias(rewritten, out_name))
        changed = True
    if not changed:
        return None
    n_group = len(agg.group_exprs)
    acc_fields = list(agg.df_schema)[n_group:]
    inner_schema = DFSchema(group_fields + acc_fields)
    for f in acc_fields:
        post_exprs.append(Alias(Column(f.name), f.name))
    new_agg = HashAggregateExec(agg.input, new_groups, agg.aggs, "partial", inner_schema)
    return ProjectionExec(new_agg, post_exprs, agg.df_schema)


def _walk_exprs(e):
    yield e
    for c in e.children():
        yield from _walk_exprs(c)


def _push_agg_through_union(agg: HashAggregateExec):
    """HashAgg(partial) over [ops...] over Union(b1..bn) →
    Union(HashAgg(partial) over [ops...] over b_i). Applied only when every
    branch schema matches the union schema exactly (names + types), so
    dropping the union's per-branch alignment cast changes nothing."""
    from ballista_tpu.plan.physical import HashJoinExec, UnionExec

    path: list[ExecutionPlan] = []  # chain nodes, agg-side first
    cur = agg.input
    while not isinstance(cur, UnionExec):
        if isinstance(cur, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
            path.append(cur)
            cur = cur.children()[0]
        elif (
            isinstance(cur, HashJoinExec)
            and cur.mode == "collect_left"
            and cur.join_type in ("inner", "right", "right_semi", "right_anti")
        ):
            # probe-side-emitting joins only: cloning a build-side-emitting
            # join (left/full/left_semi/left_anti) per union branch would
            # emit the unmatched-build tail once per branch
            path.append(cur)
            cur = cur.right
        else:
            return None
    union = cur
    us = union.schema()
    for b in union.inputs:
        bs = b.schema()
        if [(f.name, f.type) for f in bs] != [(f.name, f.type) for f in us]:
            return None
    branch_aggs = []
    for b in union.inputs:
        node: ExecutionPlan = b
        for p in reversed(path):
            if isinstance(p, HashJoinExec):
                node = p.with_children([p.left, node])
            else:
                node = p.with_children([node])
        branch_aggs.append(
            HashAggregateExec(node, agg.group_exprs, agg.aggs, "partial", agg.df_schema))
    return UnionExec(branch_aggs, agg.df_schema)


def _static_ok(agg: HashAggregateExec) -> bool:
    from ballista_tpu.plan.expressions import Alias, Column

    for g in agg.group_exprs:
        inner = g.expr if isinstance(g, Alias) else g
        if not isinstance(inner, Column):
            return False
    for d in agg.aggs:
        if d.func not in ("sum", "min", "max", "count", "count_all",
                          "welford_mean", "welford_m2"):
            return False
    return True
