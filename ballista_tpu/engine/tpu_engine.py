"""TPU engine: rewrite supported subtrees of a physical plan to XLA stages.

The seam the reference exposes as `ExecutionEngine`
(ballista/executor/src/execution_engine.rs:51): given a query stage's
physical plan, produce the executor that runs it. `ballista.executor.engine
= tpu` routes stages through here; unsupported subtrees keep their CPU
operators (per-subtree dispatch like execution_engine.rs:124-147).

v1 lowers Filter*/Projection* → HashAggregateExec(partial) pipelines over a
scan (the FLOP/bandwidth-dominant part of aggregation queries). Joins and
large-domain aggregations stay on the CPU engine this round; the device
join kernel lands with the on-device shuffle path.
"""

from __future__ import annotations

from ballista_tpu.config import BallistaConfig
from ballista_tpu.plan.physical import (
    CoalesceBatchesExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
)


def maybe_compile_tpu(physical: ExecutionPlan, config: BallistaConfig) -> ExecutionPlan:
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        if isinstance(node, HashAggregateExec) and node.mode == "partial":
            chain = _match_chain(node.input)
            if chain is not None:
                ops, scan = chain
                if _static_ok(node):
                    return TpuStageExec(node, ops, scan, config)
        kids = node.children()
        if not kids:
            return node
        new_kids = [walk(c) for c in kids]
        if all(a is b for a, b in zip(new_kids, kids)):
            return node
        return node.with_children(new_kids)

    out = walk(physical)
    _wire_device_routing(out)
    return out


def _wire_device_routing(root: ExecutionPlan) -> None:
    """When a stage's root shuffle writer hash-partitions on columns of a
    TpuStageExec's output, tell the stage to emit a device-computed __pid
    column (the writer consumes it and skips host hashing). Sorted-path
    stages honor it; others ignore it."""
    from ballista_tpu.plan.expressions import Alias as _Alias
    from ballista_tpu.plan.expressions import Column as _Column
    from ballista_tpu.ops.tpu.stage_compiler import TpuStageExec
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    if not isinstance(root, ShuffleWriterExec) or root.output_partitions <= 0:
        return
    # the stage must feed the writer DIRECTLY: an intervening operator
    # (CoalesceBatches etc.) re-asserts its declared schema and would choke
    # on the extra __pid column
    node = root.input
    if not isinstance(node, TpuStageExec):
        return
    schema = node.df_schema
    if any(f.name == "__pid" for f in schema):
        return  # never shadow a user column
    n_group = len(node.partial_agg.group_exprs)
    idxs: list[int] = []
    for k in root.keys:
        kc = k.expr if isinstance(k, _Alias) else k
        if not isinstance(kc, _Column):
            return
        i = schema.maybe_index_of(kc.name, kc.qualifier)
        if i is None:
            i = schema.maybe_index_of(kc.name, None)
        if i is None or i >= n_group:
            return  # key is not a group output column
        idxs.append(i)
    if idxs:
        node.emit_pid = (idxs, root.output_partitions)
        root.device_routed = True  # writer honors __pid only when flagged


def _match_chain(node: ExecutionPlan):
    """Descend the PROBE path through Filter/Projection/CoalesceBatches and
    CollectLeft inner hash joins to a scan; return (dataflow-ordered op
    list, scan) or None. Join build sides stay CPU-side subplans executed
    at stage start; probe-side rows never leave the device."""
    from ballista_tpu.plan.physical import HashJoinExec

    ops: list[ExecutionPlan] = []
    cur = node
    while True:
        if isinstance(cur, (ParquetScanExec, MemoryScanExec)):
            ops.reverse()
            return ops, cur
        if isinstance(cur, (FilterExec, ProjectionExec, CoalesceBatchesExec)):
            ops.append(cur)
            cur = cur.children()[0]
            continue
        if (
            isinstance(cur, HashJoinExec)
            and cur.mode == "collect_left"
            and (
                (cur.join_type in ("inner", "right") and cur.filter is None)
                or cur.join_type in ("right_semi", "right_anti")
            )
        ):
            # inner: build-column gathers join the chain; right (outer):
            # every probe row emits, unmatched gathers are NULL (validity
            # planes); right_semi/right_anti emit probe rows only — the
            # match mask IS the filter, and a join filter (e.g. q21's
            # l_suppkey <> l1.l_suppkey) ORs across build match lanes
            ops.append(cur)
            cur = cur.right  # probe side continues the device chain
            continue
        return None


def _static_ok(agg: HashAggregateExec) -> bool:
    from ballista_tpu.plan.expressions import Alias, Column

    for g in agg.group_exprs:
        inner = g.expr if isinstance(g, Alias) else g
        if not isinstance(inner, Column):
            return False
    for d in agg.aggs:
        if d.func not in ("sum", "min", "max", "count", "count_all",
                          "welford_mean", "welford_m2"):
            return False
    return True
