"""TPU engine stub — replaced by the real XLA stage compiler in ops/tpu."""
def maybe_compile_tpu(physical, config):
    return physical
