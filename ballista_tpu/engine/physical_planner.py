"""Physical planner: LogicalPlan → ExecutionPlan.

Performs what the reference delegates to DataFusion's physical planner plus
the scheduler-side JoinSelection rule
(scheduler/src/physical_optimizer/join_selection.rs): build-side choice by
estimated size, broadcast (CollectLeft) vs partitioned joins by threshold,
two-phase aggregation with hash exchanges, avg/count-distinct
decomposition, and sort/limit lowering.

RepartitionExec nodes inserted here are the stage boundaries the
distributed planner later splits at (scheduler/src/planner.rs:108).
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa

from ballista_tpu.config import (
    AQE_DYNAMIC_JOIN_SELECTION,
    AQE_JOIN_HEDGE_FACTOR,
    BROADCAST_JOIN_ROWS_THRESHOLD,
    BROADCAST_JOIN_THRESHOLD,
    BROADCAST_SEMI_KEYS_THRESHOLD,
    DEFAULT_SHUFFLE_PARTITIONS,
    EXECUTOR_ENGINE,
    PLANNER_ADAPTIVE_ENABLED,
    TARGET_PARTITIONS,
    BallistaConfig,
)
from ballista_tpu.errors import PlanningError
from ballista_tpu.plan.expressions import (
    AggregateFunction,
    Alias,
    BinaryExpr,
    Case,
    Cast,
    Column,
    Expr,
    Literal,
    ScalarFunction,
    VARIANCE_FUNCS,
    to_field,
)
from ballista_tpu.plan.logical import (
    Aggregate,
    CrossJoin,
    Distinct,
    EmptyRelation,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Sort,
    SubqueryAlias,
    TableScan,
    Union,
    Values,
    Window,
)
from ballista_tpu.plan.physical import (
    AggDesc,
    CoalescePartitionsExec,
    CrossJoinExec,
    EmptyExec,
    ExecutionPlan,
    FilterExec,
    GlobalLimitExec,
    HashAggregateExec,
    HashJoinExec,
    LocalLimitExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    RepartitionExec,
    SortExec,
    SortPreservingMergeExec,
    UnionExec,
)
from ballista_tpu.plan.provider import AppendedTable, MemoryTable, ParquetTable
from ballista_tpu.plan.schema import DFField, DFSchema


class PhysicalPlanner:
    def __init__(self, config: BallistaConfig | None = None):
        self.config = config or BallistaConfig()
        self.shuffle_partitions = int(self.config.get(DEFAULT_SHUFFLE_PARTITIONS))
        self.target_partitions = int(self.config.get(TARGET_PARTITIONS))
        self.broadcast_rows = int(self.config.get(BROADCAST_JOIN_ROWS_THRESHOLD))
        self.device_engine = str(self.config.get(EXECUTOR_ENGINE)) == "tpu"
        if self.device_engine:
            # device joins probe an HBM-resident sorted build: the collect
            # budget scales to HBM, not to the CPU broadcast wire budget —
            # and only collect-build chains compile into device stages.
            # If the device stage is later DECLINED, the oversized
            # collect_left runs on the host; HashJoinExec._build_table
            # warns when the built table exceeds the CPU rows threshold
            from ballista_tpu.config import (
                TPU_BROADCAST_JOIN_ROWS,
                TPU_HBM_BUDGET_BYTES,
                TPU_HBM_GRACE_DEPTH,
            )

            self.broadcast_rows = max(
                self.broadcast_rows, int(self.config.get(TPU_BROADCAST_JOIN_ROWS)))
            # out-of-core seam: a tight EXPLICIT HBM budget with grace
            # splitting disabled leaves no fallback between "build fits"
            # and CPU demotion, so don't let the TPU threshold raise collect
            # sizes the device can never admit. ~16 B/row is the widest
            # single-column build footprint (i64 key + i64 payload); with
            # grace enabled the admission ladder handles oversize builds.
            budget = int(self.config.get(TPU_HBM_BUDGET_BYTES))
            if budget > 0 and int(self.config.get(TPU_HBM_GRACE_DEPTH)) <= 0:
                self.broadcast_rows = min(
                    self.broadcast_rows, max(budget // 16, 1))

    def plan(self, logical: LogicalPlan) -> ExecutionPlan:
        return self._plan(logical)

    # ------------------------------------------------------------------

    def _plan(self, node: LogicalPlan) -> ExecutionPlan:
        if isinstance(node, TableScan):
            return self._plan_scan(node)
        if isinstance(node, Projection):
            child = self._plan(node.input)
            return ProjectionExec(child, node.exprs, _rebind_schema(node.schema))
        if isinstance(node, Filter):
            return FilterExec(self._plan(node.input), node.predicate)
        if isinstance(node, Aggregate):
            return self._plan_aggregate(node)
        if isinstance(node, Join):
            return self._plan_join(node)
        if isinstance(node, CrossJoin):
            left = self._plan(node.left)
            right = self._plan(node.right)
            if estimate_rows(node.left) > estimate_rows(node.right):
                # build (collected) side should be the small one
                right_first = CrossJoinExec(right, left, node.right.schema.merge(node.left.schema))
                order = [
                    Column(f.name, f.qualifier) for f in node.schema
                ]
                return ProjectionExec(right_first, order, node.schema)
            return CrossJoinExec(left, right, node.schema)
        if isinstance(node, Window):
            return self._plan_window(node)
        if isinstance(node, Sort):
            child = self._plan(node.input)
            # large full sorts scale out via the dynamic range-repartition
            # pipeline: stats tap → dam → quantile-cut router → per-range
            # sorts whose in-order concatenation IS the total order
            big = estimate_rows(node.input) > 2_000_000
            if node.fetch is None and big and child.output_partition_count() > 1 and node.keys:
                from ballista_tpu.ops.cpu.range_repartition import (
                    BufferExec,
                    RuntimeStatsExec,
                    UnorderedRangeRepartitionExec,
                )

                tapped = RuntimeStatsExec(child, node.keys[0].expr)
                dammed = BufferExec(tapped)
                ranged = UnorderedRangeRepartitionExec(
                    dammed, node.keys[0], child.output_partition_count()
                )
                return CoalescePartitionsExec(SortExec(ranged, node.keys, None))
            s = SortExec(child, node.keys, node.fetch)
            if child.output_partition_count() > 1:
                return SortPreservingMergeExec(s, node.keys, node.fetch)
            return s
        if isinstance(node, Limit):
            child = self._plan(node.input)
            fetch, skip = node.fetch, node.skip
            if child.output_partition_count() > 1:
                if fetch is not None:
                    child = LocalLimitExec(child, fetch + skip)
                child = CoalescePartitionsExec(child)
            return GlobalLimitExec(child, fetch, skip)
        if isinstance(node, Distinct):
            agg = Aggregate(node.input, [Column(f.name, f.qualifier) for f in node.schema], [])
            return self._plan_aggregate(agg)
        if isinstance(node, SubqueryAlias):
            child = self._plan(node.input)
            # carry the alias-qualified schema so parent expressions binding
            # against `alias.column` resolve (planner-created nodes are not
            # shared, so re-stamping the output schema in place is safe)
            child.df_schema = node.schema
            return child
        if isinstance(node, Union):
            return UnionExec([self._plan(c) for c in node.inputs], node.schema)
        if isinstance(node, Values):
            cols = list(zip(*node.rows)) if node.rows else []
            arrays = [pa.array(list(c)) for c in cols]
            batch = pa.RecordBatch.from_arrays(arrays, schema=node.schema.to_arrow())
            return MemoryScanExec(node.schema, [batch])
        if isinstance(node, EmptyRelation):
            return EmptyExec(node.schema, node.produce_one_row)
        raise PlanningError(f"cannot lower {type(node).__name__}")

    # ------------------------------------------------------------------

    def _plan_scan(self, node: TableScan) -> ExecutionPlan:
        provider = node.provider
        if isinstance(provider, AppendedTable):
            return self._plan_appended_scan(node, provider)
        if isinstance(provider, MemoryTable):
            child = MemoryScanExec(node.schema, provider.batches, provider.partitions)
            if node.filters:
                from ballista_tpu.plan.expressions import and_

                return FilterExec(child, and_(*node.filters))
            return child
        partitions = provider.scan_partitions(self.target_partitions)
        proj_names = [f.name for f in node.schema]
        # scan output schema must include filter-only columns for evaluation
        filter_cols: list[str] = []
        from ballista_tpu.plan.expressions import collect_columns

        for f in node.filters:
            for c in collect_columns(f):
                if c.name not in proj_names and c.name not in filter_cols:
                    filter_cols.append(c.name)
        if filter_cols:
            full = provider.df_schema().with_qualifier(node.alias or node.table_name)
            read_fields = list(node.schema.fields) + [
                full.field(full.index_of(n)) for n in filter_cols
            ]
            read_schema = DFSchema(read_fields)
            scan = ParquetScanExec(
                read_schema, partitions, [f.name for f in read_fields], node.filters, node.table_name
            )
            keep = [Column(f.name, f.qualifier) for f in node.schema]
            return ProjectionExec(scan, keep, node.schema)
        return ParquetScanExec(node.schema, partitions, proj_names, node.filters, node.table_name)

    def _plan_appended_scan(self, node: TableScan, provider: AppendedTable) -> ExecutionPlan:
        """Base scan ∪ memory scan of the append overlay (local-mode
        ingestion). The delta leg re-applies the scan's predicates — the
        base leg gets them via pushdown — and mirrors the parquet branch's
        filter-only-column handling."""
        import copy

        base_node = copy.copy(node)
        base_node.provider = provider.base
        base_plan = self._plan_scan(base_node)
        if not provider.batches:
            return base_plan
        from ballista_tpu.plan.expressions import and_, collect_columns

        proj_names = [f.name for f in node.schema]
        filter_cols: list[str] = []
        for f in node.filters:
            for c in collect_columns(f):
                if c.name not in proj_names and c.name not in filter_cols:
                    filter_cols.append(c.name)
        if filter_cols:
            full = provider.df_schema().with_qualifier(node.alias or node.table_name)
            read_fields = list(node.schema.fields) + [
                full.field(full.index_of(n)) for n in filter_cols
            ]
            delta: ExecutionPlan = MemoryScanExec(DFSchema(read_fields), provider.batches, 1)
            delta = FilterExec(delta, and_(*node.filters))
            keep = [Column(f.name, f.qualifier) for f in node.schema]
            delta = ProjectionExec(delta, keep, node.schema)
        else:
            delta = MemoryScanExec(node.schema, provider.batches, 1)
            if node.filters:
                delta = FilterExec(delta, and_(*node.filters))
        return UnionExec([base_plan, delta], node.schema)

    # ------------------------------------------------------------------

    def _plan_window(self, node: Window) -> ExecutionPlan:
        """Window partition-key groups must be partition-local: windows
        sharing PARTITION BY keys stack over one exchange; differing key
        sets chain (each WindowExec appends its __win columns).

        The reference gets this layout from DataFusion's
        BoundedWindowAggExec + its repartition rules; here the hash
        exchange doubles as the distributed stage boundary."""
        from ballista_tpu.plan.physical import WindowExec

        child = self._plan(node.input)
        groups: dict[tuple, list] = {}
        order: list[tuple] = []
        for w in node.window_exprs:
            key = tuple(str(e) for e in w.partition_by)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(w)

        from ballista_tpu.plan.schema import DFField, DFSchema

        cur = child
        cur_fields = list(node.input.schema.fields)
        for key in order:
            ws = groups[key]
            pby = list(ws[0].partition_by)
            if pby and cur.output_partition_count() > 1:
                cur = RepartitionExec(cur, "hash", self.shuffle_partitions, pby)
            elif not pby and cur.output_partition_count() > 1:
                cur = CoalescePartitionsExec(cur)
            for w in ws:
                i = node.window_exprs.index(w)
                cur_fields.append(DFField(f"__win{i}", w.data_type(node.input.schema)))
            cur = WindowExec(cur, ws, DFSchema(list(cur_fields)))
        return cur

    def _plan_aggregate(self, node: Aggregate) -> ExecutionPlan:
        child = self._plan(node.input)
        in_schema = node.input.schema
        group_exprs = node.group_exprs
        n_group = len(group_exprs)

        # count(distinct x) → dedup-then-count (two stacked aggregates)
        if any(isinstance(a, AggregateFunction) and a.func == "count_distinct" for a in node.agg_exprs):
            if not all(
                isinstance(a, AggregateFunction) and a.func == "count_distinct"
                for a in node.agg_exprs
            ):
                return self._plan_mixed_distinct(node)
            args = [a.arg for a in node.agg_exprs]
            inner = Aggregate(node.input, list(group_exprs) + args, [])
            inner_planned = self._plan_aggregate(inner)
            # outer: group by original keys, count the deduped arg
            outer_group = [Column(g.output_name()) for g in group_exprs]
            outer_aggs: list[AggDesc] = []
            result_exprs: list[Expr] = list(outer_group)
            for a, arg in zip(node.agg_exprs, args):
                outer_aggs.append(AggDesc("count", Column(arg.output_name()), a.output_name()))
                result_exprs.append(Column(a.output_name()))
            inner_logical_schema = inner.schema
            return self._two_phase(
                inner_planned,
                inner_logical_schema,
                outer_group,
                outer_aggs,
                node,
                result_exprs_override=None,
            )

        # decompose logical aggs into accumulator descriptors
        partial_aggs: list[AggDesc] = []
        result_exprs: list[Expr] = [
            Column(g.output_name(), g.qualifier if isinstance(g, Column) else None)
            for g in group_exprs
        ]
        acc_fields: list[DFField] = []
        welford_triples: dict[str, tuple[str, str, str]] = {}
        i = 0
        for a in node.agg_exprs:
            out_name = a.output_name()
            if isinstance(a, Alias):  # composed rewrites name their aggs
                a = a.expr
            assert isinstance(a, AggregateFunction), a
            if a.func == "avg":
                sname, cname = f"__acc{i}_sum", f"__acc{i}_cnt"
                partial_aggs.append(AggDesc("sum", a.arg, sname))
                partial_aggs.append(AggDesc("count", a.arg, cname))
                sum_t = _sum_type(a.arg.data_type(in_schema))
                acc_fields.append(DFField(sname, sum_t, True))
                acc_fields.append(DFField(cname, pa.int64(), False))
                result_exprs.append(
                    Alias(BinaryExpr(Column(sname), "/", Column(cname)), out_name)
                )
            elif a.func == "sum":
                nm = f"__acc{i}"
                partial_aggs.append(AggDesc("sum", a.arg, nm))
                acc_fields.append(DFField(nm, _sum_type(a.arg.data_type(in_schema)), True))
                result_exprs.append(Alias(Column(nm), out_name))
            elif a.func in ("min", "max"):
                nm = f"__acc{i}"
                partial_aggs.append(AggDesc(a.func, a.arg, nm))
                acc_fields.append(DFField(nm, a.arg.data_type(in_schema), True))
                result_exprs.append(Alias(Column(nm), out_name))
            elif a.func == "count":
                nm = f"__acc{i}"
                if a.arg is None:
                    partial_aggs.append(AggDesc("count_all", None, nm))
                else:
                    partial_aggs.append(AggDesc("count", a.arg, nm))
                acc_fields.append(DFField(nm, pa.int64(), False))
                result_exprs.append(Alias(Column(nm), out_name))
            elif a.func in VARIANCE_FUNCS:
                # Welford-style decomposition: per-partition (count, mean, M2)
                # partials — the same accumulator DataFusion's variance kernels
                # use — merged at the final phase with the mean-centered
                # formula M2 = ΣM2_i + Σn_i·(mean_i − mean)². A naive
                # sum-of-squares decomposition (q − s²/n) catastrophically
                # cancels for large-magnitude data (e.g. epoch-microsecond
                # columns); the centered form never builds huge intermediates.
                # The triple MUST stay adjacent in (cnt, mean, m2) order:
                # HashAggregateExec's final mode merges them as a unit.
                if a.distinct:
                    raise PlanningError(f"{a.func}(DISTINCT) is unsupported")
                # var_samp(v), var_pop(v), stddev(v) over the same argument
                # share ONE (cnt, mean, m2) accumulator triple — the final
                # expressions differ only in denominator/sqrt
                cached = welford_triples.get(str(a.arg))
                if cached is not None:
                    cname, mname, qname = cached
                else:
                    cname, mname, qname = f"__acc{i}_cnt", f"__acc{i}_mean", f"__acc{i}_m2"
                    x = Cast(a.arg, pa.float64())
                    partial_aggs.append(AggDesc("count", a.arg, cname))
                    partial_aggs.append(AggDesc("welford_mean", x, mname))
                    partial_aggs.append(AggDesc("welford_m2", x, qname))
                    acc_fields.append(DFField(cname, pa.int64(), False))
                    acc_fields.append(DFField(mname, pa.float64(), True))
                    acc_fields.append(DFField(qname, pa.float64(), True))
                    welford_triples[str(a.arg)] = (cname, mname, qname)
                n_f = Cast(Column(cname), pa.float64())
                denom = (
                    n_f if a.func in ("var_pop", "stddev_pop")
                    else BinaryExpr(n_f, "-", Literal(1.0))
                )
                var = BinaryExpr(Column(qname), "/", denom)
                if a.func in ("stddev_samp", "stddev_pop"):
                    var = ScalarFunction("sqrt", (var,))
                # SQL: sample forms need n>=2, population forms n>=1 (count=0
                # gives NULL sums already, but 0/0 must not leak a NaN)
                min_n = 1 if a.func in ("var_pop", "stddev_pop") else 2
                guarded = Case(
                    ((BinaryExpr(Column(cname), ">=", Literal(min_n)), var),),
                    None,
                )
                result_exprs.append(Alias(guarded, out_name))
            else:
                raise PlanningError(f"unsupported aggregate {a.func}")
            i += 1

        group_fields = [to_field(g, in_schema) for g in group_exprs]
        acc_schema = DFSchema(group_fields + acc_fields)

        partial = HashAggregateExec(child, list(group_exprs), partial_aggs, "partial", acc_schema)

        if n_group == 0:
            merged = CoalescePartitionsExec(partial)
        else:
            n = self.shuffle_partitions
            keys = [Column(f.name, f.qualifier) for f in group_fields]
            merged = RepartitionExec(partial, "hash", n, keys)

        final_group = [Column(f.name, f.qualifier) for f in group_fields]
        final_aggs = [
            AggDesc(_merge_func(d.func), Column(d.name), d.name) for d in partial_aggs
        ]
        final = HashAggregateExec(merged, final_group, final_aggs, "final", acc_schema)
        return ProjectionExec(final, result_exprs, _rebind_schema(node.schema))

    def _plan_mixed_distinct(self, node: Aggregate) -> ExecutionPlan:
        """count(DISTINCT x) mixed with mergeable aggregates — the standard
        single-distinct expansion (Spark/DataFusion do the same rewrite):

            inner:  GROUP BY keys, x  →  partials of the other aggregates
            outer:  GROUP BY keys     →  count(x) + merge of the partials

        Lowered as composed LOGICAL aggregates so the normal planner
        machinery (avg decomposition, two-phase exchange) applies at each
        level. Reference shape: q16/q94's `count(distinct order_number),
        sum(ship_cost), sum(net_profit)`."""
        distinct_aggs = [a for a in node.agg_exprs if a.func == "count_distinct"]
        dargs = {str(a.arg) for a in distinct_aggs}
        if len(dargs) > 1:
            raise PlanningError(
                "multiple DISTINCT columns mixed with other aggregates are unsupported")
        mergeable = {"sum", "count", "min", "max", "avg"}
        bad = [a.func for a in node.agg_exprs
               if a.func != "count_distinct" and a.func not in mergeable]
        if bad:
            raise PlanningError(f"count(DISTINCT) mixed with {bad[0]} is unsupported")
        darg = distinct_aggs[0].arg
        if any(str(darg) == str(g) for g in node.group_exprs):
            raise PlanningError("count(DISTINCT <group key>) is unsupported")

        inner_aggs: list[Expr] = []
        outer_aggs: list[Expr] = []
        # final projection refs are UNQUALIFIED (the outer aggregate's output
        # fields carry no qualifier); the original qualified schema is
        # re-imposed on the ProjectionExec below
        final_exprs: list[Expr] = [Column(g.output_name()) for g in node.group_exprs]
        for i, a in enumerate(node.agg_exprs):
            out_name = a.output_name()
            if a.func == "count_distinct":
                outer_aggs.append(Alias(
                    AggregateFunction("count", Column(darg.output_name())), out_name))
                final_exprs.append(Column(out_name))
            elif a.func in ("min", "max", "sum", "count"):
                nm = f"__d{i}"
                inner_aggs.append(Alias(AggregateFunction(a.func, a.arg), nm))
                outer_fn = "sum" if a.func == "count" else a.func
                outer_aggs.append(Alias(AggregateFunction(outer_fn, Column(nm)), out_name))
                final_exprs.append(Column(out_name))
            else:  # avg: sum-of-sums / sum-of-counts at the final projection
                sn, cn = f"__d{i}_s", f"__d{i}_c"
                inner_aggs.append(Alias(AggregateFunction("sum", a.arg), sn))
                inner_aggs.append(Alias(AggregateFunction("count", a.arg), cn))
                outer_aggs.append(Alias(AggregateFunction("sum", Column(sn)), sn))
                outer_aggs.append(Alias(AggregateFunction("sum", Column(cn)), cn))
                final_exprs.append(Alias(
                    BinaryExpr(Cast(Column(sn), pa.float64()), "/",
                               Cast(Column(cn), pa.float64())), out_name))

        inner = Aggregate(node.input, list(node.group_exprs) + [darg], inner_aggs)
        outer_group = [Column(g.output_name()) for g in node.group_exprs]
        outer = Aggregate(inner, outer_group, outer_aggs)
        outer_planned = self._plan_aggregate(outer)
        return ProjectionExec(outer_planned, final_exprs, _rebind_schema(node.schema))

    def _two_phase(self, inner_planned, inner_schema, outer_group, outer_aggs, node, result_exprs_override):
        """Lower the count-distinct outer aggregate over a pre-deduped input."""
        acc_fields = [to_field(g, inner_schema) for g in outer_group] + [
            DFField(d.name, pa.int64(), False) for d in outer_aggs
        ]
        acc_schema = DFSchema(acc_fields)
        partial = HashAggregateExec(inner_planned, list(outer_group), outer_aggs, "partial", acc_schema)
        if outer_group:
            keys = [Column(f.name, f.qualifier) for f in acc_fields[: len(outer_group)]]
            merged = RepartitionExec(partial, "hash", self.shuffle_partitions, keys)
        else:
            merged = CoalescePartitionsExec(partial)
        final_aggs = [AggDesc("sum", Column(d.name), d.name) for d in outer_aggs]
        final_group = [Column(f.name, f.qualifier) for f in acc_fields[: len(outer_group)]]
        final = HashAggregateExec(merged, final_group, final_aggs, "final", acc_schema)
        result_exprs = list(final_group) + [Alias(Column(d.name), d.name) for d in outer_aggs]
        return ProjectionExec(final, result_exprs, _rebind_schema(node.schema))

    # ------------------------------------------------------------------

    def _plan_join(self, node: Join) -> ExecutionPlan:
        from ballista_tpu.plan.logical import Filter as LFilter

        jt = node.join_type
        join_filter = node.filter
        # ON-clause predicates that touch only the NULL-SUPPLYING side of a
        # one-sided outer join are equivalent to pre-filtering that input
        # (a failing row can never match; it is not itself emitted). Pushing
        # them down clears the join filter — which also unlocks the device
        # outer-join lift. Invalid for FULL (both sides emit unmatched).
        if join_filter is not None and jt in ("left", "right"):
            null_side = node.right if jt == "left" else node.left
            other = node.left if jt == "left" else node.right
            if _refs_only(join_filter, null_side.schema, other.schema):
                filtered = LFilter(null_side, join_filter)
                if jt == "left":
                    node = Join(node.left, filtered, node.on, jt, None)
                else:
                    node = Join(filtered, node.right, node.on, jt, None)
                join_filter = None

        left = self._plan(node.left)
        right = self._plan(node.right)
        l_rows = estimate_rows(node.left)
        r_rows = estimate_rows(node.right)

        semi_keys_rows = int(self.config.get(BROADCAST_SEMI_KEYS_THRESHOLD))
        # choose build side (exec always builds its LEFT input)
        swap = False
        if jt in ("inner", "full"):
            swap = r_rows < l_rows
        elif jt in ("left", "right"):
            swap = r_rows < l_rows
            # engine=tpu prefers the null-supplying side as the BUILD so the
            # emitted side stays a probe-driven device chain (right outer on
            # device: unmatched probe rows gather NULL build columns) —
            # worth it when the null-supplying side is collectable
            if str(self.config.get(EXECUTOR_ENGINE)) == "tpu" and join_filter is None:
                # outer builds ship FULL payload columns, so only the normal
                # row-broadcast budget applies (not the keys-only relaxation)
                null_rows = r_rows if jt == "left" else l_rows
                if null_rows <= self.broadcast_rows:
                    swap = jt == "left"  # build must end up the null side
        elif jt in ("left_semi", "left_anti"):
            swap = True  # build the (usually small) subquery side, probe outer
            if r_rows > l_rows * 4:
                swap = False
            # engine=tpu: filterless semi/anti builds ship membership keys
            # only (the device build skips payload encode), so the collect
            # threshold relaxes — keep the subquery side as build whenever
            # its keys still fit (q4: orders SEMI lineitem). The CPU engine
            # collects full rows, so it keeps the strict rules.
            if (not swap and join_filter is None and r_rows <= semi_keys_rows
                    and str(self.config.get(EXECUTOR_ENGINE)) == "tpu"):
                swap = True
        elif jt in ("right_semi", "right_anti"):
            swap = False

        if swap:
            build, probe = right, left
            build_rows = r_rows
            on = [(r, l) for (l, r) in node.on]
            exec_jt = _swap_join_type(jt)
            build_schema, probe_schema = node.right.schema, node.left.schema
        else:
            build, probe = left, right
            build_rows = l_rows
            on = list(node.on)
            exec_jt = jt
            build_schema, probe_schema = node.left.schema, node.right.schema

        broadcast = build_rows <= self.broadcast_rows or probe.output_partition_count() == 1
        if (exec_jt in ("right_semi", "right_anti") and node.filter is None
                and build_rows <= semi_keys_rows
                and str(self.config.get(EXECUTOR_ENGINE)) == "tpu"):
            broadcast = True  # membership keys only: relaxed collect budget

        # build-side-emitting joins (left/full/left_semi/left_anti after the
        # swap) need every probe row to pass through ONE join instance before
        # the unmatched-build tail can be emitted. Distributed tasks each
        # decode their own plan copy, so CollectLeft is only sound when the
        # probe is a single partition; otherwise co-hash-partition both sides
        # and let each task own its build partition outright.
        build_emitting = exec_jt in ("left", "full", "left_semi", "left_anti")
        if build_emitting and probe.output_partition_count() > 1:
            broadcast = False

        adaptive_defer = (
            bool(self.config.get(PLANNER_ADAPTIVE_ENABLED))
            and bool(self.config.get(AQE_DYNAMIC_JOIN_SELECTION))
            and int(self.config.get(BROADCAST_JOIN_THRESHOLD)) > 0
        )
        # HEDGE: a broadcast whose build ESTIMATE lands within
        # `aqe.join.hedge.factor` of the threshold is one bad cardinality
        # guess away from collecting an oversized build on every probe task.
        # When AQE can re-decide with actual sizes, keep the co-partitioned
        # layout and defer: the node resolves to collect_left when the build
        # truly fits (broadcast confirmed / promoted) or to a partitioned
        # join when it came in oversized (broadcast DEMOTED,
        # aqe_stats.broadcast_demotions). Never hedge a single-partition
        # probe (collect there is free and sometimes the only legal mode)
        # or the keys-only semi relaxation (its build intentionally exceeds
        # the row budget). Never hedge under engine=tpu either: only
        # collect-build chains compile into device stages, so demoting a
        # near-threshold broadcast there trades a compilable plan for a
        # host-only one — the out-of-core admission ladder already covers
        # oversized device builds.
        hedged = (
            broadcast and adaptive_defer
            and not self.device_engine
            and probe.output_partition_count() > 1
            and exec_jt in ("inner", "right", "right_semi", "right_anti")
            and 0 < build_rows <= self.broadcast_rows
            and build_rows * float(self.config.get(AQE_JOIN_HEDGE_FACTOR))
            > self.broadcast_rows
        )
        if broadcast and not hedged:
            mode = "collect_left"
        else:
            mode = "partitioned"
            n = self.shuffle_partitions
            build = RepartitionExec(build, "hash", n, [l for l, _ in on])
            probe = RepartitionExec(probe, "hash", n, [r for _, r in on])

        exec_schema = _join_exec_schema(build_schema, probe_schema, exec_jt)
        if mode == "partitioned" and adaptive_defer:
            # the partitioned decision rests on row ESTIMATES: defer it.
            # The node resolves to a concrete join either at stage
            # resolution (stats known, scheduler/aqe/rules.py) or at
            # first-batch time inside the stage (ops/cpu/dynamic_join.py) —
            # the reference's DelayJoinSelectionRule + dynamic_join.rs pair.
            from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

            j: ExecutionPlan = DynamicJoinSelectionExec(
                build, probe, on, exec_jt, node.filter, exec_schema,
                planned_mode="collect_left" if hedged else "partitioned")
        else:
            j = HashJoinExec(build, probe, on, exec_jt, node.filter, mode, exec_schema)

        if swap and exec_jt in ("inner", "left", "right", "full"):
            order = [Column(f.name, f.qualifier) for f in node.schema]
            return ProjectionExec(j, order, node.schema)
        return j


def _refs_only(e: Expr, inside, outside) -> bool:
    """True iff every Column in `e` resolves in `inside` and none resolve in
    `outside` (conservative: an ambiguous name blocks the pushdown)."""
    cols: list[Column] = []

    def walk(x: Expr):
        if isinstance(x, Column):
            cols.append(x)
        for c in x.children():
            walk(c)

    walk(e)
    for c in cols:
        if inside.maybe_index_of(c.name, c.qualifier) is None:
            return False
        if outside.maybe_index_of(c.name, c.qualifier) is not None:
            return False
    return True


def _swap_join_type(jt: str) -> str:
    return {
        "inner": "inner", "left": "right", "right": "left", "full": "full",
        "left_semi": "right_semi", "left_anti": "right_anti",
        "right_semi": "left_semi", "right_anti": "left_anti",
    }[jt]


def _join_exec_schema(build_schema: DFSchema, probe_schema: DFSchema, jt: str) -> DFSchema:
    if jt in ("left_semi", "left_anti"):
        return build_schema
    if jt in ("right_semi", "right_anti"):
        return probe_schema
    return build_schema.merge(probe_schema)


def _sum_type(t: pa.DataType) -> pa.DataType:
    from ballista_tpu.plan.expressions import sum_result_type

    return sum_result_type(t)


def _merge_func(f: str) -> str:
    return {"sum": "sum", "min": "min", "max": "max", "count": "count",
            "count_all": "count_all", "welford_mean": "welford_mean",
            "welford_m2": "welford_m2"}[f]


def _rebind_schema(s: DFSchema) -> DFSchema:
    return s


# -- crude cardinality estimator (join selection / broadcast decisions) -----

from ballista_tpu.utils.lru import LruDict

_EST_CACHE = LruDict(max_entries=4096)


def estimate_rows(node: LogicalPlan) -> float:
    # id()-keyed memo MUST validate identity: CPython recycles addresses,
    # so a freed plan node's id can alias a new node and return a stale
    # estimate (observed as join-mode flapping between runs). The weakref
    # proves the cached entry belongs to THIS object.
    import weakref

    key = id(node)
    hit = _EST_CACHE.get(key)
    if hit is not None and hit[1]() is node:
        return hit[0]
    v = _estimate(node)
    try:
        ref = weakref.ref(node)
    except TypeError:  # un-weakrefable: skip caching
        return v
    _EST_CACHE[key] = (v, ref)
    return v


def _estimate(node: LogicalPlan) -> float:
    if isinstance(node, TableScan):
        stats = node.provider.statistics()
        base = float(stats.num_rows) if stats.num_rows is not None else 1e6
        return max(1.0, base * (0.3 ** len(node.filters)))
    if isinstance(node, Filter):
        return max(1.0, estimate_rows(node.input) * 0.3)
    if isinstance(node, Join):
        l, r = estimate_rows(node.left), estimate_rows(node.right)
        if node.join_type in ("left_semi", "left_anti"):
            return max(1.0, l * 0.5)
        if node.join_type in ("right_semi", "right_anti"):
            return max(1.0, r * 0.5)
        return max(l, r)
    if isinstance(node, CrossJoin):
        return max(1.0, min(estimate_rows(node.left) * estimate_rows(node.right), 1e12))
    if isinstance(node, Aggregate):
        if not node.group_exprs:
            return 1.0
        return max(1.0, estimate_rows(node.input) * 0.1)
    if isinstance(node, Distinct):
        return max(1.0, estimate_rows(node.input) * 0.5)
    if isinstance(node, Limit):
        base = estimate_rows(node.input)
        return min(base, node.fetch if node.fetch is not None else base)
    if isinstance(node, Union):
        return sum(estimate_rows(c) for c in node.inputs)
    kids = node.children()
    if kids:
        return estimate_rows(kids[0])
    return 1.0
