"""Version and wire-protocol gate.

The reference gates executor registration on an exact wire-protocol version
match (ballista/core/src/lib.rs:30-42: BALLISTA_VERSION is baked into
PollWorkParams / RegisterExecutorParams and mismatches are rejected at
registration). We keep the same behavior but separate the human version from
the wire version so bugfix releases don't force lock-step upgrades.
"""

BALLISTA_VERSION = "0.1.0"

# Bump whenever the plan protobuf, task definition, or shuffle file layout
# changes incompatibly. Schedulers reject executors with a different value.
WIRE_PROTOCOL_VERSION = "btpu-1"
