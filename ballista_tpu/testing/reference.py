"""Pandas reference implementations of the 22 TPC-H queries.

The correctness oracle: every query hand-written directly against pandas,
sharing NO code with the engine (parser/planner/operators), so a bug in the
engine cannot hide in the oracle. Plays the role of the reference's
expected-results verification (benchmarks/src/bin/tpch.rs `verify` +
.github/workflows/rust.yml "verify that benchmark queries return expected
results").

`run_reference(qnum, tables)` returns a pandas DataFrame whose columns are
ordered like the SQL SELECT list.
"""

from __future__ import annotations

import datetime as dt

import numpy as np
import pandas as pd


def load_tables(data_dir: str,
                columns: dict[str, list[str]] | None = None) -> dict[str, pd.DataFrame]:
    """Load the 8 TPC-H tables into pandas. `columns` optionally restricts
    each table to a projection — at SF10 the full tables cost ~40 GB
    (object-dtype comment strings dominate) and per-query merge
    intermediates stack on top, so large-scale gates must pass the union
    of columns their queries actually reference."""
    import glob
    import os

    import pyarrow.parquet as pq

    out = {}
    for t in ("region", "nation", "supplier", "part", "partsupp", "customer", "orders", "lineitem"):
        files = sorted(glob.glob(os.path.join(data_dir, t, "*.parquet")))
        cols = (columns or {}).get(t)
        df = pd.concat(
            [pq.read_table(f, columns=cols).to_pandas(date_as_object=False) for f in files],
            ignore_index=True)
        out[t] = df
    return out


def _d(s: str):
    return pd.Timestamp(s)


def run_reference(q: int, t: dict[str, pd.DataFrame]) -> pd.DataFrame:
    return _QUERIES[q](t)


def q1(t):
    li = t["lineitem"]
    df = li[li.l_shipdate <= _d("1998-09-02")].copy()
    df["disc_price"] = df.l_extendedprice * (1 - df.l_discount)
    df["charge"] = df.disc_price * (1 + df.l_tax)
    g = df.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    )
    return g.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)


def q2(t):
    part, supp, ps, nat, reg = t["part"], t["supplier"], t["partsupp"], t["nation"], t["region"]
    eu = reg[reg.r_name == "EUROPE"]
    n = nat.merge(eu, left_on="n_regionkey", right_on="r_regionkey")
    s = supp.merge(n, left_on="s_nationkey", right_on="n_nationkey")
    x = ps.merge(s, left_on="ps_suppkey", right_on="s_suppkey")
    mins = x.groupby("ps_partkey")["ps_supplycost"].min().rename("min_cost").reset_index()
    p = part[(part.p_size == 15) & part.p_type.str.endswith("BRASS")]
    y = x.merge(p, left_on="ps_partkey", right_on="p_partkey")
    y = y.merge(mins, on="ps_partkey")
    y = y[y.ps_supplycost == y.min_cost]
    out = y[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr", "s_address", "s_phone", "s_comment"]]
    out = out.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                          ascending=[False, True, True, True]).head(100)
    return out.reset_index(drop=True)


def q3(t):
    c = t["customer"][t["customer"].c_mktsegment == "BUILDING"]
    o = t["orders"][t["orders"].o_orderdate < _d("1995-03-15")]
    l = t["lineitem"][t["lineitem"].l_shipdate > _d("1995-03-15")].copy()
    x = c.merge(o, left_on="c_custkey", right_on="o_custkey").merge(
        l, left_on="o_orderkey", right_on="l_orderkey"
    )
    x["revenue"] = x.l_extendedprice * (1 - x.l_discount)
    g = x.groupby(["l_orderkey", "o_orderdate", "o_shippriority"], as_index=False)["revenue"].sum()
    g = g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]]
    return g.sort_values(["revenue", "o_orderdate"], ascending=[False, True]).head(10).reset_index(drop=True)


def q4(t):
    o = t["orders"]
    o = o[(o.o_orderdate >= _d("1993-07-01")) & (o.o_orderdate < _d("1993-10-01"))]
    l = t["lineitem"]
    l = l[l.l_commitdate < l.l_receiptdate]
    ok = o[o.o_orderkey.isin(l.l_orderkey)]
    g = ok.groupby("o_orderpriority", as_index=False).size().rename(columns={"size": "order_count"})
    return g.sort_values("o_orderpriority").reset_index(drop=True)


def q5(t):
    r = t["region"][t["region"].r_name == "ASIA"]
    n = t["nation"].merge(r, left_on="n_regionkey", right_on="r_regionkey")
    o = t["orders"]
    o = o[(o.o_orderdate >= _d("1994-01-01")) & (o.o_orderdate < _d("1995-01-01"))]
    x = (
        t["customer"]
        .merge(o, left_on="c_custkey", right_on="o_custkey")
        .merge(t["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    )
    x = x[x.c_nationkey == x.s_nationkey]
    x = x.merge(n, left_on="s_nationkey", right_on="n_nationkey")
    x["revenue"] = x.l_extendedprice * (1 - x.l_discount)
    g = x.groupby("n_name", as_index=False)["revenue"].sum()
    return g.sort_values("revenue", ascending=False).reset_index(drop=True)


def q6(t):
    l = t["lineitem"]
    m = (
        (l.l_shipdate >= _d("1994-01-01"))
        & (l.l_shipdate < _d("1995-01-01"))
        & (l.l_discount >= 0.05)
        & (l.l_discount <= 0.07)
        & (l.l_quantity < 24)
    )
    return pd.DataFrame({"revenue": [(l[m].l_extendedprice * l[m].l_discount).sum()]})


def q7(t):
    n1 = t["nation"].rename(columns=lambda c: c + "_1")
    n2 = t["nation"].rename(columns=lambda c: c + "_2")
    x = (
        t["supplier"]
        .merge(t["lineitem"], left_on="s_suppkey", right_on="l_suppkey")
        .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
        .merge(n1, left_on="s_nationkey", right_on="n_nationkey_1")
        .merge(n2, left_on="c_nationkey", right_on="n_nationkey_2")
    )
    x = x[
        ((x.n_name_1 == "FRANCE") & (x.n_name_2 == "GERMANY"))
        | ((x.n_name_1 == "GERMANY") & (x.n_name_2 == "FRANCE"))
    ]
    x = x[(x.l_shipdate >= _d("1995-01-01")) & (x.l_shipdate <= _d("1996-12-31"))].copy()
    x["l_year"] = x.l_shipdate.dt.year
    x["volume"] = x.l_extendedprice * (1 - x.l_discount)
    g = x.groupby(["n_name_1", "n_name_2", "l_year"], as_index=False)["volume"].sum()
    g.columns = ["supp_nation", "cust_nation", "l_year", "revenue"]
    return g.sort_values(["supp_nation", "cust_nation", "l_year"]).reset_index(drop=True)


def q8(t):
    r = t["region"][t["region"].r_name == "AMERICA"]
    n1 = t["nation"].merge(r, left_on="n_regionkey", right_on="r_regionkey")
    p = t["part"][t["part"].p_type == "ECONOMY ANODIZED STEEL"]
    o = t["orders"]
    o = o[(o.o_orderdate >= _d("1995-01-01")) & (o.o_orderdate <= _d("1996-12-31"))]
    x = (
        p.merge(t["lineitem"], left_on="p_partkey", right_on="l_partkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
        .merge(t["customer"], left_on="o_custkey", right_on="c_custkey")
        .merge(n1[["n_nationkey"]], left_on="c_nationkey", right_on="n_nationkey")
        .merge(t["nation"][["n_nationkey", "n_name"]].rename(columns={"n_nationkey": "nk2", "n_name": "nation"}),
               left_on="s_nationkey", right_on="nk2")
    )
    x["o_year"] = x.o_orderdate.dt.year
    x["volume"] = x.l_extendedprice * (1 - x.l_discount)
    x["brazil_volume"] = np.where(x.nation == "BRAZIL", x.volume, 0.0)
    g = x.groupby("o_year", as_index=False).agg(bv=("brazil_volume", "sum"), v=("volume", "sum"))
    g["mkt_share"] = g.bv / g.v
    return g[["o_year", "mkt_share"]].sort_values("o_year").reset_index(drop=True)


def q9(t):
    p = t["part"][t["part"].p_name.str.contains("green")]
    x = (
        p.merge(t["lineitem"], left_on="p_partkey", right_on="l_partkey")
        .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
        .merge(t["partsupp"], left_on=["l_suppkey", "l_partkey"], right_on=["ps_suppkey", "ps_partkey"])
        .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
        .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
    )
    x["o_year"] = x.o_orderdate.dt.year
    x["amount"] = x.l_extendedprice * (1 - x.l_discount) - x.ps_supplycost * x.l_quantity
    g = x.groupby(["n_name", "o_year"], as_index=False)["amount"].sum()
    g.columns = ["nation", "o_year", "sum_profit"]
    return g.sort_values(["nation", "o_year"], ascending=[True, False]).reset_index(drop=True)


def q10(t):
    o = t["orders"]
    o = o[(o.o_orderdate >= _d("1993-10-01")) & (o.o_orderdate < _d("1994-01-01"))]
    l = t["lineitem"][t["lineitem"].l_returnflag == "R"]
    x = (
        t["customer"]
        .merge(o, left_on="c_custkey", right_on="o_custkey")
        .merge(l, left_on="o_orderkey", right_on="l_orderkey")
        .merge(t["nation"], left_on="c_nationkey", right_on="n_nationkey")
    )
    x["revenue"] = x.l_extendedprice * (1 - x.l_discount)
    g = x.groupby(
        ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name", "c_address", "c_comment"],
        as_index=False,
    )["revenue"].sum()
    g = g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name", "c_address", "c_phone", "c_comment"]]
    return g.sort_values("revenue", ascending=False).head(20).reset_index(drop=True)


def q11(t):
    n = t["nation"][t["nation"].n_name == "GERMANY"]
    x = (
        t["partsupp"]
        .merge(t["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
    )
    x["value"] = x.ps_supplycost * x.ps_availqty
    total = x.value.sum() * 0.0001
    g = x.groupby("ps_partkey", as_index=False)["value"].sum()
    g = g[g.value > total]
    return g.sort_values("value", ascending=False).reset_index(drop=True)


def q12(t):
    l = t["lineitem"]
    l = l[
        l.l_shipmode.isin(["MAIL", "SHIP"])
        & (l.l_commitdate < l.l_receiptdate)
        & (l.l_shipdate < l.l_commitdate)
        & (l.l_receiptdate >= _d("1994-01-01"))
        & (l.l_receiptdate < _d("1995-01-01"))
    ]
    x = t["orders"].merge(l, left_on="o_orderkey", right_on="l_orderkey")
    hi = x.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    x = x.assign(high_line=np.where(hi, 1, 0), low_line=np.where(~hi, 1, 0))
    g = x.groupby("l_shipmode", as_index=False).agg(
        high_line_count=("high_line", "sum"), low_line_count=("low_line", "sum")
    )
    return g.sort_values("l_shipmode").reset_index(drop=True)


def q13(t):
    o = t["orders"][~t["orders"].o_comment.str.contains("special.*requests", regex=True)]
    merged = t["customer"].merge(o, left_on="c_custkey", right_on="o_custkey", how="left")
    g = merged.groupby("c_custkey")["o_orderkey"].count().rename("c_count").reset_index()
    d = g.groupby("c_count", as_index=False).size().rename(columns={"size": "custdist"})
    d = d[["c_count", "custdist"]]
    return d.sort_values(["custdist", "c_count"], ascending=[False, False]).reset_index(drop=True)


def q14(t):
    l = t["lineitem"]
    l = l[(l.l_shipdate >= _d("1995-09-01")) & (l.l_shipdate < _d("1995-10-01"))]
    x = l.merge(t["part"], left_on="l_partkey", right_on="p_partkey")
    x["rev"] = x.l_extendedprice * (1 - x.l_discount)
    promo = x[x.p_type.str.startswith("PROMO")].rev.sum()
    return pd.DataFrame({"promo_revenue": [100.0 * promo / x.rev.sum()]})


def q15(t):
    l = t["lineitem"]
    l = l[(l.l_shipdate >= _d("1996-01-01")) & (l.l_shipdate < _d("1996-04-01"))].copy()
    l["rev"] = l.l_extendedprice * (1 - l.l_discount)
    rev = l.groupby("l_suppkey", as_index=False)["rev"].sum()
    rev.columns = ["supplier_no", "total_revenue"]
    mx = rev.total_revenue.max()
    x = t["supplier"].merge(rev[rev.total_revenue == mx], left_on="s_suppkey", right_on="supplier_no")
    out = x[["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]
    return out.sort_values("s_suppkey").reset_index(drop=True)


def q16(t):
    bad_supp = t["supplier"][t["supplier"].s_comment.str.contains("Customer.*Complaints", regex=True)].s_suppkey
    p = t["part"]
    p = p[(p.p_brand != "Brand#45") & ~p.p_type.str.startswith("MEDIUM POLISHED")
          & p.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    x = t["partsupp"].merge(p, left_on="ps_partkey", right_on="p_partkey")
    x = x[~x.ps_suppkey.isin(bad_supp)]
    g = (
        x.groupby(["p_brand", "p_type", "p_size"])["ps_suppkey"]
        .nunique()
        .rename("supplier_cnt")
        .reset_index()
    )
    g = g[["p_brand", "p_type", "p_size", "supplier_cnt"]]
    return g.sort_values(
        ["supplier_cnt", "p_brand", "p_type", "p_size"], ascending=[False, True, True, True]
    ).reset_index(drop=True)


def q17(t):
    p = t["part"][(t["part"].p_brand == "Brand#23") & (t["part"].p_container == "MED BOX")]
    l = t["lineitem"]
    avg_qty = l.groupby("l_partkey")["l_quantity"].mean().rename("avg_q").reset_index()
    x = l.merge(p, left_on="l_partkey", right_on="p_partkey").merge(avg_qty, on="l_partkey")
    x = x[x.l_quantity < 0.2 * x.avg_q]
    return pd.DataFrame({"avg_yearly": [x.l_extendedprice.sum() / 7.0]})


def q18(t):
    l = t["lineitem"]
    big = l.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > 300].index
    o = t["orders"][t["orders"].o_orderkey.isin(big)]
    x = t["customer"].merge(o, left_on="c_custkey", right_on="o_custkey").merge(
        l, left_on="o_orderkey", right_on="l_orderkey"
    )
    g = x.groupby(
        ["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"], as_index=False
    )["l_quantity"].sum()
    g.columns = ["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice", "total_quantity"]
    return g.sort_values(["o_totalprice", "o_orderdate"], ascending=[False, True]).head(100).reset_index(drop=True)


def q19(t):
    x = t["lineitem"].merge(t["part"], left_on="l_partkey", right_on="p_partkey")
    x = x[x.l_shipmode.isin(["AIR", "AIR REG"]) & (x.l_shipinstruct == "DELIVER IN PERSON")]
    b1 = (
        (x.p_brand == "Brand#12")
        & x.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
        & (x.l_quantity >= 1) & (x.l_quantity <= 11)
        & (x.p_size >= 1) & (x.p_size <= 5)
    )
    b2 = (
        (x.p_brand == "Brand#23")
        & x.p_container.isin(["MED BAG", "MED BOX", "MED PKG", "MED PACK"])
        & (x.l_quantity >= 10) & (x.l_quantity <= 20)
        & (x.p_size >= 1) & (x.p_size <= 10)
    )
    b3 = (
        (x.p_brand == "Brand#34")
        & x.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
        & (x.l_quantity >= 20) & (x.l_quantity <= 30)
        & (x.p_size >= 1) & (x.p_size <= 15)
    )
    sel = x[b1 | b2 | b3]
    return pd.DataFrame({"revenue": [(sel.l_extendedprice * (1 - sel.l_discount)).sum()]})


def q20(t):
    forest = t["part"][t["part"].p_name.str.startswith("forest")].p_partkey
    l = t["lineitem"]
    l = l[(l.l_shipdate >= _d("1994-01-01")) & (l.l_shipdate < _d("1995-01-01"))]
    half = (
        l.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum().rename("qty").reset_index()
    )
    ps = t["partsupp"][t["partsupp"].ps_partkey.isin(forest)]
    x = ps.merge(half, left_on=["ps_partkey", "ps_suppkey"], right_on=["l_partkey", "l_suppkey"])
    x = x[x.ps_availqty > 0.5 * x.qty]
    n = t["nation"][t["nation"].n_name == "CANADA"]
    s = t["supplier"][t["supplier"].s_suppkey.isin(x.ps_suppkey)]
    s = s.merge(n, left_on="s_nationkey", right_on="n_nationkey")
    return s[["s_name", "s_address"]].sort_values("s_name").reset_index(drop=True)


def q21(t):
    l = t["lineitem"]
    n = t["nation"][t["nation"].n_name == "SAUDI ARABIA"]
    o = t["orders"][t["orders"].o_orderstatus == "F"]
    l1 = l[l.l_receiptdate > l.l_commitdate]
    x = (
        t["supplier"]
        .merge(n, left_on="s_nationkey", right_on="n_nationkey")
        .merge(l1, left_on="s_suppkey", right_on="l_suppkey")
        .merge(o, left_on="l_orderkey", right_on="o_orderkey")
    )
    # exists: another supplier on the same order
    sup_per_order = l.groupby("l_orderkey")["l_suppkey"].nunique().rename("nsupp")
    x = x.join(sup_per_order, on="l_orderkey")
    x = x[x.nsupp > 1]
    # not exists: no OTHER supplier was late on the order
    late = l[l.l_receiptdate > l.l_commitdate]
    late_sup_per_order = late.groupby("l_orderkey")["l_suppkey"].nunique().rename("nlate")
    x = x.join(late_sup_per_order, on="l_orderkey")
    x = x[x.nlate == 1]  # only this supplier late
    g = x.groupby("s_name", as_index=False).size().rename(columns={"size": "numwait"})
    return g.sort_values(["numwait", "s_name"], ascending=[False, True]).head(100).reset_index(drop=True)


def q22(t):
    c = t["customer"].copy()
    c["cntrycode"] = c.c_phone.str[:2]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    c = c[c.cntrycode.isin(codes)]
    avg_bal = c[c.c_acctbal > 0.0].c_acctbal.mean()
    c = c[c.c_acctbal > avg_bal]
    c = c[~c.c_custkey.isin(t["orders"].o_custkey)]
    g = c.groupby("cntrycode", as_index=False).agg(
        numcust=("c_acctbal", "size"), totacctbal=("c_acctbal", "sum")
    )
    return g.sort_values("cntrycode").reset_index(drop=True)


_QUERIES = {
    1: q1, 2: q2, 3: q3, 4: q4, 5: q5, 6: q6, 7: q7, 8: q8, 9: q9, 10: q10,
    11: q11, 12: q12, 13: q13, 14: q14, 15: q15, 16: q16, 17: q17, 18: q18,
    19: q19, 20: q20, 21: q21, 22: q22,
}


def compare_results(engine_table, ref_df: pd.DataFrame, q: int, sort_insensitive_tail: bool = True,
                    rtol: float = 1e-6) -> list[str]:
    """Compare engine output (pa.Table) with the oracle. Returns a list of
    mismatch descriptions (empty = pass). Column names are compared
    positionally; floats with relative tolerance; fully-sorted queries
    compare row-for-row, ties broken by sorting both sides identically."""
    problems: list[str] = []
    eng = engine_table.to_pandas(date_as_object=False)
    if len(eng) != len(ref_df):
        problems.append(f"q{q}: row count {len(eng)} != expected {len(ref_df)}")
        return problems
    if len(eng.columns) != len(ref_df.columns):
        problems.append(f"q{q}: column count {len(eng.columns)} != {len(ref_df.columns)}")
        return problems
    eng = eng.copy()
    eng.columns = list(ref_df.columns)
    # canonical order: sort both by all columns (stable for ties/limit-less)
    def canon(df):
        cols = list(df.columns)
        try:
            return df.sort_values(cols, kind="mergesort").reset_index(drop=True)
        except Exception:
            return df.reset_index(drop=True)

    a, b = canon(eng), canon(ref_df)
    for col in ref_df.columns:
        av, bv = a[col], b[col]
        if pd.api.types.is_float_dtype(bv) or pd.api.types.is_float_dtype(av):
            av = av.astype(float)
            bv = bv.astype(float)
            bad = ~np.isclose(av, bv, rtol=rtol, equal_nan=True)
            if bad.any():
                i = int(np.argmax(bad))
                problems.append(f"q{q}: col {col} mismatch at row {i}: {av[i]} != {bv[i]}")
        else:
            if pd.api.types.is_datetime64_any_dtype(bv) or pd.api.types.is_datetime64_any_dtype(av):
                av = pd.to_datetime(av)
                bv = pd.to_datetime(bv)
            bad = av.astype(object) != bv.astype(object)
            if bad.any():
                i = int(np.argmax(bad.values))
                problems.append(f"q{q}: col {col} mismatch at row {i}: {av.iloc[i]!r} != {bv.iloc[i]!r}")
    return problems
