"""Seeded synthetic TPC-DS data generator (core retail-sales tables).

Schema-faithful (column names/types the query set references) rebuild of
the reference's tpcds benchmark data leg (benchmarks/src/bin/tpcds.rs uses
externally generated data; zero-egress here, so we generate). Value
distributions are simplified but seeded and referentially intact: every
store_sales foreign key resolves, dates cover 1998-2002 with proper
year/moy/dom breakdowns.

Scale: `scale=1.0` ≈ 300k store_sales rows (tunable; the point is plan
shape + correctness, perf scaling comes from --scale).
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

BRANDS = [f"brand#{i}" for i in range(1, 61)]
CATEGORIES = ["Sports", "Books", "Home", "Electronics", "Jewelry", "Men", "Women",
              "Music", "Children", "Shoes"]
CLASSES = [f"class#{i}" for i in range(1, 31)]
CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
          "Liberty", "Pleasant Hill", "Union", "Salem", "Georgetown"]
COUNTIES = [f"{c} County" for c in ("Williamson", "Walker", "Ziebach", "Daviess",
                                    "Barrow", "Franklin", "Luce", "Richland")]
STATES = ["TN", "TX", "SD", "IN", "GA", "OH", "MI", "MT", "CA", "NY"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"]


def generate_tpcds(out_dir: str, scale: float = 1.0, seed: int = 17,
                   files_per_table: int = 2) -> None:
    rng = np.random.default_rng(seed)
    n_sales = max(int(300_000 * scale), 1_000)
    n_items = max(int(2_000 * scale**0.5), 200)
    n_customers = max(int(10_000 * scale**0.5), 500)
    n_addresses = max(n_customers // 2, 250)
    n_stores = max(int(12 * scale**0.5), 6)
    n_cd = 1920  # cross of demographics like the spec
    n_hd = 720
    n_promos = 30

    # ---- date_dim: calendar 1998-01-01 .. 2002-12-31 --------------------
    start = dt.date(1998, 1, 1)
    days = (dt.date(2002, 12, 31) - start).days + 1
    dates = [start + dt.timedelta(days=i) for i in range(days)]
    date_dim = pa.table({
        "d_date_sk": pa.array(range(2450815, 2450815 + days), pa.int64()),
        "d_date": pa.array(dates, pa.date32()),
        "d_year": pa.array([d.year for d in dates], pa.int64()),
        "d_moy": pa.array([d.month for d in dates], pa.int64()),
        "d_dom": pa.array([d.day for d in dates], pa.int64()),
        "d_qoy": pa.array([(d.month - 1) // 3 + 1 for d in dates], pa.int64()),
        "d_day_name": pa.array([DAY_NAMES[d.isoweekday() % 7] for d in dates]),
    })

    # ---- time_dim --------------------------------------------------------
    secs = np.arange(0, 86400, 60)  # minute granularity keeps it small
    time_dim = pa.table({
        "t_time_sk": pa.array(secs, pa.int64()),
        "t_hour": pa.array(secs // 3600, pa.int64()),
        "t_minute": pa.array((secs % 3600) // 60, pa.int64()),
    })

    # ---- item ------------------------------------------------------------
    brand_ids = rng.integers(1, 1000, n_items)
    cat_ids = rng.integers(0, len(CATEGORIES), n_items)
    class_ids = rng.integers(0, len(CLASSES), n_items)
    item = pa.table({
        "i_item_sk": pa.array(range(1, n_items + 1), pa.int64()),
        "i_item_id": pa.array([f"AAAAAAAA{i:08d}" for i in range(1, n_items + 1)]),
        "i_item_desc": pa.array([f"item description {i}" for i in range(1, n_items + 1)]),
        "i_brand_id": pa.array(brand_ids, pa.int64()),
        "i_brand": pa.array([BRANDS[b % len(BRANDS)] for b in brand_ids]),
        "i_category_id": pa.array(cat_ids + 1, pa.int64()),
        "i_category": pa.array([CATEGORIES[c] for c in cat_ids]),
        "i_class_id": pa.array(class_ids + 1, pa.int64()),
        "i_class": pa.array([CLASSES[c] for c in class_ids]),
        "i_manufact_id": pa.array(rng.integers(1, 1000, n_items), pa.int64()),
        "i_manager_id": pa.array(rng.integers(1, 100, n_items), pa.int64()),
        "i_current_price": pa.array(np.round(rng.uniform(0.5, 300, n_items), 2)),
    })

    # ---- store -----------------------------------------------------------
    store = pa.table({
        "s_store_sk": pa.array(range(1, n_stores + 1), pa.int64()),
        "s_store_id": pa.array([f"AAAAAAAA{i:04d}BAAA" for i in range(1, n_stores + 1)]),
        "s_store_name": pa.array([f"store {i}" for i in range(1, n_stores + 1)]),
        "s_number_employees": pa.array(rng.integers(200, 300, n_stores), pa.int64()),
        "s_city": pa.array(rng.choice(CITIES, n_stores)),
        "s_county": pa.array(rng.choice(COUNTIES, n_stores)),
        "s_state": pa.array(rng.choice(STATES, n_stores)),
        "s_zip": pa.array([f"{z:05d}" for z in rng.integers(10000, 99999, n_stores)]),
        "s_gmt_offset": pa.array(rng.choice([-5.0, -6.0, -7.0, -8.0], n_stores)),
    })

    # ---- demographics ----------------------------------------------------
    cd_idx = np.arange(n_cd)
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(cd_idx + 1, pa.int64()),
        "cd_gender": pa.array(np.where(cd_idx % 2 == 0, "M", "F")),
        "cd_marital_status": pa.array([["M", "S", "D", "W", "U"][i % 5] for i in cd_idx]),
        "cd_education_status": pa.array([EDUCATION[i % len(EDUCATION)] for i in cd_idx]),
    })
    hd_idx = np.arange(n_hd)
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(hd_idx + 1, pa.int64()),
        "hd_buy_potential": pa.array([BUY_POTENTIAL[i % len(BUY_POTENTIAL)] for i in hd_idx]),
        "hd_dep_count": pa.array(hd_idx % 10, pa.int64()),
        "hd_vehicle_count": pa.array(hd_idx % 5, pa.int64()),
    })

    # ---- customer_address / customer ------------------------------------
    customer_address = pa.table({
        "ca_address_sk": pa.array(range(1, n_addresses + 1), pa.int64()),
        "ca_city": pa.array(rng.choice(CITIES, n_addresses)),
        "ca_county": pa.array(rng.choice(COUNTIES, n_addresses)),
        "ca_state": pa.array(rng.choice(STATES, n_addresses)),
        "ca_zip": pa.array([f"{z:05d}" for z in rng.integers(10000, 99999, n_addresses)]),
        "ca_country": pa.array(["United States"] * n_addresses),
        "ca_gmt_offset": pa.array(rng.choice([-5.0, -6.0, -7.0, -8.0], n_addresses)),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(range(1, n_customers + 1), pa.int64()),
        "c_customer_id": pa.array([f"AAAAAAAA{i:08d}" for i in range(1, n_customers + 1)]),
        "c_first_name": pa.array([f"First{i % 997}" for i in range(1, n_customers + 1)]),
        "c_last_name": pa.array([f"Last{i % 499}" for i in range(1, n_customers + 1)]),
        "c_current_addr_sk": pa.array(rng.integers(1, n_addresses + 1, n_customers), pa.int64()),
        "c_current_cdemo_sk": pa.array(rng.integers(1, n_cd + 1, n_customers), pa.int64()),
        "c_current_hdemo_sk": pa.array(rng.integers(1, n_hd + 1, n_customers), pa.int64()),
        "c_birth_country": pa.array(["UNITED STATES"] * n_customers),
    })

    # ---- promotion -------------------------------------------------------
    promotion = pa.table({
        "p_promo_sk": pa.array(range(1, n_promos + 1), pa.int64()),
        "p_channel_email": pa.array(["N" if i % 3 else "Y" for i in range(n_promos)]),
        "p_channel_event": pa.array(["N" if i % 2 else "Y" for i in range(n_promos)]),
    })

    # ---- store_sales (the fact table) -----------------------------------
    qty = rng.integers(1, 101, n_sales)
    wholesale = np.round(rng.uniform(1, 100, n_sales), 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, n_sales), 2)
    sales_price = np.round(list_price * rng.uniform(0.3, 1.0, n_sales), 2)
    ext_sales = np.round(sales_price * qty, 2)
    ext_list = np.round(list_price * qty, 2)
    ext_wholesale = np.round(wholesale * qty, 2)
    ext_discount = np.round(ext_list - ext_sales, 2)
    ext_tax = np.round(ext_sales * 0.06, 2)
    coupon = np.where(rng.random(n_sales) < 0.1, np.round(ext_sales * 0.1, 2), 0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    net_profit = np.round(net_paid - ext_wholesale, 2)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(rng.integers(2450815, 2450815 + days, n_sales), pa.int64()),
        "ss_sold_time_sk": pa.array(rng.choice(secs, n_sales), pa.int64()),
        "ss_item_sk": pa.array(rng.integers(1, n_items + 1, n_sales), pa.int64()),
        "ss_customer_sk": pa.array(rng.integers(1, n_customers + 1, n_sales), pa.int64()),
        "ss_cdemo_sk": pa.array(rng.integers(1, n_cd + 1, n_sales), pa.int64()),
        "ss_hdemo_sk": pa.array(rng.integers(1, n_hd + 1, n_sales), pa.int64()),
        "ss_addr_sk": pa.array(rng.integers(1, n_addresses + 1, n_sales), pa.int64()),
        "ss_store_sk": pa.array(rng.integers(1, n_stores + 1, n_sales), pa.int64()),
        "ss_promo_sk": pa.array(rng.integers(1, n_promos + 1, n_sales), pa.int64()),
        "ss_ticket_number": pa.array(rng.integers(1, n_sales // 3 + 2, n_sales), pa.int64()),
        "ss_quantity": pa.array(qty, pa.int64()),
        "ss_wholesale_cost": pa.array(wholesale),
        "ss_list_price": pa.array(list_price),
        "ss_sales_price": pa.array(sales_price),
        "ss_ext_discount_amt": pa.array(ext_discount),
        "ss_ext_sales_price": pa.array(ext_sales),
        "ss_ext_wholesale_cost": pa.array(ext_wholesale),
        "ss_ext_list_price": pa.array(ext_list),
        "ss_ext_tax": pa.array(ext_tax),
        "ss_coupon_amt": pa.array(coupon),
        "ss_net_paid": pa.array(net_paid),
        "ss_net_profit": pa.array(net_profit),
    })

    # ---- catalog_sales / web_sales (cross-channel queries) ---------------
    def channel_fact(prefix: str, rows: int, seed_off: int) -> pa.Table:
        r = np.random.default_rng(seed + seed_off)
        cqty = r.integers(1, 101, rows)
        cprice = np.round(r.uniform(1, 200, rows), 2)
        ext = np.round(cprice * cqty, 2)
        return pa.table({
            f"{prefix}_sold_date_sk": pa.array(r.integers(2450815, 2450815 + days, rows), pa.int64()),
            f"{prefix}_item_sk": pa.array(r.integers(1, n_items + 1, rows), pa.int64()),
            f"{prefix}_bill_customer_sk": pa.array(r.integers(1, n_customers + 1, rows), pa.int64()),
            f"{prefix}_bill_addr_sk": pa.array(r.integers(1, n_addresses + 1, rows), pa.int64()),
            f"{prefix}_quantity": pa.array(cqty, pa.int64()),
            f"{prefix}_sales_price": pa.array(cprice),
            f"{prefix}_ext_sales_price": pa.array(ext),
            f"{prefix}_net_profit": pa.array(np.round(ext * r.uniform(-0.2, 0.4, rows), 2)),
        })

    catalog_sales = channel_fact("cs", max(n_sales // 2, 500), 101)
    web_sales = channel_fact("ws", max(n_sales // 4, 500), 202)

    tables = {
        "date_dim": date_dim, "time_dim": time_dim, "item": item, "store": store,
        "customer": customer, "customer_address": customer_address,
        "customer_demographics": customer_demographics,
        "household_demographics": household_demographics,
        "promotion": promotion, "store_sales": store_sales,
        "catalog_sales": catalog_sales, "web_sales": web_sales,
    }
    for name, tbl in tables.items():
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        nfiles = files_per_table if name.endswith("_sales") else 1
        rows_per = (tbl.num_rows + nfiles - 1) // nfiles
        for i in range(nfiles):
            part = tbl.slice(i * rows_per, rows_per)
            pq.write_table(part, os.path.join(d, f"part-{i}.parquet"))


TPCDS_TABLES = [
    "date_dim", "time_dim", "item", "store", "customer", "customer_address",
    "customer_demographics", "household_demographics", "promotion", "store_sales",
    "catalog_sales", "web_sales",
]


def register_tpcds(ctx, data_dir: str) -> None:
    for t in TPCDS_TABLES:
        ctx.register_parquet(t, os.path.join(data_dir, t))
