"""Seeded synthetic TPC-DS data generator (core retail-sales tables).

Schema-faithful (column names/types the query set references) rebuild of
the reference's tpcds benchmark data leg (benchmarks/src/bin/tpcds.rs uses
externally generated data; zero-egress here, so we generate). Value
distributions are simplified but seeded and referentially intact: every
store_sales foreign key resolves, dates cover 1998-2002 with proper
year/moy/dom breakdowns.

Scale: `scale=1.0` ≈ 300k store_sales rows (tunable; the point is plan
shape + correctness, perf scaling comes from --scale).
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

BRANDS = [f"brand#{i}" for i in range(1, 61)]
CATEGORIES = ["Sports", "Books", "Home", "Electronics", "Jewelry", "Men", "Women",
              "Music", "Children", "Shoes"]
CLASSES = [f"class#{i}" for i in range(1, 31)]
CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
          "Liberty", "Pleasant Hill", "Union", "Salem", "Georgetown"]
COUNTIES = [f"{c} County" for c in ("Williamson", "Walker", "Ziebach", "Daviess",
                                    "Barrow", "Franklin", "Luce", "Richland")]
STATES = ["TN", "TX", "SD", "IN", "GA", "OH", "MI", "MT", "CA", "NY"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000", "0-500", "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"]
ITEM_COLORS = ["maroon", "burnished", "dim", "frosted", "papaya", "peach",
               "orchid", "pale", "metallic", "lace", "chiffon", "smoke"]
ITEM_SIZES = ["small", "medium", "large", "extra large", "petite", "N/A"]
ITEM_UNITS = ["Ounce", "Oz", "Bunch", "Ton", "Each", "Pound", "Pallet",
              "Gross", "Cup", "Dram", "Bundle"]
CREDIT_RATINGS = ["Low Risk", "Good", "High Risk", "Unknown"]
STREET_TYPES = ["Street", "Ave", "Blvd", "Court", "Drive", "Lane", "Parkway", "Way"]
LOCATION_TYPES = ["apartment", "condo", "single family"]
# low-cardinality zip pool (spec zips repeat; q8's preferred-customer-count
# per zip is meaningless over unique random zips)
ZIP_POOL = [f"{z:05d}" for z in range(24000, 24600, 10)]


def generate_tpcds(out_dir: str, scale: float = 1.0, seed: int = 17,
                   files_per_table: int = 2) -> None:
    rng = np.random.default_rng(seed)
    n_sales = max(int(300_000 * scale), 1_000)
    n_items = max(int(2_000 * scale**0.5), 200)
    n_customers = max(int(10_000 * scale**0.5), 500)
    n_addresses = max(n_customers // 2, 250)
    n_stores = max(int(12 * scale**0.5), 6)
    n_cd = 1920  # cross of demographics like the spec
    n_hd = 720
    n_promos = 30

    # ---- date_dim: calendar 1998-01-01 .. 2002-12-31 --------------------
    start = dt.date(1998, 1, 1)
    days = (dt.date(2002, 12, 31) - start).days + 1
    dates = [start + dt.timedelta(days=i) for i in range(days)]
    # week_seq/month_seq: sequential like the spec (absolute origin arbitrary
    # but stable — queries only use differences and +/- offsets)
    date_dim = pa.table({
        "d_date_sk": pa.array(range(2450815, 2450815 + days), pa.int64()),
        "d_date": pa.array(dates, pa.date32()),
        "d_year": pa.array([d.year for d in dates], pa.int64()),
        "d_moy": pa.array([d.month for d in dates], pa.int64()),
        "d_dom": pa.array([d.day for d in dates], pa.int64()),
        "d_qoy": pa.array([(d.month - 1) // 3 + 1 for d in dates], pa.int64()),
        "d_dow": pa.array([d.isoweekday() % 7 for d in dates], pa.int64()),  # 0=Sunday
        "d_day_name": pa.array([DAY_NAMES[d.isoweekday() % 7] for d in dates]),
        "d_quarter_name": pa.array([f"{d.year}Q{(d.month - 1) // 3 + 1}" for d in dates]),
        "d_week_seq": pa.array(
            [5270 + ((d - start).days + start.isoweekday() % 7) // 7 for d in dates],
            pa.int64()),
        "d_month_seq": pa.array(
            [1176 + (d.year - 1998) * 12 + d.month - 1 for d in dates], pa.int64()),
    })

    # ---- time_dim --------------------------------------------------------
    secs = np.arange(0, 86400, 60)  # minute granularity keeps it small
    hours = secs // 3600
    meal = np.where(
        (hours >= 6) & (hours <= 8), "breakfast",
        np.where((hours >= 17) & (hours <= 20), "dinner", ""))
    time_dim = pa.table({
        "t_time_sk": pa.array(secs, pa.int64()),
        "t_time": pa.array(secs, pa.int64()),  # seconds since midnight (spec)
        "t_hour": pa.array(hours, pa.int64()),
        "t_minute": pa.array((secs % 3600) // 60, pa.int64()),
        "t_meal_time": pa.array(meal),
    })

    # ---- item ------------------------------------------------------------
    brand_ids = rng.integers(1, 1000, n_items)
    cat_ids = rng.integers(0, len(CATEGORIES), n_items)
    class_ids = rng.integers(0, len(CLASSES), n_items)
    item = pa.table({
        "i_item_sk": pa.array(range(1, n_items + 1), pa.int64()),
        "i_item_id": pa.array([f"AAAAAAAA{i:08d}" for i in range(1, n_items + 1)]),
        "i_item_desc": pa.array([f"item description {i}" for i in range(1, n_items + 1)]),
        "i_brand_id": pa.array(brand_ids, pa.int64()),
        "i_brand": pa.array([BRANDS[b % len(BRANDS)] for b in brand_ids]),
        "i_category_id": pa.array(cat_ids + 1, pa.int64()),
        "i_category": pa.array([CATEGORIES[c] for c in cat_ids]),
        "i_class_id": pa.array(class_ids + 1, pa.int64()),
        "i_class": pa.array([CLASSES[c] for c in class_ids]),
        "i_manufact_id": pa.array(rng.integers(1, 200, n_items), pa.int64()),
        "i_manager_id": pa.array(rng.integers(1, 100, n_items), pa.int64()),
        "i_current_price": pa.array(np.round(rng.uniform(0.5, 300, n_items), 2)),
        "i_wholesale_cost": pa.array(np.round(rng.uniform(0.5, 100, n_items), 2)),
        # attribute columns (separate stream keeps prior draws stable)
        **(lambda r: {
            "i_product_name": pa.array([f"product#{i}" for i in range(1, n_items + 1)]),
            "i_manufact": pa.array([f"manufact#{m}" for m in r.integers(1, 100, n_items)]),
            "i_color": pa.array(r.choice(ITEM_COLORS, n_items)),
            "i_size": pa.array(r.choice(ITEM_SIZES, n_items)),
            "i_units": pa.array(r.choice(ITEM_UNITS, n_items)),
            "i_container": pa.array(["Unknown"] * n_items),
        })(np.random.default_rng(seed + 11)),
    })

    # ---- store -----------------------------------------------------------
    store = pa.table({
        "s_store_sk": pa.array(range(1, n_stores + 1), pa.int64()),
        "s_store_id": pa.array([f"AAAAAAAA{i:04d}BAAA" for i in range(1, n_stores + 1)]),
        "s_store_name": pa.array([f"store {i}" for i in range(1, n_stores + 1)]),
        "s_number_employees": pa.array(rng.integers(200, 300, n_stores), pa.int64()),
        # cyclic assignment: the city/county/offset values the query set
        # filters on must exist at EVERY scale (a random draw of 6 stores
        # can miss 'Williamson County' and silently zero out q34/q73)
        "s_city": pa.array([CITIES[i % len(CITIES)] for i in range(n_stores)]),
        "s_county": pa.array([COUNTIES[i % len(COUNTIES)] for i in range(n_stores)]),
        "s_state": pa.array([STATES[i % len(STATES)] for i in range(n_stores)]),
        "s_zip": pa.array([ZIP_POOL[i * 7 % len(ZIP_POOL)] for i in range(n_stores)]),
        "s_gmt_offset": pa.array([[-5.0, -6.0, -7.0, -8.0][i % 4] for i in range(n_stores)]),
        "s_market_id": pa.array([i % 10 + 1 for i in range(n_stores)], pa.int64()),
        "s_company_id": pa.array([1] * n_stores, pa.int64()),
        "s_company_name": pa.array(["Unknown"] * n_stores),
        "s_street_number": pa.array([str(100 + i) for i in range(n_stores)]),
        "s_street_name": pa.array([f"Commerce {i}" for i in range(n_stores)]),
        "s_street_type": pa.array([STREET_TYPES[i % len(STREET_TYPES)] for i in range(n_stores)]),
        "s_suite_number": pa.array([f"Suite {i}" for i in range(n_stores)]),
    })

    # ---- demographics ----------------------------------------------------
    cd_idx = np.arange(n_cd)
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(cd_idx + 1, pa.int64()),
        "cd_gender": pa.array(np.where(cd_idx % 2 == 0, "M", "F")),
        "cd_marital_status": pa.array([["M", "S", "D", "W", "U"][i % 5] for i in cd_idx]),
        "cd_education_status": pa.array([EDUCATION[i % len(EDUCATION)] for i in cd_idx]),
        "cd_purchase_estimate": pa.array((cd_idx % 20 + 1) * 500, pa.int64()),
        "cd_credit_rating": pa.array([CREDIT_RATINGS[i % len(CREDIT_RATINGS)] for i in cd_idx]),
        "cd_dep_count": pa.array(cd_idx % 7, pa.int64()),
        "cd_dep_employed_count": pa.array(cd_idx % 5, pa.int64()),
        "cd_dep_college_count": pa.array(cd_idx % 4, pa.int64()),
    })
    hd_idx = np.arange(n_hd)
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(hd_idx + 1, pa.int64()),
        "hd_income_band_sk": pa.array(hd_idx % 20 + 1, pa.int64()),
        "hd_buy_potential": pa.array([BUY_POTENTIAL[i % len(BUY_POTENTIAL)] for i in hd_idx]),
        "hd_dep_count": pa.array(hd_idx % 10, pa.int64()),
        "hd_vehicle_count": pa.array(hd_idx % 5, pa.int64()),
    })

    # ---- customer_address / customer ------------------------------------
    _ra = np.random.default_rng(seed + 12)
    # county/state follow the same cyclic pairing as stores, so the
    # "customer's county has a store" join (q54) is satisfiable
    _ca_idx = rng.integers(0, 10_000, n_addresses)
    customer_address = pa.table({
        "ca_address_sk": pa.array(range(1, n_addresses + 1), pa.int64()),
        "ca_city": pa.array(rng.choice(CITIES, n_addresses)),
        "ca_county": pa.array([COUNTIES[i % len(COUNTIES)] for i in _ca_idx]),
        "ca_state": pa.array([STATES[i % len(STATES)] for i in _ca_idx]),
        "ca_zip": pa.array(rng.choice(ZIP_POOL, n_addresses)),
        "ca_country": pa.array(["United States"] * n_addresses),
        "ca_gmt_offset": pa.array(rng.choice([-5.0, -6.0, -7.0, -8.0], n_addresses)),
        "ca_street_number": pa.array([str(x) for x in _ra.integers(1, 1000, n_addresses)]),
        "ca_street_name": pa.array([f"{a} {b}" for a, b in zip(
            _ra.choice(["Oak", "Main", "Elm", "Pine", "Maple"], n_addresses),
            _ra.choice(["Hill", "Ridge", "Park", "View", "Creek"], n_addresses))]),
        "ca_street_type": pa.array(_ra.choice(STREET_TYPES, n_addresses)),
        "ca_suite_number": pa.array([f"Suite {x}" for x in _ra.integers(0, 100, n_addresses)]),
        "ca_location_type": pa.array(_ra.choice(LOCATION_TYPES, n_addresses)),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(range(1, n_customers + 1), pa.int64()),
        "c_customer_id": pa.array([f"AAAAAAAA{i:08d}" for i in range(1, n_customers + 1)]),
        "c_first_name": pa.array([f"First{i % 997}" for i in range(1, n_customers + 1)]),
        "c_last_name": pa.array([f"Last{i % 499}" for i in range(1, n_customers + 1)]),
        "c_salutation": pa.array([["Mr.", "Ms.", "Dr.", "Miss", "Sir"][i % 5]
                                  for i in range(1, n_customers + 1)]),
        "c_preferred_cust_flag": pa.array([["Y", "N"][i % 2] for i in range(1, n_customers + 1)]),
        "c_current_addr_sk": pa.array(rng.integers(1, n_addresses + 1, n_customers), pa.int64()),
        "c_current_cdemo_sk": pa.array(rng.integers(1, n_cd + 1, n_customers), pa.int64()),
        "c_current_hdemo_sk": pa.array(rng.integers(1, n_hd + 1, n_customers), pa.int64()),
        **(lambda r: {
            # mostly-domestic with a foreign tail: q24's
            # `c_birth_country <> upper(ca_country)` must be satisfiable
            "c_birth_country": pa.array(np.where(
                r.random(n_customers) < 0.9, "UNITED STATES", "CANADA")),
            "c_birth_day": pa.array(r.integers(1, 29, n_customers), pa.int64()),
            "c_birth_month": pa.array(r.integers(1, 13, n_customers), pa.int64()),
            "c_birth_year": pa.array(r.integers(1930, 1993, n_customers), pa.int64()),
            "c_email_address": pa.array(
                [f"c{i}@example.com" for i in range(1, n_customers + 1)]),
            "c_login": pa.array([f"login{i}" for i in range(1, n_customers + 1)]),
            "c_first_sales_date_sk": pa.array(
                r.integers(2450815, 2450815 + 365, n_customers), pa.int64()),
            "c_first_shipto_date_sk": pa.array(
                r.integers(2450815, 2450815 + 730, n_customers), pa.int64()),
        })(np.random.default_rng(seed + 13)),
    })

    # ---- promotion -------------------------------------------------------
    promotion = pa.table({
        "p_promo_sk": pa.array(range(1, n_promos + 1), pa.int64()),
        "p_promo_id": pa.array([f"AAAAAAAA{i:08d}" for i in range(1, n_promos + 1)]),
        "p_promo_name": pa.array([f"promo {i}" for i in range(1, n_promos + 1)]),
        "p_channel_email": pa.array(["N" if i % 3 else "Y" for i in range(n_promos)]),
        "p_channel_event": pa.array(["N" if i % 2 else "Y" for i in range(n_promos)]),
        "p_channel_tv": pa.array(["N" if i % 4 else "Y" for i in range(n_promos)]),
        "p_channel_dmail": pa.array(["N" if (i + 1) % 3 else "Y" for i in range(n_promos)]),
    })

    # ---- store_sales (the fact table) -----------------------------------
    qty = rng.integers(1, 101, n_sales)
    wholesale = np.round(rng.uniform(1, 100, n_sales), 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, n_sales), 2)
    sales_price = np.round(list_price * rng.uniform(0.3, 1.0, n_sales), 2)
    ext_sales = np.round(sales_price * qty, 2)
    ext_list = np.round(list_price * qty, 2)
    ext_wholesale = np.round(wholesale * qty, 2)
    ext_discount = np.round(ext_list - ext_sales, 2)
    ext_tax = np.round(ext_sales * 0.06, 2)
    coupon = np.where(rng.random(n_sales) < 0.1, np.round(ext_sales * 0.1, 2), 0.0)
    net_paid = np.round(ext_sales - coupon, 2)
    net_profit = np.round(net_paid - ext_wholesale, 2)
    # tickets are BASKETS: every row of a ticket shares the visit's date,
    # time, store, customer, household and address (the ticket-grouping
    # queries — q34/q46/q68/q73/q79 — are meaningless over per-row noise)
    n_tickets = max(n_sales // 8, 100)
    tid = rng.integers(1, n_tickets + 1, n_sales)
    t_cust = rng.integers(1, n_customers + 1, n_tickets + 1)
    t_date = rng.integers(2450815, 2450815 + days, n_tickets + 1)
    t_time = rng.choice(secs, n_tickets + 1)
    t_store = rng.integers(1, n_stores + 1, n_tickets + 1)
    t_hdemo = rng.integers(1, n_hd + 1, n_tickets + 1)
    t_addr = rng.integers(1, n_addresses + 1, n_tickets + 1)
    # item popularity skew + a rotating per-day "deal item" taking ~15% of
    # that day's rows: frequent-item queries (q23/q14) group by (item, day)
    # with HAVING count>k — uniform draws never repeat within a day
    _rskew = np.random.default_rng(seed + 17)
    base_draw = np.minimum(
        (n_items * _rskew.power(3.0, n_sales)).astype(np.int64) + 1, n_items)
    deal_item = t_date[tid] % n_items + 1
    item_draw = np.where(_rskew.random(n_sales) < 0.15, deal_item, base_draw)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(t_date[tid], pa.int64()),
        "ss_sold_time_sk": pa.array(t_time[tid], pa.int64()),
        "ss_item_sk": pa.array(item_draw, pa.int64()),
        "ss_customer_sk": pa.array(t_cust[tid], pa.int64()),
        "ss_cdemo_sk": pa.array(rng.integers(1, n_cd + 1, n_sales), pa.int64()),
        "ss_hdemo_sk": pa.array(t_hdemo[tid], pa.int64()),
        "ss_addr_sk": pa.array(t_addr[tid], pa.int64(),
                               mask=_rskew.random(n_sales) < 0.015),
        "ss_store_sk": pa.array(t_store[tid], pa.int64()),
        "ss_promo_sk": pa.array(rng.integers(1, n_promos + 1, n_sales), pa.int64()),
        "ss_ticket_number": pa.array(tid, pa.int64()),
        "ss_quantity": pa.array(qty, pa.int64()),
        "ss_wholesale_cost": pa.array(wholesale),
        "ss_list_price": pa.array(list_price),
        "ss_sales_price": pa.array(sales_price),
        "ss_ext_discount_amt": pa.array(ext_discount),
        "ss_ext_sales_price": pa.array(ext_sales),
        "ss_ext_wholesale_cost": pa.array(ext_wholesale),
        "ss_ext_list_price": pa.array(ext_list),
        "ss_ext_tax": pa.array(ext_tax),
        "ss_coupon_amt": pa.array(coupon),
        "ss_net_paid": pa.array(net_paid),
        "ss_net_profit": pa.array(net_profit),
    })

    # ---- small dims (warehouse / ship_mode / call_center / web_page /
    #      reason / income_band) — tiny static tables many queries join ----
    n_wh = 5
    warehouse = pa.table({
        "w_warehouse_sk": pa.array(range(1, n_wh + 1), pa.int64()),
        "w_warehouse_name": pa.array([f"Warehouse {i}" for i in range(1, n_wh + 1)]),
        "w_warehouse_sq_ft": pa.array(rng.integers(50_000, 1_000_000, n_wh), pa.int64()),
        "w_city": pa.array(rng.choice(CITIES, n_wh)),
        "w_county": pa.array(rng.choice(COUNTIES, n_wh)),
        "w_state": pa.array(rng.choice(STATES, n_wh)),
        "w_country": pa.array(["United States"] * n_wh),
    })
    sm_types = ["EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY", "LIBRARY"]
    ship_mode = pa.table({
        "sm_ship_mode_sk": pa.array(range(1, 21), pa.int64()),
        "sm_type": pa.array([sm_types[i % len(sm_types)] for i in range(20)]),
        "sm_code": pa.array([f"code{i % 4}" for i in range(20)]),
        "sm_carrier": pa.array([f"CARRIER{i % 6}" for i in range(20)]),
    })
    call_center = pa.table({
        "cc_call_center_sk": pa.array(range(1, 5), pa.int64()),
        "cc_call_center_id": pa.array([f"AAAAAAAA{i:04d}BAAA" for i in range(1, 5)]),
        "cc_name": pa.array([f"call center {i}" for i in range(1, 5)]),
        "cc_county": pa.array(rng.choice(COUNTIES, 4)),
        "cc_manager": pa.array([f"Manager{i}" for i in range(1, 5)]),
    })
    web_site = pa.table({
        "web_site_sk": pa.array(range(1, 7), pa.int64()),
        "web_site_id": pa.array([f"AAAAAAAA{i:04d}CAAA" for i in range(1, 7)]),
        "web_name": pa.array([f"site_{i}" for i in range(6)]),
        "web_company_name": pa.array([["pri", "sec", "third"][i % 3] for i in range(6)]),
    })
    web_page = pa.table({
        "wp_web_page_sk": pa.array(range(1, 41), pa.int64()),
        "wp_char_count": pa.array(rng.integers(100, 8000, 40), pa.int64()),
    })
    reason = pa.table({
        "r_reason_sk": pa.array(range(1, 36), pa.int64()),
        "r_reason_desc": pa.array([f"reason {i}" for i in range(1, 36)]),
    })
    income_band = pa.table({
        "ib_income_band_sk": pa.array(range(1, 21), pa.int64()),
        "ib_lower_bound": pa.array([i * 10_000 for i in range(20)], pa.int64()),
        "ib_upper_bound": pa.array([(i + 1) * 10_000 for i in range(20)], pa.int64()),
    })

    # ---- inventory -------------------------------------------------------
    # weekly snapshots per (warehouse, item) like the spec — the stddev/mean
    # queries (q21/q22/q39) need several observations per (item, wh, month),
    # which random-sparse rows never give
    n_inv_items = min(max(int(200 * scale), 40), n_items)
    week_starts = np.arange(0, days, 7)
    inv_items = np.arange(1, n_inv_items + 1)
    grid_wh, grid_item, grid_week = np.meshgrid(
        np.arange(1, n_wh + 1), inv_items, week_starts, indexing="ij")
    _ri = np.random.default_rng(seed + 14)
    inventory = pa.table({
        "inv_date_sk": pa.array(2450815 + grid_week.ravel(), pa.int64()),
        "inv_item_sk": pa.array(grid_item.ravel(), pa.int64()),
        "inv_warehouse_sk": pa.array(grid_wh.ravel(), pa.int64()),
        "inv_quantity_on_hand": pa.array(
            _ri.integers(0, 1000, grid_week.size), pa.int64()),
    })

    # ---- catalog_sales / web_sales (cross-channel queries) ---------------
    def channel_fact(prefix: str, rows: int, seed_off: int) -> pa.Table:
        r = np.random.default_rng(seed + seed_off)
        cqty = r.integers(1, 101, rows)
        cwhole = np.round(r.uniform(1, 100, rows), 2)
        clist = np.round(cwhole * r.uniform(1.0, 2.0, rows), 2)
        cprice = np.round(clist * r.uniform(0.3, 1.0, rows), 2)
        ext = np.round(cprice * cqty, 2)
        ext_list = np.round(clist * cqty, 2)
        coupon = np.where(r.random(rows) < 0.1, np.round(ext * 0.1, 2), 0.0)
        sold = r.integers(2450815, 2450815 + days, rows)
        cols = {
            f"{prefix}_sold_date_sk": pa.array(sold, pa.int64()),
            f"{prefix}_ship_date_sk": pa.array(sold + r.integers(1, 120, rows), pa.int64()),
            f"{prefix}_sold_time_sk": pa.array(r.choice(secs, rows), pa.int64()),
            f"{prefix}_item_sk": pa.array(r.integers(1, n_items + 1, rows), pa.int64()),
            f"{prefix}_bill_customer_sk": pa.array(r.integers(1, n_customers + 1, rows), pa.int64()),
            f"{prefix}_bill_cdemo_sk": pa.array(r.integers(1, n_cd + 1, rows), pa.int64()),
            f"{prefix}_bill_hdemo_sk": pa.array(r.integers(1, n_hd + 1, rows), pa.int64()),
            f"{prefix}_bill_addr_sk": pa.array(r.integers(1, n_addresses + 1, rows), pa.int64()),
            f"{prefix}_promo_sk": pa.array(r.integers(1, n_promos + 1, rows), pa.int64()),
            f"{prefix}_order_number": pa.array(r.integers(1, rows // 2 + 2, rows), pa.int64()),
            f"{prefix}_warehouse_sk": pa.array(r.integers(1, n_wh + 1, rows), pa.int64()),
            f"{prefix}_ship_mode_sk": pa.array(r.integers(1, 21, rows), pa.int64()),
            f"{prefix}_quantity": pa.array(cqty, pa.int64()),
            f"{prefix}_wholesale_cost": pa.array(cwhole),
            f"{prefix}_list_price": pa.array(clist),
            f"{prefix}_sales_price": pa.array(cprice),
            f"{prefix}_coupon_amt": pa.array(coupon),
            f"{prefix}_ext_sales_price": pa.array(ext),
            f"{prefix}_ext_list_price": pa.array(ext_list),
            f"{prefix}_ext_discount_amt": pa.array(np.round(ext_list - ext, 2)),
            f"{prefix}_ext_ship_cost": pa.array(np.round(ext * r.uniform(0.01, 0.2, rows), 2)),
            f"{prefix}_net_paid": pa.array(np.round(ext - coupon, 2)),
            f"{prefix}_net_profit": pa.array(np.round(ext * r.uniform(-0.2, 0.4, rows), 2)),
        }
        def _with_nulls(vals: np.ndarray, frac: float) -> pa.Array:
            # sparse NULL foreign keys (the spec has them; q76-style queries
            # count them, join queries must drop them consistently)
            mask = r.random(rows) < frac
            return pa.array(vals, pa.int64(), mask=mask)

        if prefix == "cs":
            cols["cs_call_center_sk"] = pa.array(r.integers(1, 5, rows), pa.int64())
            cols["cs_ship_customer_sk"] = pa.array(
                r.integers(1, n_customers + 1, rows), pa.int64())
            cols["cs_ship_addr_sk"] = _with_nulls(
                r.integers(1, n_addresses + 1, rows), 0.02)
        if prefix == "ws":
            cols["ws_web_page_sk"] = pa.array(r.integers(1, 41, rows), pa.int64())
            cols["ws_ship_hdemo_sk"] = pa.array(r.integers(1, n_hd + 1, rows), pa.int64())
            cols["ws_web_site_sk"] = pa.array(r.integers(1, 7, rows), pa.int64())
            cols["ws_ship_addr_sk"] = pa.array(r.integers(1, n_addresses + 1, rows), pa.int64())
            cols["ws_ship_customer_sk"] = _with_nulls(
                r.integers(1, n_customers + 1, rows), 0.02)
        return pa.table(cols)

    catalog_sales = channel_fact("cs", max(n_sales // 2, 500), 101)
    web_sales = channel_fact("ws", max(n_sales // 4, 500), 202)

    # cross-channel correlation: a third of catalog/web purchases come from
    # (customer, item) pairs seen in store_sales — without this, queries
    # that chain store → returns → catalog (q17/q25/q29) or compare a
    # customer's channels (q4/q11) join near-empty sets at test scales
    def _correlate(fact: pa.Table, prefix: str, seed_off: int) -> pa.Table:
        r = np.random.default_rng(seed + seed_off)
        n = fact.num_rows
        src = r.integers(0, n_sales, n)
        take = r.random(n) < 0.33
        cust = np.where(take, t_cust[tid][src], fact.column(f"{prefix}_bill_customer_sk").to_numpy())
        item = np.where(take, store_sales.column("ss_item_sk").to_numpy()[src],
                        fact.column(f"{prefix}_item_sk").to_numpy())
        cols = {c: fact.column(c) for c in fact.column_names}
        cols[f"{prefix}_bill_customer_sk"] = pa.array(cust, pa.int64())
        cols[f"{prefix}_item_sk"] = pa.array(item, pa.int64())
        return pa.table(cols)

    catalog_sales = _correlate(catalog_sales, "cs", 15)
    web_sales = _correlate(web_sales, "ws", 16)

    # ---- returns: seeded subsets of the sales facts ----------------------
    def returns_of(sales: pa.Table, prefix: str, src_prefix: str, frac: float,
                   seed_off: int, extra: dict | None = None) -> pa.Table:
        r = np.random.default_rng(seed + seed_off)
        n = sales.num_rows
        sel = np.sort(r.choice(n, max(int(n * frac), 50), replace=False))
        sub = sales.take(pa.array(sel))
        rq = np.maximum(1, (sub.column(f"{src_prefix}_quantity").to_numpy() *
                            r.uniform(0.1, 1.0, len(sel))).astype(np.int64))
        price = sub.column(f"{src_prefix}_sales_price").to_numpy()
        amt = np.round(price * rq, 2)
        sold = sub.column(f"{src_prefix}_sold_date_sk").to_numpy()
        cols = {
            f"{prefix}_returned_date_sk": pa.array(
                np.minimum(sold + r.integers(1, 90, len(sel)), 2450815 + days - 1), pa.int64()),
            f"{prefix}_item_sk": sub.column(f"{src_prefix}_item_sk"),
            f"{prefix}_return_quantity": pa.array(rq, pa.int64()),
            f"{prefix}_return_amt": pa.array(amt),
            f"{prefix}_net_loss": pa.array(np.round(amt * r.uniform(0.0, 0.5, len(sel)), 2)),
            f"{prefix}_reason_sk": pa.array(r.integers(1, 36, len(sel)), pa.int64()),
        }
        for name, src_col in (extra or {}).items():
            cols[name] = sub.column(src_col)
        return pa.table(cols)

    store_returns = returns_of(store_sales, "sr", "ss", 0.10, 303, {
        "sr_customer_sk": "ss_customer_sk", "sr_ticket_number": "ss_ticket_number",
        "sr_store_sk": "ss_store_sk", "sr_cdemo_sk": "ss_cdemo_sk",
    })
    catalog_returns = returns_of(catalog_sales, "cr", "cs", 0.20, 404, {
        "cr_order_number": "cs_order_number",
        "cr_returning_customer_sk": "cs_bill_customer_sk",
        "cr_returning_addr_sk": "cs_bill_addr_sk",
        "cr_call_center_sk": "cs_call_center_sk",
    })
    web_returns = returns_of(web_sales, "wr", "ws", 0.20, 505, {
        "wr_order_number": "ws_order_number",
        "wr_returning_customer_sk": "ws_bill_customer_sk",
        "wr_returning_cdemo_sk": "ws_bill_cdemo_sk",
        "wr_refunded_cdemo_sk": "ws_bill_cdemo_sk",
        "wr_refunded_addr_sk": "ws_bill_addr_sk",
        "wr_web_page_sk": "ws_web_page_sk",
    })
    _rwr = np.random.default_rng(seed + 18)
    _wr_amt = web_returns.column("wr_return_amt").to_numpy()
    web_returns = web_returns.append_column(
        "wr_fee", pa.array(np.round(_rwr.uniform(0.5, 100.0, len(_wr_amt)), 2)))
    web_returns = web_returns.append_column(
        "wr_refund_cash",
        pa.array(np.round(_wr_amt * _rwr.uniform(0.2, 1.0, len(_wr_amt)), 2)))

    tables = {
        "date_dim": date_dim, "time_dim": time_dim, "item": item, "store": store,
        "customer": customer, "customer_address": customer_address,
        "customer_demographics": customer_demographics,
        "household_demographics": household_demographics,
        "promotion": promotion, "store_sales": store_sales,
        "catalog_sales": catalog_sales, "web_sales": web_sales,
        "store_returns": store_returns, "catalog_returns": catalog_returns,
        "web_returns": web_returns, "inventory": inventory,
        "warehouse": warehouse, "ship_mode": ship_mode, "call_center": call_center,
        "web_page": web_page, "reason": reason, "income_band": income_band,
        "web_site": web_site,
    }
    for name, tbl in tables.items():
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        nfiles = files_per_table if name.endswith("_sales") else 1
        rows_per = (tbl.num_rows + nfiles - 1) // nfiles
        for i in range(nfiles):
            part = tbl.slice(i * rows_per, rows_per)
            pq.write_table(part, os.path.join(d, f"part-{i}.parquet"))


TPCDS_TABLES = [
    "date_dim", "time_dim", "item", "store", "customer", "customer_address",
    "customer_demographics", "household_demographics", "promotion", "store_sales",
    "catalog_sales", "web_sales", "store_returns", "catalog_returns",
    "web_returns", "inventory", "warehouse", "ship_mode", "call_center",
    "web_page", "reason", "income_band", "web_site",
]


def register_tpcds(ctx, data_dir: str) -> None:
    for t in TPCDS_TABLES:
        ctx.register_parquet(t, os.path.join(data_dir, t))
