"""Deterministic TPC-H data generator (dbgen-shaped).

The reference generates benchmark data with tpchgen-cli
(.github/workflows/tpch.yml) and registers parquet tables
(benchmarks/src/bin/tpch.rs). We can't ship dbgen, so this module generates
spec-shaped data directly with numpy/pyarrow:

- exact table cardinalities per scale factor,
- the key relationships queries join on (partsupp's 4-suppliers-per-part
  formula so lineitem (partkey,suppkey) pairs exist in partsupp),
- the value distributions the 22 queries' predicates select on (dates,
  segments, types, brands, containers, ship modes, comment tokens like
  'special requests' / 'Customer Complaints', color-word part names),
- monetary columns as float64 (engine-wide decimal policy for v1; the TPU
  engine re-encodes to int64 cents on device for exact aggregation).

Not a bit-exact dbgen clone: comments/addresses are abbreviated. Expected
query answers are computed by the pandas reference executor in
ballista_tpu.testing.reference, so correctness checks are self-consistent
the same way the reference's "verify expected results" CI leg is.
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

STARTDATE = np.datetime64("1992-01-01")
ENDDATE = np.datetime64("1998-12-31")
CURRENTDATE = np.datetime64("1995-06-17")

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic", "midnight",
    "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange", "orchid",
    "pale", "papaya", "peach", "peru", "pink", "plum", "powder", "puff", "purple",
    "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
    "sienna", "sky", "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
    "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]
WORDS = (
    "carefully regular instructions sleep blithely final deposits haggle quickly "
    "express packages cajole furiously silent requests boost even ideas nag ironic "
    "accounts wake slyly pending theodolites integrate daringly bold pinto beans "
    "above the unusual foxes detect along platelets across fluffily busy dependencies"
).split()


def _take(choices: list[str], idx: np.ndarray) -> pa.Array:
    return pa.DictionaryArray.from_arrays(
        pa.array(idx.astype(np.int32)), pa.array(choices)
    ).cast(pa.string())


def _comments(rng: np.random.Generator, n: int, nwords: int = 5, inject: str | None = None,
              inject_rate: float = 0.0) -> pa.Array:
    import pyarrow.compute as pc

    cols = [_take(WORDS, rng.integers(0, len(WORDS), n)) for _ in range(nwords)]
    out = pc.binary_join_element_wise(*cols, " ")
    if inject and inject_rate > 0:
        mask = rng.random(n) < inject_rate
        if mask.any():
            injected = pc.binary_join_element_wise(out, pa.scalar(inject), " ")
            out = pc.if_else(pa.array(mask), injected, out)
    return out


def _money(rng: np.random.Generator, n: int, lo: float, hi: float) -> np.ndarray:
    return np.round(rng.uniform(lo, hi, n), 2)


def _dates(rng: np.random.Generator, n: int, lo: np.datetime64, hi: np.datetime64) -> np.ndarray:
    span = (hi - lo).astype("int64")
    return lo + rng.integers(0, span + 1, n).astype("timedelta64[D]")


def _retail_price(pk: np.ndarray) -> np.ndarray:
    return (90000 + ((pk // 10) % 20001) + 100 * (pk % 1000)) / 100.0


def _ps_suppkey(pk: np.ndarray, i: int, s_count: int) -> np.ndarray:
    # dbgen's formula: the i-th (0..3) supplier for part pk
    return (pk + i * (s_count // 4 + (pk - 1) // s_count)) % s_count + 1


def generate_tpch(out_dir: str, scale: float = 0.01, seed: int = 42,
                  files_per_table: int = 1, row_group_rows: int = 256 * 1024) -> dict[str, str]:
    """Generate all 8 tables as parquet under out_dir/<table>/part-*.parquet.

    Returns {table_name: directory}.
    """
    rng = np.random.default_rng(seed)
    os.makedirs(out_dir, exist_ok=True)

    n_supp = max(10, int(10_000 * scale))
    n_part = max(200, int(200_000 * scale))
    n_cust = max(150, int(150_000 * scale))
    n_ord = max(1500, int(1_500_000 * scale))

    paths: dict[str, str] = {}

    def write(name: str, table: pa.Table, nfiles: int = 1) -> None:
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        n = table.num_rows
        nfiles = max(1, min(nfiles, n))
        step = -(-n // nfiles)
        for i in range(nfiles):
            sl = table.slice(i * step, step)
            if sl.num_rows == 0:
                break
            pq.write_table(sl, os.path.join(d, f"part-{i:03d}.parquet"),
                           row_group_size=row_group_rows, compression="zstd")
        paths[name] = d

    # -- region / nation ----------------------------------------------------
    write("region", pa.table({
        "r_regionkey": pa.array(range(5), pa.int64()),
        "r_name": pa.array(REGIONS),
        "r_comment": _comments(rng, 5),
    }))
    write("nation", pa.table({
        "n_nationkey": pa.array(range(25), pa.int64()),
        "n_name": pa.array([n for n, _ in NATIONS]),
        "n_regionkey": pa.array([r for _, r in NATIONS], pa.int64()),
        "n_comment": _comments(rng, 25),
    }))

    # -- supplier -----------------------------------------------------------
    sk = np.arange(1, n_supp + 1, dtype=np.int64)
    write("supplier", pa.table({
        "s_suppkey": sk,
        "s_name": pa.array([f"Supplier#{i:09d}" for i in sk]),
        "s_address": _comments(rng, n_supp, 2),
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int64),
        "s_phone": pa.array([f"{10 + i % 25}-{i % 900 + 100}-{i % 900 + 100}-{i % 9000 + 1000}" for i in sk]),
        "s_acctbal": _money(rng, n_supp, -999.99, 9999.99),
        "s_comment": _comments(rng, n_supp, 6, inject="Customer Complaints", inject_rate=0.0005),
    }), files_per_table)

    # -- part ---------------------------------------------------------------
    pk = np.arange(1, n_part + 1, dtype=np.int64)
    import pyarrow.compute as pc
    name_words = [_take(COLORS, rng.integers(0, len(COLORS), n_part)) for _ in range(5)]
    p_name = pc.binary_join_element_wise(*name_words, " ")
    t1 = rng.integers(0, len(TYPE_S1), n_part)
    t2 = rng.integers(0, len(TYPE_S2), n_part)
    t3 = rng.integers(0, len(TYPE_S3), n_part)
    p_type = pc.binary_join_element_wise(_take(TYPE_S1, t1), _take(TYPE_S2, t2), _take(TYPE_S3, t3), " ")
    brand_m = rng.integers(1, 6, n_part)
    brand_n = rng.integers(1, 6, n_part)
    p_brand = pa.array([f"Brand#{m}{n}" for m, n in zip(brand_m, brand_n)])
    cont = pc.binary_join_element_wise(
        _take(CONTAINER_1, rng.integers(0, 5, n_part)),
        _take(CONTAINER_2, rng.integers(0, 8, n_part)), " ")
    write("part", pa.table({
        "p_partkey": pk,
        "p_name": p_name,
        "p_mfgr": pa.array([f"Manufacturer#{m}" for m in brand_m]),
        "p_brand": p_brand,
        "p_type": p_type,
        "p_size": rng.integers(1, 51, n_part).astype(np.int64),
        "p_container": cont,
        "p_retailprice": _retail_price(pk),
        "p_comment": _comments(rng, n_part, 3),
    }), files_per_table)

    # -- partsupp (4 suppliers per part, dbgen formula) ---------------------
    ps_pk = np.repeat(pk, 4)
    ps_sk = np.concatenate([_ps_suppkey(pk, i, n_supp) for i in range(4)])
    # interleave: order by partkey then i
    order = np.argsort(np.concatenate([pk * 4 + i for i in range(4)]), kind="stable")
    ps_sk = ps_sk[order]
    n_ps = len(ps_pk)
    write("partsupp", pa.table({
        "ps_partkey": ps_pk,
        "ps_suppkey": ps_sk.astype(np.int64),
        "ps_availqty": rng.integers(1, 10_000, n_ps).astype(np.int64),
        "ps_supplycost": _money(rng, n_ps, 1.0, 1000.0),
        "ps_comment": _comments(rng, n_ps, 4),
    }), files_per_table)

    # -- customer -----------------------------------------------------------
    ck = np.arange(1, n_cust + 1, dtype=np.int64)
    c_nat = rng.integers(0, 25, n_cust)
    write("customer", pa.table({
        "c_custkey": ck,
        "c_name": pa.array([f"Customer#{i:09d}" for i in ck]),
        "c_address": _comments(rng, n_cust, 2),
        "c_nationkey": c_nat.astype(np.int64),
        "c_phone": pa.array([f"{10 + n}-{int(x) % 900 + 100}-{int(x) % 900 + 100}-{int(x) % 9000 + 1000}"
                             for n, x in zip(c_nat, ck)]),
        "c_acctbal": _money(rng, n_cust, -999.99, 9999.99),
        "c_mktsegment": _take(SEGMENTS, rng.integers(0, 5, n_cust)),
        "c_comment": _comments(rng, n_cust, 6, inject="special requests", inject_rate=0.002),
    }), files_per_table)

    # -- orders -------------------------------------------------------------
    ok = (np.arange(1, n_ord + 1, dtype=np.int64) * 4) - 3  # sparse keys like dbgen
    # only customers with custkey % 3 != 0 place orders (q13/q22 shape)
    eligible = ck[ck % 3 != 0]
    o_ck = eligible[rng.integers(0, len(eligible), n_ord)]
    o_date = _dates(rng, n_ord, STARTDATE, ENDDATE - np.timedelta64(151, "D"))

    # lineitems: 1..7 per order
    lines_per = rng.integers(1, 8, n_ord)
    l_ok = np.repeat(ok, lines_per)
    l_odate = np.repeat(o_date, lines_per)
    n_li = len(l_ok)
    l_pk = rng.integers(1, n_part + 1, n_li).astype(np.int64)
    supp_choice = rng.integers(0, 4, n_li)
    l_sk = _ps_suppkey(l_pk, 0, n_supp)
    for i in (1, 2, 3):
        sel = supp_choice == i
        l_sk[sel] = _ps_suppkey(l_pk[sel], i, n_supp)
    l_qty = rng.integers(1, 51, n_li).astype(np.int64)
    l_price = np.round(l_qty * _retail_price(l_pk), 2)
    l_disc = np.round(rng.integers(0, 11, n_li) / 100.0, 2)
    l_tax = np.round(rng.integers(0, 9, n_li) / 100.0, 2)
    l_ship = l_odate + rng.integers(1, 122, n_li).astype("timedelta64[D]")
    l_commit = l_odate + rng.integers(30, 91, n_li).astype("timedelta64[D]")
    l_receipt = l_ship + rng.integers(1, 31, n_li).astype("timedelta64[D]")
    l_rflag = np.where(
        l_receipt <= CURRENTDATE,
        np.where(rng.random(n_li) < 0.5, "R", "A"),
        "N",
    )
    l_lstatus = np.where(l_ship > CURRENTDATE, "O", "F")

    # order status from line statuses
    any_open = np.zeros(n_ord, dtype=bool)
    all_open = np.ones(n_ord, dtype=bool)
    idx = np.repeat(np.arange(n_ord), lines_per)
    open_line = l_lstatus == "O"
    np.logical_or.at(any_open, idx, open_line)
    np.logical_and.at(all_open, idx, open_line)
    o_status = np.where(all_open, "O", np.where(any_open, "P", "F"))

    o_total = np.zeros(n_ord)
    np.add.at(o_total, idx, l_price * (1 + l_tax) * (1 - l_disc))
    o_total = np.round(o_total, 2)

    write("orders", pa.table({
        "o_orderkey": ok,
        "o_custkey": o_ck,
        "o_orderstatus": pa.array(o_status),
        "o_totalprice": o_total,
        "o_orderdate": pa.array(o_date),
        "o_orderpriority": _take(PRIORITIES, rng.integers(0, 5, n_ord)),
        "o_clerk": pa.array([f"Clerk#{int(c) % max(1, n_ord // 1000) + 1:09d}" for c in rng.integers(0, 1 << 30, n_ord)]),
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        "o_comment": _comments(rng, n_ord, 5, inject="special requests", inject_rate=0.01),
    }), files_per_table)

    l_linenumber = np.concatenate([np.arange(1, c + 1) for c in lines_per]).astype(np.int64)
    write("lineitem", pa.table({
        "l_orderkey": l_ok,
        "l_partkey": l_pk,
        "l_suppkey": l_sk.astype(np.int64),
        "l_linenumber": l_linenumber,
        "l_quantity": l_qty.astype(np.float64),
        "l_extendedprice": l_price,
        "l_discount": l_disc,
        "l_tax": l_tax,
        "l_returnflag": pa.array(l_rflag),
        "l_linestatus": pa.array(l_lstatus),
        "l_shipdate": pa.array(l_ship),
        "l_commitdate": pa.array(l_commit),
        "l_receiptdate": pa.array(l_receipt),
        "l_shipinstruct": _take(INSTRUCTS, rng.integers(0, 4, n_li)),
        "l_shipmode": _take(SHIPMODES, rng.integers(0, 7, n_li)),
        "l_comment": _comments(rng, n_li, 3),
    }), max(files_per_table, files_per_table * 4))

    return paths


TPCH_TABLES = ["region", "nation", "supplier", "part", "partsupp", "customer", "orders", "lineitem"]


def register_tpch(ctx, data_dir: str) -> None:
    """Register all 8 tables on a session context."""
    from ballista_tpu.plan.provider import ParquetTable

    for t in TPCH_TABLES:
        ctx.register_table(t, ParquetTable(os.path.join(data_dir, t)))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--files-per-table", type=int, default=1)
    args = ap.parse_args()
    generate_tpch(args.out_dir, args.scale, args.seed, args.files_per_table)
    print(f"generated TPC-H sf={args.scale} under {args.out_dir}")
