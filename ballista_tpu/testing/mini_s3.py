"""A minimal in-process S3-compatible HTTP server for integration tests.

The reference tests S3 scans with testcontainers + MinIO
(examples/tests/object_store.rs); this build environment has zero network
egress and no container runtime, so the equivalent is a tiny S3 protocol
shim serving a local directory: HEAD/GET (with Range) for objects and
ListObjectsV2 for discovery — exactly the calls pyarrow's S3FileSystem
(the AWS SDK) issues for dataset registration and parquet reads.
Signatures are not validated (the SDK signs; we accept)."""

from __future__ import annotations

import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse
from xml.sax.saxutils import escape


class _Handler(BaseHTTPRequestHandler):
    root: str = ""
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # noqa: D102 — quiet
        return

    def _object_path(self) -> str:
        # /bucket/key... → {root}/bucket/key
        return os.path.join(self.root, unquote(urlparse(self.path).path.lstrip("/")))

    def do_HEAD(self):  # noqa: N802
        p = self._object_path()
        if os.path.isfile(p):
            self.send_response(200)
            self.send_header("Content-Length", str(os.path.getsize(p)))
            self.send_header("Accept-Ranges", "bytes")
            self.send_header("ETag", '"mini"')
            self.send_header("Last-Modified", "Thu, 01 Jan 2026 00:00:00 GMT")
            self.end_headers()
        else:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

    def do_GET(self):  # noqa: N802
        url = urlparse(self.path)
        q = parse_qs(url.query)
        if "list-type" in q or "prefix" in q or url.path.count("/") == 1:
            return self._list(url, q)
        p = self._object_path()
        if not os.path.isfile(p):
            body = b"<Error><Code>NoSuchKey</Code></Error>"
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        size = os.path.getsize(p)
        rng = self.headers.get("Range")
        start, end = 0, size - 1
        status = 200
        if rng and rng.startswith("bytes="):
            spec = rng[len("bytes="):]
            s, _, e = spec.partition("-")
            start = int(s) if s else max(0, size - int(e))
            end = int(e) if e and s else (size - 1 if s else size - 1)
            end = min(end, size - 1)
            status = 206
        length = end - start + 1
        self.send_response(status)
        self.send_header("Content-Length", str(length))
        self.send_header("Accept-Ranges", "bytes")
        if status == 206:
            self.send_header("Content-Range", f"bytes {start}-{end}/{size}")
        self.end_headers()
        with open(p, "rb") as f:
            f.seek(start)
            self.wfile.write(f.read(length))

    def _list(self, url, q):
        bucket = url.path.strip("/").split("/")[0]
        prefix = q.get("prefix", [""])[0]
        base = os.path.join(self.root, bucket)
        keys = []
        for root_dir, _dirs, files in os.walk(base):
            for f in files:
                full = os.path.join(root_dir, f)
                key = os.path.relpath(full, base).replace(os.sep, "/")
                if key.startswith(prefix):
                    keys.append((key, os.path.getsize(full)))
        keys.sort()
        parts = [
            "<?xml version=\"1.0\" encoding=\"UTF-8\"?>",
            "<ListBucketResult><IsTruncated>false</IsTruncated>",
            f"<Name>{escape(bucket)}</Name>",
            f"<Prefix>{escape(prefix)}</Prefix>",
            f"<KeyCount>{len(keys)}</KeyCount>",
        ]
        for key, size in keys:
            parts.append(
                f"<Contents><Key>{escape(key)}</Key><Size>{size}</Size>"
                "<LastModified>2026-01-01T00:00:00.000Z</LastModified>"
                "<ETag>\"mini\"</ETag><StorageClass>STANDARD</StorageClass></Contents>"
            )
        parts.append("</ListBucketResult>")
        body = "".join(parts).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/xml")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def start_mini_s3(root: str, host: str = "127.0.0.1", port: int = 0):
    """Serve `root` as S3 buckets; returns (server, endpoint_url)."""
    handler = type("MiniS3Handler", (_Handler,), {"root": root})
    srv = ThreadingHTTPServer((host, port), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True, name="mini-s3")
    t.start()
    return srv, f"http://{host}:{srv.server_address[1]}"
