"""Importable UDFs used by tests (and as the pattern for user UDF modules):
importing this module registers its functions, which is how executors
re-materialize session UDFs shipped by reference (ballista_tpu/udf.py)."""

import pyarrow as pa
import pyarrow.compute as pc

from ballista_tpu import udf


def double_it(a: pa.Array) -> pa.Array:
    return pc.multiply(pc.cast(a, pa.int64()), 2)


def shout(s: pa.Array) -> pa.Array:
    return pc.binary_join_element_wise(pc.utf8_upper(s), "!", "")


def hard_crash(a: pa.Array) -> pa.Array:
    """Kills the interpreter without cleanup — a stand-in for a segfaulting
    native kernel, used to prove process-isolation crash containment."""
    import os

    os._exit(77)


def slow_identity(a: pa.Array) -> pa.Array:
    """Sleeps long enough for a cancel to land mid-task."""
    import time

    time.sleep(30)
    return pc.cast(a, pa.int64())


udf.register_udf("double_it", double_it, pa.int64())
udf.register_udf("shout", shout, pa.string())
udf.register_udf("hard_crash", hard_crash, pa.int64())
udf.register_udf("slow_identity", slow_identity, pa.int64())
