"""pandas oracle for the TPC-DS query subset (benchmarks/tpcds/queries).

Mirrors testing/reference.py's role for TPC-H: an independent computation
of each query used by --verify and the test suite. Sort-prefix comparison
semantics: rows are compared on the ORDER BY prefix columns; full-row sets
must match.
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq

from ballista_tpu.testing.tpcdsgen import TPCDS_TABLES


def load_tables(data_dir: str) -> dict[str, pd.DataFrame]:
    out = {}
    for t in TPCDS_TABLES:
        out[t] = pq.read_table(os.path.join(data_dir, t)).to_pandas()
    return out


def run_reference(q: int, t: dict[str, pd.DataFrame]) -> pd.DataFrame:
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    if q == 3:
        m = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manufact_id == 128], left_on="ss_item_sk", right_on="i_item_sk")
        m = m[m.d_moy == 11]
        g = m.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False).agg(
            sum_agg=("ss_ext_sales_price", "sum"))
        g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
        return g.sort_values(["d_year", "sum_agg", "brand_id"],
                             ascending=[True, False, True]).head(100).reset_index(drop=True)
    if q == 7:
        cd, pr = t["customer_demographics"], t["promotion"]
        m = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        cdf = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                 & (cd.cd_education_status == "College")]
        m = m.merge(cdf, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        prf = pr[(pr.p_channel_email == "N") | (pr.p_channel_event == "N")]
        m = m.merge(prf, left_on="ss_promo_sk", right_on="p_promo_sk")
        g = m.groupby("i_item_id", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
            agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
        return g.sort_values("i_item_id").head(100).reset_index(drop=True)
    if q == 19:
        cu, ca, st = t["customer"], t["customer_address"], t["store"]
        m = ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manager_id == 8], left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        m = m[m.ca_state != m.s_state]
        g = m.groupby(["i_brand_id", "i_brand", "i_manufact_id"], as_index=False).agg(
            ext_price=("ss_ext_sales_price", "sum"))
        g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
        return g.sort_values(["ext_price", "brand_id", "i_manufact_id"],
                             ascending=[False, True, True]).head(100).reset_index(drop=True)
    if q in (42, 52, 55):
        mgr = {42: 1, 52: 1, 55: 28}[q]
        year = {42: 2000, 52: 2000, 55: 1999}[q]
        m = ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == year)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manager_id == mgr], left_on="ss_item_sk", right_on="i_item_sk")
        if q == 42:
            g = m.groupby(["d_year", "i_category_id", "i_category"], as_index=False).agg(
                total=("ss_ext_sales_price", "sum"))
            return g.sort_values(["total", "d_year", "i_category_id", "i_category"],
                                 ascending=[False, True, True, True]).head(100).reset_index(drop=True)
        g = m.groupby((["d_year"] if q == 52 else []) + ["i_brand_id", "i_brand"],
                      as_index=False).agg(ext_price=("ss_ext_sales_price", "sum"))
        g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
        if q == 52:
            return g.sort_values(["d_year", "ext_price", "brand_id"],
                                 ascending=[True, False, True]).head(100).reset_index(drop=True)
        return g.sort_values(["ext_price", "brand_id"],
                             ascending=[False, True]).head(100).reset_index(drop=True)
    if q == 68:
        cu, ca, st, hd = t["customer"], t["customer_address"], t["store"], t["household_demographics"]
        m = ss.merge(dd[(dd.d_dom.between(1, 2)) & (dd.d_year.isin([1999, 2000, 2001]))],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_city.isin(["Midway", "Fairview"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
        dn = m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "ca_city"],
                       as_index=False).agg(extended_price=("ss_ext_sales_price", "sum"),
                                           list_price=("ss_ext_list_price", "sum"),
                                           extended_tax=("ss_ext_tax", "sum"))
        dn = dn.rename(columns={"ca_city": "bought_city"})
        dn = dn.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        dn = dn.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        dn = dn[dn.ca_city != dn.bought_city]
        out = dn[["c_last_name", "c_first_name", "ca_city", "bought_city", "ss_ticket_number",
                  "extended_price", "extended_tax", "list_price"]]
        return out.sort_values(["c_last_name", "ss_ticket_number"]).head(100).reset_index(drop=True)
    if q == 73:
        cu, st, hd = t["customer"], t["store"], t["household_demographics"]
        m = ss.merge(dd[(dd.d_dom.between(1, 2)) & (dd.d_year.isin([1999, 2000, 2001]))],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_county.isin(["Williamson County", "Walker County"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[((hd.hd_buy_potential == ">10000") | (hd.hd_buy_potential == "Unknown"))
                       & (hd.hd_vehicle_count > 0)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        dj = m.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False).agg(
            cnt=("ss_ticket_number", "size"))
        dj = dj[dj.cnt.between(1, 5)]
        dj = dj.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        out = dj[["c_last_name", "c_first_name", "c_customer_sk", "ss_ticket_number", "cnt"]]
        out = out.rename(columns={"c_customer_sk": "c_salutation"})
        return out.sort_values(["cnt", "c_last_name"],
                               ascending=[False, True]).head(100).reset_index(drop=True)
    if q == 96:
        td, st, hd = t["time_dim"], t["store"], t["household_demographics"]
        m = ss.merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                     left_on="ss_sold_time_sk", right_on="t_time_sk")
        m = m.merge(hd[hd.hd_dep_count == 7], left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(st[st.s_store_name == "store 1"], left_on="ss_store_sk", right_on="s_store_sk")
        return pd.DataFrame({"cnt": [len(m)]})
    if q == 98:
        m = ss.merge(it[it.i_category.isin(["Sports", "Books", "Home"])],
                     left_on="ss_item_sk", right_on="i_item_sk")
        lo, hi = dt.date(1999, 2, 22), dt.date(1999, 3, 24)
        dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)]
        m = m.merge(dsel, left_on="ss_sold_date_sk", right_on="d_date_sk")
        g = m.groupby(["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
                      as_index=False).agg(itemrevenue=("ss_ext_sales_price", "sum"))
        class_tot = g.groupby("i_class")["itemrevenue"].transform("sum")
        g["revenueratio"] = g.itemrevenue * 100.0 / class_tot
        return g.sort_values(["i_category", "i_class", "i_item_id", "i_item_desc", "revenueratio"]
                             ).head(100).reset_index(drop=True)
    if q == 36:
        st = t["store"]
        m = ss.merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(st[st.s_state.isin(["TN", "TX", "SD", "IN", "GA", "OH", "MI", "MT"])],
                    left_on="ss_store_sk", right_on="s_store_sk")

        def gm(g):
            return g.ss_net_profit.sum() / g.ss_ext_sales_price.sum()

        rows = []
        full = m.groupby(["i_category", "i_class"])
        for (cat, cls), g in full:
            rows.append((gm(g), cat, cls, 0))
        for cat, g in m.groupby("i_category"):
            rows.append((gm(g), cat, None, 1))
        rows.append((gm(m), None, None, 2))
        out = pd.DataFrame(rows, columns=["gross_margin", "i_category", "i_class", "lochierarchy"])
        out["rank_within_parent"] = (
            out.groupby("lochierarchy")["gross_margin"].rank(method="min").astype(int)
        )
        out = out.sort_values(
            ["lochierarchy", "i_category", "i_class"],
            ascending=[False, True, True], na_position="first",
        ).head(100).reset_index(drop=True)
        return out
    if q == 33:
        ca = t["customer_address"]
        out_frames = []
        for fact, date_col, item_col, addr_col, price_col in (
            (t["store_sales"], "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk", "ss_ext_sales_price"),
            (t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk", "cs_ext_sales_price"),
            (t["web_sales"], "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
        ):
            m = fact.merge(dd[(dd.d_year == 1999) & (dd.d_moy == 3)],
                           left_on=date_col, right_on="d_date_sk")
            m = m.merge(it[it.i_category == "Books"], left_on=item_col, right_on="i_item_sk")
            m = m.merge(ca[ca.ca_gmt_offset == -5.0], left_on=addr_col, right_on="ca_address_sk")
            g = m.groupby("i_manufact_id", as_index=False).agg(total_sales=(price_col, "sum"))
            out_frames.append(g)
        allc = pd.concat(out_frames, ignore_index=True)
        g = allc.groupby("i_manufact_id", as_index=False).agg(total_sales=("total_sales", "sum"))
        return g.sort_values(["total_sales", "i_manufact_id"]).head(100).reset_index(drop=True)
    raise ValueError(f"no oracle for q{q}")


# queries whose LIMIT can cut through ties: only the ORDER BY key columns
# are deterministic, so the comparison restricts to them
TIE_KEYS = {73: ["cnt", "c_last_name"]}


def compare_results(engine_table, ref: pd.DataFrame, q: int) -> list[str]:
    """Column-by-column comparison after aligning on a full sort. For
    queries in TIE_KEYS, compares the ORDER BY key multiset only (rows
    beyond the keys are tie-broken arbitrarily by any conforming engine)."""
    problems = []
    out = engine_table.to_pandas()
    if len(out.columns) != len(ref.columns):
        return [f"q{q}: column count {len(out.columns)} != {len(ref.columns)}"]
    if len(out) != len(ref):
        return [f"q{q}: row count {len(out)} != {len(ref)}"]
    if len(ref) == 0:
        return []
    r = ref.copy()
    r.columns = list(out.columns)  # positional: engine aliases win
    if q in TIE_KEYS:
        keys = TIE_KEYS[q]
        out = out[keys]
        r = r[keys]
    o = out.sort_values(list(out.columns), kind="stable").reset_index(drop=True)
    r = r.sort_values(list(r.columns), kind="stable").reset_index(drop=True)
    for c in o.columns:
        sa, sb = o[c], r[c]
        na_a, na_b = pd.isna(sa).values, pd.isna(sb).values
        a, b = sa.values, sb.values
        try:
            if not (na_a == na_b).all():
                ok = False
            elif np.asarray(a).dtype.kind == "f" or np.asarray(b).dtype.kind == "f":
                ok = np.allclose(
                    np.asarray(a, float), np.asarray(b, float),
                    rtol=1e-6, atol=1e-6, equal_nan=True,
                )
            else:
                # nulls already matched positionally; compare the rest
                # (None vs np.nan representations must not differ)
                ok = (a[~na_a] == b[~na_b]).all()
        except (TypeError, ValueError):
            ok = list(a) == list(b)
        if not ok:
            problems.append(f"q{q}: column {c} mismatch")
    return problems
