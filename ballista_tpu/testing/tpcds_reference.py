"""pandas oracle for the TPC-DS query subset (benchmarks/tpcds/queries).

Mirrors testing/reference.py's role for TPC-H: an independent computation
of each query used by --verify and the test suite. Sort-prefix comparison
semantics: rows are compared on the ORDER BY prefix columns; full-row sets
must match.
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np
import pandas as pd
import pyarrow.parquet as pq

from ballista_tpu.testing.tpcdsgen import TPCDS_TABLES


def _rollup(m: pd.DataFrame, cols: list, valcol, how: str) -> pd.DataFrame:
    """GROUP BY ROLLUP(cols): one frame per prefix level (full detail down
    to grand total), grouped-out keys padded with None. `valcol` may be a
    single column name or a list. Adds a `lochierarchy` column (= number
    of grouped-out keys, the grouping()-sum the rollup queries select)."""
    vals = [valcol] if isinstance(valcol, str) else list(valcol)
    frames = []
    for k in range(len(cols), -1, -1):
        keys = cols[:k]
        if keys:
            g = getattr(m.groupby(keys, as_index=False)[vals], how)()
        else:
            g = pd.DataFrame({v: [getattr(m[v], how)()] for v in vals})
        for c in cols[k:]:
            g[c] = None
        g["lochierarchy"] = len(cols) - k
        frames.append(g[cols + vals + ["lochierarchy"]])
    return pd.concat(frames, ignore_index=True)


def load_tables(data_dir: str) -> dict[str, pd.DataFrame]:
    out = {}
    for t in TPCDS_TABLES:
        out[t] = pq.read_table(os.path.join(data_dir, t)).to_pandas()
    return out


def run_reference(q: int, t: dict[str, pd.DataFrame]) -> pd.DataFrame:
    ss, dd, it = t["store_sales"], t["date_dim"], t["item"]
    if q == 3:
        m = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manufact_id == 128], left_on="ss_item_sk", right_on="i_item_sk")
        m = m[m.d_moy == 11]
        g = m.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False).agg(
            sum_agg=("ss_ext_sales_price", "sum"))
        g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
        return g.sort_values(["d_year", "sum_agg", "brand_id"],
                             ascending=[True, False, True]).head(100).reset_index(drop=True)
    if q == 7:
        cd, pr = t["customer_demographics"], t["promotion"]
        m = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        cdf = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                 & (cd.cd_education_status == "College")]
        m = m.merge(cdf, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        prf = pr[(pr.p_channel_email == "N") | (pr.p_channel_event == "N")]
        m = m.merge(prf, left_on="ss_promo_sk", right_on="p_promo_sk")
        g = m.groupby("i_item_id", as_index=False).agg(
            agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
            agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
        return g.sort_values("i_item_id").head(100).reset_index(drop=True)
    if q == 19:
        cu, ca, st = t["customer"], t["customer_address"], t["store"]
        m = ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1998)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manager_id == 8], left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        m = m[m.ca_state != m.s_state]
        g = m.groupby(["i_brand_id", "i_brand", "i_manufact_id"], as_index=False).agg(
            ext_price=("ss_ext_sales_price", "sum"))
        g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
        return g.sort_values(["ext_price", "brand_id", "i_manufact_id"],
                             ascending=[False, True, True]).head(100).reset_index(drop=True)
    if q in (42, 52, 55):
        mgr = {42: 1, 52: 1, 55: 28}[q]
        year = {42: 2000, 52: 2000, 55: 1999}[q]
        m = ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == year)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[it.i_manager_id == mgr], left_on="ss_item_sk", right_on="i_item_sk")
        if q == 42:
            g = m.groupby(["d_year", "i_category_id", "i_category"], as_index=False).agg(
                total=("ss_ext_sales_price", "sum"))
            return g.sort_values(["total", "d_year", "i_category_id", "i_category"],
                                 ascending=[False, True, True, True]).head(100).reset_index(drop=True)
        g = m.groupby((["d_year"] if q == 52 else []) + ["i_brand_id", "i_brand"],
                      as_index=False).agg(ext_price=("ss_ext_sales_price", "sum"))
        g = g.rename(columns={"i_brand_id": "brand_id", "i_brand": "brand"})
        if q == 52:
            return g.sort_values(["d_year", "ext_price", "brand_id"],
                                 ascending=[True, False, True]).head(100).reset_index(drop=True)
        return g.sort_values(["ext_price", "brand_id"],
                             ascending=[False, True]).head(100).reset_index(drop=True)
    if q == 68:
        cu, ca, st, hd = t["customer"], t["customer_address"], t["store"], t["household_demographics"]
        m = ss.merge(dd[(dd.d_dom.between(1, 2)) & (dd.d_year.isin([1999, 2000, 2001]))],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_city.isin(["Midway", "Fairview"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
        dn = m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "ca_city"],
                       as_index=False).agg(extended_price=("ss_ext_sales_price", "sum"),
                                           list_price=("ss_ext_list_price", "sum"),
                                           extended_tax=("ss_ext_tax", "sum"))
        dn = dn.rename(columns={"ca_city": "bought_city"})
        dn = dn.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        dn = dn.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        dn = dn[dn.ca_city != dn.bought_city]
        out = dn[["c_last_name", "c_first_name", "ca_city", "bought_city", "ss_ticket_number",
                  "extended_price", "extended_tax", "list_price"]]
        return out.sort_values(["c_last_name", "ss_ticket_number"]).head(100).reset_index(drop=True)
    if q == 73:
        cu, st, hd = t["customer"], t["store"], t["household_demographics"]
        m = ss.merge(dd[(dd.d_dom.between(1, 2)) & (dd.d_year.isin([1999, 2000, 2001]))],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_county.isin(["Williamson County", "Walker County"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[((hd.hd_buy_potential == ">10000") | (hd.hd_buy_potential == "Unknown"))
                       & (hd.hd_vehicle_count > 0)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        dj = m.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False).agg(
            cnt=("ss_ticket_number", "size"))
        dj = dj[dj.cnt.between(1, 5)]
        dj = dj.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        out = dj[["c_last_name", "c_first_name", "c_customer_sk", "ss_ticket_number", "cnt"]]
        out = out.rename(columns={"c_customer_sk": "c_salutation"})
        return out.sort_values(["cnt", "c_last_name"],
                               ascending=[False, True]).head(100).reset_index(drop=True)
    if q == 96:
        td, st, hd = t["time_dim"], t["store"], t["household_demographics"]
        m = ss.merge(td[(td.t_hour == 20) & (td.t_minute >= 30)],
                     left_on="ss_sold_time_sk", right_on="t_time_sk")
        m = m.merge(hd[hd.hd_dep_count == 7], left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(st[st.s_store_name == "store 1"], left_on="ss_store_sk", right_on="s_store_sk")
        return pd.DataFrame({"cnt": [len(m)]})
    if q == 98:
        m = ss.merge(it[it.i_category.isin(["Sports", "Books", "Home"])],
                     left_on="ss_item_sk", right_on="i_item_sk")
        lo, hi = dt.date(1999, 2, 22), dt.date(1999, 3, 24)
        dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)]
        m = m.merge(dsel, left_on="ss_sold_date_sk", right_on="d_date_sk")
        g = m.groupby(["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
                      as_index=False).agg(itemrevenue=("ss_ext_sales_price", "sum"))
        class_tot = g.groupby("i_class")["itemrevenue"].transform("sum")
        g["revenueratio"] = g.itemrevenue * 100.0 / class_tot
        return g.sort_values(["i_category", "i_class", "i_item_id", "i_item_desc", "revenueratio"]
                             ).head(100).reset_index(drop=True)
    if q == 36:
        st = t["store"]
        m = ss.merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(st[st.s_state.isin(["TN", "TX", "SD", "IN", "GA", "OH", "MI", "MT"])],
                    left_on="ss_store_sk", right_on="s_store_sk")

        def gm(g):
            return g.ss_net_profit.sum() / g.ss_ext_sales_price.sum()

        rows = []
        full = m.groupby(["i_category", "i_class"])
        for (cat, cls), g in full:
            rows.append((gm(g), cat, cls, 0))
        for cat, g in m.groupby("i_category"):
            rows.append((gm(g), cat, None, 1))
        rows.append((gm(m), None, None, 2))
        out = pd.DataFrame(rows, columns=["gross_margin", "i_category", "i_class", "lochierarchy"])
        out["rank_within_parent"] = (
            out.groupby("lochierarchy")["gross_margin"].rank(method="min").astype(int)
        )
        out = out.sort_values(
            ["lochierarchy", "i_category", "i_class"],
            ascending=[False, True, True], na_position="first",
        ).head(100).reset_index(drop=True)
        return out
    if q == 33:
        ca = t["customer_address"]
        out_frames = []
        for fact, date_col, item_col, addr_col, price_col in (
            (t["store_sales"], "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk", "ss_ext_sales_price"),
            (t["catalog_sales"], "cs_sold_date_sk", "cs_item_sk", "cs_bill_addr_sk", "cs_ext_sales_price"),
            (t["web_sales"], "ws_sold_date_sk", "ws_item_sk", "ws_bill_addr_sk", "ws_ext_sales_price"),
        ):
            m = fact.merge(dd[(dd.d_year == 1999) & (dd.d_moy == 3)],
                           left_on=date_col, right_on="d_date_sk")
            m = m.merge(it[it.i_category == "Books"], left_on=item_col, right_on="i_item_sk")
            m = m.merge(ca[ca.ca_gmt_offset == -5.0], left_on=addr_col, right_on="ca_address_sk")
            g = m.groupby("i_manufact_id", as_index=False).agg(total_sales=(price_col, "sum"))
            out_frames.append(g)
        allc = pd.concat(out_frames, ignore_index=True)
        g = allc.groupby("i_manufact_id", as_index=False).agg(total_sales=("total_sales", "sum"))
        return g.sort_values(["total_sales", "i_manufact_id"]).head(100).reset_index(drop=True)
    if q == 6:
        cu, ca = t["customer"], t["customer_address"]
        cat_avg = it.groupby("i_category")["i_current_price"].transform("mean")
        hot = it[it.i_current_price > 1.2 * cat_avg]
        m = ss.merge(dd[(dd.d_year == 2001) & (dd.d_moy == 1)],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(hot, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        g = m.groupby("ca_state", as_index=False).agg(cnt=("ca_state", "size"))
        g = g[g.cnt >= 10].rename(columns={"ca_state": "state"})
        return g.sort_values(["cnt", "state"]).head(100).reset_index(drop=True)
    if q in (12, 20):
        fact, pfx = (t["web_sales"], "ws") if q == 12 else (t["catalog_sales"], "cs")
        m = fact.merge(it[it.i_category.isin(["Sports", "Books", "Home"])],
                       left_on=f"{pfx}_item_sk", right_on="i_item_sk")
        lo, hi = dt.date(1999, 2, 22), dt.date(1999, 3, 24)
        m = m.merge(dd[(dd.d_date >= lo) & (dd.d_date <= hi)],
                    left_on=f"{pfx}_sold_date_sk", right_on="d_date_sk")
        g = m.groupby(["i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"],
                      as_index=False).agg(itemrevenue=(f"{pfx}_ext_sales_price", "sum"))
        g["revenueratio"] = g.itemrevenue * 100.0 / g.groupby("i_class")["itemrevenue"].transform("sum")
        return g.sort_values(["i_category", "i_class", "i_item_id", "i_item_desc", "revenueratio"]
                             ).head(100).reset_index(drop=True)
    if q == 13:
        cd, hd, ca, st = (t["customer_demographics"], t["household_demographics"],
                          t["customer_address"], t["store"])
        m = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(dd[dd.d_year == 2001], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(ca[ca.ca_country == "United States"],
                    left_on="ss_addr_sk", right_on="ca_address_sk")
        c1 = ((m.cd_marital_status == "M") & (m.cd_education_status == "College")
              & m.ss_sales_price.between(100, 150) & (m.hd_dep_count == 3))
        c2 = ((m.cd_marital_status == "S") & (m.cd_education_status == "Primary")
              & m.ss_sales_price.between(50, 100) & (m.hd_dep_count == 1))
        c3 = ((m.cd_marital_status == "W") & (m.cd_education_status == "2 yr Degree")
              & m.ss_sales_price.between(150, 200) & (m.hd_dep_count == 1))
        g1 = (m.ca_state.isin(["TX", "OH"]) & m.ss_net_profit.between(100, 200))
        g2 = (m.ca_state.isin(["OR", "NM", "KY"]) & m.ss_net_profit.between(150, 300))
        g3 = (m.ca_state.isin(["VA", "TX", "MS"]) & m.ss_net_profit.between(50, 250))
        m = m[(c1 | c2 | c3) & (g1 | g2 | g3)]
        return pd.DataFrame({
            "avg_q": [m.ss_quantity.mean()], "avg_esp": [m.ss_ext_sales_price.mean()],
            "avg_ewc": [m.ss_ext_wholesale_cost.mean()],
            "sum_ewc": [m.ss_ext_wholesale_cost.sum() if len(m) else None],
        })
    if q == 15:
        cs, cu, ca = t["catalog_sales"], t["customer"], t["customer_address"]
        m = cs.merge(cu, left_on="cs_bill_customer_sk", right_on="c_customer_sk")
        m = m.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
        zips = {"85669", "86197", "88274", "83405", "86475", "85392", "85460",
                "80348", "81792"}
        m = m[m.ca_zip.str[:5].isin(zips) | m.ca_state.isin(["CA", "WA", "GA"])
              | (m.cs_sales_price > 500)]
        g = m.groupby("ca_zip", as_index=False).agg(s=("cs_sales_price", "sum"))
        return g.sort_values("ca_zip").head(100).reset_index(drop=True)
    if q in (25, 29):
        sr, cs, st = t["store_returns"], t["catalog_sales"], t["store"]
        if q == 25:
            d1 = dd[(dd.d_moy == 4) & (dd.d_year == 2001)]
            d2 = dd[(dd.d_moy.between(4, 10)) & (dd.d_year == 2001)]
            d3 = d2
        else:
            d1 = dd[(dd.d_moy == 9) & (dd.d_year == 1999)]
            d2 = dd[(dd.d_moy.between(9, 12)) & (dd.d_year == 1999)]
            d3 = dd[dd.d_year.isin([1999, 2000, 2001])]
        m = ss.merge(d1[["d_date_sk"]], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(sr, left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
                    right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
        m = m.merge(d2[["d_date_sk"]].rename(columns={"d_date_sk": "_d2sk"}),
                    left_on="sr_returned_date_sk", right_on="_d2sk")
        m = m.merge(cs, left_on=["sr_customer_sk", "sr_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"])
        m = m.merge(d3[["d_date_sk"]].rename(columns={"d_date_sk": "_d3sk"}),
                    left_on="cs_sold_date_sk", right_on="_d3sk")
        if q == 25:
            g = m.groupby(["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
                          as_index=False).agg(a=("ss_net_profit", "sum"),
                                              b=("sr_net_loss", "sum"),
                                              c=("cs_net_profit", "sum"))
        else:
            g = m.groupby(["i_item_id", "i_item_desc", "s_store_id", "s_store_name"],
                          as_index=False).agg(a=("ss_quantity", "sum"),
                                              b=("sr_return_quantity", "sum"),
                                              c=("cs_quantity", "sum"))
        return g.sort_values(["i_item_id", "i_item_desc", "s_store_id", "s_store_name"]
                             ).head(100).reset_index(drop=True)
    if q == 26:
        cs, cd, pr = t["catalog_sales"], t["customer_demographics"], t["promotion"]
        m = cs.merge(dd[dd.d_year == 2000], left_on="cs_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="cs_item_sk", right_on="i_item_sk")
        cdf = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                 & (cd.cd_education_status == "College")]
        m = m.merge(cdf, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        prf = pr[(pr.p_channel_email == "N") | (pr.p_channel_event == "N")]
        m = m.merge(prf, left_on="cs_promo_sk", right_on="p_promo_sk")
        g = m.groupby("i_item_id", as_index=False).agg(
            agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
            agg3=("cs_coupon_amt", "mean"), agg4=("cs_sales_price", "mean"))
        return g.sort_values("i_item_id").head(100).reset_index(drop=True)
    if q in (32, 92):
        fact, pfx, mid = ((t["catalog_sales"], "cs", 77) if q == 32
                          else (t["web_sales"], "ws", 53))
        lo, hi = dt.date(2000, 1, 27), dt.date(2000, 4, 26)
        dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]]
        win = fact.merge(dsel, left_on=f"{pfx}_sold_date_sk", right_on="d_date_sk")
        thresh = win.groupby(f"{pfx}_item_sk")[f"{pfx}_ext_discount_amt"].transform("mean") * 1.3
        hot = win[win[f"{pfx}_ext_discount_amt"] > thresh]
        hot = hot.merge(it[it.i_manufact_id == mid], left_on=f"{pfx}_item_sk",
                        right_on="i_item_sk")
        total = hot[f"{pfx}_ext_discount_amt"].sum() if len(hot) else None
        return pd.DataFrame({"excess_discount_amount": [total]})
    if q == 34:
        cu, st, hd = t["customer"], t["store"], t["household_demographics"]
        m = ss.merge(dd[(dd.d_dom.between(1, 3) | dd.d_dom.between(25, 28))
                        & dd.d_year.isin([1999, 2000, 2001])],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_county.isin(["Williamson County", "Walker County"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[((hd.hd_buy_potential == ">10000") | (hd.hd_buy_potential == "Unknown"))
                       & (hd.hd_vehicle_count > 0)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        dn = m.groupby(["ss_ticket_number", "ss_customer_sk"], as_index=False).agg(
            cnt=("ss_ticket_number", "size"))
        dn = dn[dn.cnt.between(5, 10)]
        dn = dn.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        out = dn[["c_last_name", "c_first_name", "c_salutation", "c_preferred_cust_flag",
                  "ss_ticket_number", "cnt"]]
        return out.sort_values(
            ["c_last_name", "c_first_name", "c_salutation", "c_preferred_cust_flag",
             "ss_ticket_number"], ascending=[True, True, True, False, True],
        ).reset_index(drop=True)
    if q == 37 or q == 82:
        inv, fact = t["inventory"], t["catalog_sales"] if q == 37 else t["store_sales"]
        item_col = "cs_item_sk" if q == 37 else "ss_item_sk"
        price_lo, price_hi = (10, 150) if q == 37 else (10, 150)
        mids = [67, 96, 91, 84] if q == 37 else [43, 12, 72, 66]
        lo, hi = ((dt.date(2000, 2, 1), dt.date(2000, 4, 1)) if q == 37
                  else (dt.date(2002, 5, 30), dt.date(2002, 7, 30)))
        itf = it[it.i_current_price.between(price_lo, price_hi)
                 & it.i_manufact_id.isin(mids)]
        m = itf.merge(inv, left_on="i_item_sk", right_on="inv_item_sk")
        m = m.merge(dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]],
                    left_on="inv_date_sk", right_on="d_date_sk")
        m = m[m.inv_quantity_on_hand.between(100, 500)]
        m = m.merge(fact[[item_col]], left_on="i_item_sk", right_on=item_col)
        g = m[["i_item_id", "i_item_desc", "i_current_price"]].drop_duplicates()
        return g.sort_values("i_item_id").head(100).reset_index(drop=True)
    if q == 40:
        cs, wh = t["catalog_sales"], t["warehouse"]
        lo, hi = dt.date(2000, 2, 10), dt.date(2000, 4, 10)
        cut = dt.date(2000, 3, 11)
        m = cs.merge(it[it.i_current_price.between(0.99, 110.99)],
                     left_on="cs_item_sk", right_on="i_item_sk")
        m = m.merge(wh, left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
        m = m.merge(dd[(dd.d_date >= lo) & (dd.d_date <= hi)],
                    left_on="cs_sold_date_sk", right_on="d_date_sk")
        m["sales_before"] = np.where(m.d_date < cut, m.cs_sales_price, 0.0)
        m["sales_after"] = np.where(m.d_date >= cut, m.cs_sales_price, 0.0)
        g = m.groupby(["w_state", "i_item_id"], as_index=False).agg(
            sales_before=("sales_before", "sum"), sales_after=("sales_after", "sum"))
        return g.sort_values(["w_state", "i_item_id"]).head(100).reset_index(drop=True)
    if q == 43:
        st = t["store"]
        m = ss.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_gmt_offset == -5.0], left_on="ss_store_sk", right_on="s_store_sk")
        g = m.groupby(["s_store_name", "s_store_id"], as_index=False).apply(
            lambda x: pd.Series({
                d: x.loc[x.d_day_name == n, "ss_sales_price"].sum()
                if (x.d_day_name == n).any() else np.nan
                for d, n in zip(
                    ["sun_sales", "mon_sales", "tue_sales", "wed_sales",
                     "thu_sales", "fri_sales", "sat_sales"],
                    ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
                     "Friday", "Saturday"])
            }), include_groups=False).reset_index()
        g = g.drop(columns=[c for c in g.columns
                            if str(c).startswith("level") or str(c) == "index"],
                   errors="ignore")
        return g.sort_values(["s_store_name", "s_store_id"]).head(100).reset_index(drop=True)
    if q == 45:
        ws, cu, ca = t["web_sales"], t["customer"], t["customer_address"]
        m = ws.merge(cu, left_on="ws_bill_customer_sk", right_on="c_customer_sk")
        m = m.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(it, left_on="ws_item_sk", right_on="i_item_sk")
        m = m.merge(dd[(dd.d_qoy == 2) & (dd.d_year == 2001)][["d_date_sk"]],
                    left_on="ws_sold_date_sk", right_on="d_date_sk")
        zips = {"85669", "86197", "88274", "83405", "86475", "85392", "85460",
                "80348", "81792"}
        hot_ids = set(it[it.i_item_sk.isin([2, 3, 5, 7, 11, 13, 17, 19, 23, 29])].i_item_id)
        m = m[m.ca_zip.str[:5].isin(zips) | m.i_item_id.isin(hot_ids)]
        g = m.groupby(["ca_zip", "ca_city"], as_index=False).agg(s=("ws_sales_price", "sum"))
        return g.sort_values(["ca_zip", "ca_city"]).head(100).reset_index(drop=True)
    if q == 46:
        cu, ca, st, hd = (t["customer"], t["customer_address"], t["store"],
                          t["household_demographics"])
        m = ss.merge(dd[dd.d_dow.isin([6, 0]) & dd.d_year.isin([1999, 2000, 2001])],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_city.isin(["Fairview", "Midway"])],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[(hd.hd_dep_count == 4) | (hd.hd_vehicle_count == 3)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk")
        dn = m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "ca_city"],
                       as_index=False).agg(amt=("ss_coupon_amt", "sum"),
                                           profit=("ss_net_profit", "sum"))
        dn = dn.rename(columns={"ca_city": "bought_city"})
        dn = dn.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        dn = dn.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        dn = dn[dn.ca_city != dn.bought_city]
        out = dn[["c_last_name", "c_first_name", "ca_city", "bought_city",
                  "ss_ticket_number", "amt", "profit"]]
        return out.sort_values(["c_last_name", "c_first_name", "ca_city", "bought_city",
                                "ss_ticket_number"]).head(100).reset_index(drop=True)
    if q == 48:
        cd, ca, st = t["customer_demographics"], t["customer_address"], t["store"]
        m = ss.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(ca[ca.ca_country == "United States"],
                    left_on="ss_addr_sk", right_on="ca_address_sk")
        c1 = ((m.cd_marital_status == "M") & (m.cd_education_status == "4 yr Degree")
              & m.ss_sales_price.between(100, 150))
        c2 = ((m.cd_marital_status == "D") & (m.cd_education_status == "2 yr Degree")
              & m.ss_sales_price.between(50, 100))
        c3 = ((m.cd_marital_status == "S") & (m.cd_education_status == "College")
              & m.ss_sales_price.between(150, 200))
        g1 = (m.ca_state.isin(["CO", "OH", "TX"]) & m.ss_net_profit.between(0, 2000))
        g2 = (m.ca_state.isin(["OR", "MN", "KY"]) & m.ss_net_profit.between(150, 3000))
        g3 = (m.ca_state.isin(["VA", "CA", "MS"]) & m.ss_net_profit.between(50, 25000))
        m = m[(c1 | c2 | c3) & (g1 | g2 | g3)]
        return pd.DataFrame({"sq": [m.ss_quantity.sum() if len(m) else None]})
    if q == 50:
        sr, st = t["store_returns"], t["store"]
        m = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk", "ss_customer_sk"],
                     right_on=["sr_ticket_number", "sr_item_sk", "sr_customer_sk"])
        m = m.merge(dd[(dd.d_year == 2001) & (dd.d_moy == 8)][["d_date_sk"]],
                    left_on="sr_returned_date_sk", right_on="d_date_sk")
        m = m.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        lag = m.sr_returned_date_sk - m.ss_sold_date_sk
        m["d30"] = (lag <= 30).astype(int)
        m["d31_60"] = ((lag > 30) & (lag <= 60)).astype(int)
        m["d_gt_60"] = (lag > 60).astype(int)
        g = m.groupby(["s_store_name", "s_county"], as_index=False).agg(
            d30=("d30", "sum"), d31_60=("d31_60", "sum"), d_gt_60=("d_gt_60", "sum"))
        return g.sort_values(["s_store_name", "s_county"]).head(100).reset_index(drop=True)
    if q == 61:
        st, pr, cu, ca = t["store"], t["promotion"], t["customer"], t["customer_address"]
        base = ss.merge(dd[(dd.d_year == 1998) & (dd.d_moy == 11)],
                        left_on="ss_sold_date_sk", right_on="d_date_sk")
        base = base.merge(st[st.s_gmt_offset == -5.0], left_on="ss_store_sk",
                          right_on="s_store_sk")
        base = base.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        base = base.merge(ca[ca.ca_gmt_offset == -5.0], left_on="c_current_addr_sk",
                          right_on="ca_address_sk")
        base = base.merge(it[it.i_category == "Jewelry"], left_on="ss_item_sk",
                          right_on="i_item_sk")
        prf = pr[(pr.p_channel_email == "Y") | (pr.p_channel_event == "Y")]
        promo = base.merge(prf, left_on="ss_promo_sk", right_on="p_promo_sk")
        p_sum = promo.ss_ext_sales_price.sum()
        t_sum = base.ss_ext_sales_price.sum()
        return pd.DataFrame({"promotions": [p_sum], "total": [t_sum],
                             "ratio": [p_sum / t_sum * 100 if t_sum else None]})
    if q == 65:
        st = t["store"]
        m = ss.merge(dd[dd.d_year == 2000][["d_date_sk"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        sc = m.groupby(["ss_store_sk", "ss_item_sk"], as_index=False).agg(
            revenue=("ss_sales_price", "sum"))
        sb = sc.groupby("ss_store_sk", as_index=False).agg(ave=("revenue", "mean"))
        j = sc.merge(sb, on="ss_store_sk")
        j = j[j.revenue <= 0.1 * j.ave]
        j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
        j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        out = j[["s_store_name", "i_item_desc", "revenue", "i_current_price",
                 "i_wholesale_cost", "i_brand"]]
        return out.sort_values(["s_store_name", "i_item_desc", "revenue"]
                               ).head(100).reset_index(drop=True)
    if q == 79:
        cu, st, hd = t["customer"], t["store"], t["household_demographics"]
        m = ss.merge(dd[(dd.d_dow == 1) & dd.d_year.isin([1999, 2000, 2001])],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[st.s_number_employees.between(200, 295)],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(hd[(hd.hd_dep_count == 6) | (hd.hd_vehicle_count > 2)],
                    left_on="ss_hdemo_sk", right_on="hd_demo_sk")
        ms = m.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk", "s_city"],
                       as_index=False, dropna=False).agg(amt=("ss_coupon_amt", "sum"),
                                                         profit=("ss_net_profit", "sum"))
        ms = ms.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        ms["city30"] = ms.s_city.str[:30]
        out = ms[["c_last_name", "c_first_name", "city30", "ss_ticket_number",
                  "amt", "profit"]]
        return out.sort_values(["c_last_name", "c_first_name", "city30", "profit",
                                "ss_ticket_number"]).head(100).reset_index(drop=True)
    if q == 88:
        td, st, hd = t["time_dim"], t["store"], t["household_demographics"]
        hdf = hd[((hd.hd_dep_count == 4) & (hd.hd_vehicle_count <= 6))
                 | ((hd.hd_dep_count == 2) & (hd.hd_vehicle_count <= 4))
                 | ((hd.hd_dep_count == 0) & (hd.hd_vehicle_count <= 2))]
        stf = st[st.s_store_name == "store 1"]

        def bucket(hour, half):
            m = ss.merge(td[(td.t_hour == hour)
                            & ((td.t_minute >= 30) if half else (td.t_minute < 30))],
                         left_on="ss_sold_time_sk", right_on="t_time_sk")
            m = m.merge(hdf, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
            m = m.merge(stf, left_on="ss_store_sk", right_on="s_store_sk")
            return len(m)

        return pd.DataFrame({
            "h8_30_to_9": [bucket(8, True)], "h9_to_9_30": [bucket(9, False)],
            "h9_30_to_10": [bucket(9, True)], "h10_to_10_30": [bucket(10, False)],
        })
    if q == 90:
        ws, td, hd, wp = (t["web_sales"], t["time_dim"], t["household_demographics"],
                          t["web_page"])
        hdf = hd[hd.hd_dep_count == 6]
        wpf = wp[wp.wp_char_count.between(5000, 5200)]

        def cnt(h_lo, h_hi):
            m = ws.merge(td[td.t_hour.between(h_lo, h_hi)],
                         left_on="ws_sold_time_sk", right_on="t_time_sk")
            m = m.merge(hdf, left_on="ws_ship_hdemo_sk", right_on="hd_demo_sk")
            m = m.merge(wpf, left_on="ws_web_page_sk", right_on="wp_web_page_sk")
            return len(m)

        amc, pmc = cnt(8, 9), cnt(19, 20)
        return pd.DataFrame({"am_pm_ratio": [amc / pmc if pmc else None]})
    if q == 93:
        sr, rs = t["store_returns"], t["reason"]
        srf = sr.merge(rs[rs.r_reason_desc == "reason 28"],
                       left_on="sr_reason_sk", right_on="r_reason_sk")
        m = ss.merge(srf, left_on=["ss_item_sk", "ss_ticket_number"],
                     right_on=["sr_item_sk", "sr_ticket_number"])
        m["act_sales"] = np.where(m.sr_return_quantity.notna(),
                                  (m.ss_quantity - m.sr_return_quantity) * m.ss_sales_price,
                                  m.ss_quantity * m.ss_sales_price)
        g = m.groupby("ss_customer_sk", as_index=False).agg(sumsales=("act_sales", "sum"))
        return g.sort_values(["sumsales", "ss_customer_sk"]).head(100).reset_index(drop=True)
    if q == 99:
        cs, wh, sm, cc = (t["catalog_sales"], t["warehouse"], t["ship_mode"],
                          t["call_center"])
        m = cs.merge(dd[dd.d_year == 2001][["d_date_sk"]],
                     left_on="cs_ship_date_sk", right_on="d_date_sk")
        m = m.merge(wh, left_on="cs_warehouse_sk", right_on="w_warehouse_sk")
        m = m.merge(sm, left_on="cs_ship_mode_sk", right_on="sm_ship_mode_sk")
        m = m.merge(cc, left_on="cs_call_center_sk", right_on="cc_call_center_sk")
        lag = m.cs_ship_date_sk - m.cs_sold_date_sk
        m["d30"] = (lag <= 30).astype(int)
        m["d31_60"] = ((lag > 30) & (lag <= 60)).astype(int)
        m["d_gt_60"] = (lag > 60).astype(int)
        m["wname"] = m.w_warehouse_name.str[:20]
        g = m.groupby(["wname", "sm_type", "cc_name"], as_index=False).agg(
            d30=("d30", "sum"), d31_60=("d31_60", "sum"), d_gt_60=("d_gt_60", "sum"))
        return g.sort_values(["wname", "sm_type", "cc_name"]).head(100).reset_index(drop=True)
    if q in (1, 30, 81):
        # customer_total_return shape: per-customer returns vs 1.2x the
        # state/store average (correlated scalar subquery over a CTE)
        cu, ca = t["customer"], t["customer_address"]
        if q == 1:
            sr, st = t["store_returns"], t["store"]
            m = sr.merge(dd[dd.d_year == 2000][["d_date_sk"]],
                         left_on="sr_returned_date_sk", right_on="d_date_sk")
            ctr = m.groupby(["sr_customer_sk", "sr_store_sk"], as_index=False).agg(
                ctr_total_return=("sr_return_amt", "sum"))
            avg_grp = ctr.groupby("sr_store_sk")["ctr_total_return"].transform("mean")
            hot = ctr[ctr.ctr_total_return > avg_grp * 1.2]
            hot = hot.merge(st[st.s_state == "TN"][["s_store_sk"]],
                            left_on="sr_store_sk", right_on="s_store_sk")
            hot = hot.merge(cu, left_on="sr_customer_sk", right_on="c_customer_sk")
            return hot[["c_customer_id"]].sort_values("c_customer_id").head(100).reset_index(drop=True)
        if q == 30:
            wr = t["web_returns"]
            m = wr.merge(dd[dd.d_year == 2002][["d_date_sk"]],
                         left_on="wr_returned_date_sk", right_on="d_date_sk")
            m = m.merge(ca[["ca_address_sk", "ca_state"]],
                        left_on="wr_refunded_addr_sk", right_on="ca_address_sk")
            ctr = m.groupby(["wr_returning_customer_sk", "ca_state"], as_index=False).agg(
                ctr_total_return=("wr_return_amt", "sum"))
            cust_col = "wr_returning_customer_sk"
        else:
            cr = t["catalog_returns"]
            m = cr.merge(dd[dd.d_year == 2000][["d_date_sk"]],
                         left_on="cr_returned_date_sk", right_on="d_date_sk")
            m = m.merge(ca[["ca_address_sk", "ca_state"]],
                        left_on="cr_returning_addr_sk", right_on="ca_address_sk")
            ctr = m.groupby(["cr_returning_customer_sk", "ca_state"], as_index=False).agg(
                ctr_total_return=("cr_return_amt", "sum"))
            cust_col = "cr_returning_customer_sk"
        avg_grp = ctr.groupby("ca_state")["ctr_total_return"].transform("mean")
        hot = ctr[ctr.ctr_total_return > avg_grp * 1.2]
        hot = hot.merge(cu, left_on=cust_col, right_on="c_customer_sk")
        hot = hot.merge(ca.add_suffix("_cur"), left_on="c_current_addr_sk",
                        right_on="ca_address_sk_cur")
        hot = hot[hot.ca_state_cur == "GA"]
        if q == 30:
            cols = ["c_customer_id", "c_salutation", "c_first_name", "c_last_name",
                    "c_preferred_cust_flag", "c_birth_day", "c_birth_month",
                    "c_birth_year", "c_birth_country", "c_login", "c_email_address",
                    "ctr_total_return"]
            out = hot[cols]
        else:
            out = pd.DataFrame({
                "c_customer_id": hot.c_customer_id, "c_salutation": hot.c_salutation,
                "c_first_name": hot.c_first_name, "c_last_name": hot.c_last_name,
                "ca_street_number": hot.ca_street_number_cur,
                "ca_street_name": hot.ca_street_name_cur,
                "ca_street_type": hot.ca_street_type_cur,
                "ca_suite_number": hot.ca_suite_number_cur,
                "ca_city": hot.ca_city_cur, "ca_county": hot.ca_county_cur,
                "ca_state": hot.ca_state_cur, "ca_zip": hot.ca_zip_cur,
                "ca_country": hot.ca_country_cur,
                "ca_gmt_offset": hot.ca_gmt_offset_cur,
                "ca_location_type": hot.ca_location_type_cur,
                "ctr_total_return": hot.ctr_total_return})
        return out.sort_values(list(out.columns)).head(100).reset_index(drop=True)
    if q == 17:
        sr, cs, st = t["store_returns"], t["catalog_sales"], t["store"]
        d1 = dd[dd.d_quarter_name == "2001Q1"][["d_date_sk"]]
        d23 = dd[dd.d_quarter_name.isin(["2001Q1", "2001Q2", "2001Q3"])][["d_date_sk"]]
        m = ss.merge(d1, left_on="ss_sold_date_sk", right_on="d_date_sk")
        srx = sr.merge(d23, left_on="sr_returned_date_sk", right_on="d_date_sk")
        m = m.merge(srx, left_on=["ss_customer_sk", "ss_item_sk", "ss_ticket_number"],
                    right_on=["sr_customer_sk", "sr_item_sk", "sr_ticket_number"])
        csx = cs.merge(d23, left_on="cs_sold_date_sk", right_on="d_date_sk")
        m = m.merge(csx, left_on=["sr_customer_sk", "sr_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"])
        m = m.merge(st[["s_store_sk", "s_state"]], left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(it[["i_item_sk", "i_item_id", "i_item_desc"]],
                    left_on="ss_item_sk", right_on="i_item_sk")
        g = m.groupby(["i_item_id", "i_item_desc", "s_state"], as_index=False).agg(
            c1=("ss_quantity", "count"), a1=("ss_quantity", "mean"), s1=("ss_quantity", "std"),
            c2=("sr_return_quantity", "count"), a2=("sr_return_quantity", "mean"),
            s2=("sr_return_quantity", "std"),
            c3=("cs_quantity", "count"), a3=("cs_quantity", "mean"), s3=("cs_quantity", "std"))
        for i in (1, 2, 3):
            g[f"cov{i}"] = g[f"s{i}"] / g[f"a{i}"]
        out = g[["i_item_id", "i_item_desc", "s_state", "c1", "a1", "s1", "cov1",
                 "c2", "a2", "s2", "cov2", "c3", "a3", "s3", "cov3"]]
        return out.sort_values(["i_item_id", "i_item_desc", "s_state"]).head(100).reset_index(drop=True)
    if q == 21:
        inv, wh = t["inventory"], t["warehouse"]
        dsel = dd[(dd.d_date >= dt.date(2000, 2, 10)) & (dd.d_date <= dt.date(2000, 4, 10))]
        m = inv.merge(dsel[["d_date_sk", "d_date"]], left_on="inv_date_sk", right_on="d_date_sk")
        m = m.merge(it[(it.i_current_price >= 0.99) & (it.i_current_price <= 29.49)][
            ["i_item_sk", "i_item_id"]], left_on="inv_item_sk", right_on="i_item_sk")
        m = m.merge(wh[["w_warehouse_sk", "w_warehouse_name"]],
                    left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
        pivot = dt.date(2000, 3, 11)
        m["before"] = np.where([d < pivot for d in m.d_date], m.inv_quantity_on_hand, 0)
        m["after"] = np.where([d >= pivot for d in m.d_date], m.inv_quantity_on_hand, 0)
        g = m.groupby(["w_warehouse_name", "i_item_id"], as_index=False).agg(
            inv_before=("before", "sum"), inv_after=("after", "sum"))
        ratio = np.where(g.inv_before > 0,
                         g.inv_after / np.where(g.inv_before > 0, g.inv_before, 1), np.nan)
        g = g[(ratio >= 2.0 / 3.0) & (ratio <= 1.5)]
        return g.sort_values(["w_warehouse_name", "i_item_id"]).head(100).reset_index(drop=True)
    if q == 22:
        inv = t["inventory"]
        dsel = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][["d_date_sk"]]
        m = inv.merge(dsel, left_on="inv_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="inv_item_sk", right_on="i_item_sk")
        cols = ["i_product_name", "i_brand", "i_class", "i_category"]
        out = _rollup(m, cols, "inv_quantity_on_hand", "mean").drop(
            columns=["lochierarchy"]).rename(columns={"inv_quantity_on_hand": "qoh"})
        out = out[cols + ["qoh"]]
        return out.sort_values(["qoh"] + cols, na_position="last").head(100).reset_index(drop=True)
    if q == 39:
        inv, wh = t["inventory"], t["warehouse"]
        m = inv.merge(dd[dd.d_year == 2001][["d_date_sk", "d_moy"]],
                      left_on="inv_date_sk", right_on="d_date_sk")
        m = m.merge(it[["i_item_sk"]], left_on="inv_item_sk", right_on="i_item_sk")
        m = m.merge(wh[["w_warehouse_sk", "w_warehouse_name"]],
                    left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
        g = m.groupby(["w_warehouse_name", "w_warehouse_sk", "i_item_sk", "d_moy"],
                      as_index=False).agg(stdev=("inv_quantity_on_hand", "std"),
                                          mean=("inv_quantity_on_hand", "mean"))
        sel = np.where(g["mean"] == 0, 0.0, g.stdev / g["mean"]) > 1
        g = g[sel].copy()
        g["cov"] = np.where(g["mean"] == 0, np.nan, g.stdev / g["mean"])
        j = g[g.d_moy == 1].merge(g[g.d_moy == 2], on=["i_item_sk", "w_warehouse_sk"],
                                  suffixes=("_1", "_2"))
        out = pd.DataFrame({
            "wsk1": j.w_warehouse_sk, "isk1": j.i_item_sk, "moy1": j.d_moy_1,
            "mean1": j.mean_1, "cov1": j.cov_1,
            "wsk2": j.w_warehouse_sk, "isk2": j.i_item_sk, "moy2": j.d_moy_2,
            "mean2": j.mean_2, "cov2": j.cov_2})
        return out.sort_values(list(out.columns)).reset_index(drop=True)
    if q == 62:
        ws, wh, sm, web = t["web_sales"], t["warehouse"], t["ship_mode"], t["web_site"]
        m = ws.merge(dd[dd.d_year == 2001][["d_date_sk"]],
                     left_on="ws_ship_date_sk", right_on="d_date_sk")
        m = m.merge(wh, left_on="ws_warehouse_sk", right_on="w_warehouse_sk")
        m = m.merge(sm, left_on="ws_ship_mode_sk", right_on="sm_ship_mode_sk")
        m = m.merge(web, left_on="ws_web_site_sk", right_on="web_site_sk")
        lag = m.ws_ship_date_sk - m.ws_sold_date_sk
        m["d30"] = (lag <= 30).astype(int)
        m["d31_60"] = ((lag > 30) & (lag <= 60)).astype(int)
        m["d_gt_60"] = (lag > 60).astype(int)
        m["wname"] = m.w_warehouse_name.str[:20]
        g = m.groupby(["wname", "sm_type", "web_name"], as_index=False).agg(
            d30=("d30", "sum"), d31_60=("d31_60", "sum"), d_gt_60=("d_gt_60", "sum"))
        return g.sort_values(["wname", "sm_type", "web_name"]).head(100).reset_index(drop=True)
    if q == 86:
        ws = t["web_sales"]
        dsel = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][["d_date_sk"]]
        m = ws.merge(dsel, left_on="ws_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it, left_on="ws_item_sk", right_on="i_item_sk")
        out = _rollup(m, ["i_category", "i_class"], "ws_net_paid", "sum").rename(
            columns={"ws_net_paid": "total_sum"})
        out["rank_within_parent"] = out.groupby("lochierarchy")["total_sum"].rank(
            method="min", ascending=False).astype(int)
        out = out.sort_values(["lochierarchy", "i_category", "i_class"],
                              ascending=[False, True, True], na_position="last")
        return out[["total_sum", "i_category", "i_class", "lochierarchy",
                    "rank_within_parent"]].head(100).reset_index(drop=True)
    if q == 91:
        cc, cr, cu = t["call_center"], t["catalog_returns"], t["customer"]
        ca, cd, hd = t["customer_address"], t["customer_demographics"], t["household_demographics"]
        m = cr.merge(cc, left_on="cr_call_center_sk", right_on="cc_call_center_sk")
        m = m.merge(dd[dd.d_year == 1998][["d_date_sk"]],
                    left_on="cr_returned_date_sk", right_on="d_date_sk")
        m = m.merge(cu, left_on="cr_returning_customer_sk", right_on="c_customer_sk")
        cdf = cd[((cd.cd_marital_status == "M") & (cd.cd_education_status == "Unknown"))
                 | ((cd.cd_marital_status == "W") & (cd.cd_education_status == "Advanced Degree"))]
        m = m.merge(cdf, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(hd[hd.hd_buy_potential.str.startswith("Unknown")],
                    left_on="c_current_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(ca[ca.ca_gmt_offset == -7], left_on="c_current_addr_sk",
                    right_on="ca_address_sk")
        g = m.groupby(["cc_call_center_id", "cc_name", "cc_manager",
                       "cd_marital_status", "cd_education_status"], as_index=False).agg(
            returns_loss=("cr_net_loss", "sum"))
        out = g[["cc_call_center_id", "cc_name", "cc_manager", "returns_loss"]]
        return out.sort_values("returns_loss", ascending=False).reset_index(drop=True)
    if q in (47, 57):
        # month-over-month outliers: windowed year-avg + lag/lead via rank
        # self-joins on a CTE
        dsel = dd[(dd.d_year == 1999) | ((dd.d_year == 1998) & (dd.d_moy == 12))
                  | ((dd.d_year == 2000) & (dd.d_moy == 1))][["d_date_sk", "d_year", "d_moy"]]
        if q == 47:
            st = t["store"]
            m = ss.merge(dsel, left_on="ss_sold_date_sk", right_on="d_date_sk")
            m = m.merge(it[["i_item_sk", "i_category", "i_brand"]],
                        left_on="ss_item_sk", right_on="i_item_sk")
            m = m.merge(st[["s_store_sk", "s_store_name", "s_company_name"]],
                        left_on="ss_store_sk", right_on="s_store_sk")
            keys, val = ["i_category", "i_brand", "s_store_name", "s_company_name"], "ss_sales_price"
            tie = ["s_store_name", "i_category", "i_brand", "s_company_name", "d_year", "d_moy"]
        else:
            cc = t["call_center"]
            m = t["catalog_sales"].merge(dsel, left_on="cs_sold_date_sk", right_on="d_date_sk")
            m = m.merge(it[["i_item_sk", "i_category", "i_brand"]],
                        left_on="cs_item_sk", right_on="i_item_sk")
            m = m.merge(cc[["cc_call_center_sk", "cc_name"]],
                        left_on="cs_call_center_sk", right_on="cc_call_center_sk")
            keys, val = ["i_category", "i_brand", "cc_name"], "cs_sales_price"
            tie = ["cc_name", "i_category", "i_brand", "d_year", "d_moy"]
        g = m.groupby(keys + ["d_year", "d_moy"], as_index=False).agg(sum_sales=(val, "sum"))
        g["avg_monthly_sales"] = g.groupby(keys + ["d_year"])["sum_sales"].transform("mean")
        g = g.sort_values(keys + ["d_year", "d_moy"]).reset_index(drop=True)
        g["rn"] = g.groupby(keys).cumcount() + 1
        lagd = g[keys + ["rn", "sum_sales"]].rename(columns={"sum_sales": "psum"})
        lagd = lagd.assign(rn=lagd.rn + 1)
        leadd = g[keys + ["rn", "sum_sales"]].rename(columns={"sum_sales": "nsum"})
        leadd = leadd.assign(rn=leadd.rn - 1)
        j = g.merge(lagd, on=keys + ["rn"]).merge(leadd, on=keys + ["rn"])
        j = j[(j.d_year == 1999) & (j.avg_monthly_sales > 0)]
        rel = np.abs(j.sum_sales - j.avg_monthly_sales) / j.avg_monthly_sales
        j = j[rel > 0.1].copy()
        j["_diff"] = j.sum_sales - j.avg_monthly_sales
        cols = keys + ["d_year", "d_moy", "avg_monthly_sales", "sum_sales", "psum", "nsum"]
        return j.sort_values(["_diff"] + tie).head(100)[cols].reset_index(drop=True)
    if q in (53, 63):
        st = t["store"]
        key, per = ("i_manufact_id", "d_qoy") if q == 53 else ("i_manager_id", "d_moy")
        m = ss.merge(dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][
            ["d_date_sk", per]], left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[["s_store_sk"]], left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        sel = ((m.i_category.isin(["Books", "Children", "Electronics"])
                & m.i_class.isin(["class#1", "class#2", "class#3"]))
               | (m.i_category.isin(["Women", "Music", "Men"])
                  & m.i_class.isin(["class#4", "class#5", "class#6"])))
        m = m[sel]
        g = m.groupby([key, per], as_index=False).agg(sum_sales=("ss_sales_price", "sum"))
        g["avg_s"] = g.groupby(key)["sum_sales"].transform("mean")
        g = g[np.where(g.avg_s > 0, np.abs(g.sum_sales - g.avg_s) / g.avg_s, np.nan) > 0.1]
        out = g[[key, "sum_sales", "avg_s"]]
        order = (["avg_s", "sum_sales", key] if q == 53 else [key, "avg_s", "sum_sales"])
        return out.sort_values(order).head(100).reset_index(drop=True)
    if q == 89:
        st = t["store"]
        m = ss.merge(dd[dd.d_year == 1999][["d_date_sk", "d_moy"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[["s_store_sk", "s_store_name", "s_company_name"]],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        sel = ((m.i_category.isin(["Books", "Electronics", "Sports"])
                & m.i_class.isin(["class#1", "class#2", "class#3"]))
               | (m.i_category.isin(["Men", "Jewelry", "Women"])
                  & m.i_class.isin(["class#4", "class#5", "class#6"])))
        m = m[sel]
        keys = ["i_category", "i_class", "i_brand", "s_store_name", "s_company_name"]
        g = m.groupby(keys + ["d_moy"], as_index=False).agg(sum_sales=("ss_sales_price", "sum"))
        # the window partition deliberately OMITS i_class (official shape):
        # a brand's average spans its classes
        g["avg_monthly_sales"] = g.groupby(
            ["i_category", "i_brand", "s_store_name", "s_company_name"]
        )["sum_sales"].transform("mean")
        g = g[np.where(g.avg_monthly_sales != 0,
                       np.abs(g.sum_sales - g.avg_monthly_sales) / g.avg_monthly_sales,
                       np.nan) > 0.1].copy()
        g["_diff"] = g.sum_sales - g.avg_monthly_sales
        out = g.sort_values(["_diff", "s_store_name", "i_category", "i_class",
                             "i_brand", "s_company_name", "d_moy"]).head(100)
        return out[["i_category", "i_class", "i_brand", "s_store_name",
                    "s_company_name", "d_moy", "sum_sales",
                    "avg_monthly_sales"]].reset_index(drop=True)
    if q == 59:
        st = t["store"]
        m = ss.merge(dd[["d_date_sk", "d_week_seq", "d_day_name"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"]
        dcols = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
        for day, c in zip(days, dcols):
            m[c] = np.where(m.d_day_name == day, m.ss_sales_price, np.nan)
        wss = m.groupby(["d_week_seq", "ss_store_sk"], as_index=False)[dcols].sum(min_count=1)

        def leg(lo, hi):
            weeks = dd[(dd.d_month_seq >= lo) & (dd.d_month_seq <= hi)][["d_week_seq"]]
            x = wss.merge(weeks, on="d_week_seq")  # replicated per matching day, like the SQL
            return x.merge(st[["s_store_sk", "s_store_name", "s_store_id"]],
                           left_on="ss_store_sk", right_on="s_store_sk")

        y = leg(1188, 1199).copy()
        x2 = leg(1200, 1211).copy()
        x2["wk_minus_52"] = x2.d_week_seq - 52
        j = y.merge(x2, left_on=["s_store_id", "d_week_seq"],
                    right_on=["s_store_id", "wk_minus_52"], suffixes=("_1", "_2"))
        out = pd.DataFrame({
            "s_store_name1": j.s_store_name_1, "s_store_id1": j.s_store_id,
            "d_week_seq1": j.d_week_seq_1,
            **{f"r_{c}": j[f"{c}_1"] / j[f"{c}_2"] for c in dcols}})
        return out.sort_values(["s_store_name1", "s_store_id1", "d_week_seq1"]
                               ).head(100).reset_index(drop=True)
    if q == 67:
        st = t["store"]
        m = ss.merge(dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][
            ["d_date_sk", "d_year", "d_qoy", "d_moy"]],
            left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[["s_store_sk", "s_store_id"]], left_on="ss_store_sk",
                    right_on="s_store_sk")
        m = m.merge(it[["i_item_sk", "i_category", "i_class", "i_brand", "i_product_name"]],
                    left_on="ss_item_sk", right_on="i_item_sk")
        m["val"] = (m.ss_sales_price * m.ss_quantity).fillna(0)
        cols = ["i_category", "i_class", "i_brand", "i_product_name", "d_year",
                "d_qoy", "d_moy", "s_store_id"]
        outp = _rollup(m, cols, "val", "sum").drop(columns=["lochierarchy"]).rename(
            columns={"val": "sumsales"})
        outp["rk"] = outp.groupby(outp.i_category.fillna("\x00null"))["sumsales"].rank(
            method="min", ascending=False).astype(int)
        outp = outp[outp.rk <= 100]
        return outp.sort_values(cols + ["sumsales", "rk"], na_position="last"
                                ).head(100).reset_index(drop=True)
    if q == 70:
        st = t["store"]
        m = ss.merge(dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][["d_date_sk"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[["s_store_sk", "s_state", "s_county"]],
                    left_on="ss_store_sk", right_on="s_store_sk")
        # inner ranking partitions by its own group key, so every state ranks 1
        out = _rollup(m, ["s_state", "s_county"], "ss_net_profit", "sum").rename(
            columns={"ss_net_profit": "total_sum"})
        out["rank_within_parent"] = out.groupby("lochierarchy")["total_sum"].rank(
            method="min", ascending=False).astype(int)
        out = out.sort_values(["lochierarchy", "s_state", "s_county"],
                              ascending=[False, True, True], na_position="last")
        return out[["total_sum", "s_state", "s_county", "lochierarchy",
                    "rank_within_parent"]].head(100).reset_index(drop=True)
    if q == 71:
        td = t["time_dim"]
        frames = []
        for fact, pfx in ((t["web_sales"], "ws"), (t["catalog_sales"], "cs"), (ss, "ss")):
            mm = fact.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)][["d_date_sk"]],
                            left_on=f"{pfx}_sold_date_sk", right_on="d_date_sk")
            frames.append(pd.DataFrame({
                "ext_price": mm[f"{pfx}_ext_sales_price"],
                "sold_item_sk": mm[f"{pfx}_item_sk"],
                "time_sk": mm[f"{pfx}_sold_time_sk"]}))
        u = pd.concat(frames, ignore_index=True)
        u = u.merge(it[it.i_manager_id == 1][["i_item_sk", "i_brand_id", "i_brand"]],
                    left_on="sold_item_sk", right_on="i_item_sk")
        u = u.merge(td[td.t_meal_time.isin(["breakfast", "dinner"])][
            ["t_time_sk", "t_hour", "t_minute"]], left_on="time_sk", right_on="t_time_sk")
        g = u.groupby(["i_brand", "i_brand_id", "t_hour", "t_minute"], as_index=False).agg(
            ext_price=("ext_price", "sum"))
        out = g[["i_brand_id", "i_brand", "t_hour", "t_minute", "ext_price"]]
        return out.sort_values(["ext_price", "i_brand_id", "t_hour", "t_minute"],
                               ascending=[False, True, True, True]).reset_index(drop=True)
    if q == 8:
        ca, cu, st = t["customer_address"], t["customer"], t["store"]
        zips = {"24000", "24050", "24100", "24150", "24200", "24250", "24300",
                "24350", "24400", "24450", "24500", "24550", "24010", "24060",
                "24110", "24160", "24210", "24260", "24310", "24360", "24410",
                "24460", "24510", "24560"}
        s1 = set(ca.ca_zip.str[:5][ca.ca_zip.str[:5].isin(zips)])
        pref = ca.merge(cu[cu.c_preferred_cust_flag == "Y"],
                        left_on="ca_address_sk", right_on="c_current_addr_sk")
        cnt = pref.groupby(pref.ca_zip.str[:5]).size()
        sel = sorted(s1 & set(cnt[cnt > 10].index))
        vz = pd.DataFrame({"ca_zip": sel})
        vz["p2"] = vz.ca_zip.str[:2]
        m = ss.merge(dd[(dd.d_qoy == 2) & (dd.d_year == 1998)][["d_date_sk"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(st[["s_store_sk", "s_store_name", "s_zip"]],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m["p2"] = m.s_zip.str[:2]
        m = m.merge(vz, on="p2")  # row multiplication per matching zip, like the SQL
        g = m.groupby("s_store_name", as_index=False)["ss_net_profit"].sum()
        return g.sort_values("s_store_name").head(100).reset_index(drop=True)
    if q in (10, 35, 69):
        cu, ca, cd = t["customer"], t["customer_address"], t["customer_demographics"]
        if q == 10:
            dfilt = (dd.d_year == 2002) & dd.d_moy.between(1, 4)
        elif q == 35:
            dfilt = (dd.d_year == 2002) & (dd.d_qoy < 4)
        else:
            dfilt = (dd.d_year == 2001) & dd.d_moy.between(4, 6)
        dsel = dd[dfilt][["d_date_sk"]]

        def bought(fact, dkey, ckey):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            return set(mm[ckey].dropna())

        sset = bought(ss, "ss_sold_date_sk", "ss_customer_sk")
        wset = bought(t["web_sales"], "ws_sold_date_sk", "ws_bill_customer_sk")
        cset = bought(t["catalog_sales"], "cs_sold_date_sk", "cs_bill_customer_sk")
        m = cu.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        if q == 10:
            m = m[m.ca_county.isin(["Williamson County", "Walker County",
                                    "Ziebach County", "Daviess County", "Barrow County"])]
        if q == 69:
            m = m[m.ca_state.isin(["TN", "TX", "SD"])]
            keep = (m.c_customer_sk.isin(sset) & ~m.c_customer_sk.isin(wset)
                    & ~m.c_customer_sk.isin(cset))
        else:
            keep = m.c_customer_sk.isin(sset) & (m.c_customer_sk.isin(wset)
                                                 | m.c_customer_sk.isin(cset))
        m = m[keep]
        if q == 35:
            keys = ["ca_state", "cd_gender", "cd_marital_status", "cd_dep_count",
                    "cd_dep_employed_count", "cd_dep_college_count"]
            g = m.groupby(keys, as_index=False).size().rename(columns={"size": "cnt"})
            out = pd.DataFrame({
                "ca_state": g.ca_state, "cd_gender": g.cd_gender,
                "cd_marital_status": g.cd_marital_status,
                "cd_dep_count": g.cd_dep_count, "cnt1": g.cnt,
                "avg1": g.cd_dep_count.astype(float), "max1": g.cd_dep_count,
                "sum1": g.cd_dep_count * g.cnt,
                "cd_dep_employed_count": g.cd_dep_employed_count, "cnt2": g.cnt,
                "avg2": g.cd_dep_employed_count.astype(float),
                "max2": g.cd_dep_employed_count,
                "sum2": g.cd_dep_employed_count * g.cnt,
                "cd_dep_college_count": g.cd_dep_college_count, "cnt3": g.cnt,
                "avg3": g.cd_dep_college_count.astype(float),
                "max3": g.cd_dep_college_count,
                "sum3": g.cd_dep_college_count * g.cnt})
            return out.sort_values(keys).head(100).reset_index(drop=True)
        if q == 10:
            keys = ["cd_gender", "cd_marital_status", "cd_education_status",
                    "cd_purchase_estimate", "cd_credit_rating", "cd_dep_count",
                    "cd_dep_employed_count", "cd_dep_college_count"]
            g = m.groupby(keys, as_index=False).size().rename(columns={"size": "cnt"})
            out = pd.DataFrame({
                "cd_gender": g.cd_gender, "cd_marital_status": g.cd_marital_status,
                "cd_education_status": g.cd_education_status, "cnt1": g.cnt,
                "cd_purchase_estimate": g.cd_purchase_estimate, "cnt2": g.cnt,
                "cd_credit_rating": g.cd_credit_rating, "cnt3": g.cnt,
                "cd_dep_count": g.cd_dep_count, "cnt4": g.cnt,
                "cd_dep_employed_count": g.cd_dep_employed_count, "cnt5": g.cnt,
                "cd_dep_college_count": g.cd_dep_college_count, "cnt6": g.cnt})
            return out.sort_values(keys).head(100).reset_index(drop=True)
        keys = ["cd_gender", "cd_marital_status", "cd_education_status",
                "cd_purchase_estimate", "cd_credit_rating"]
        g = m.groupby(keys, as_index=False).size().rename(columns={"size": "cnt"})
        out = pd.DataFrame({
            "cd_gender": g.cd_gender, "cd_marital_status": g.cd_marital_status,
            "cd_education_status": g.cd_education_status, "cnt1": g.cnt,
            "cd_purchase_estimate": g.cd_purchase_estimate, "cnt2": g.cnt,
            "cd_credit_rating": g.cd_credit_rating, "cnt3": g.cnt})
        return out.sort_values(keys).head(100).reset_index(drop=True)
    if q == 23:
        cu = t["customer"]
        years = [1999, 2000, 2001, 2002]
        m = ss.merge(dd[dd.d_year.isin(years)][["d_date_sk", "d_date"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[["i_item_sk", "i_item_desc"]], left_on="ss_item_sk",
                    right_on="i_item_sk")
        m["itemdesc"] = m.i_item_desc.str[:30]
        fcnt = m.groupby(["itemdesc", "i_item_sk", "d_date"]).size()
        freq_items = set(fcnt[fcnt > 4].reset_index().i_item_sk)
        m2 = ss.merge(dd[dd.d_year.isin(years)][["d_date_sk"]],
                      left_on="ss_sold_date_sk", right_on="d_date_sk")
        m2 = m2.merge(cu[["c_customer_sk"]], left_on="ss_customer_sk",
                      right_on="c_customer_sk")
        m2["v"] = m2.ss_quantity * m2.ss_sales_price
        cmax = m2.groupby("c_customer_sk")["v"].sum().max()
        allm = ss.merge(cu[["c_customer_sk"]], left_on="ss_customer_sk",
                        right_on="c_customer_sk")
        allm["v"] = allm.ss_quantity * allm.ss_sales_price
        ssales = allm.groupby("c_customer_sk")["v"].sum()
        best = set(ssales[ssales > 0.5 * cmax].index)
        dsel = dd[(dd.d_year == 2000) & (dd.d_moy == 2)][["d_date_sk"]]
        total, n = 0.0, 0
        for fact, pfx in ((t["catalog_sales"], "cs"), (t["web_sales"], "ws")):
            mm = fact.merge(dsel, left_on=f"{pfx}_sold_date_sk", right_on="d_date_sk")
            mm = mm[mm[f"{pfx}_item_sk"].isin(freq_items)
                    & mm[f"{pfx}_bill_customer_sk"].isin(best)]
            total += (mm[f"{pfx}_quantity"] * mm[f"{pfx}_list_price"]).sum()
            n += len(mm)
        return pd.DataFrame({"sum_sales": [total if n else None]})
    if q in (38, 87):
        cu = t["customer"]
        dsel = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][
            ["d_date_sk", "d_date"]]

        def chan(fact, dkey, ckey):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(cu[["c_customer_sk", "c_last_name", "c_first_name"]],
                          left_on=ckey, right_on="c_customer_sk")
            return set(map(tuple, mm[["c_last_name", "c_first_name", "d_date"]]
                           .drop_duplicates().values))

        a = chan(ss, "ss_sold_date_sk", "ss_customer_sk")
        b = chan(t["catalog_sales"], "cs_sold_date_sk", "cs_bill_customer_sk")
        c = chan(t["web_sales"], "ws_sold_date_sk", "ws_bill_customer_sk")
        n = len(a & b & c) if q == 38 else len(a - b - c)
        return pd.DataFrame({"cnt": [n]})
    if q == 76:
        frames = []
        for fact, pfx, nullcol, label in (
            (ss, "ss", "ss_addr_sk", "store"),
            (t["web_sales"], "ws", "ws_ship_customer_sk", "web"),
            (t["catalog_sales"], "cs", "cs_ship_addr_sk", "catalog"),
        ):
            selr = fact[fact[nullcol].isna()]
            mm = selr.merge(dd[["d_date_sk", "d_year", "d_qoy"]],
                            left_on=f"{pfx}_sold_date_sk", right_on="d_date_sk")
            mm = mm.merge(it[["i_item_sk", "i_category"]],
                          left_on=f"{pfx}_item_sk", right_on="i_item_sk")
            frames.append(pd.DataFrame({
                "channel": label, "col_name": nullcol, "d_year": mm.d_year,
                "d_qoy": mm.d_qoy, "i_category": mm.i_category,
                "ext": mm[f"{pfx}_ext_sales_price"]}))
        u = pd.concat(frames, ignore_index=True)
        g = u.groupby(["channel", "col_name", "d_year", "d_qoy", "i_category"],
                      as_index=False).agg(sales_cnt=("ext", "size"),
                                          sales_amt=("ext", "sum"))
        return g.sort_values(["channel", "col_name", "d_year", "d_qoy",
                              "i_category"]).head(100).reset_index(drop=True)
    if q == 97:
        cs = t["catalog_sales"]
        dsel = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][["d_date_sk"]]
        a = ss.merge(dsel, left_on="ss_sold_date_sk", right_on="d_date_sk")[
            ["ss_customer_sk", "ss_item_sk"]].drop_duplicates()
        b = cs.merge(dsel, left_on="cs_sold_date_sk", right_on="d_date_sk")[
            ["cs_bill_customer_sk", "cs_item_sk"]].drop_duplicates()
        j = a.merge(b, left_on=["ss_customer_sk", "ss_item_sk"],
                    right_on=["cs_bill_customer_sk", "cs_item_sk"],
                    how="outer", indicator=True)
        return pd.DataFrame({
            "store_only": [int((j._merge == "left_only").sum())],
            "catalog_only": [int((j._merge == "right_only").sum())],
            "store_and_catalog": [int((j._merge == "both").sum())]})
    if q in (16, 94, 95):
        ca = t["customer_address"]
        if q == 16:
            fact, pfx = t["catalog_sales"], "cs"
            rets, rkey = t["catalog_returns"], "cr_order_number"
            lo, hi = dt.date(2000, 2, 1), dt.date(2000, 4, 2)
            m = fact.merge(dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]],
                           left_on="cs_ship_date_sk", right_on="d_date_sk")
            m = m.merge(ca[ca.ca_state == "GA"][["ca_address_sk"]],
                        left_on="cs_ship_addr_sk", right_on="ca_address_sk")
            cc = t["call_center"]
            m = m.merge(cc[cc.cc_county.isin(["Williamson County", "Walker County",
                                              "Ziebach County"])][["cc_call_center_sk"]],
                        left_on="cs_call_center_sk", right_on="cc_call_center_sk")
        else:
            fact, pfx = t["web_sales"], "ws"
            rets, rkey = t["web_returns"], "wr_order_number"
            lo, hi = dt.date(1999, 2, 1), dt.date(1999, 4, 2)
            m = fact.merge(dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]],
                           left_on="ws_ship_date_sk", right_on="d_date_sk")
            m = m.merge(ca[ca.ca_state == "TX"][["ca_address_sk"]],
                        left_on="ws_ship_addr_sk", right_on="ca_address_sk")
            web = t["web_site"]
            m = m.merge(web[web.web_company_name == "pri"][["web_site_sk"]],
                        left_on="ws_web_site_sk", right_on="web_site_sk")
        onum, wh = f"{pfx}_order_number", f"{pfx}_warehouse_sk"
        wh_counts = fact.groupby(onum)[wh].nunique()
        multi = set(wh_counts[wh_counts > 1].index)
        returned = set(rets[rkey])
        if q == 95:
            m = m[m[onum].isin(multi) & m[onum].isin(returned & multi)]
        else:
            m = m[m[onum].isin(multi) & ~m[onum].isin(returned)]
        return pd.DataFrame({
            "order_count": [int(m[onum].nunique())],
            "total_shipping_cost": [m[f"{pfx}_ext_ship_cost"].sum() if len(m) else None],
            "total_net_profit": [m[f"{pfx}_net_profit"].sum() if len(m) else None]})
    if q == 28:
        buckets = [
            ((0, 5), (8, 18), (459, 1459), (57, 77)),
            ((6, 10), (90, 100), (2323, 3323), (31, 51)),
            ((11, 15), (142, 152), (12214, 13214), (79, 99)),
            ((16, 20), (135, 145), (6071, 7071), (38, 58)),
            ((21, 25), (122, 132), (836, 1836), (17, 37)),
            ((26, 30), (154, 164), (7326, 8326), (7, 27)),
        ]
        vals = {}
        for i, (qt, lp, cp, wc) in enumerate(buckets, 1):
            b = ss[ss.ss_quantity.between(*qt)
                   & (ss.ss_list_price.between(*lp)
                      | ss.ss_coupon_amt.between(*cp)
                      | ss.ss_wholesale_cost.between(*wc))]
            vals[f"b{i}_lp"] = [b.ss_list_price.mean() if len(b) else None]
            vals[f"b{i}_cnt"] = [int(b.ss_list_price.count())]
            vals[f"b{i}_cntd"] = [int(b.ss_list_price.nunique())]
        return pd.DataFrame(vals)
    if q == 2:
        frames = []
        for fact, pfx in ((t["web_sales"], "ws"), (t["catalog_sales"], "cs")):
            frames.append(pd.DataFrame({
                "sold_date_sk": fact[f"{pfx}_sold_date_sk"],
                "sales_price": fact[f"{pfx}_ext_sales_price"]}))
        u = pd.concat(frames, ignore_index=True)
        m = u.merge(dd[["d_date_sk", "d_week_seq", "d_day_name"]],
                    left_on="sold_date_sk", right_on="d_date_sk")
        days = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday", "Friday", "Saturday"]
        dcols = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
        for day, c in zip(days, dcols):
            m[c] = np.where(m.d_day_name == day, m.sales_price, np.nan)
        wss = m.groupby("d_week_seq", as_index=False)[dcols].sum(min_count=1)

        def leg(year):
            weeks = dd[dd.d_year == year][["d_week_seq"]]
            return wss.merge(weeks, on="d_week_seq")  # per-day dup, like the SQL

        y = leg(1999)
        z = leg(2000).copy()
        z["wk_minus"] = z.d_week_seq - 53
        j = y.merge(z, left_on="d_week_seq", right_on="wk_minus", suffixes=("_1", "_2"))
        out = pd.DataFrame({"d_week_seq1": j.d_week_seq_1,
                            **{f"r_{c}": np.round(j[f"{c}_1"] / j[f"{c}_2"], 2)
                               for c in dcols}})
        return out.sort_values("d_week_seq1").reset_index(drop=True)
    if q == 18:
        cs, cd, cu, ca = (t["catalog_sales"], t["customer_demographics"],
                          t["customer"], t["customer_address"])
        m = cs.merge(dd[dd.d_year == 1998][["d_date_sk"]],
                     left_on="cs_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[["i_item_sk", "i_item_id"]], left_on="cs_item_sk",
                    right_on="i_item_sk")
        cd1 = cd[(cd.cd_gender == "F") & (cd.cd_education_status == "Unknown")]
        m = m.merge(cd1[["cd_demo_sk", "cd_dep_count"]],
                    left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(cu[cu.c_birth_month.isin([1, 6, 8, 9, 12, 2])][
            ["c_customer_sk", "c_current_cdemo_sk", "c_current_addr_sk", "c_birth_year"]],
            left_on="cs_bill_customer_sk", right_on="c_customer_sk")
        m = m.merge(cd[["cd_demo_sk"]].rename(columns={"cd_demo_sk": "cd2_sk"}),
                    left_on="c_current_cdemo_sk", right_on="cd2_sk")
        m = m.merge(ca[ca.ca_state.isin(["MT", "CA", "NY"])][
            ["ca_address_sk", "ca_country", "ca_state", "ca_county"]],
            left_on="c_current_addr_sk", right_on="ca_address_sk")
        for src, nm in (("cs_quantity", "agg1"), ("cs_list_price", "agg2"),
                        ("cs_coupon_amt", "agg3"), ("cs_sales_price", "agg4"),
                        ("cs_net_profit", "agg5"), ("c_birth_year", "agg6"),
                        ("cd_dep_count", "agg7")):
            m[nm] = m[src].astype(float)
        cols = ["i_item_id", "ca_country", "ca_state", "ca_county"]
        vals = [f"agg{i}" for i in range(1, 8)]
        out = _rollup(m, cols, vals, "mean").drop(columns=["lochierarchy"])
        out = out[cols + vals]
        return out.sort_values(["ca_country", "ca_state", "ca_county", "i_item_id"],
                               na_position="last").head(100).reset_index(drop=True)
    if q == 27:
        cd, st = t["customer_demographics"], t["store"]
        m = ss.merge(dd[dd.d_year == 2002][["d_date_sk"]],
                     left_on="ss_sold_date_sk", right_on="d_date_sk")
        m = m.merge(it[["i_item_sk", "i_item_id"]], left_on="ss_item_sk",
                    right_on="i_item_sk")
        m = m.merge(st[st.s_state.isin(["TN", "TX", "SD", "IN", "GA", "OH"])][
            ["s_store_sk", "s_state"]], left_on="ss_store_sk", right_on="s_store_sk")
        cdf = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
                 & (cd.cd_education_status == "College")]
        m = m.merge(cdf[["cd_demo_sk"]], left_on="ss_cdemo_sk", right_on="cd_demo_sk")
        for src, nm in (("ss_quantity", "agg1"), ("ss_list_price", "agg2"),
                        ("ss_coupon_amt", "agg3"), ("ss_sales_price", "agg4")):
            m[nm] = m[src].astype(float)
        vals = [f"agg{i}" for i in range(1, 5)]
        out = _rollup(m, ["i_item_id", "s_state"], vals, "mean")
        out["g_state"] = (out.lochierarchy >= 1).astype(int)
        out = out[["i_item_id", "s_state", "g_state"] + vals]
        return out.sort_values(["i_item_id", "s_state"], na_position="last"
                               ).head(100).reset_index(drop=True)
    if q == 31:
        ca = t["customer_address"]

        def cte(fact, dkey, akey, val, name):
            mm = fact.merge(dd[["d_date_sk", "d_qoy", "d_year"]],
                            left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(ca[["ca_address_sk", "ca_county"]],
                          left_on=akey, right_on="ca_address_sk")
            return mm.groupby(["ca_county", "d_qoy", "d_year"], as_index=False).agg(
                **{name: (val, "sum")})

        sscte = cte(ss, "ss_sold_date_sk", "ss_addr_sk", "ss_ext_sales_price", "store_sales")
        wscte = cte(t["web_sales"], "ws_sold_date_sk", "ws_bill_addr_sk",
                    "ws_ext_sales_price", "web_sales")

        def pick(c, qoy, name):
            sel = c[(c.d_qoy == qoy) & (c.d_year == 2000)][["ca_county", name]]
            return sel.rename(columns={name: f"{name}{qoy}"})

        j = pick(sscte, 1, "store_sales").merge(pick(sscte, 2, "store_sales"), on="ca_county")
        j = j.merge(pick(sscte, 3, "store_sales"), on="ca_county")
        j = j.merge(pick(wscte, 1, "web_sales"), on="ca_county")
        j = j.merge(pick(wscte, 2, "web_sales"), on="ca_county")
        j = j.merge(pick(wscte, 3, "web_sales"), on="ca_county")
        w12 = np.where(j.web_sales1 > 0, j.web_sales2 / j.web_sales1, np.nan)
        s12 = np.where(j.store_sales1 > 0, j.store_sales2 / j.store_sales1, np.nan)
        w23 = np.where(j.web_sales2 > 0, j.web_sales3 / j.web_sales2, np.nan)
        s23 = np.where(j.store_sales2 > 0, j.store_sales3 / j.store_sales2, np.nan)
        j = j[(w12 > s12) & (w23 > s23)]
        out = pd.DataFrame({
            "ca_county": j.ca_county, "d_year": 2000,
            "web_q1_q2_increase": j.web_sales2 / j.web_sales1,
            "store_q1_q2_increase": j.store_sales2 / j.store_sales1,
            "web_q2_q3_increase": j.web_sales3 / j.web_sales2,
            "store_q2_q3_increase": j.store_sales3 / j.store_sales2})
        return out.sort_values("ca_county").reset_index(drop=True)
    if q == 54:
        cu, ca, st = t["customer"], t["customer_address"], t["store"]
        frames = []
        for fact, pfx in ((t["catalog_sales"], "cs"), (t["web_sales"], "ws")):
            frames.append(pd.DataFrame({
                "sold_date_sk": fact[f"{pfx}_sold_date_sk"],
                "customer_sk": fact[f"{pfx}_bill_customer_sk"],
                "item_sk": fact[f"{pfx}_item_sk"]}))
        u = pd.concat(frames, ignore_index=True)
        u = u.merge(dd[(dd.d_moy == 12) & (dd.d_year == 1998)][["d_date_sk"]],
                    left_on="sold_date_sk", right_on="d_date_sk")
        u = u.merge(it[(it.i_category == "Women") & (it.i_class == "class#1")][
            ["i_item_sk"]], left_on="item_sk", right_on="i_item_sk")
        u = u.merge(cu[["c_customer_sk", "c_current_addr_sk"]],
                    left_on="customer_sk", right_on="c_customer_sk")
        my_customers = u[["c_customer_sk", "c_current_addr_sk"]].drop_duplicates()
        base_seq = int(dd[(dd.d_year == 1998) & (dd.d_moy == 12)].d_month_seq.iloc[0])
        dsel = dd[(dd.d_month_seq >= base_seq + 1) & (dd.d_month_seq <= base_seq + 3)][["d_date_sk"]]
        mm = my_customers.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        mm = mm.merge(st, left_on=["ca_county", "ca_state"], right_on=["s_county", "s_state"])
        mm = mm.merge(ss, left_on="c_customer_sk", right_on="ss_customer_sk")
        mm = mm.merge(dsel, left_on="ss_sold_date_sk", right_on="d_date_sk")
        rev = mm.groupby("c_customer_sk")["ss_ext_sales_price"].sum()
        seg = (rev / 50).astype(int)
        g = seg.value_counts().sort_index()
        out = pd.DataFrame({"segment": g.index, "num_customers": g.values})
        out["segment_base"] = out.segment * 50
        return out.sort_values(["segment", "num_customers"]).head(100).reset_index(drop=True)
    if q in (56, 60):
        ca = t["customer_address"]
        if q == 56:
            items = set(it[it.i_color.isin(["papaya", "burnished", "smoke"])].i_item_id)
            yr, moy, gmt = 2000, 2, -5
        else:
            items = set(it[it.i_category == "Music"].i_item_id)
            yr, moy, gmt = 1998, 9, -6
        dsel = dd[(dd.d_year == yr) & (dd.d_moy == moy)][["d_date_sk"]]
        casel = ca[ca.ca_gmt_offset == gmt][["ca_address_sk"]]
        frames = []
        for fact, dkey, akey, ikey, val in (
            (ss, "ss_sold_date_sk", "ss_addr_sk", "ss_item_sk", "ss_ext_sales_price"),
            (t["catalog_sales"], "cs_sold_date_sk", "cs_bill_addr_sk", "cs_item_sk", "cs_ext_sales_price"),
            (t["web_sales"], "ws_sold_date_sk", "ws_bill_addr_sk", "ws_item_sk", "ws_ext_sales_price"),
        ):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(casel, left_on=akey, right_on="ca_address_sk")
            mm = mm.merge(it[["i_item_sk", "i_item_id"]], left_on=ikey, right_on="i_item_sk")
            mm = mm[mm.i_item_id.isin(items)]
            frames.append(mm.groupby("i_item_id", as_index=False).agg(total_sales=(val, "sum")))
        u = pd.concat(frames, ignore_index=True)
        g = u.groupby("i_item_id", as_index=False)["total_sales"].sum()
        order = ["total_sales", "i_item_id"] if q == 56 else ["i_item_id", "total_sales"]
        return g.sort_values(order).head(100).reset_index(drop=True)
    if q == 58:
        wk = int(dd[dd.d_date == dt.date(2000, 1, 3)].d_week_seq.iloc[0])
        dsel = dd[dd.d_week_seq == wk][["d_date_sk"]]

        def chan(fact, ikey, dkey, val, name):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(it[["i_item_sk", "i_item_id"]], left_on=ikey, right_on="i_item_sk")
            return mm.groupby("i_item_id", as_index=False).agg(**{name: (val, "sum")})

        a = chan(ss, "ss_item_sk", "ss_sold_date_sk", "ss_ext_sales_price", "ss_item_rev")
        b = chan(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk",
                 "cs_ext_sales_price", "cs_item_rev")
        c = chan(t["web_sales"], "ws_item_sk", "ws_sold_date_sk",
                 "ws_ext_sales_price", "ws_item_rev")
        j = a.merge(b, on="i_item_id").merge(c, on="i_item_id")
        sel = (j.ss_item_rev.between(0.9 * j.cs_item_rev, 1.1 * j.cs_item_rev)
               & j.ss_item_rev.between(0.9 * j.ws_item_rev, 1.1 * j.ws_item_rev)
               & j.cs_item_rev.between(0.9 * j.ss_item_rev, 1.1 * j.ss_item_rev)
               & j.cs_item_rev.between(0.9 * j.ws_item_rev, 1.1 * j.ws_item_rev)
               & j.ws_item_rev.between(0.9 * j.ss_item_rev, 1.1 * j.ss_item_rev)
               & j.ws_item_rev.between(0.9 * j.cs_item_rev, 1.1 * j.cs_item_rev))
        j = j[sel]
        avg3 = (j.ss_item_rev + j.cs_item_rev + j.ws_item_rev) / 3
        out = pd.DataFrame({
            "item_id": j.i_item_id, "ss_item_rev": j.ss_item_rev,
            "ss_dev": j.ss_item_rev / avg3 * 100, "cs_item_rev": j.cs_item_rev,
            "cs_dev": j.cs_item_rev / avg3 * 100, "ws_item_rev": j.ws_item_rev,
            "ws_dev": j.ws_item_rev / avg3 * 100, "average": avg3})
        return out.sort_values(["item_id", "ss_item_rev"]).head(100).reset_index(drop=True)
    if q == 66:
        wh, td, sm = t["warehouse"], t["time_dim"], t["ship_mode"]
        frames = []
        for fact, pfx in ((t["web_sales"], "ws"), (t["catalog_sales"], "cs")):
            mm = fact.merge(dd[dd.d_year == 2001][["d_date_sk", "d_moy"]],
                            left_on=f"{pfx}_sold_date_sk", right_on="d_date_sk")
            mm = mm.merge(td[(td.t_time >= 30838) & (td.t_time <= 30838 + 28800)][
                ["t_time_sk"]], left_on=f"{pfx}_sold_time_sk", right_on="t_time_sk")
            mm = mm.merge(sm[sm.sm_carrier.isin(["CARRIER1", "CARRIER3"])][
                ["sm_ship_mode_sk"]], left_on=f"{pfx}_ship_mode_sk",
                right_on="sm_ship_mode_sk")
            mm = mm.merge(wh, left_on=f"{pfx}_warehouse_sk", right_on="w_warehouse_sk")
            price, net = f"{pfx}_ext_sales_price", f"{pfx}_net_paid"
            qty = f"{pfx}_quantity"
            months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
                      "sep", "oct", "nov", "dec"]
            for i, mo in enumerate(months, 1):
                mm[f"{mo}_sales"] = np.where(mm.d_moy == i, mm[price] * mm[qty], 0.0)
                mm[f"{mo}_net"] = np.where(mm.d_moy == i, mm[net] * mm[qty], 0.0)
            keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
                    "w_state", "w_country"]
            cols = [f"{mo}_sales" for mo in months] + [f"{mo}_net" for mo in months]
            g = mm.groupby(keys, as_index=False)[cols].sum()
            g["ship_carriers"] = "CARRIER1,CARRIER3"
            g["year_"] = 2001
            frames.append(g)
        u = pd.concat(frames, ignore_index=True)
        keys = ["w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county",
                "w_state", "w_country", "ship_carriers", "year_"]
        months = ["jan", "feb", "mar", "apr", "may", "jun", "jul", "aug",
                  "sep", "oct", "nov", "dec"]
        cols = [f"{mo}_sales" for mo in months] + [f"{mo}_net" for mo in months]
        g = u.groupby(keys, as_index=False)[cols].sum()
        return g[keys + cols].sort_values("w_warehouse_name").head(100).reset_index(drop=True)
    if q == 74:
        cu = t["customer"]

        def yt(fact, ckey, dkey, val, stype):
            mm = fact.merge(dd[dd.d_year.isin([1999, 2000])][["d_date_sk", "d_year"]],
                            left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(cu[["c_customer_sk", "c_customer_id", "c_first_name",
                              "c_last_name"]], left_on=ckey, right_on="c_customer_sk")
            g = mm.groupby(["c_customer_id", "c_first_name", "c_last_name", "d_year"],
                           as_index=False).agg(year_total=(val, "sum"))
            g["sale_type"] = stype
            return g

        u = pd.concat([
            yt(ss, "ss_customer_sk", "ss_sold_date_sk", "ss_net_paid", "s"),
            yt(t["web_sales"], "ws_bill_customer_sk", "ws_sold_date_sk",
               "ws_net_paid", "w")], ignore_index=True)

        def leg(stype, year, name):
            sel = u[(u.sale_type == stype) & (u.d_year == year)]
            return sel[["c_customer_id", "c_first_name", "c_last_name", "year_total"]
                       ].rename(columns={"year_total": name})

        j = leg("s", 1999, "s1").merge(leg("s", 2000, "s2"),
                                       on=["c_customer_id", "c_first_name", "c_last_name"])
        j = j.merge(leg("w", 1999, "w1"), on=["c_customer_id", "c_first_name", "c_last_name"])
        j = j.merge(leg("w", 2000, "w2"), on=["c_customer_id", "c_first_name", "c_last_name"])
        j = j[(j.s1 > 0) & (j.w1 > 0)]
        j = j[np.where(j.w1 > 0, j.w2 / j.w1, np.nan)
              > np.where(j.s1 > 0, j.s2 / j.s1, np.nan)]
        out = j[["c_customer_id", "c_first_name", "c_last_name"]]
        return out.sort_values(list(out.columns)).head(100).reset_index(drop=True)
    if q == 83:
        dates = [dt.date(2000, 6, 30), dt.date(2000, 9, 27), dt.date(2000, 11, 17)]
        wks = set(dd[dd.d_date.isin(dates)].d_week_seq)
        dsel = dd[dd.d_week_seq.isin(wks)][["d_date_sk"]]

        def chan(fact, ikey, dkey, val, name):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(it[["i_item_sk", "i_item_id"]], left_on=ikey, right_on="i_item_sk")
            return mm.groupby("i_item_id", as_index=False).agg(**{name: (val, "sum")})

        a = chan(t["store_returns"], "sr_item_sk", "sr_returned_date_sk",
                 "sr_return_quantity", "sr_item_qty")
        b = chan(t["catalog_returns"], "cr_item_sk", "cr_returned_date_sk",
                 "cr_return_quantity", "cr_item_qty")
        c = chan(t["web_returns"], "wr_item_sk", "wr_returned_date_sk",
                 "wr_return_quantity", "wr_item_qty")
        j = a.merge(b, on="i_item_id").merge(c, on="i_item_id")
        tot = j.sr_item_qty + j.cr_item_qty + j.wr_item_qty
        out = pd.DataFrame({
            "item_id": j.i_item_id, "sr_item_qty": j.sr_item_qty,
            "sr_dev": j.sr_item_qty / tot / 3.0 * 100, "cr_item_qty": j.cr_item_qty,
            "cr_dev": j.cr_item_qty / tot / 3.0 * 100, "wr_item_qty": j.wr_item_qty,
            "wr_dev": j.wr_item_qty / tot / 3.0 * 100, "average": tot / 3.0})
        return out.sort_values(["item_id", "sr_item_qty"]).head(100).reset_index(drop=True)
    if q == 84:
        cu, ca, cd = t["customer"], t["customer_address"], t["customer_demographics"]
        hd, ib, sr = t["household_demographics"], t["income_band"], t["store_returns"]
        m = cu.merge(ca[ca.ca_city == "Fairview"][["ca_address_sk"]],
                     left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m.merge(hd[["hd_demo_sk", "hd_income_band_sk"]],
                    left_on="c_current_hdemo_sk", right_on="hd_demo_sk")
        ibf = ib[(ib.ib_lower_bound >= 38128) & (ib.ib_upper_bound <= 38128 + 50000)]
        m = m.merge(ibf[["ib_income_band_sk"]], left_on="hd_income_band_sk",
                    right_on="ib_income_band_sk")
        m = m.merge(cd[["cd_demo_sk"]], left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(sr[["sr_cdemo_sk"]], left_on="cd_demo_sk", right_on="sr_cdemo_sk")
        out = pd.DataFrame({
            "customer_id": m.c_customer_id,
            "customername": m.c_last_name + ", " + m.c_first_name})
        return out.sort_values("customer_id").head(100).reset_index(drop=True)
    if q == 85:
        wsx, wr, wp = t["web_sales"], t["web_returns"], t["web_page"]
        cd, ca, rs = t["customer_demographics"], t["customer_address"], t["reason"]
        m = wsx.merge(wr, left_on=["ws_item_sk", "ws_order_number"],
                      right_on=["wr_item_sk", "wr_order_number"])
        m = m.merge(wp[["wp_web_page_sk"]], left_on="ws_web_page_sk",
                    right_on="wp_web_page_sk")
        m = m.merge(dd[dd.d_year == 2000][["d_date_sk"]],
                    left_on="ws_sold_date_sk", right_on="d_date_sk")
        m = m.merge(cd.add_prefix("c1_"), left_on="wr_refunded_cdemo_sk",
                    right_on="c1_cd_demo_sk")
        m = m.merge(cd.add_prefix("c2_"), left_on="wr_returning_cdemo_sk",
                    right_on="c2_cd_demo_sk")
        m = m.merge(ca, left_on="wr_refunded_addr_sk", right_on="ca_address_sk")
        m = m.merge(rs, left_on="wr_reason_sk", right_on="r_reason_sk")
        ms_eq = ((m.c1_cd_marital_status == m.c2_cd_marital_status)
                 & (m.c1_cd_education_status == m.c2_cd_education_status))
        c1 = (ms_eq & (m.c1_cd_marital_status == "M")
              & (m.c1_cd_education_status == "Advanced Degree")
              & m.ws_sales_price.between(100.0, 150.0))
        c2 = (ms_eq & (m.c1_cd_marital_status == "S")
              & (m.c1_cd_education_status == "College")
              & m.ws_sales_price.between(50.0, 100.0))
        c3 = (ms_eq & (m.c1_cd_marital_status == "W")
              & (m.c1_cd_education_status == "2 yr Degree")
              & m.ws_sales_price.between(150.0, 200.0))
        a1 = ((m.ca_country == "United States") & m.ca_state.isin(["IN", "OH", "NJ"])
              & m.ws_net_profit.between(10, 2000))
        a2 = ((m.ca_country == "United States") & m.ca_state.isin(["CA", "TX", "MT"])
              & m.ws_net_profit.between(15, 3000))
        a3 = ((m.ca_country == "United States") & m.ca_state.isin(["GA", "TN", "NY"])
              & m.ws_net_profit.between(5, 2500))
        m = m[(c1 | c2 | c3) & (a1 | a2 | a3)]
        g = m.groupby("r_reason_desc", as_index=False).agg(
            avg_qty=("ws_quantity", "mean"), avg_refund=("wr_refund_cash", "mean"),
            avg_fee=("wr_fee", "mean"))
        g["reason20"] = g.r_reason_desc.str[:20]
        out = g[["reason20", "avg_qty", "avg_refund", "avg_fee"]]
        return out.sort_values(list(out.columns)).head(100).reset_index(drop=True)
    if q in (4, 11):
        cu = t["customer"]
        keys = ["c_customer_id", "c_first_name", "c_last_name",
                "c_preferred_cust_flag", "c_birth_country", "c_login",
                "c_email_address"]

        def yt(fact, ckey, dkey, val_fn, stype):
            mm = fact.merge(dd[["d_date_sk", "d_year"]], left_on=dkey,
                            right_on="d_date_sk")
            mm = mm.merge(cu[["c_customer_sk"] + keys], left_on=ckey,
                          right_on="c_customer_sk")
            mm["v"] = val_fn(mm)
            g = mm.groupby(keys + ["d_year"], as_index=False).agg(year_total=("v", "sum"))
            g["sale_type"] = stype
            return g

        if q == 11:
            legs = [
                yt(ss, "ss_customer_sk", "ss_sold_date_sk",
                   lambda m: m.ss_ext_list_price - m.ss_ext_discount_amt, "s"),
                yt(t["web_sales"], "ws_bill_customer_sk", "ws_sold_date_sk",
                   lambda m: m.ws_ext_list_price - m.ws_ext_discount_amt, "w")]
            types = ["s", "w"]
            sel_col = "c_email_address"
        else:
            legs = [
                yt(ss, "ss_customer_sk", "ss_sold_date_sk",
                   lambda m: ((m.ss_ext_list_price - m.ss_ext_wholesale_cost
                               - m.ss_ext_discount_amt) + m.ss_ext_sales_price) / 2, "s"),
                yt(t["catalog_sales"], "cs_bill_customer_sk", "cs_sold_date_sk",
                   lambda m: ((m.cs_ext_list_price - m.cs_wholesale_cost * m.cs_quantity
                               - m.cs_ext_discount_amt) + m.cs_ext_sales_price) / 2, "c"),
                yt(t["web_sales"], "ws_bill_customer_sk", "ws_sold_date_sk",
                   lambda m: ((m.ws_ext_list_price - m.ws_wholesale_cost * m.ws_quantity
                               - m.ws_ext_discount_amt) + m.ws_ext_sales_price) / 2, "w")]
            types = ["s", "c", "w"]
            sel_col = "c_preferred_cust_flag"
        u = pd.concat(legs, ignore_index=True)

        def leg(stype, year, name):
            sel = u[(u.sale_type == stype) & (u.d_year == year)]
            return sel[keys + ["year_total"]].rename(columns={"year_total": name})

        j = leg("s", 2001, "s1").merge(leg("s", 2002, "s2"), on=keys)
        if q == 4:
            j = j.merge(leg("c", 2001, "c1"), on=keys).merge(leg("c", 2002, "c2"), on=keys)
        j = j.merge(leg("w", 2001, "w1"), on=keys).merge(leg("w", 2002, "w2"), on=keys)
        if q == 11:
            j = j[(j.s1 > 0) & (j.w1 > 0)]
            wr_ = np.where(j.w1 > 0, j.w2 / j.w1, 0.0)
            sr_ = np.where(j.s1 > 0, j.s2 / j.s1, 0.0)
            j = j[wr_ > sr_]
        else:
            j = j[(j.s1 > 0) & (j.c1 > 0) & (j.w1 > 0)]
            cr_ = np.where(j.c1 > 0, j.c2 / j.c1, np.nan)
            sr_ = np.where(j.s1 > 0, j.s2 / j.s1, np.nan)
            wr_ = np.where(j.w1 > 0, j.w2 / j.w1, np.nan)
            j = j[(cr_ > sr_) & (cr_ > wr_)]
        out = j[["c_customer_id", "c_first_name", "c_last_name", sel_col]]
        return out.sort_values(list(out.columns)).head(100).reset_index(drop=True)
    if q == 44:
        base = ss[ss.ss_store_sk == 4]
        nulladdr = base[base.ss_addr_sk.isna()]
        thresh = 0.9 * nulladdr.ss_net_profit.mean()
        g = base.groupby("ss_item_sk", as_index=False).agg(
            rank_col=("ss_net_profit", "mean"))
        g = g[g.rank_col > thresh]
        g["rnk_asc"] = g.rank_col.rank(method="min").astype(int)
        g["rnk_desc"] = g.rank_col.rank(method="min", ascending=False).astype(int)
        asc = g[g.rnk_asc < 11][["ss_item_sk", "rnk_asc"]].rename(
            columns={"rnk_asc": "rnk"})
        desc = g[g.rnk_desc < 11][["ss_item_sk", "rnk_desc"]].rename(
            columns={"rnk_desc": "rnk"})
        j = asc.merge(desc, on="rnk", suffixes=("_a", "_d"))
        j = j.merge(it[["i_item_sk", "i_product_name"]].rename(
            columns={"i_product_name": "best_performing"}),
            left_on="ss_item_sk_a", right_on="i_item_sk")
        j = j.merge(it[["i_item_sk", "i_product_name"]].rename(
            columns={"i_product_name": "worst_performing"}),
            left_on="ss_item_sk_d", right_on="i_item_sk")
        out = j[["rnk", "best_performing", "worst_performing"]]
        return out.sort_values("rnk").head(100).reset_index(drop=True)
    if q == 49:
        frames = []
        for label, fact, rets, skey, rkey, qty, rqty, paid, ramt, prof in (
            ("web", t["web_sales"], t["web_returns"],
             ["ws_order_number", "ws_item_sk"], ["wr_order_number", "wr_item_sk"],
             "ws_quantity", "wr_return_quantity", "ws_net_paid", "wr_return_amt",
             "ws_net_profit"),
            ("catalog", t["catalog_sales"], t["catalog_returns"],
             ["cs_order_number", "cs_item_sk"], ["cr_order_number", "cr_item_sk"],
             "cs_quantity", "cr_return_quantity", "cs_net_paid", "cr_return_amt",
             "cs_net_profit"),
            ("store", ss, t["store_returns"],
             ["ss_ticket_number", "ss_item_sk"], ["sr_ticket_number", "sr_item_sk"],
             "ss_quantity", "sr_return_quantity", "ss_net_paid", "sr_return_amt",
             "ss_net_profit"),
        ):
            dsel = dd[(dd.d_year == 2001) & (dd.d_moy == 12)][["d_date_sk"]]
            mm = fact.merge(rets, left_on=skey, right_on=rkey, how="left")
            mm = mm.merge(dsel, left_on=skey[0].replace("order_number", "sold_date_sk")
                          .replace("ticket_number", "sold_date_sk"), right_on="d_date_sk")
            mm = mm[(mm[ramt] > 100) & (mm[prof] > 1) & (mm[paid] > 0) & (mm[qty] > 0)]
            g = mm.groupby(skey[1], as_index=False).agg(
                rq=(rqty, lambda s: s.fillna(0).sum()),
                sq=(qty, "sum"), ra=(ramt, lambda s: s.fillna(0).sum()),
                np_=(paid, "sum"))
            g["return_ratio"] = g.rq / g.sq
            g["currency_ratio"] = g.ra / g.np_
            g["return_rank"] = g.return_ratio.rank(method="min").astype(int)
            g["currency_rank"] = g.currency_ratio.rank(method="min").astype(int)
            g = g[(g.return_rank <= 10) | (g.currency_rank <= 10)]
            frames.append(pd.DataFrame({
                "channel": label, "item": g[skey[1]],
                "return_ratio": g.return_ratio, "return_rank": g.return_rank,
                "currency_rank": g.currency_rank}))
        u = pd.concat(frames, ignore_index=True).drop_duplicates()
        return u.sort_values(["channel", "return_rank", "currency_rank", "item"]
                             ).head(100).reset_index(drop=True)
    if q == 51:
        dsel = dd[(dd.d_month_seq >= 1200) & (dd.d_month_seq <= 1211)][
            ["d_date_sk", "d_date"]]

        def v1(fact, ikey, dkey, price):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            g = mm.groupby([ikey, "d_date"], as_index=False).agg(s=(price, "sum"))
            g = g.sort_values([ikey, "d_date"])
            g["cume_sales"] = g.groupby(ikey)["s"].cumsum()
            return g.rename(columns={ikey: "item_sk"})[["item_sk", "d_date", "cume_sales"]]

        web = v1(t["web_sales"], "ws_item_sk", "ws_sold_date_sk", "ws_sales_price")
        store = v1(ss, "ss_item_sk", "ss_sold_date_sk", "ss_sales_price")
        j = web.merge(store, on=["item_sk", "d_date"], how="outer",
                      suffixes=("_w", "_s"))
        j = j.sort_values(["item_sk", "d_date"]).reset_index(drop=True)
        # SQL MAX ignores NULLs over the frame: a side's running max carries
        # through rows where that side is absent (pandas cummax leaves NaN
        # at those positions — forward-fill within the partition)
        j["web_cumulative"] = j.groupby("item_sk")["cume_sales_w"].cummax()
        j["web_cumulative"] = j.groupby("item_sk")["web_cumulative"].ffill()
        j["store_cumulative"] = j.groupby("item_sk")["cume_sales_s"].cummax()
        j["store_cumulative"] = j.groupby("item_sk")["store_cumulative"].ffill()
        j = j[j.web_cumulative > j.store_cumulative]
        out = pd.DataFrame({
            "item_sk": j.item_sk, "d_date": j.d_date,
            "web_sales": j.cume_sales_w, "store_sales": j.cume_sales_s,
            "web_cumulative": j.web_cumulative, "store_cumulative": j.store_cumulative})
        return out.sort_values(["item_sk", "d_date"]).head(100).reset_index(drop=True)
    if q == 5:
        lo, hi = dt.date(2000, 8, 23), dt.date(2000, 9, 6)
        dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]]
        st, cc, web, wr = t["store"], t["call_center"], t["web_site"], t["web_returns"]

        def chan(sales_rows, dim, dim_key, dim_id):
            mm = sales_rows.merge(dsel, left_on="date_sk", right_on="d_date_sk")
            mm = mm.merge(dim[[dim_key, dim_id]], left_on="loc_sk", right_on=dim_key)
            return mm.groupby(dim_id, as_index=False).agg(
                sales=("sales_price", "sum"), profit=("profit", "sum"),
                returns_=("return_amt", "sum"), profit_loss=("net_loss", "sum"))

        def rows(df, loc, date, price=None, prof=None, ramt=None, loss=None):
            return pd.DataFrame({
                "loc_sk": df[loc], "date_sk": df[date],
                "sales_price": df[price] if price else 0.0,
                "profit": df[prof] if prof else 0.0,
                "return_amt": df[ramt] if ramt else 0.0,
                "net_loss": df[loss] if loss else 0.0})

        ssr = chan(pd.concat([
            rows(ss, "ss_store_sk", "ss_sold_date_sk", "ss_ext_sales_price",
                 "ss_net_profit"),
            rows(t["store_returns"], "sr_store_sk", "sr_returned_date_sk",
                 ramt="sr_return_amt", loss="sr_net_loss")], ignore_index=True),
            st, "s_store_sk", "s_store_id")
        csr = chan(pd.concat([
            rows(t["catalog_sales"], "cs_call_center_sk", "cs_sold_date_sk",
                 "cs_ext_sales_price", "cs_net_profit"),
            rows(t["catalog_returns"], "cr_call_center_sk", "cr_returned_date_sk",
                 ramt="cr_return_amt", loss="cr_net_loss")], ignore_index=True),
            cc, "cc_call_center_sk", "cc_call_center_id")
        wrj = wr.merge(t["web_sales"][["ws_item_sk", "ws_order_number", "ws_web_site_sk"]],
                       left_on=["wr_item_sk", "wr_order_number"],
                       right_on=["ws_item_sk", "ws_order_number"], how="left")
        wsr = chan(pd.concat([
            rows(t["web_sales"], "ws_web_site_sk", "ws_sold_date_sk",
                 "ws_ext_sales_price", "ws_net_profit"),
            rows(wrj, "ws_web_site_sk", "wr_returned_date_sk",
                 ramt="wr_return_amt", loss="wr_net_loss")], ignore_index=True),
            web, "web_site_sk", "web_site_id")
        frames = []
        for label, d_, idc in (("store channel", ssr, "s_store_id"),
                               ("catalog channel", csr, "cc_call_center_id"),
                               ("web channel", wsr, "web_site_id")):
            frames.append(pd.DataFrame({
                "channel": label, "id": d_[idc], "sales": d_.sales,
                "returns_": d_.returns_, "profit": d_.profit - d_.profit_loss}))
        u = pd.concat(frames, ignore_index=True)
        out = _rollup(u, ["channel", "id"], ["sales", "returns_", "profit"], "sum")
        out = out.drop(columns=["lochierarchy"])
        return out[["channel", "id", "sales", "returns_", "profit"]].sort_values(
            ["channel", "id"], na_position="last").head(100).reset_index(drop=True)
    if q == 9:
        vals = {}
        for i, ((qlo, qhi), thresh) in enumerate(zip(
                [(1, 20), (21, 40), (41, 60), (61, 80), (81, 100)],
                [3500, 3000, 10000, 2500, 15000]), 1):
            b = ss[ss.ss_quantity.between(qlo, qhi)]
            vals[f"bucket{i}"] = [b.ss_ext_discount_amt.mean() if len(b) > thresh
                                  else b.ss_net_paid.mean()]
        return pd.DataFrame(vals)
    if q == 41:
        i1 = it[it.i_manufact_id.between(70, 110)]
        combos = (
            ("Women", ["papaya", "frosted"], ["Ounce", "Ton"], ["medium", "extra large"]),
            ("Women", ["chiffon", "lace"], ["Pound", "Dram"], ["economy", "small"]),
            ("Men", ["orchid", "peach"], ["Bundle", "Gross"], ["N/A", "large"]),
            ("Men", ["smoke", "dim"], ["Each", "Oz"], ["medium", "petite"]),
        )
        sel = np.zeros(len(it), dtype=bool)
        for cat, colors, units, sizes in combos:
            sel |= ((it.i_category == cat) & it.i_color.isin(colors)
                    & it.i_units.isin(units) & it.i_size.isin(sizes)).values
        good_manufacts = set(it[sel].i_manufact)
        out = i1[i1.i_manufact.isin(good_manufacts)][["i_product_name"]].drop_duplicates()
        return out.sort_values("i_product_name").head(100).reset_index(drop=True)
    if q == 75:
        frames = []
        for fact, rets, ikey, dkey, skey, rkey, qty, rqty, price, ramt in (
            (t["catalog_sales"], t["catalog_returns"], "cs_item_sk", "cs_sold_date_sk",
             ["cs_order_number", "cs_item_sk"], ["cr_order_number", "cr_item_sk"],
             "cs_quantity", "cr_return_quantity", "cs_ext_sales_price", "cr_return_amt"),
            (ss, t["store_returns"], "ss_item_sk", "ss_sold_date_sk",
             ["ss_ticket_number", "ss_item_sk"], ["sr_ticket_number", "sr_item_sk"],
             "ss_quantity", "sr_return_quantity", "ss_ext_sales_price", "sr_return_amt"),
            (t["web_sales"], t["web_returns"], "ws_item_sk", "ws_sold_date_sk",
             ["ws_order_number", "ws_item_sk"], ["wr_order_number", "wr_item_sk"],
             "ws_quantity", "wr_return_quantity", "ws_ext_sales_price", "wr_return_amt"),
        ):
            mm = fact.merge(it[it.i_category == "Books"][
                ["i_item_sk", "i_brand_id", "i_class_id", "i_category_id", "i_manufact_id"]],
                left_on=ikey, right_on="i_item_sk")
            mm = mm.merge(dd[["d_date_sk", "d_year"]], left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(rets, left_on=skey, right_on=rkey, how="left")
            frames.append(pd.DataFrame({
                "d_year": mm.d_year, "i_brand_id": mm.i_brand_id,
                "i_class_id": mm.i_class_id, "i_category_id": mm.i_category_id,
                "i_manufact_id": mm.i_manufact_id,
                "sales_cnt": mm[qty] - mm[rqty].fillna(0),
                "sales_amt": mm[price] - mm[ramt].fillna(0.0)}))
        u = pd.concat(frames, ignore_index=True).drop_duplicates()  # UNION distinct
        g = u.groupby(["d_year", "i_brand_id", "i_class_id", "i_category_id",
                       "i_manufact_id"], as_index=False).agg(
            sales_cnt=("sales_cnt", "sum"), sales_amt=("sales_amt", "sum"))
        keys = ["i_brand_id", "i_class_id", "i_category_id", "i_manufact_id"]
        cur = g[g.d_year == 2002].merge(
            g[g.d_year == 2001], on=keys, suffixes=("_c", "_p"))
        cur = cur[cur.sales_cnt_c / cur.sales_cnt_p < 0.9]
        out = pd.DataFrame({
            "prev_year": 2001, "year_": 2002, "i_brand_id": cur.i_brand_id,
            "i_class_id": cur.i_class_id, "i_category_id": cur.i_category_id,
            "i_manufact_id": cur.i_manufact_id, "prev_yr_cnt": cur.sales_cnt_p,
            "curr_yr_cnt": cur.sales_cnt_c,
            "sales_cnt_diff": cur.sales_cnt_c - cur.sales_cnt_p,
            "sales_amt_diff": cur.sales_amt_c - cur.sales_amt_p})
        return out.sort_values(["sales_cnt_diff", "sales_amt_diff"]
                               ).head(100).reset_index(drop=True)
    if q == 77:
        lo, hi = dt.date(2000, 8, 23), dt.date(2000, 9, 22)
        dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]]

        def agg(fact, dkey, gkey, cols):
            mm = fact.merge(dsel, left_on=dkey, right_on="d_date_sk")
            if gkey is None:
                return pd.DataFrame({k: [mm[v].sum()] for k, v in cols.items()})
            return mm.groupby(gkey, as_index=False).agg(
                **{k: (v, "sum") for k, v in cols.items()})

        ssx = agg(ss, "ss_sold_date_sk", "ss_store_sk",
                  {"sales": "ss_ext_sales_price", "profit": "ss_net_profit"})
        srx = agg(t["store_returns"], "sr_returned_date_sk", "sr_store_sk",
                  {"returns_": "sr_return_amt", "profit_loss": "sr_net_loss"})
        csx = agg(t["catalog_sales"], "cs_sold_date_sk", "cs_call_center_sk",
                  {"sales": "cs_ext_sales_price", "profit": "cs_net_profit"})
        crx = agg(t["catalog_returns"], "cr_returned_date_sk", None,
                  {"returns_": "cr_return_amt", "profit_loss": "cr_net_loss"})
        wsx = agg(t["web_sales"], "ws_sold_date_sk", "ws_web_page_sk",
                  {"sales": "ws_ext_sales_price", "profit": "ws_net_profit"})
        wrx = agg(t["web_returns"], "wr_returned_date_sk", "wr_web_page_sk",
                  {"returns_": "wr_return_amt", "profit_loss": "wr_net_loss"})
        s = ssx.merge(srx, left_on="ss_store_sk", right_on="sr_store_sk", how="left")
        sdf = pd.DataFrame({"channel": "store channel", "id": s.ss_store_sk,
                            "sales": s.sales, "returns_": s.returns_.fillna(0),
                            "profit": s.profit - s.profit_loss.fillna(0)})
        c = csx.assign(returns_=crx.returns_[0], profit_loss=crx.profit_loss[0])
        cdf = pd.DataFrame({"channel": "catalog channel", "id": c.cs_call_center_sk,
                            "sales": c.sales, "returns_": c.returns_,
                            "profit": c.profit - c.profit_loss})
        w = wsx.merge(wrx, left_on="ws_web_page_sk", right_on="wr_web_page_sk", how="left")
        wdf = pd.DataFrame({"channel": "web channel", "id": w.ws_web_page_sk,
                            "sales": w.sales, "returns_": w.returns_.fillna(0),
                            "profit": w.profit - w.profit_loss.fillna(0)})
        u = pd.concat([sdf, cdf, wdf], ignore_index=True)
        out = _rollup(u, ["channel", "id"], ["sales", "returns_", "profit"], "sum")
        out = out.drop(columns=["lochierarchy"])
        return out[["channel", "id", "sales", "returns_", "profit"]].sort_values(
            ["channel", "id"], na_position="last").head(100).reset_index(drop=True)
    if q == 78:
        def yr(fact, rets, skey, rkey, dkey, ikey, ckey, qty, wc, sp, pfx):
            mm = fact.merge(rets[rkey].to_frame().assign(__hit=1),
                            left_on=skey, right_on=rkey, how="left")
            mm = mm[mm.__hit.isna()]
            mm = mm.merge(dd[["d_date_sk", "d_year"]], left_on=dkey, right_on="d_date_sk")
            g = mm.groupby(["d_year", ikey, ckey], as_index=False).agg(
                **{f"{pfx}_qty": (qty, "sum"), f"{pfx}_wc": (wc, "sum"),
                   f"{pfx}_sp": (sp, "sum")})
            return g.rename(columns={"d_year": f"{pfx}_sold_year", ikey: f"{pfx}_item_sk",
                                     ckey: f"{pfx}_customer_sk"})

        # join on the PAIR keys, not a single column (a sale is returned if a
        # return row matches both its order/ticket and item)
        def yr2(fact, rets, skeys, rkeys, dkey, ikey, ckey, qty, wc, sp, pfx):
            rsub = rets[rkeys].drop_duplicates().assign(__hit=1)
            mm = fact.merge(rsub, left_on=skeys, right_on=rkeys, how="left")
            mm = mm[mm.__hit.isna()]
            mm = mm.merge(dd[["d_date_sk", "d_year"]], left_on=dkey, right_on="d_date_sk")
            g = mm.groupby(["d_year", ikey, ckey], as_index=False).agg(
                **{f"{pfx}_qty": (qty, "sum"), f"{pfx}_wc": (wc, "sum"),
                   f"{pfx}_sp": (sp, "sum")})
            return g.rename(columns={"d_year": f"{pfx}_sold_year", ikey: f"{pfx}_item_sk",
                                     ckey: f"{pfx}_customer_sk"})

        wsy = yr2(t["web_sales"], t["web_returns"], ["ws_order_number", "ws_item_sk"],
                  ["wr_order_number", "wr_item_sk"], "ws_sold_date_sk", "ws_item_sk",
                  "ws_bill_customer_sk", "ws_quantity", "ws_wholesale_cost",
                  "ws_sales_price", "ws")
        csy = yr2(t["catalog_sales"], t["catalog_returns"],
                  ["cs_order_number", "cs_item_sk"], ["cr_order_number", "cr_item_sk"],
                  "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
                  "cs_quantity", "cs_wholesale_cost", "cs_sales_price", "cs")
        ssy = yr2(ss, t["store_returns"], ["ss_ticket_number", "ss_item_sk"],
                  ["sr_ticket_number", "sr_item_sk"], "ss_sold_date_sk", "ss_item_sk",
                  "ss_customer_sk", "ss_quantity", "ss_wholesale_cost",
                  "ss_sales_price", "ss")
        j = ssy.merge(wsy, left_on=["ss_sold_year", "ss_item_sk", "ss_customer_sk"],
                      right_on=["ws_sold_year", "ws_item_sk", "ws_customer_sk"],
                      how="left")
        j = j.merge(csy, left_on=["ss_sold_year", "ss_item_sk", "ss_customer_sk"],
                    right_on=["cs_sold_year", "cs_item_sk", "cs_customer_sk"],
                    how="left")
        j = j[(j.ws_qty.fillna(0) > 0) | (j.cs_qty.fillna(0) > 0)]
        j = j[j.ss_sold_year == 2000]
        out = pd.DataFrame({
            "ss_sold_year": j.ss_sold_year, "ss_item_sk": j.ss_item_sk,
            "ss_customer_sk": j.ss_customer_sk,
            "ratio": np.round(j.ss_qty / (j.ws_qty.fillna(0) + j.cs_qty.fillna(0)), 2),
            "store_qty": j.ss_qty, "store_wholesale_cost": j.ss_wc,
            "store_sales_price": j.ss_sp,
            "other_chan_qty": j.ws_qty.fillna(0) + j.cs_qty.fillna(0),
            "other_chan_wholesale_cost": j.ws_wc.fillna(0) + j.cs_wc.fillna(0),
            "other_chan_sales_price": j.ws_sp.fillna(0) + j.cs_sp.fillna(0)})
        out = out.sort_values(
            ["ss_sold_year", "ss_item_sk", "ss_customer_sk", "store_qty",
             "store_wholesale_cost", "store_sales_price", "other_chan_qty",
             "other_chan_wholesale_cost", "other_chan_sales_price", "ratio"],
            ascending=[True, True, True, False, False, False, True, True, True, True])
        return out.head(100).reset_index(drop=True)
    if q == 80:
        lo, hi = dt.date(2000, 8, 23), dt.date(2000, 9, 22)
        dsel = dd[(dd.d_date >= lo) & (dd.d_date <= hi)][["d_date_sk"]]
        pr = t["promotion"]

        def chan(fact, rets, skeys, rkeys, dkey, ikey, pkey, lkey, dim, dkey2,
                 idc, price, prof, ramt, loss, label):
            mm = fact.merge(rets[rkeys + [ramt, loss]], left_on=skeys,
                            right_on=rkeys, how="left")
            mm = mm.merge(dsel, left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(it[it.i_current_price > 50][["i_item_sk"]],
                          left_on=ikey, right_on="i_item_sk")
            mm = mm.merge(pr[pr.p_channel_tv == "N"][["p_promo_sk"]],
                          left_on=pkey, right_on="p_promo_sk")
            mm = mm.merge(dim[[dkey2, idc]], left_on=lkey, right_on=dkey2)
            g = mm.groupby(idc, as_index=False).apply(
                lambda x: pd.Series({
                    "sales": x[price].sum(),
                    "returns_": x[ramt].fillna(0).sum(),
                    "profit": (x[prof] - x[loss].fillna(0)).sum()}),
                include_groups=False)
            return pd.DataFrame({"channel": label, "id": g[idc], "sales": g.sales,
                                 "returns_": g.returns_, "profit": g.profit})

        sdf = chan(ss, t["store_returns"], ["ss_item_sk", "ss_ticket_number"],
                   ["sr_item_sk", "sr_ticket_number"], "ss_sold_date_sk",
                   "ss_item_sk", "ss_promo_sk", "ss_store_sk", t["store"],
                   "s_store_sk", "s_store_id", "ss_ext_sales_price",
                   "ss_net_profit", "sr_return_amt", "sr_net_loss", "store channel")
        cdf = chan(t["catalog_sales"], t["catalog_returns"],
                   ["cs_item_sk", "cs_order_number"], ["cr_item_sk", "cr_order_number"],
                   "cs_sold_date_sk", "cs_item_sk", "cs_promo_sk",
                   "cs_call_center_sk", t["call_center"], "cc_call_center_sk",
                   "cc_call_center_id", "cs_ext_sales_price", "cs_net_profit",
                   "cr_return_amt", "cr_net_loss", "catalog channel")
        wdf = chan(t["web_sales"], t["web_returns"], ["ws_item_sk", "ws_order_number"],
                   ["wr_item_sk", "wr_order_number"], "ws_sold_date_sk",
                   "ws_item_sk", "ws_promo_sk", "ws_web_site_sk", t["web_site"],
                   "web_site_sk", "web_site_id", "ws_ext_sales_price",
                   "ws_net_profit", "wr_return_amt", "wr_net_loss", "web channel")
        u = pd.concat([sdf, cdf, wdf], ignore_index=True)
        out = _rollup(u, ["channel", "id"], ["sales", "returns_", "profit"], "sum")
        out = out.drop(columns=["lochierarchy"])
        return out[["channel", "id", "sales", "returns_", "profit"]].sort_values(
            ["channel", "id"], na_position="last").head(100).reset_index(drop=True)
    if q == 14:
        def brand_sets(fact, ikey, dkey):
            mm = fact.merge(dd[(dd.d_year >= 1999) & (dd.d_year <= 2001)][["d_date_sk"]],
                            left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(it[["i_item_sk", "i_brand_id", "i_class_id", "i_category_id"]],
                          left_on=ikey, right_on="i_item_sk")
            return set(map(tuple, mm[["i_brand_id", "i_class_id", "i_category_id"]]
                           .drop_duplicates().values))

        common = (brand_sets(ss, "ss_item_sk", "ss_sold_date_sk")
                  & brand_sets(t["catalog_sales"], "cs_item_sk", "cs_sold_date_sk")
                  & brand_sets(t["web_sales"], "ws_item_sk", "ws_sold_date_sk"))
        trip = it[["i_item_sk", "i_brand_id", "i_class_id", "i_category_id"]].copy()
        cross_items = set(trip[[tuple(r) in common for r in
                                trip[["i_brand_id", "i_class_id", "i_category_id"]].values]]
                          .i_item_sk)
        ql = []
        for fact, qty, lp, dkey in ((ss, "ss_quantity", "ss_list_price", "ss_sold_date_sk"),
                                    (t["catalog_sales"], "cs_quantity", "cs_list_price", "cs_sold_date_sk"),
                                    (t["web_sales"], "ws_quantity", "ws_list_price", "ws_sold_date_sk")):
            mm = fact.merge(dd[(dd.d_year >= 1999) & (dd.d_year <= 2001)][["d_date_sk"]],
                            left_on=dkey, right_on="d_date_sk")
            ql.append(mm[qty] * mm[lp])
        average_sales = pd.concat(ql, ignore_index=True).mean()
        frames = []
        for label, fact, ikey, qty, lp, dkey in (
            ("store", ss, "ss_item_sk", "ss_quantity", "ss_list_price", "ss_sold_date_sk"),
            ("catalog", t["catalog_sales"], "cs_item_sk", "cs_quantity", "cs_list_price", "cs_sold_date_sk"),
            ("web", t["web_sales"], "ws_item_sk", "ws_quantity", "ws_list_price", "ws_sold_date_sk"),
        ):
            mm = fact[fact[ikey].isin(cross_items)]
            mm = mm.merge(dd[(dd.d_year == 2001) & (dd.d_moy == 11)][["d_date_sk"]],
                          left_on=dkey, right_on="d_date_sk")
            mm = mm.merge(it[["i_item_sk", "i_brand_id", "i_class_id", "i_category_id"]],
                          left_on=ikey, right_on="i_item_sk")
            mm["v"] = mm[qty] * mm[lp]
            g = mm.groupby(["i_brand_id", "i_class_id", "i_category_id"],
                           as_index=False).agg(sales=("v", "sum"), number_sales=("v", "size"))
            g = g[g.sales > average_sales]
            g.insert(0, "channel", label)
            frames.append(g)
        u = pd.concat(frames, ignore_index=True)
        out = _rollup(u, ["channel", "i_brand_id", "i_class_id", "i_category_id"],
                      ["sales", "number_sales"], "sum").drop(columns=["lochierarchy"])
        out = out[["channel", "i_brand_id", "i_class_id", "i_category_id",
                   "sales", "number_sales"]]
        return out.sort_values(["channel", "i_brand_id", "i_class_id", "i_category_id"],
                               na_position="last").head(100).reset_index(drop=True)
    if q == 24:
        sr, st, cu, ca = t["store_returns"], t["store"], t["customer"], t["customer_address"]
        m = ss.merge(sr[["sr_ticket_number", "sr_item_sk"]],
                     left_on=["ss_ticket_number", "ss_item_sk"],
                     right_on=["sr_ticket_number", "sr_item_sk"])
        m = m.merge(st[st.s_market_id == 8], left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
        m = m.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
        m = m[(m.c_birth_country != m.ca_country.str.upper()) & (m.s_zip == m.ca_zip)]
        keys = ["c_last_name", "c_first_name", "s_store_name", "ca_state", "s_state",
                "i_color", "i_current_price", "i_manager_id", "i_units", "i_size"]
        ssales = m.groupby(keys, as_index=False).agg(netpaid=("ss_net_paid", "sum"))
        thresh = 0.05 * ssales.netpaid.mean()
        peach = ssales[ssales.i_color == "peach"]
        g = peach.groupby(["c_last_name", "c_first_name", "s_store_name"],
                          as_index=False).agg(paid=("netpaid", "sum"))
        g = g[g.paid > thresh]
        return g.sort_values(["c_last_name", "c_first_name", "s_store_name"]
                             ).reset_index(drop=True)
    if q == 72:
        cs, inv, wh = t["catalog_sales"], t["inventory"], t["warehouse"]
        cd, hd, pr, cr = (t["customer_demographics"], t["household_demographics"],
                          t["promotion"], t["catalog_returns"])
        m = cs.merge(dd[dd.d_year == 1999][["d_date_sk", "d_week_seq", "d_date"]]
                     .rename(columns={"d_date_sk": "d1_sk", "d_week_seq": "wk1",
                                      "d_date": "date1"}),
                     left_on="cs_sold_date_sk", right_on="d1_sk")
        m = m.merge(cd[cd.cd_marital_status == "D"][["cd_demo_sk"]],
                    left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
        m = m.merge(hd[hd.hd_buy_potential == ">10000"][["hd_demo_sk"]],
                    left_on="cs_bill_hdemo_sk", right_on="hd_demo_sk")
        m = m.merge(dd[["d_date_sk", "d_date"]].rename(
            columns={"d_date_sk": "d3_sk", "d_date": "date3"}),
            left_on="cs_ship_date_sk", right_on="d3_sk")
        m = m[[d3 > d1 + dt.timedelta(days=5)
               for d1, d3 in zip(m.date1, m.date3)]]
        m = m.merge(inv, left_on="cs_item_sk", right_on="inv_item_sk")
        m = m.merge(dd[["d_date_sk", "d_week_seq"]].rename(
            columns={"d_date_sk": "d2_sk", "d_week_seq": "wk2"}),
            left_on="inv_date_sk", right_on="d2_sk")
        m = m[(m.wk1 == m.wk2) & (m.inv_quantity_on_hand < m.cs_quantity)]
        m = m.merge(wh[["w_warehouse_sk", "w_warehouse_name"]],
                    left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
        m = m.merge(it[["i_item_sk", "i_item_desc"]], left_on="cs_item_sk",
                    right_on="i_item_sk")
        m = m.merge(pr[["p_promo_sk"]], left_on="cs_promo_sk", right_on="p_promo_sk",
                    how="left")
        m = m.merge(cr[["cr_item_sk", "cr_order_number"]],
                    left_on=["cs_item_sk", "cs_order_number"],
                    right_on=["cr_item_sk", "cr_order_number"], how="left")
        g = m.groupby(["i_item_desc", "w_warehouse_name", "wk1"], as_index=False).agg(
            no_promo=("p_promo_sk", lambda s: int(s.isna().sum())),
            promo=("p_promo_sk", lambda s: int(s.notna().sum())),
            total_cnt=("p_promo_sk", "size"))
        out = g.rename(columns={"wk1": "d_week_seq"})
        return out.sort_values(["total_cnt", "i_item_desc", "w_warehouse_name",
                                "d_week_seq"], ascending=[False, True, True, True]
                               ).head(100).reset_index(drop=True)
    if q == 64:
        cs, cr, sr = t["catalog_sales"], t["catalog_returns"], t["store_returns"]
        st, cu, ca = t["store"], t["customer"], t["customer_address"]
        cd, hd, pr, ib = (t["customer_demographics"], t["household_demographics"],
                          t["promotion"], t["income_band"])
        ui = cs.merge(cr, left_on=["cs_item_sk", "cs_order_number"],
                      right_on=["cr_item_sk", "cr_order_number"])
        ui["refund"] = ui.cr_return_amt + ui.cr_net_loss
        g = ui.groupby("cs_item_sk", as_index=False).agg(
            sale=("cs_ext_list_price", "sum"), refund=("refund", "sum"))
        cs_ui_items = set(g[g.sale > 2 * g.refund].cs_item_sk)

        itf = it[(it.i_color.isin(["maroon", "burnished", "dim", "frosted",
                                   "papaya", "peach"]))
                 & (it.i_current_price >= 65) & (it.i_current_price <= 74)]
        m = ss.merge(sr[["sr_item_sk", "sr_ticket_number"]],
                     left_on=["ss_item_sk", "ss_ticket_number"],
                     right_on=["sr_item_sk", "sr_ticket_number"])
        m = m[m.ss_item_sk.isin(cs_ui_items)]
        m = m.merge(itf[["i_item_sk", "i_product_name"]], left_on="ss_item_sk",
                    right_on="i_item_sk")
        m = m.merge(st[["s_store_sk", "s_store_name", "s_zip"]],
                    left_on="ss_store_sk", right_on="s_store_sk")
        m = m.merge(dd[["d_date_sk", "d_year"]].rename(
            columns={"d_date_sk": "d1", "d_year": "syear"}),
            left_on="ss_sold_date_sk", right_on="d1")
        m = m.merge(cu[["c_customer_sk", "c_current_cdemo_sk", "c_current_hdemo_sk",
                        "c_current_addr_sk", "c_first_sales_date_sk",
                        "c_first_shipto_date_sk"]],
                    left_on="ss_customer_sk", right_on="c_customer_sk")
        m = m.merge(cd[["cd_demo_sk", "cd_marital_status"]].add_prefix("x1_"),
                    left_on="ss_cdemo_sk", right_on="x1_cd_demo_sk")
        m = m.merge(cd[["cd_demo_sk", "cd_marital_status"]].add_prefix("x2_"),
                    left_on="c_current_cdemo_sk", right_on="x2_cd_demo_sk")
        m = m[m.x1_cd_marital_status != m.x2_cd_marital_status]
        m = m.merge(hd[["hd_demo_sk", "hd_income_band_sk"]].add_prefix("h1_"),
                    left_on="ss_hdemo_sk", right_on="h1_hd_demo_sk")
        m = m.merge(hd[["hd_demo_sk", "hd_income_band_sk"]].add_prefix("h2_"),
                    left_on="c_current_hdemo_sk", right_on="h2_hd_demo_sk")
        m = m.merge(ib[["ib_income_band_sk"]].add_prefix("b1_"),
                    left_on="h1_hd_income_band_sk", right_on="b1_ib_income_band_sk")
        m = m.merge(ib[["ib_income_band_sk"]].add_prefix("b2_"),
                    left_on="h2_hd_income_band_sk", right_on="b2_ib_income_band_sk")
        m = m.merge(ca.add_prefix("a1_"), left_on="ss_addr_sk",
                    right_on="a1_ca_address_sk")
        m = m.merge(ca.add_prefix("a2_"), left_on="c_current_addr_sk",
                    right_on="a2_ca_address_sk")
        m = m.merge(pr[["p_promo_sk"]], left_on="ss_promo_sk", right_on="p_promo_sk")
        m = m.merge(dd[["d_date_sk", "d_year"]].rename(
            columns={"d_date_sk": "d2", "d_year": "fsyear"}),
            left_on="c_first_sales_date_sk", right_on="d2")
        m = m.merge(dd[["d_date_sk", "d_year"]].rename(
            columns={"d_date_sk": "d3", "d_year": "s2year"}),
            left_on="c_first_shipto_date_sk", right_on="d3")
        keys = ["i_product_name", "ss_item_sk", "s_store_name", "s_zip",
                "a1_ca_street_number", "a1_ca_street_name", "a1_ca_zip",
                "a2_ca_street_number", "a2_ca_street_name", "a2_ca_zip",
                "syear", "fsyear", "s2year"]
        cross = m.groupby(keys, as_index=False).agg(
            cnt=("ss_item_sk", "size"), s1=("ss_wholesale_cost", "sum"),
            s2=("ss_list_price", "sum"), s3=("ss_coupon_amt", "sum"))
        c1 = cross[cross.syear == 1999]
        c2 = cross[cross.syear == 2000]
        j = c1.merge(c2, left_on=["ss_item_sk", "s_store_name", "s_zip"],
                     right_on=["ss_item_sk", "s_store_name", "s_zip"],
                     suffixes=("", "_2"))
        j = j[j.cnt_2 <= j.cnt]
        out = pd.DataFrame({
            "product_name": j.i_product_name, "store_name": j.s_store_name,
            "store_zip": j.s_zip, "b_street_number": j.a1_ca_street_number,
            "b_street_name": j.a1_ca_street_name, "b_zip": j.a1_ca_zip,
            "c_street_number": j.a2_ca_street_number,
            "c_street_name": j.a2_ca_street_name, "c_zip": j.a2_ca_zip,
            "syear": j.syear, "cnt": j.cnt, "s11": j.s1, "s21": j.s2,
            "s31": j.s3, "s12": j.s1_2, "s22": j.s2_2, "s32": j.s3_2,
            "syear2": j.syear_2, "cnt2": j.cnt_2})
        return out.sort_values(["product_name", "store_name", "cnt2", "s11", "s12"]
                               ).head(100).reset_index(drop=True)
    raise ValueError(f"no oracle for q{q}")


# queries whose LIMIT can cut through ties: only the ORDER BY key columns
# are deterministic, so the comparison restricts to them
TIE_KEYS = {73: ["cnt", "c_last_name"]}


def compare_results(engine_table, ref: pd.DataFrame, q: int) -> list[str]:
    """Column-by-column comparison after aligning on a full sort. For
    queries in TIE_KEYS, compares the ORDER BY key multiset only (rows
    beyond the keys are tie-broken arbitrarily by any conforming engine)."""
    problems = []
    out = engine_table.to_pandas()
    if len(out.columns) != len(ref.columns):
        return [f"q{q}: column count {len(out.columns)} != {len(ref.columns)}"]
    if len(out) != len(ref):
        return [f"q{q}: row count {len(out)} != {len(ref)}"]
    if len(ref) == 0:
        return []
    r = ref.copy()
    r.columns = list(out.columns)  # positional: engine aliases win
    if q in TIE_KEYS:
        keys = TIE_KEYS[q]
        out = out[keys]
        r = r[keys]
    o = out.sort_values(list(out.columns), kind="stable").reset_index(drop=True)
    r = r.sort_values(list(r.columns), kind="stable").reset_index(drop=True)
    for c in o.columns:
        sa, sb = o[c], r[c]
        na_a, na_b = pd.isna(sa).values, pd.isna(sb).values
        a, b = sa.values, sb.values
        try:
            if not (na_a == na_b).all():
                ok = False
            elif np.asarray(a).dtype.kind == "f" or np.asarray(b).dtype.kind == "f":
                ok = np.allclose(
                    np.asarray(a, float), np.asarray(b, float),
                    rtol=1e-6, atol=1e-6, equal_nan=True,
                )
            else:
                # nulls already matched positionally; compare the rest
                # (None vs np.nan representations must not differ)
                ok = (a[~na_a] == b[~na_b]).all()
        except (TypeError, ValueError):
            ok = list(a) == list(b)
        if not ok:
            problems.append(f"q{q}: column {c} mismatch")
    return problems
