#!/bin/sh
# regenerate ballista_pb2.py from ballista.proto
cd "$(dirname "$0")" && protoc --python_out=. ballista.proto keda.proto
