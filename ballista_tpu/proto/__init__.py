"""Generated protobuf wire format.

Regenerate with: protoc --python_out=. ballista.proto  (see build.sh)
"""
from ballista_tpu.proto import ballista_pb2 as pb

__all__ = ["pb"]
