"""High-QPS serving tier: plan cache, prepared statements, result cache.

The scheduler-side machinery that turns repeated small queries from a
full parse→optimize→plan→DAG round trip into a cache hit:

- `normalize`: lift literals out of an optimized logical plan into
  parameter slots, fingerprint the shape, and bind values back into a
  cached physical-plan template.
- `tier`: the `ServingTier` facade owning the LRU-bounded plan/result
  caches, table-version invalidation, and prepared-statement registry.
- `incremental`: delta maintenance over append ingestion — eligibility
  analysis, retained-delta registry, maintain-plan construction, and
  continuous-query subscriptions (docs/streaming.md).
"""

from ballista_tpu.serving.incremental import (
    DeltaRegistry,
    IncrementalDecision,
    Subscription,
    SubscriptionRegistry,
    analyze_plan,
    build_maintain_plan,
    decide,
    graft_append_scans,
    graft_delta_scan,
    render_finisher,
    split_finisher,
)
from ballista_tpu.serving.normalize import (
    LiftResult,
    bind_logical,
    bind_physical,
    collect_physical_params,
    collect_scan_tables,
    config_fingerprint,
    decode_params,
    encode_params,
    lift_parameters,
)
from ballista_tpu.serving.tier import PlanTemplate, PreparedStatement, ServingTier, StateEntry

__all__ = [
    "DeltaRegistry",
    "IncrementalDecision",
    "LiftResult",
    "PlanTemplate",
    "PreparedStatement",
    "ServingTier",
    "StateEntry",
    "Subscription",
    "SubscriptionRegistry",
    "analyze_plan",
    "bind_logical",
    "bind_physical",
    "build_maintain_plan",
    "collect_physical_params",
    "collect_scan_tables",
    "config_fingerprint",
    "decide",
    "decode_params",
    "encode_params",
    "graft_append_scans",
    "graft_delta_scan",
    "lift_parameters",
    "render_finisher",
    "split_finisher",
]
