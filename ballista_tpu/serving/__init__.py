"""High-QPS serving tier: plan cache, prepared statements, result cache.

The scheduler-side machinery that turns repeated small queries from a
full parse→optimize→plan→DAG round trip into a cache hit:

- `normalize`: lift literals out of an optimized logical plan into
  parameter slots, fingerprint the shape, and bind values back into a
  cached physical-plan template.
- `tier`: the `ServingTier` facade owning the LRU-bounded plan/result
  caches, table-version invalidation, and prepared-statement registry.
"""

from ballista_tpu.serving.normalize import (
    LiftResult,
    bind_logical,
    bind_physical,
    collect_physical_params,
    config_fingerprint,
    decode_params,
    encode_params,
    lift_parameters,
)
from ballista_tpu.serving.tier import PlanTemplate, PreparedStatement, ServingTier

__all__ = [
    "LiftResult",
    "PlanTemplate",
    "PreparedStatement",
    "ServingTier",
    "bind_logical",
    "bind_physical",
    "collect_physical_params",
    "config_fingerprint",
    "decode_params",
    "encode_params",
    "lift_parameters",
]
