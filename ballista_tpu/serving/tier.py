"""ServingTier: the scheduler's caches for high-QPS repeated queries.

Three layers, all bounded by the thread-safe `LruDict` (entry caps plus a
byte budget for results, env-tunable through the `ballista.serving.*`
knobs) and all evictable in one call when memory-pressure shedding wants
the headroom back:

- L1 text cache: exact SQL text + config fingerprint → (plan key, bound
  values). A hit skips parsing AND optimization.
- L2 plan cache: plan key → `PlanTemplate` (a physical tree with tagged
  literal slots). A hit skips physical planning; same shape with
  different literals maps to the same entry.
- result cache: (plan key, values, table versions) → result table.
  Table versions bump on every catalog re-registration or DDL, so a
  re-registered table orphans its cached results without scanning them.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ballista_tpu.config import (
    SERVING_INCREMENTAL_STATE_BYTES,
    SERVING_INCREMENTAL_STATE_ENTRIES,
    SERVING_PLAN_CACHE_ENTRIES,
    SERVING_RESULT_CACHE_BYTES,
    SERVING_RESULT_CACHE_ENTRIES,
    SERVING_RESULT_MAX_BYTES,
    BallistaConfig,
)
from ballista_tpu.ops.tpu.stage_compiler import LruDict
from ballista_tpu.plan.physical import ExecutionPlan


@dataclass
class PlanTemplate:
    """One cached physical-plan template: the tagged tree plus everything
    needed to bind, admit, and invalidate executions of its shape."""

    key: str
    physical: ExecutionPlan  # literals carry param slot tags; never executed as-is
    type_tags: tuple[str, ...]
    values: tuple  # the values it was planned with (exact-repeat fallback)
    tables: tuple[str, ...]
    bindable: bool  # every slot survived into the physical tree
    single_stage: bool | None = None  # learned at first stage planning
    hits: int = 0
    # merge-eligibility decision, analyzed once per template by
    # serving/incremental.py: "aggregate" | "append" | "none" (+ reason),
    # surfaced in the serving snapshot so fallbacks are diagnosable
    incremental_mode: str | None = None
    incremental_reason: str = ""
    incremental_tables: tuple[str, ...] = ()

    def accepts(self, values: tuple) -> bool:
        """A non-bindable template (the physical planner consumed a slot)
        can only serve the exact values it was planned with."""
        if len(values) != len(self.type_tags):
            return False
        return self.bindable or values == self.values


@dataclass
class PreparedStatement:
    """Server-side prepared statement: sql text kept for template
    re-creation after an eviction, plus the slot signature clients bind."""

    statement_id: str
    sql: str
    session_id: str
    key: str
    type_tags: tuple[str, ...]
    default_values: tuple  # the literals the statement was prepared with
    created_at: float = field(default_factory=time.time)


class _TableVersions:
    """Monotonic per-table counters; absent tables are version 0. Bumped
    on catalog changes so result-cache keys referencing the old data stop
    matching (invalidation by orphaning, never by scanning)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._versions: dict[str, int] = {}
        self.bumps = 0

    def bump(self, table: str) -> int:
        """Returns the new version — append ingestion retains each delta
        set under the version its bump produced."""
        with self._lock:
            v = self._versions.get(table, 0) + 1
            self._versions[table] = v
            self.bumps += 1
            return v

    def vector(self, tables: tuple[str, ...]) -> tuple:
        with self._lock:
            return tuple((t, self._versions.get(t, 0)) for t in tables)


@dataclass
class StateEntry:
    """Cached maintenance state for one (template, values) pair: the
    accumulator table of an aggregate (pre-finisher) or the full result
    of an append-mode plan, tagged with the table-version vector it
    reflects. A maintained refresh merges only the delta versions between
    `vector` and the current one."""

    vector: tuple  # ((table, version), ...) in template-table order
    table: object  # pa.Table — accumulator rows or append-mode result
    kind: str  # "aggregate" | "append"


class ServingTier:
    """Process-wide serving caches for one scheduler. Enablement is
    checked per submission from the session config; the tier itself is
    sized once from defaults + env escape hatches."""

    def __init__(self, config: BallistaConfig | None = None):
        cfg = config or BallistaConfig()
        plan_entries = int(cfg.get(SERVING_PLAN_CACHE_ENTRIES))
        self.plan_cache: LruDict = LruDict(plan_entries)
        # exact-text hits are cheap to store and skip the parser entirely;
        # give them headroom over the template cache they point into
        self.text_cache: LruDict = LruDict(plan_entries * 4)
        self.result_cache: LruDict = LruDict(
            int(cfg.get(SERVING_RESULT_CACHE_ENTRIES)),
            max_bytes=int(cfg.get(SERVING_RESULT_CACHE_BYTES)),
            sizer=lambda t: int(t.nbytes),
        )
        self.result_max_bytes = int(cfg.get(SERVING_RESULT_MAX_BYTES))
        # maintenance state: (plan key, values) → StateEntry. Unlike the
        # result cache it is NOT version-keyed — an entry at an older
        # vector is exactly what a maintained refresh merges deltas into.
        self.state_cache: LruDict = LruDict(
            int(cfg.get(SERVING_INCREMENTAL_STATE_ENTRIES)),
            max_bytes=int(cfg.get(SERVING_INCREMENTAL_STATE_BYTES)),
            sizer=lambda e: int(e.table.nbytes),
        )
        self.table_versions = _TableVersions()
        self.prepared: dict[str, PreparedStatement] = {}
        self._lock = threading.Lock()
        self.plan_hits = 0
        self.plan_misses = 0
        self.text_hits = 0
        self.result_hits = 0
        self.result_misses = 0
        self.fast_lane_executed = 0
        self.fast_lane_fallbacks = 0
        self.uncacheable = 0
        self.cleared = 0
        self.maintained = 0
        self.bootstraps = 0
        self.state_renders = 0
        self.recomputes = 0
        self.recompute_reasons: dict[str, int] = {}
        self.appends = 0
        self.appended_rows = 0

    # -- text (L1) ---------------------------------------------------------

    def lookup_text(self, sql: str, cfg_fp: str):
        """Exact-text hit: (key, values) if both the text mapping and its
        plan template are still resident, else None."""
        got = self.text_cache.get((sql, cfg_fp))
        if got is None:
            return None
        key, values = got
        template = self.plan_cache.get(key)
        if template is None or not template.accepts(values):
            return None
        with self._lock:
            # a text hit implies a plan hit: both layers were skipped
            self.text_hits += 1
            self.plan_hits += 1
        return key, values, template

    def remember_text(self, sql: str, cfg_fp: str, key: str, values: tuple) -> None:
        self.text_cache[(sql, cfg_fp)] = (key, values)

    # -- templates (L2) ----------------------------------------------------

    def lookup_template(self, key: str, values: tuple) -> PlanTemplate | None:
        template = self.plan_cache.get(key)
        if template is None or not template.accepts(values):
            with self._lock:
                self.plan_misses += 1
            return None
        with self._lock:
            self.plan_hits += 1
            template.hits += 1
        return template

    def store_template(self, template: PlanTemplate) -> None:
        self.plan_cache[template.key] = template

    def note_uncacheable(self) -> None:
        with self._lock:
            self.uncacheable += 1

    def note_fast_lane(self, outcome: str) -> None:
        with self._lock:
            if outcome == "executed":
                self.fast_lane_executed += 1
            else:
                self.fast_lane_fallbacks += 1

    # -- results -----------------------------------------------------------

    def result_key(self, key: str, values: tuple, tables: tuple[str, ...]):
        return (key, values, self.table_versions.vector(tables))

    def lookup_result(self, rkey):
        tbl = self.result_cache.get(rkey)
        with self._lock:
            if tbl is None:
                self.result_misses += 1
            else:
                self.result_hits += 1
        return tbl

    def store_result(self, rkey, table) -> None:
        if int(table.nbytes) > self.result_max_bytes:
            return
        self.result_cache[rkey] = table

    # -- incremental maintenance state ---------------------------------------

    def lookup_state(self, key: str, values: tuple) -> StateEntry | None:
        return self.state_cache.get((key, values))

    def store_state(self, key: str, values: tuple, entry: StateEntry) -> None:
        self.state_cache[(key, values)] = entry

    def note_incremental(self, outcome: str, reason: str = "") -> None:
        """Record a refresh decision: "maintained" (delta merge),
        "bootstrap" (first state computation), "state_render" (result
        rebuilt from current state, no job), "recompute" (+ reason)."""
        with self._lock:
            if outcome == "maintained":
                self.maintained += 1
            elif outcome == "bootstrap":
                self.bootstraps += 1
            elif outcome == "state_render":
                self.state_renders += 1
            else:
                self.recomputes += 1
                if reason:
                    self.recompute_reasons[reason] = (
                        self.recompute_reasons.get(reason, 0) + 1)

    def note_append(self, rows: int) -> None:
        with self._lock:
            self.appends += 1
            self.appended_rows += int(rows)

    # -- prepared statements -----------------------------------------------

    def register_prepared(self, stmt: PreparedStatement) -> None:
        with self._lock:
            self.prepared[stmt.statement_id] = stmt

    def get_prepared(self, statement_id: str) -> PreparedStatement | None:
        with self._lock:
            return self.prepared.get(statement_id)

    def close_prepared(self, statement_id: str) -> None:
        with self._lock:
            self.prepared.pop(statement_id, None)

    # -- pressure / introspection -------------------------------------------

    def clear(self) -> None:
        """Drop every cached plan and result (memory-pressure eviction
        path; prepared statements keep their sql and re-template lazily)."""
        self.plan_cache.clear()
        self.text_cache.clear()
        self.result_cache.clear()
        self.state_cache.clear()
        with self._lock:
            self.cleared += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "plan_cache": {
                    "hits": self.plan_hits,
                    "misses": self.plan_misses,
                    "text_hits": self.text_hits,
                    "entries": len(self.plan_cache),
                    "evictions": self.plan_cache.evictions,
                    "uncacheable": self.uncacheable,
                },
                "result_cache": {
                    "hits": self.result_hits,
                    "misses": self.result_misses,
                    "entries": len(self.result_cache),
                    "nbytes": self.result_cache.nbytes(),
                    "evictions": self.result_cache.evictions,
                    "invalidations": self.table_versions.bumps,
                },
                "fast_lane": {
                    "executed": self.fast_lane_executed,
                    "fallbacks": self.fast_lane_fallbacks,
                },
                "incremental": {
                    "maintained": self.maintained,
                    "bootstraps": self.bootstraps,
                    "state_renders": self.state_renders,
                    "recomputes": self.recomputes,
                    "recompute_reasons": dict(self.recompute_reasons),
                    "state_entries": len(self.state_cache),
                    "state_nbytes": self.state_cache.nbytes(),
                    "state_evictions": self.state_cache.evictions,
                    "appends": self.appends,
                    "appended_rows": self.appended_rows,
                    "modes": {
                        key: {"mode": t.incremental_mode or "unanalyzed",
                              "reason": t.incremental_reason}
                        for key, t in self.plan_cache.items()
                    },
                },
                "prepared_statements": len(self.prepared),
                "cleared": self.cleared,
            }
