"""Revocable executor lease tokens: scheduler-less direct dispatch.

The serving tier's fast lane (single-stage dispatch from the submit
thread) still pays one scheduler round trip per query. A lease takes the
scheduler out of the hot path entirely: it mints a token carrying a
capacity slice on one warm executor (slots), an expiry, and a reserved
task-id band, and hands it to a client holding a prepared statement. The
client binds parameters and dispatches single-stage jobs straight to the
executor — the scheduler only hears about completed work through
asynchronous reconciliation (`SchedulerServer.reconcile_direct_dispatch`).

Three parties, three structures:

- `ExecutorLease` — the token itself. The client's copy allocates task
  ids from the band; the executor's copy enforces it.
- `LeaseRegistry` — scheduler side: band allocation (disjoint by
  construction, verified by `analysis.plan_check.verify_lease_bands`),
  expiry sweeping, revocation, and dispatch accounting for KEDA.
- `LeaseTable` — executor side: admits a direct task only when the lease
  is known, unexpired, unrevoked, inside its band, under its concurrency
  slice, and — when the executor runs attached to a device daemon —
  only while the daemon's boot generation still matches the one the
  grant was stamped with ("stale-daemon-generation" fences dispatch
  against a silently restarted daemon; see
  docs/device_daemon.md#failure-domain). A rejection reason string is
  the demotion signal — the client falls back to the scheduled graph
  path, which produces byte-identical results.

Task ids: graph tasks stay below `FAST_TASK_ID_BASE` (1_000_000),
fast-lane tasks live in [FAST_TASK_ID_BASE, DIRECT_TASK_ID_BASE), and
direct-dispatch bands start at `DIRECT_TASK_ID_BASE` — a stale direct
result can never collide with a scheduler-assigned task id.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace

from ballista_tpu.serving.fast_lane import FAST_TASK_ID_BASE  # noqa: F401 — band layout

# direct-dispatch task ids start one band family above the fast lane
DIRECT_TASK_ID_BASE = 2_000_000
DEFAULT_BAND_SIZE = 10_000
DEFAULT_LEASE_TTL_S = 30.0
DEFAULT_LEASE_SLOTS = 2


@dataclass
class ExecutorLease:
    """A revocable capacity slice on one executor.

    `band_start`/`band_size` reserve a private task-id range; the client
    allocates ids from it monotonically (`take_task_id`) and the executor
    rejects anything outside it. Wire-friendly: `to_wire`/`from_wire`
    round-trip through a plain dict (the Flight action body)."""

    lease_id: str
    executor_id: str
    host: str
    flight_port: int
    session_id: str
    slots: int
    expires_at: float
    band_start: int
    band_size: int
    revoked: bool = False
    # device-daemon boot generation the warm capacity was promised
    # against ("" = unfenced). A daemon that silently restarted between
    # grant and dispatch has cold caches and a different failure history;
    # the executor's LeaseTable compares this token against its live
    # attachment and demotes mismatched dispatches to the scheduled path
    # (docs/device_daemon.md#failure-domain).
    daemon_generation: str = ""
    # client-side band cursor / executor-side accounting
    next_offset: int = 0
    inflight: int = 0
    tasks_total: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False, compare=False)

    def rejection(self, now: float | None = None) -> str | None:
        """Why this token must not admit another task, or None."""
        if self.revoked:
            return "revoked"
        if (now if now is not None else time.time()) >= self.expires_at:
            return "expired"
        if self.next_offset >= self.band_size:
            return "band-exhausted"
        return None

    def take_task_id(self) -> int | None:
        """Allocate the next task id from the reserved band (client side);
        None once the band is exhausted — time for a fresh lease."""
        with self._lock:
            if self.next_offset >= self.band_size:
                return None
            tid = self.band_start + self.next_offset
            self.next_offset += 1
            return tid

    def owns_task_id(self, task_id: int) -> bool:
        return self.band_start <= task_id < self.band_start + self.band_size

    def to_wire(self) -> dict:
        return {
            "lease_id": self.lease_id, "executor_id": self.executor_id,
            "host": self.host, "flight_port": self.flight_port,
            "session_id": self.session_id, "slots": self.slots,
            "expires_at": self.expires_at,
            "band_start": self.band_start, "band_size": self.band_size,
            "daemon_generation": self.daemon_generation,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "ExecutorLease":
        return cls(
            lease_id=str(d["lease_id"]), executor_id=str(d["executor_id"]),
            host=str(d.get("host", "")), flight_port=int(d.get("flight_port", 0)),
            session_id=str(d.get("session_id", "")), slots=int(d["slots"]),
            expires_at=float(d["expires_at"]),
            band_start=int(d["band_start"]), band_size=int(d["band_size"]),
            daemon_generation=str(d.get("daemon_generation", "")),
        )

    def clone(self) -> "ExecutorLease":
        """Independent copy with fresh accounting (the executor's table and
        the client each hold their own view of the same token)."""
        return replace(self, next_offset=0, inflight=0, tasks_total=0,
                       _lock=threading.Lock())


class LeaseRegistry:
    """Scheduler-side lease ledger: mint, revoke, expire, reconcile."""

    def __init__(self, base: int = DIRECT_TASK_ID_BASE,
                 band_size: int = DEFAULT_BAND_SIZE):
        self.base = base
        self.default_band_size = band_size
        self._lock = threading.Lock()
        self._leases: dict[str, ExecutorLease] = {}
        self._next_band = 0
        self._seq = 0
        # counters (KEDA / REST / metrics): lifetime, never reset
        self.minted = 0
        self.denied = 0
        self.revoked_total = 0
        self.expired_total = 0
        self.reconciled_jobs = 0
        self.reconciled_tasks = 0
        self.demoted_jobs = 0

    def mint(self, executor_id: str, host: str, flight_port: int,
             session_id: str, slots: int, ttl_s: float,
             band_size: int | None = None,
             daemon_generation: str = "") -> ExecutorLease:
        size = self.default_band_size if band_size is None else int(band_size)
        with self._lock:
            self._seq += 1
            band_start = self.base + self._next_band
            self._next_band += size
            lease = ExecutorLease(
                lease_id=f"lease-{self._seq}-{executor_id[:8]}",
                executor_id=executor_id, host=host, flight_port=flight_port,
                session_id=session_id, slots=max(1, int(slots)),
                expires_at=time.time() + ttl_s,
                band_start=band_start, band_size=size,
                daemon_generation=daemon_generation,
            )
            self._leases[lease.lease_id] = lease
            self.minted += 1
            return lease

    def get(self, lease_id: str) -> ExecutorLease | None:
        with self._lock:
            return self._leases.get(lease_id)

    def revoke(self, lease_id: str) -> ExecutorLease | None:
        """Mark revoked and unlink; returns the lease so the caller can
        return its slots and push the revocation to the executor."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return None
            lease.revoked = True
            self.revoked_total += 1
            return lease

    def expire(self, now: float | None = None) -> list[ExecutorLease]:
        """Drop leases past expiry; returns them for slot return + push."""
        now = time.time() if now is None else now
        out = []
        with self._lock:
            for lid, lease in list(self._leases.items()):
                if now >= lease.expires_at:
                    lease.revoked = True
                    out.append(self._leases.pop(lid))
            self.expired_total += len(out)
        return out

    def note_reconciled(self, lease_id: str | None, tasks: int) -> None:
        with self._lock:
            self.reconciled_jobs += 1
            self.reconciled_tasks += max(0, int(tasks))
            lease = self._leases.get(lease_id or "")
            if lease is not None:
                lease.tasks_total += max(0, int(tasks))

    def note_demoted(self) -> None:
        with self._lock:
            self.demoted_jobs += 1

    def active(self) -> list[ExecutorLease]:
        with self._lock:
            return list(self._leases.values())

    def active_count(self) -> int:
        with self._lock:
            return len(self._leases)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active": len(self._leases),
                "minted": self.minted,
                "denied": self.denied,
                "revoked": self.revoked_total,
                "expired": self.expired_total,
                "direct_jobs_reconciled": self.reconciled_jobs,
                "direct_tasks_reconciled": self.reconciled_tasks,
                "direct_jobs_demoted": self.demoted_jobs,
            }


class LeaseTable:
    """Executor-side lease enforcement. The scheduler pushes grants and
    revocations through the launcher/Flight seam; `admit` gates every
    direct-dispatch task on validity, band membership, and the lease's
    concurrency slice. Counters ride the executor heartbeat."""

    def __init__(self, generation_probe=None):
        self._lock = threading.Lock()
        self._leases: dict[str, ExecutorLease] = {}
        self.tasks_total = 0  # direct_dispatch_tasks heartbeat gauge
        self.rejections = 0
        # () -> str: the live device-daemon generation this executor is
        # attached to ("" when unattached). Grants are stamped with it and
        # admit re-probes — a silently restarted daemon fails the fence.
        self._generation_probe = generation_probe

    def _probe_generation(self) -> str:
        if self._generation_probe is None:
            return ""
        try:
            return str(self._generation_probe() or "")
        except Exception:  # noqa: BLE001 — fencing must not break admits
            return ""

    def grant(self, lease: ExecutorLease) -> None:
        granted = lease.clone()
        if not granted.daemon_generation:
            # scheduler minted without a generation (it cannot see this
            # executor's daemon): stamp the live one at grant time, so
            # the fence measures drift from THIS moment
            granted.daemon_generation = self._probe_generation()
        with self._lock:
            self._leases[lease.lease_id] = granted

    def revoke(self, lease_id: str) -> None:
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is not None:
                lease.revoked = True

    def admit(self, lease_id: str, task_id: int) -> str | None:
        """Admission check for one direct task: None = admitted (call
        `release` when the task finishes), else the rejection reason."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                self.rejections += 1
                return "unknown-lease"
            reason = lease.rejection()
            if reason is None and not lease.owns_task_id(task_id):
                reason = "band-violation"
            if reason is None and lease.daemon_generation:
                live = self._probe_generation()
                if live != lease.daemon_generation:
                    # the daemon restarted (or detached) since the grant:
                    # the warm capacity this lease promised is gone, and a
                    # replayed poison stage would meet an unfenced daemon.
                    # Demote to the scheduled path — byte-identical there.
                    reason = "stale-daemon-generation"
            if reason is None and lease.inflight >= lease.slots:
                reason = "capacity"
            if reason is not None:
                self.rejections += 1
                return reason
            lease.inflight += 1
            lease.tasks_total += 1
            self.tasks_total += 1
            return None

    def release(self, lease_id: str) -> None:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is not None and lease.inflight > 0:
                lease.inflight -= 1

    def expire(self, now: float | None = None) -> int:
        now = time.time() if now is None else now
        with self._lock:
            dead = [lid for lid, le in self._leases.items() if now >= le.expires_at]
            for lid in dead:
                del self._leases[lid]
            return len(dead)

    def active_count(self) -> int:
        with self._lock:
            return len(self._leases)
