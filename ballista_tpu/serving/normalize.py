"""Plan normalization: literal lifting, shape fingerprints, param binding.

The parameterized plan cache keys on the *optimized* logical plan with
every literal replaced by a typed parameter slot. Normalizing after the
optimizer (not the parser) is deliberate: constant folding collapses
expressions like `date '1998-12-01' - interval '90' day` into a single
literal, so two texts that fold to the same shape share one entry, and a
folded constant becomes an ordinary parameter of the folded plan rather
than a hole the optimizer can no longer reach.

Binding happens at the PHYSICAL level. Physical nodes embed the same
logical `Expr` objects they were planned from, so a cached template is a
physical tree whose literals carry `param` slot tags; executing it for
new values is a structural rebuild (fresh node copies, fresh metrics)
that substitutes `Literal(values[i])` for every tagged literal — no
re-planning, no shared mutable state with the cached copy.
"""

from __future__ import annotations

import copy
import hashlib
from dataclasses import dataclass, field

from ballista_tpu.plan.expressions import Expr, Literal, SortKey, literal_type, transform_expr
from ballista_tpu.plan.logical import LogicalPlan, Values
from ballista_tpu.plan.physical import ExecutionPlan, Metrics


class _Slot:
    """Placeholder literal value used only while rendering the cache key;
    its str() masks the concrete value with the slot index + arrow type."""

    __slots__ = ("token",)

    def __init__(self, token: str):
        self.token = token

    def __str__(self) -> str:
        return self.token

    __repr__ = __str__


@dataclass
class LiftResult:
    """Outcome of lifting literals out of one optimized logical plan."""

    tagged: LogicalPlan | None  # literals carry param slot tags
    values: tuple  # slot index -> literal value
    type_tags: tuple[str, ...]  # slot index -> str(arrow type)
    tables: tuple[str, ...]  # referenced table names (version vector)
    cacheable: bool = True
    reason: str = ""  # why not, when cacheable is False
    key: str = field(default="", repr=False)  # shape fingerprint (no config)


def _map_value(v, expr_fn, seen_plans):
    """Map `expr_fn` over every Expr reachable from a node attribute,
    rebuilding containers (lists/tuples/dicts/SortKeys) and nested plan
    nodes along the way. Returns the original object when untouched."""
    if isinstance(v, Expr):
        return transform_expr(v, expr_fn)
    if isinstance(v, SortKey):
        e = transform_expr(v.expr, expr_fn)
        return v if e is v.expr else SortKey(e, v.ascending, v.nulls_first)
    if isinstance(v, LogicalPlan):
        return _rebuild_logical(v, expr_fn, seen_plans)
    if isinstance(v, ExecutionPlan):
        return _rebuild_physical(v, expr_fn)
    if isinstance(v, list):
        out = [_map_value(x, expr_fn, seen_plans) for x in v]
        return v if all(a is b for a, b in zip(out, v)) else out
    if isinstance(v, tuple):
        out = tuple(_map_value(x, expr_fn, seen_plans) for x in v)
        return v if all(a is b for a, b in zip(out, v)) else out
    if isinstance(v, dict):
        out = {k: _map_value(x, expr_fn, seen_plans) for k, x in v.items()}
        return v if all(out[k] is v[k] for k in v) else out
    return v


def _rebuild_logical(p: LogicalPlan, expr_fn, seen_plans=None) -> LogicalPlan:
    """Shallow-copy rebuild of a logical node with `expr_fn` applied to
    every embedded expression. Generic over node shape (attribute scan) so
    new node types cannot silently dodge the walk; the schema attribute is
    carried over untouched (exprs never change result types here)."""
    if seen_plans is None:
        seen_plans = {}
    got = seen_plans.get(id(p))
    if got is not None:
        return got
    new = copy.copy(p)
    for name, val in list(vars(new).items()):
        if name == "schema":
            continue
        mapped = _map_value(val, expr_fn, seen_plans)
        if mapped is not val:
            object.__setattr__(new, name, mapped)
    seen_plans[id(p)] = new
    return new


def _rebuild_physical(node: ExecutionPlan, expr_fn) -> ExecutionPlan:
    """Shallow-copy rebuild of a physical tree with `expr_fn` applied to
    every embedded logical expression. Every node gets fresh Metrics so a
    bound copy never shares counters with the cached template (or with
    another in-flight job bound from the same template)."""
    new = copy.copy(node)
    new.metrics = Metrics()
    for name, val in list(vars(new).items()):
        if name == "metrics":
            continue
        mapped = _map_value(val, expr_fn, {})
        if mapped is not val:
            setattr(new, name, mapped)
    return new


def _walk_exprs(node, visit, seen):
    """Visit every Expr reachable from a plan tree (logical or physical)."""
    if id(node) in seen:
        return
    seen.add(id(node))

    def scan(v):
        if isinstance(v, Expr):
            visit(v)
            for c in v.children():
                scan(c)
        elif isinstance(v, SortKey):
            scan(v.expr)
        elif isinstance(v, (LogicalPlan, ExecutionPlan)):
            _walk_exprs(v, visit, seen)
        elif isinstance(v, (list, tuple)):
            for x in v:
                scan(x)
        elif isinstance(v, dict):
            for x in v.values():
                scan(x)

    for name, val in vars(node).items():
        if name in ("schema", "metrics"):
            continue
        scan(val)


def lift_parameters(optimized: LogicalPlan) -> LiftResult:
    """Lift every literal of an optimized plan into a parameter slot.

    Returns a tagged copy of the plan (each Literal annotated with its
    slot index), the slot values/types in deterministic walk order, the
    referenced table names, and the shape fingerprint. Plans the cache
    cannot represent soundly (subqueries the decorrelator left behind,
    VALUES rows, literal types the engine cannot re-type) come back
    `cacheable=False` and are planned the ordinary way."""
    from ballista_tpu.plan.logical import TableScan

    values: list = []
    tags: list[str] = []
    tables: list[str] = []
    bad: list[str] = []

    def tag(e: Expr) -> Expr:
        if getattr(e, "plan", None) is not None and not isinstance(e, Literal):
            # un-decorrelated subquery: its inner plan has its own literals
            # that this walk does not reach — refuse rather than alias them
            bad.append(f"subquery expr {type(e).__name__}")
            return e
        if isinstance(e, Literal) and e.param is None:
            try:
                t = str(literal_type(e.value))
            except Exception:  # noqa: BLE001 — exotic literal type
                bad.append(f"unsupported literal {type(e.value).__name__}")
                return e
            idx = len(values)
            values.append(e.value)
            tags.append(t)
            return Literal(e.value, param=idx)
        return e

    tagged = _rebuild_logical(optimized, tag)

    def check(p: LogicalPlan):
        if isinstance(p, Values):
            bad.append("VALUES rows")
        if isinstance(p, TableScan):
            tables.append(p.table_name.lower())
        for c in p.children():
            check(c)

    check(tagged)
    if bad:
        return LiftResult(None, (), (), tuple(sorted(set(tables))),
                          cacheable=False, reason="; ".join(sorted(set(bad))))

    # render the shape key from a masked copy: every tagged literal prints
    # as ?slot:type, so the key is independent of the bound values but not
    # of their types (decimal literal types carry value-derived scale)
    def mask(e: Expr) -> Expr:
        if isinstance(e, Literal) and e.param is not None:
            return Literal(_Slot(f"?{e.param}:{tags[e.param]}"))
        return e

    masked = _rebuild_logical(tagged, mask)
    key = hashlib.sha256(masked.display().encode()).hexdigest()
    return LiftResult(tagged, tuple(values), tuple(tags),
                      tuple(sorted(set(tables))), key=key)


def config_fingerprint(cfg) -> str:
    """Session-config fingerprint folded into every cache key: catalog
    registrations ride in the config (`ballista.catalog.table.*`), so a
    table pointed at a new path naturally changes every dependent key."""
    pairs = sorted(cfg.to_key_value_pairs())
    return hashlib.sha256(repr(pairs).encode()).hexdigest()[:16]


def collect_physical_params(plan: ExecutionPlan) -> set[int]:
    """Slot indices that survived physical planning. A slot the planner
    consumed (constant-folded into a scan range, say) cannot be re-bound;
    the template then only serves exact-value repeats."""
    out: set[int] = set()

    def visit(e: Expr):
        if isinstance(e, Literal) and e.param is not None:
            out.add(e.param)

    _walk_exprs(plan, visit, set())
    return out


def collect_scan_tables(plan: ExecutionPlan) -> set[str]:
    """Named tables a physical plan scans (lower-cased). Used to decide
    whether a cached plan is exposed to append ingestion: direct dispatch
    demotes to the scheduler when any of these tables has retained deltas,
    and the serving tier subscribes continuous queries to exactly this
    set. Memory scans have no name and so never appear."""
    out: set[str] = set()
    stack = [plan]
    while stack:
        node = stack.pop()
        name = getattr(node, "table_name", "")
        if name:
            out.add(str(name).lower())
        stack.extend(node.children())
    return out


def bind_physical(template: ExecutionPlan, values: tuple) -> ExecutionPlan:
    """Fresh executable copy of a cached template with `values` bound into
    its parameter slots. Always rebuilds — even for the template's own
    values — so no two jobs (nor the cache itself) share node state."""

    def bind(e: Expr) -> Expr:
        if isinstance(e, Literal) and e.param is not None:
            return Literal(values[e.param])
        return e

    return _rebuild_physical(template, bind)


def bind_logical(tagged: LogicalPlan, values: tuple) -> LogicalPlan:
    """Bind values into a tagged LOGICAL plan. Fallback for templates the
    physical planner made non-bindable (it consumed a slot): substitute at
    the logical level, then run physical planning normally."""

    def bind(e: Expr) -> Expr:
        if isinstance(e, Literal) and e.param is not None:
            return Literal(values[e.param])
        return e

    return _rebuild_logical(tagged, bind)


def encode_params(values) -> str:
    """JSON-encode prepared-statement parameters for the wire. Dates and
    decimals don't survive plain JSON, so each value rides with a tag."""
    import json
    from datetime import date, datetime
    from decimal import Decimal

    out = []
    for v in values:
        if isinstance(v, datetime):
            out.append({"t": "datetime", "v": v.isoformat()})
        elif isinstance(v, date):
            out.append({"t": "date", "v": v.isoformat()})
        elif isinstance(v, Decimal):
            out.append({"t": "decimal", "v": str(v)})
        else:
            out.append({"t": "raw", "v": v})
    return json.dumps(out)


def decode_params(payload: str) -> tuple:
    import json
    from datetime import date, datetime
    from decimal import Decimal

    out = []
    for item in json.loads(payload):
        t, v = item["t"], item["v"]
        if t == "date":
            out.append(date.fromisoformat(v))
        elif t == "datetime":
            out.append(datetime.fromisoformat(v))
        elif t == "decimal":
            out.append(Decimal(v))
        else:
            out.append(v)
    return tuple(out)
