"""Fast-lane job record: a single-stage job with no execution graph.

The short-query fast lane dispatches a single-stage plan straight from
the submit path to warm executors and collects task results on the
executor's reporting thread — the scheduler event loop never sees the
job. `FastJob` stands in for `ExecutionGraph` in the scheduler's jobs
dict, so everything that enumerates jobs (REST handlers, sweeps, offer
rotation, EXPLAIN ANALYZE) keeps working; the graph-shaped methods it
exposes are deliberate no-ops because a fast job has no stage state to
mutate. On failure or timeout the scheduler demotes the job to a real
ExecutionGraph built from the same stages (`FastJob.stages_for_fallback`).
"""

from __future__ import annotations

import threading
import time

from ballista_tpu.scheduler.state.execution_graph import JobState, StageState

# fast-lane task ids start far above any graph-assigned id so a stale
# fast result arriving after a fallback can never collide with a task of
# the replacement graph
FAST_TASK_ID_BASE = 1_000_000


class _FastStageView:
    """StageRecord lookalike for the single live stage of a fast job, so
    the REST /stages, /graph and dot endpoints render fast-lane jobs the
    same way as queued ones. Fast tasks launch immediately, so nothing is
    ever `pending` — unfinished partitions show as `running`."""

    pending: frozenset = frozenset()

    def __init__(self, job: "FastJob", spec):
        self._job = job
        self.spec = spec
        self.attempt = 0

    @property
    def state(self) -> StageState:
        st = self._job.status
        if st is JobState.RUNNING:
            return StageState.RUNNING
        if st is JobState.SUCCESSFUL:
            return StageState.SUCCESSFUL
        return StageState.FAILED

    @property
    def running(self) -> frozenset:
        if self._job.status is JobState.RUNNING:
            return frozenset(self._job._pending)
        return frozenset()

    @property
    def completed(self) -> frozenset:
        return frozenset(set(range(self.spec.partitions)) - self._job._pending)


class FastJob:
    def __init__(self, job_id: str, job_name: str, session_id: str, config,
                 stages=None, rc_key=None, inline_result=None):
        self.job_id = job_id
        self.job_name = job_name
        self.session_id = session_id
        self.config = config
        self.queued_at = time.time()
        self.started_at = self.queued_at
        self.ended_at = 0.0
        self.error = ""
        # graph-shaped surface for REST /stages, /graph, dot rendering
        self.stages: dict = {}
        self.stage_metrics: dict[int, list] = {}
        self.output_links: dict[int, list[int]] = {}
        self.rc_key = rc_key  # result-cache slot to fill on success
        self.inline_result = inline_result  # pa.Table served without a fetch
        self._lock = threading.Lock()
        self._stages = list(stages or [])
        self._pending: set[int] = set()
        self._locations: list = []
        self._failed = False
        if inline_result is not None:
            # a result-cache hit is born terminal
            self.status = JobState.SUCCESSFUL
            self.ended_at = self.queued_at
        else:
            self.status = JobState.RUNNING
            stage = self._stages[0]
            self._pending = set(range(stage.partitions))
            self._df_schema = stage.plan.input.df_schema
            self.stages = {stage.stage_id: _FastStageView(self, stage)}

    # -- result ingestion (executor reporting threads) ---------------------

    def on_result(self, r) -> str | None:
        """Fold one TaskResult in; returns "finished" when the last
        partition landed, "failed" on the first failure, else None."""
        with self._lock:
            if self.status is not JobState.RUNNING:
                return None
            if r.metrics:
                self.stage_metrics.setdefault(self._stages[0].stage_id, []).extend(r.metrics)
            if r.state == "success":
                self._locations.extend(r.locations or [])
                self._pending -= set(r.partitions or [])
                if not self._pending:
                    self.status = JobState.SUCCESSFUL
                    self.ended_at = time.time()
                    return "finished"
                return None
            if r.state == "failed":
                self._failed = True
                self.error = r.error or "fast-lane task failed"
                return "failed"
            return None

    def demote(self) -> list:
        """Hand back the stages for a full-DAG fallback; the record itself
        is replaced in the jobs dict by the new ExecutionGraph."""
        with self._lock:
            return list(self._stages)

    def expired(self, now: float, timeout_s: float) -> bool:
        with self._lock:
            return (self.status is JobState.RUNNING
                    and now - self.started_at > timeout_s)

    # -- graph-shaped surface ----------------------------------------------

    def job_status(self) -> dict:
        with self._lock:
            out = {
                "job_id": self.job_id,
                "job_name": self.job_name,
                "state": self.status.value,
                "error": self.error,
                "completed_stages": 1 if self.status is JobState.SUCCESSFUL else 0,
                "total_stages": 1 if self._stages else 0,
                "queued_at": self.queued_at,
                "ended_at": self.ended_at,
                "fast_lane": True,
            }
            if self.inline_result is not None:
                out["inline_result"] = self.inline_result
                out["partitions"] = []
            elif self._stages:
                out["schema"] = self._df_schema
                if self.status is JobState.SUCCESSFUL:
                    out["partitions"] = sorted(
                        self._locations,
                        key=lambda l: (l.output_partition, l.map_partition))
            return out

    def cancel(self) -> None:
        with self._lock:
            if self.status is JobState.RUNNING:
                self.status = JobState.CANCELLED
                self.ended_at = time.time()

    # no stage state to offer, expire, speculate on, or roll back
    def available_task_count(self) -> int:
        return 0

    def pop_next_task(self, executor_id: str):
        return None

    def return_task(self, task) -> None:
        return

    def expire_overdue_tasks(self, now: float):
        return [], False

    def speculation_candidates(self, now: float):
        return []

    def drain_cancelled_tasks(self):
        return []

    def reset_stages_on_lost_executor(self, executor_id: str) -> int:
        return 0

    def update_task_status(self, *args, **kwargs):
        # stale duplicate result after the job went terminal: nothing to do
        return []
