"""Incremental materialized views: delta-maintained serving results.

PR 8's result cache invalidates on every table-version bump, so an
append-heavy dashboard workload pays a full distributed recompute per
refresh. This module turns "invalidate on bump" into "maintain on bump":

- `DeltaRegistry`: the scheduler's retained append sets. `ctx.append`
  bumps the table's version AND retains the delta batches under that
  version; memory is bounded by the shared `LruDict` byte accounting —
  crossing the `ballista.ingest.*` budgets folds the oldest deltas into
  parquet spool parts (they are table content, never droppable), so
  memory cannot grow with append rate.
- `analyze_plan`: the merge-eligibility ladder. A cached plan template is
  incrementally maintainable when it is the standard two-phase aggregate
  (partial → hash exchange → final) over distributive/algebraic
  accumulators (SUM/COUNT/COUNT(*)/MIN/MAX; AVG arrives pre-decomposed as
  SUM÷COUNT) sourced from named scans — one table, or one inner equi-join
  of two tables (delta-join: Δ(A⋈B) = ΔA⋈B when only A appended). Plain
  filter/project trees maintain by concatenation ("append" mode).
  Everything else records a fallback reason (`incremental_mode` /
  `incremental_reason` in serving stats) and recomputes.
  SUM over floating accumulators is ineligible ("float-sum"): grouped
  float sums are not bit-stable under re-association, and maintained
  results must be byte-equivalent to a from-scratch execution. Exact
  types (ints, decimal128 — the TPC-H path) maintain; MIN/MAX/COUNT
  maintain for any type.
- graft transformers: planning contexts stay base-only; every dispatch
  path stamps scans at bind time. `graft_append_scans` unions a named
  scan with its folded parts + retained deltas (full current view);
  `graft_delta_scan` replaces a table's scan with ONLY its new deltas
  (the delta query of a maintained refresh).
- `split_finisher` / `render_finisher` / `build_maintain_plan`: a
  maintained refresh dispatches partial-aggregate work over the deltas,
  unions the cached accumulator state into the exchange, and re-merges
  through the template's own final aggregate — the dispatched plan is an
  ordinary two-phase stage DAG, so AQE and plan verification see a valid
  shape. The finisher (projection/HAVING/sort/limit) renders on the
  scheduler over the merged state, which is small by construction.
- `SubscriptionRegistry`: continuous queries. A prepared statement
  subscribes to its tables' versions and re-executes (incrementally when
  eligible) on every bump, pushing fresh results over a bounded
  freshest-wins queue.

See docs/streaming.md for the eligibility matrix and operational notes.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from dataclasses import dataclass, field

import pyarrow as pa

from ballista_tpu.config import (
    INGEST_COMPACTION_DIR,
    INGEST_DELTA_RETAIN_BYTES,
    INGEST_DELTA_RETAIN_VERSIONS,
    BallistaConfig,
)
from ballista_tpu.plan.physical import (
    CoalesceBatchesExec,
    CoalescePartitionsExec,
    FilterExec,
    GlobalLimitExec,
    HashAggregateExec,
    HashJoinExec,
    LocalLimitExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    RepartitionExec,
    SortExec,
    SortPreservingMergeExec,
    TaskContext,
    UnionExec,
)
from ballista_tpu.utils.lru import LruDict

log = logging.getLogger(__name__)

# aggregate accumulators that merge by re-applying the final-phase merge
# function (sum-of-sums, min-of-mins, ...); welford triples merge
# nonlinearly over floats and count_distinct needs the dedup relation
MAINTAINABLE_FUNCS = {"sum", "count", "count_all", "min", "max"}

# single-child nodes allowed ABOVE the final aggregate (rendered on the
# scheduler over the merged state)
_FINISHER_NODES = (
    ProjectionExec, FilterExec, SortExec, SortPreservingMergeExec,
    GlobalLimitExec, LocalLimitExec, CoalescePartitionsExec,
    CoalesceBatchesExec, RepartitionExec,
)

# stateless row-wise nodes: results maintain by concatenating the delta
# query's rows onto the cached result (sorts/limits change membership
# or order under appends and fall back)
_APPEND_NODES = (
    ProjectionExec, FilterExec, CoalesceBatchesExec,
    CoalescePartitionsExec, RepartitionExec,
)

# wrappers that may sit between the partial aggregate and its scans
_SOURCE_WRAPPERS = (
    ProjectionExec, FilterExec, CoalesceBatchesExec, RepartitionExec,
)


# ---------------------------------------------------------------------------
# retained delta sets
# ---------------------------------------------------------------------------


@dataclass
class DeltaView:
    """One table's current overlay: folded parquet parts (oldest appends,
    compacted to disk) + still-in-memory batches in version order."""

    folded_files: list[str]
    batches: list[pa.RecordBatch]


class _TableDeltas:
    def __init__(self):
        self.versions: list[int] = []  # unfolded retained versions, ascending
        self.folded_files: list[str] = []
        self.folded_through = 0  # highest version folded into the base view


class DeltaRegistry:
    """Per-table retained append sets, bounded by the shared `LruDict`
    byte accounting. Deltas are the only copy of appended rows, so the
    budget is enforced by FOLDING the oldest versions into parquet spool
    parts (compaction), never by dropping. A maintained refresh that
    reaches past the fold horizon falls back with reason
    "delta-compacted"; the folded parts still serve every full read
    through the append graft."""

    def __init__(self, config: BallistaConfig | None = None):
        cfg = config or BallistaConfig()
        self.retain_bytes = int(cfg.get(INGEST_DELTA_RETAIN_BYTES))
        self.retain_versions = int(cfg.get(INGEST_DELTA_RETAIN_VERSIONS))
        self._spool = str(cfg.get(INGEST_COMPACTION_DIR) or "")
        # max_bytes stays 0: LruDict auto-eviction would DROP table content;
        # _enforce folds against retain_bytes using the same byte accounting
        self.retained: LruDict = LruDict(
            1 << 20, sizer=lambda bs: int(sum(b.nbytes for b in bs)))
        self._lock = threading.RLock()
        self._tables: dict[str, _TableDeltas] = {}
        self._fold_order: list[tuple[str, int]] = []  # arrival order
        self.appends = 0
        self.appended_rows = 0
        self.appended_bytes = 0
        self.folded_versions = 0
        self.folded_bytes = 0
        self.resets = 0

    def configure(self, cfg: BallistaConfig) -> None:
        """Adopt the appending session's retention budgets: the registry is
        scheduler-wide but the `ballista.ingest.*` knobs travel per-session
        (there is no global scheduler config), so each append re-reads
        them — last writer wins, matching every other session-scoped knob."""
        with self._lock:
            self.retain_bytes = int(cfg.get(INGEST_DELTA_RETAIN_BYTES))
            self.retain_versions = int(cfg.get(INGEST_DELTA_RETAIN_VERSIONS))
            spool = str(cfg.get(INGEST_COMPACTION_DIR) or "")
            if spool:
                self._spool = spool

    def spool_dir(self) -> str:
        with self._lock:
            if not self._spool:
                import tempfile

                self._spool = tempfile.mkdtemp(prefix="ballista-ingest-")
            os.makedirs(self._spool, exist_ok=True)
            return self._spool

    def empty(self) -> bool:
        with self._lock:
            return not self._tables

    def tables_with_deltas(self) -> set[str]:
        with self._lock:
            return {t for t, td in self._tables.items()
                    if td.versions or td.folded_files}

    def append(self, table: str, version: int, batches: list[pa.RecordBatch]) -> None:
        table = table.lower()
        with self._lock:
            td = self._tables.setdefault(table, _TableDeltas())
            td.versions.append(version)
            self._fold_order.append((table, version))
            self.appends += 1
            self.appended_rows += sum(b.num_rows for b in batches)
            self.appended_bytes += sum(b.nbytes for b in batches)
        self.retained[(table, version)] = list(batches)
        self._enforce()

    def reset(self, table: str) -> None:
        """Catalog re-registration/DDL: the table has a new lineage, so its
        retained deltas and folded parts no longer apply."""
        table = table.lower()
        with self._lock:
            td = self._tables.pop(table, None)
            if td is None:
                return
            for v in td.versions:
                self.retained.pop((table, v))
            self._fold_order = [(t, v) for t, v in self._fold_order if t != table]
            self.resets += 1

    def range(self, table: str, after: int, upto: int):
        """The delta batches for versions (after, upto], or (None, reason)
        when a maintained refresh cannot be served from memory."""
        table = table.lower()
        with self._lock:
            td = self._tables.get(table)
            if td is None:
                return None, "delta-unavailable"
            if td.folded_through > after:
                return None, "delta-compacted"
            have = set(td.versions)
        need = list(range(after + 1, upto + 1))
        if not set(need) <= have:
            # a version bumped without a retained delta (DDL raced in)
            return None, "delta-unavailable"
        out: list[pa.RecordBatch] = []
        for v in need:
            got = self.retained.get((table, v))
            if got is None:
                return None, "delta-evicted"
            out.extend(got)
        return out, ""

    def view(self) -> dict[str, DeltaView]:
        """Point-in-time overlay per table with any retained content —
        what the append graft unions into named scans."""
        with self._lock:
            items = [(t, list(td.folded_files), list(td.versions))
                     for t, td in self._tables.items()]
        out: dict[str, DeltaView] = {}
        for t, files, versions in items:
            batches: list[pa.RecordBatch] = []
            for v in versions:
                got = self.retained.get((t, v))
                if got:
                    batches.extend(got)
            if files or batches:
                out[t] = DeltaView(files, batches)
        return out

    def _enforce(self) -> None:
        """Fold oldest-first while over the byte budget or a table is over
        its version cap. Folding is the ONLY eviction: rows move to disk,
        never away."""
        while True:
            with self._lock:
                over = self.retain_bytes > 0 and self.retained.nbytes() > self.retain_bytes
                crowded = [t for t, td in self._tables.items()
                           if len(td.versions) > self.retain_versions]
                if crowded:
                    t = crowded[0]
                    v = self._tables[t].versions[0]
                elif over and self._fold_order:
                    t, v = self._fold_order[0]
                else:
                    return
            self._fold(t, v)

    def _fold(self, table: str, version: int) -> None:
        import pyarrow.parquet as pq

        batches = self.retained.pop((table, version))
        path = ""
        nbytes = 0
        if batches:
            path = os.path.join(self.spool_dir(), f"{table}-v{version}.parquet")
            tbl = pa.Table.from_batches(batches, batches[0].schema)
            pq.write_table(tbl, path)
            nbytes = int(tbl.nbytes)
        with self._lock:
            td = self._tables.get(table)
            if td is not None:
                if version in td.versions:
                    td.versions.remove(version)
                if path:
                    td.folded_files.append(path)
                td.folded_through = max(td.folded_through, version)
            if (table, version) in self._fold_order:
                self._fold_order.remove((table, version))
            self.folded_versions += 1
            self.folded_bytes += nbytes

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tables": len(self._tables),
                "retained_versions": len(self._fold_order),
                "retained_bytes": self.retained.nbytes(),
                "appends": self.appends,
                "appended_rows": self.appended_rows,
                "appended_bytes": self.appended_bytes,
                "folded_versions": self.folded_versions,
                "folded_bytes": self.folded_bytes,
                "resets": self.resets,
            }


# ---------------------------------------------------------------------------
# merge-eligibility ladder
# ---------------------------------------------------------------------------


@dataclass
class IncrementalDecision:
    mode: str  # "aggregate" | "append" | "none"
    reason: str = ""
    tables: tuple[str, ...] = ()


def _analyze_source(node):
    """Validate the subtree feeding the partial aggregate: named scans
    under stateless wrappers, at most one inner equi-join of two distinct
    tables. Returns (tables, reason)."""
    if isinstance(node, ParquetScanExec):
        if not node.table_name:
            return None, "unnamed-scan"
        return (node.table_name.lower(),), ""
    if isinstance(node, _SOURCE_WRAPPERS):
        return _analyze_source(node.children()[0])
    if isinstance(node, HashJoinExec):
        if node.join_type != "inner":
            # appends can flip null-extended rows of outer joins
            return None, f"join-{node.join_type}"
        lt, lr = _analyze_source(node.left)
        if lt is None:
            return None, lr
        rt, rr = _analyze_source(node.right)
        if rt is None:
            return None, rr
        if len(lt) > 1 or len(rt) > 1:
            return None, "multi-join"
        if set(lt) & set(rt):
            return None, "self-join"
        return lt + rt, ""
    if isinstance(node, MemoryScanExec):
        return None, "memory-table"
    return None, f"source-{type(node).__name__}"


def analyze_plan(physical) -> IncrementalDecision:
    """Classify a plan template: "aggregate" (delta partials merge into
    cached accumulator state), "append" (delta rows concatenate onto the
    cached result), or "none" with the fallback reason."""
    node = physical
    while isinstance(node, _FINISHER_NODES):
        node = node.children()[0]
    if isinstance(node, HashAggregateExec):
        if node.mode != "final":
            return IncrementalDecision("none", "single-phase-aggregate")
        merged = node.input
        if not isinstance(merged, (RepartitionExec, CoalescePartitionsExec)):
            return IncrementalDecision("none", "no-exchange")
        partial = merged.input
        if not (isinstance(partial, HashAggregateExec) and partial.mode == "partial"):
            return IncrementalDecision("none", "no-partial-phase")
        n_group = len(partial.group_exprs)
        for i, d in enumerate(partial.aggs):
            if d.func not in MAINTAINABLE_FUNCS:
                return IncrementalDecision("none", f"aggregate-{d.func}")
            acc = partial.df_schema.fields[n_group + i]
            if d.func == "sum" and pa.types.is_floating(acc.dtype):
                # float sums are not bit-stable under re-association;
                # byte-equivalence to full recompute would not hold
                return IncrementalDecision("none", "float-sum")
        tables, why = _analyze_source(partial.input)
        if tables is None:
            return IncrementalDecision("none", why)
        return IncrementalDecision("aggregate", "", tables)
    node = physical
    while isinstance(node, _APPEND_NODES):
        node = node.children()[0]
    if isinstance(node, ParquetScanExec) and node.table_name:
        return IncrementalDecision("append", "", (node.table_name.lower(),))
    return IncrementalDecision("none", f"shape-{type(node).__name__}")


def decide(template) -> IncrementalDecision:
    """Analyze once per template; the decision is recorded on the entry
    (`incremental_mode`/`incremental_reason`) so fallbacks are diagnosable
    from the serving snapshot."""
    if template.incremental_mode is None:
        d = analyze_plan(template.physical)
        template.incremental_mode = d.mode
        template.incremental_reason = d.reason
        template.incremental_tables = d.tables
    return IncrementalDecision(template.incremental_mode,
                               template.incremental_reason,
                               getattr(template, "incremental_tables", ()))


# ---------------------------------------------------------------------------
# scan grafts (bind-time delta stamping)
# ---------------------------------------------------------------------------


def _delta_leg(scan: ParquetScanExec, batches: list[pa.RecordBatch]):
    """A memory-scan stand-in for `scan` over delta batches. Full-schema
    batches align (select + cast) to the scan's projected schema by name;
    the scan's pushed-down predicates re-apply as a FilterExec."""
    from ballista_tpu.plan.expressions import and_

    leg = MemoryScanExec(scan.df_schema, list(batches), 1)
    if scan.filters:
        return FilterExec(leg, and_(*scan.filters))
    return leg


def graft_append_scans(physical, views: dict[str, DeltaView]):
    """Union every named base scan with its table's folded parquet parts
    and retained in-memory deltas. Planning contexts stay base-only; this
    runs at dispatch time on every path, so full executions always reflect
    the current table versions."""

    def rec(node):
        if isinstance(node, ParquetScanExec):
            view = views.get(node.table_name.lower()) if node.table_name else None
            if view is None:
                return node
            legs = [node]
            if view.folded_files:
                part = {"files": [{"file": f, "row_groups": None}
                                  for f in view.folded_files]}
                legs.append(ParquetScanExec(
                    node.df_schema, [part], list(node.projection),
                    list(node.filters), node.table_name))
            if view.batches:
                legs.append(_delta_leg(node, view.batches))
            if len(legs) == 1:
                return node
            return UnionExec(legs, node.df_schema)
        kids = node.children()
        if not kids:
            return node
        return node.with_children([rec(c) for c in kids])

    return rec(physical)


def graft_delta_scan(physical, table: str, batches: list[pa.RecordBatch]):
    """Replace `table`'s scan with ONLY its new delta batches — the delta
    query of a maintained refresh. Other tables' scans are untouched (the
    caller augments them to their full current view)."""
    table = table.lower()

    def rec(node):
        if isinstance(node, ParquetScanExec) and node.table_name.lower() == table:
            return _delta_leg(node, batches)
        kids = node.children()
        if not kids:
            return node
        return node.with_children([rec(c) for c in kids])

    return rec(physical)


# ---------------------------------------------------------------------------
# state split / maintain plan / finisher render
# ---------------------------------------------------------------------------


def split_finisher(bound):
    """Split a bound aggregate plan at the final HashAggregateExec:
    returns (final_node, finisher_chain root→just-above-final). Only
    valid after `analyze_plan` said "aggregate"."""
    chain = []
    node = bound
    while not (isinstance(node, HashAggregateExec) and node.mode == "final"):
        chain.append(node)
        node = node.children()[0]
    return node, chain


def build_maintain_plan(bound, table: str, delta_batches, state_batches):
    """The maintained refresh: delta rows flow through the template's own
    partial aggregate, union with the cached accumulator state, and
    re-merge through the template's exchange + final aggregate. The
    result is an ordinary two-phase stage DAG (AQE/plan verification see
    a valid shape); the finisher renders separately over the merged
    state. The state leg bypasses the partial phase — its rows are
    already accumulators, and COUNT partials would re-count them."""
    final, _chain = split_finisher(bound)
    merged = final.input  # RepartitionExec(hash) | CoalescePartitionsExec
    partial = merged.input  # HashAggregateExec(partial)
    delta_sub = graft_delta_scan(partial, table, delta_batches)
    state_leg = MemoryScanExec(partial.df_schema, list(state_batches), 1)
    union = UnionExec([delta_sub, state_leg], partial.df_schema)
    return final.with_children([merged.with_children([union])])


def render_finisher(chain, final_node, state_batches, config) -> pa.Table:
    """Rebuild the finisher chain over an in-memory scan of the merged
    accumulator state and execute it locally — grouped state is small by
    construction, and rendering on the scheduler keeps the dispatched
    job a pure state computation."""
    node = MemoryScanExec(final_node.df_schema, list(state_batches), 1)
    for parent in reversed(chain):
        node = parent.with_children([node])
    ctx = TaskContext(config)
    batches: list[pa.RecordBatch] = []
    for p in range(node.output_partition_count()):
        batches.extend(b for b in node.execute(p, ctx) if b.num_rows)
    schema = node.schema()
    if not batches:
        return pa.table({f.name: pa.array([], f.type) for f in schema},
                        schema=schema)
    return pa.Table.from_batches(batches, schema=schema)


# ---------------------------------------------------------------------------
# continuous queries
# ---------------------------------------------------------------------------


class Subscription:
    """One continuous query: a prepared statement + bound params that
    re-executes on every bump of its tables. Results push into a bounded
    freshest-wins queue; refreshes coalesce (a bump during a refresh
    marks it dirty and re-runs once, not once per bump)."""

    def __init__(self, sub_id: str, statement_id: str, params, session_id: str,
                 maxsize: int, inline: bool):
        self.sub_id = sub_id
        self.statement_id = statement_id
        self.params = params
        self.session_id = session_id
        self.inline = inline
        self.tables: tuple[str, ...] = ()
        self.queue: "queue.Queue[dict]" = queue.Queue(max(1, int(maxsize)))
        self.pushed = 0
        self.dropped = 0
        self.errors = 0
        self.closed = False
        self._lock = threading.Lock()
        self._inflight = False
        self._dirty = False

    def offer(self, status: dict) -> None:
        with self._lock:
            self.pushed += 1
        while True:
            try:
                self.queue.put_nowait(status)
                return
            except queue.Full:
                try:
                    self.queue.get_nowait()
                    with self._lock:
                        self.dropped += 1  # freshest-wins: oldest falls out
                except queue.Empty:
                    pass

    def note_error(self, err: str) -> None:
        with self._lock:
            self.errors += 1
        self.offer({"state": "failed", "error": err,
                    "subscription_id": self.sub_id})

    def begin_refresh(self) -> bool:
        """True when the caller should run the refresh; a refresh already
        in flight absorbs the bump as a dirty mark instead."""
        with self._lock:
            if self.closed:
                return False
            if self._inflight:
                self._dirty = True
                return False
            self._inflight = True
            return True

    def end_refresh(self) -> bool:
        """True when bumps arrived mid-refresh and the caller should run
        one more round."""
        with self._lock:
            if self._dirty and not self.closed:
                self._dirty = False
                return True
            self._inflight = False
            return False


class SubscriptionRegistry:
    """Continuous-query registry: statement subscriptions indexed by the
    tables their plan scans, so a version bump fans out to exactly the
    affected subscribers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, Subscription] = {}
        self._seq = 0
        # lifetime totals survive unsubscribe (a closed sub's counters fold
        # in here so /api/state keeps the history)
        self._pushed = 0
        self._dropped = 0
        self._errors = 0

    def register(self, statement_id: str, params, session_id: str,
                 tables: tuple[str, ...], maxsize: int,
                 inline: bool) -> Subscription:
        with self._lock:
            self._seq += 1
            sub_id = f"sub-{self._seq}"
            sub = Subscription(sub_id, statement_id, params, session_id,
                               maxsize, inline)
            sub.tables = tuple(t.lower() for t in tables)
            self._subs[sub_id] = sub
            return sub

    def bind_tables(self, sub: Subscription, tables: tuple[str, ...]) -> None:
        with self._lock:
            sub.tables = tuple(t.lower() for t in tables)

    def remove(self, sub_id: str) -> None:
        with self._lock:
            sub = self._subs.pop(sub_id, None)
            if sub is not None:
                sub.closed = True
                self._pushed += sub.pushed
                self._dropped += sub.dropped
                self._errors += sub.errors

    def get(self, sub_id: str):
        with self._lock:
            return self._subs.get(sub_id)

    def for_table(self, table: str) -> list[Subscription]:
        table = table.lower()
        with self._lock:
            return [s for s in self._subs.values()
                    if not s.tables or table in s.tables]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active": len(self._subs),
                "pushed": self._pushed + sum(s.pushed for s in self._subs.values()),
                "dropped": self._dropped + sum(s.dropped for s in self._subs.values()),
                "errors": self._errors + sum(s.errors for s in self._subs.values()),
            }
