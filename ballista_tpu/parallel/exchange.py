"""Intra-slice collective exchange: the ICI data plane.

The reference's data plane is Arrow-IPC-over-Flight between executor
processes (SURVEY.md §2.5). On a TPU pod slice, co-scheduled stages can
exchange partitions over ICI instead of files: this module implements the
stage patterns as jittable collectives under `shard_map` over a Mesh:

- `partial_then_psum`: per-device partial aggregation merged with psum —
  the collective form of partial-agg → shuffle(1) → final-agg.
- `hash_exchange_all_to_all`: rows routed by the engine-wide key hash
  (bit-identical twin of ops/hashing.py) into fixed-capacity per-device
  buckets, exchanged with all_to_all — the collective form of
  ShuffleWriter(hash K) → ShuffleReader. Fixed capacity keeps shapes
  static for XLA; overflow falls back to the file shuffle path (the
  capacity check happens host-side before dispatch).

The file-based Flight shuffle remains the general path (elasticity, retry,
cross-host); gated by `ballista.tpu.collective.exchange`.
"""

from __future__ import annotations

from functools import partial

import numpy as np


class ExchangeCapacityExceeded(Exception):
    """A fixed-capacity collective exchange cannot hold the routed rows.

    The device kernel's per-(sender, destination) buckets have `capacity`
    slots; at least one pair needs `required` of them. Raised by the
    host-side gate BEFORE any device dispatch, so no row is ever silently
    truncated — the caller demotes the stage to the per-partition
    file-shuffle path and logs the reason."""

    def __init__(self, required: int, capacity: int, n_devices: int):
        self.required = required
        self.capacity = capacity
        self.n_devices = n_devices
        super().__init__(
            f"collective exchange needs {required} slots per (sender, dest) "
            f"pair but capacity is {capacity} ({n_devices} devices); "
            "demote to the file shuffle path"
        )


def make_mesh(n_devices: int | None = None, axis: str = "part"):
    """1-D device mesh over the partition axis (data parallel over rows).

    Falls back to the CPU backend's virtual devices when the default
    platform has fewer chips than requested (the driver validates
    multi-chip sharding with xla_force_host_platform_device_count; the
    axon TPU plugin ignores JAX_PLATFORMS, so ask the cpu backend
    explicitly)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    return Mesh(np.array(devs[:n]), (axis,))


def partial_then_psum(values, gmask_fn, num_groups: int, mesh, axis: str = "part"):
    """Group-aggregate values sharded by rows across the mesh; returns the
    globally-merged per-group (sums, counts) replicated on every device.

    values: [rows] array sharded on `axis`; gmask_fn(local_rows) -> bool
    masks [num_groups, local_rows].
    """
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def local(vals):
        gm = gmask_fn(vals)
        sums = jnp.stack([jnp.where(gm[g], vals, 0).sum() for g in range(num_groups)])
        cnts = jnp.stack([gm[g].sum() for g in range(num_groups)])
        sums = jax.lax.psum(sums, axis)
        cnts = jax.lax.psum(cnts, axis)
        return sums, cnts

    return shard_map(local, mesh=mesh, in_specs=(P(axis),), out_specs=(P(), P()))(values)


def required_exchange_capacity(key_arrays, n_devices: int, *, prehashed: bool = False) -> int:
    """Slots per (sending device, destination) pair the routed rows need:
    the max bucket fill over every pair. `key_arrays` is the per-device list
    of host arrays — raw int64 keys hashed with the engine-wide key hash
    (ops/hashing.py splitmix64, bit-exact twin of the device hash64), or,
    with `prehashed`, already-combined uint64 row hashes (the multi-column
    `hash_arrays` form that `hash_exchange_table` routes on)."""
    from ballista_tpu.ops.hashing import splitmix64

    worst = 0
    for k in key_arrays:
        k = np.asarray(k)
        if prehashed:
            h = k.astype(np.uint64)
        else:
            h = splitmix64(k.astype(np.uint64))
        dest = h % np.uint64(n_devices)
        counts = np.bincount(dest.astype(np.int64), minlength=n_devices)
        worst = max(worst, int(counts.max(initial=0)))
    return worst


def exchange_capacity_fits(key_arrays, n_devices: int, capacity: int,
                           *, prehashed: bool = False) -> bool:
    """Host-side capacity check (the gate the docstring above promises):
    True iff, for every (sending device, destination) pair, the number of
    rows routed there fits in `capacity` slots. Rows beyond capacity would
    be dropped by the fixed-shape kernel, so a False verdict must route the
    exchange down the file-shuffle path instead."""
    return required_exchange_capacity(key_arrays, n_devices, prehashed=prehashed) <= capacity


def require_exchange_capacity(key_arrays, n_devices: int, capacity: int,
                              *, prehashed: bool = False) -> int:
    """The raising form of `exchange_capacity_fits`: returns the required
    per-pair slot count when it fits, raises the typed
    `ExchangeCapacityExceeded` when it does not (silent truncation is never
    an option — the executor catches the error and demotes the stage to the
    per-partition path)."""
    required = required_exchange_capacity(key_arrays, n_devices, prehashed=prehashed)
    if required > capacity:
        raise ExchangeCapacityExceeded(required, capacity, n_devices)
    return required


def hash_exchange_all_to_all(keys, payload, mesh, axis: str = "part", capacity: int | None = None):
    """Route (key, payload) rows to device hash(key) % n via all_to_all.

    keys/payload: [rows] int64 sharded on `axis`. Every device receives the
    rows whose key hashes to it, in fixed-capacity slots:
    returns (keys_out, payload_out, valid_out) with per-device shape
    [n_dev * capacity] where valid marks real rows.

    Overflow rows (more than `capacity` for one destination) land in a
    dump slot that is sliced away before the exchange — they can NEVER
    clobber a valid row. Callers gate dispatch with
    `exchange_capacity_fits` and fall back to the file shuffle when the
    data does not fit.
    """
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()  # x64: the key hash works on uint64 lanes
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from ballista_tpu.ops.tpu.kernels import hash64

    n = mesh.devices.size
    local_rows = keys.shape[0] // n
    cap = capacity or local_rows  # worst case: all rows to one bucket

    def local(k, v):
        dest = (hash64(k.astype(jnp.uint64)) % jnp.uint64(n)).astype(jnp.int32)
        # stable slot assignment per destination bucket
        slot = jnp.zeros_like(dest)
        for d in range(n):
            is_d = dest == d
            slot = jnp.where(is_d, jnp.cumsum(is_d) - 1, slot)
        # scatter into [n, cap+1] send buffers: slot `cap` is a write-only
        # dump for overflow rows (duplicate-index .at[].set ordering is
        # unspecified, so overflow must never share a slot with valid data)
        ok = slot < cap
        slot_w = jnp.where(ok, slot, cap)
        send_k = jnp.zeros((n, cap + 1), dtype=k.dtype).at[dest, slot_w].set(k)
        send_v = jnp.zeros((n, cap + 1), dtype=v.dtype).at[dest, slot_w].set(v)
        send_ok = jnp.zeros((n, cap + 1), dtype=bool).at[dest, slot_w].set(ok)
        rk = jax.lax.all_to_all(send_k[:, :cap], axis, 0, 0, tiled=True)
        rv = jax.lax.all_to_all(send_v[:, :cap], axis, 0, 0, tiled=True)
        ro = jax.lax.all_to_all(send_ok[:, :cap], axis, 0, 0, tiled=True)
        return rk.reshape(-1), rv.reshape(-1), ro.reshape(-1)

    return shard_map(
        local, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis), P(axis))
    )(keys, payload)


def hash_exchange_table(hashes, lanes, live, mesh, axis: str = "part",
                        capacity: int | None = None):
    """Route a whole table's rows to device hash % n via one all_to_all
    routing decision shared by every column.

    The single-payload form above hashes raw keys on device; real stage
    output rows carry multi-column (possibly string/dictionary) keys, so
    here the caller ships the PRE-combined row hash (`ops/hashing.py
    hash_arrays`, uint64 bit-cast to int64) and the device only takes
    `% n_devices` — host gate and device routing are the same hash by
    construction.

    hashes: [rows] int64 (bit-cast uint64 row hash), sharded on `axis`.
    lanes:  list of [rows] int64 payload lanes (every column of the table
            encoded to one or more int64 lanes by the caller).
    live:   [rows] bool — padding rows (added to make rows divisible by the
            device count) carry False and are never routed.

    Returns (hashes_out, lanes_out, valid_out), each with per-device shape
    [n_dev * capacity] (global [n_dev² * capacity]); `valid_out` marks real
    rows. Callers MUST gate with `require_exchange_capacity(...,
    prehashed=True)` first: rows beyond `capacity` for one (sender, dest)
    pair land in a write-only dump slot and are dropped, exactly like the
    single-payload kernel."""
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()  # x64: routing works on uint64 lanes
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    n = mesh.devices.size
    local_rows = hashes.shape[0] // n
    cap = capacity or local_rows

    def local(h, lv, *ls):
        dest = (h.astype(jnp.uint64) % jnp.uint64(n)).astype(jnp.int32)
        # stable slot assignment per destination bucket; dead (padding) rows
        # never claim a slot
        slot = jnp.zeros_like(dest)
        for d in range(n):
            is_d = (dest == d) & lv
            slot = jnp.where(is_d, jnp.cumsum(is_d) - 1, slot)
        ok = lv & (slot < cap)
        # slot `cap` is a write-only dump for overflow + padding rows
        # (duplicate-index .at[].set ordering is unspecified, so they must
        # never share a slot with valid data)
        slot_w = jnp.where(ok, slot, cap)
        outs = []
        for a in (h,) + ls:
            send = jnp.zeros((n, cap + 1), dtype=a.dtype).at[dest, slot_w].set(a)
            outs.append(jax.lax.all_to_all(send[:, :cap], axis, 0, 0, tiled=True).reshape(-1))
        send_ok = jnp.zeros((n, cap + 1), dtype=bool).at[dest, slot_w].set(ok)
        ro = jax.lax.all_to_all(send_ok[:, :cap], axis, 0, 0, tiled=True).reshape(-1)
        return outs[0], tuple(outs[1:]), ro

    spec = P(axis)
    out = shard_map(
        local, mesh=mesh,
        in_specs=(spec, spec) + (spec,) * len(lanes),
        out_specs=(spec, tuple(spec for _ in lanes), spec),
    )(hashes, live, *lanes)
    return out
