"""Intra-slice collective exchange: the ICI data plane.

The reference's data plane is Arrow-IPC-over-Flight between executor
processes (SURVEY.md §2.5). On a TPU pod slice, co-scheduled stages can
exchange partitions over ICI instead of files: this module implements the
stage patterns as jittable collectives under `shard_map` over a Mesh:

- `partial_then_psum`: per-device partial aggregation merged with psum —
  the collective form of partial-agg → shuffle(1) → final-agg.
- `hash_exchange_all_to_all`: rows routed by the engine-wide key hash
  (bit-identical twin of ops/hashing.py) into fixed-capacity per-device
  buckets, exchanged with all_to_all — the collective form of
  ShuffleWriter(hash K) → ShuffleReader. Fixed capacity keeps shapes
  static for XLA; overflow falls back to the file shuffle path (the
  capacity check happens host-side before dispatch).

The file-based Flight shuffle remains the general path (elasticity, retry,
cross-host); gated by `ballista.tpu.collective.exchange`.
"""

from __future__ import annotations

from functools import partial

import numpy as np


def make_mesh(n_devices: int | None = None, axis: str = "part"):
    """1-D device mesh over the partition axis (data parallel over rows).

    Falls back to the CPU backend's virtual devices when the default
    platform has fewer chips than requested (the driver validates
    multi-chip sharding with xla_force_host_platform_device_count; the
    axon TPU plugin ignores JAX_PLATFORMS, so ask the cpu backend
    explicitly)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    return Mesh(np.array(devs[:n]), (axis,))


def partial_then_psum(values, gmask_fn, num_groups: int, mesh, axis: str = "part"):
    """Group-aggregate values sharded by rows across the mesh; returns the
    globally-merged per-group (sums, counts) replicated on every device.

    values: [rows] array sharded on `axis`; gmask_fn(local_rows) -> bool
    masks [num_groups, local_rows].
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def local(vals):
        gm = gmask_fn(vals)
        sums = jnp.stack([jnp.where(gm[g], vals, 0).sum() for g in range(num_groups)])
        cnts = jnp.stack([gm[g].sum() for g in range(num_groups)])
        sums = jax.lax.psum(sums, axis)
        cnts = jax.lax.psum(cnts, axis)
        return sums, cnts

    return shard_map(local, mesh=mesh, in_specs=(P(axis),), out_specs=(P(), P()))(values)


def hash_exchange_all_to_all(keys, payload, mesh, axis: str = "part", capacity: int | None = None):
    """Route (key, payload) rows to device hash(key) % n via all_to_all.

    keys/payload: [rows] int64 sharded on `axis`. Every device receives the
    rows whose key hashes to it, in fixed-capacity slots:
    returns (keys_out, payload_out, valid_out) with per-device shape
    [n_dev * capacity] where valid marks real rows.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from ballista_tpu.ops.tpu.kernels import hash64

    n = mesh.devices.size
    local_rows = keys.shape[0] // n
    cap = capacity or local_rows  # worst case: all rows to one bucket

    def local(k, v):
        dest = (hash64(k.astype(jnp.uint64)) % jnp.uint64(n)).astype(jnp.int32)
        # stable slot assignment per destination bucket
        slot = jnp.zeros_like(dest)
        eye = []
        for d in range(n):
            is_d = dest == d
            slot = jnp.where(is_d, jnp.cumsum(is_d) - 1, slot)
            eye.append(is_d)
        # scatter into [n, cap] send buffers (overflow rows dropped — caller
        # guarantees capacity; the file shuffle path is the escape hatch)
        send_k = jnp.zeros((n, cap), dtype=k.dtype)
        send_v = jnp.zeros((n, cap), dtype=v.dtype)
        send_ok = jnp.zeros((n, cap), dtype=bool)
        ok = slot < cap
        send_k = send_k.at[dest, jnp.where(ok, slot, cap - 1)].set(jnp.where(ok, k, 0))
        send_v = send_v.at[dest, jnp.where(ok, slot, cap - 1)].set(jnp.where(ok, v, 0))
        send_ok = send_ok.at[dest, jnp.where(ok, slot, cap - 1)].set(ok)
        rk = jax.lax.all_to_all(send_k, axis, 0, 0, tiled=True)
        rv = jax.lax.all_to_all(send_v, axis, 0, 0, tiled=True)
        ro = jax.lax.all_to_all(send_ok, axis, 0, 0, tiled=True)
        return rk.reshape(-1), rv.reshape(-1), ro.reshape(-1)

    return shard_map(
        local, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis), P(axis))
    )(keys, payload)
