"""Intra-slice collective exchange: the ICI data plane.

The reference's data plane is Arrow-IPC-over-Flight between executor
processes (SURVEY.md §2.5). On a TPU pod slice, co-scheduled stages can
exchange partitions over ICI instead of files: this module implements the
stage patterns as jittable collectives under `shard_map` over a Mesh:

- `partial_then_psum`: per-device partial aggregation merged with psum —
  the collective form of partial-agg → shuffle(1) → final-agg.
- `hash_exchange_all_to_all`: rows routed by the engine-wide key hash
  (bit-identical twin of ops/hashing.py) into fixed-capacity per-device
  buckets, exchanged with all_to_all — the collective form of
  ShuffleWriter(hash K) → ShuffleReader. Fixed capacity keeps shapes
  static for XLA; overflow falls back to the file shuffle path (the
  capacity check happens host-side before dispatch).

The file-based Flight shuffle remains the general path (elasticity, retry,
cross-host); gated by `ballista.tpu.collective.exchange`.
"""

from __future__ import annotations

from functools import partial

import numpy as np


def make_mesh(n_devices: int | None = None, axis: str = "part"):
    """1-D device mesh over the partition axis (data parallel over rows).

    Falls back to the CPU backend's virtual devices when the default
    platform has fewer chips than requested (the driver validates
    multi-chip sharding with xla_force_host_platform_device_count; the
    axon TPU plugin ignores JAX_PLATFORMS, so ask the cpu backend
    explicitly)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None and len(devs) < n_devices:
        try:
            devs = jax.devices("cpu")
        except RuntimeError:
            pass
    n = n_devices or len(devs)
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} devices, have {len(devs)} "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=N)"
        )
    return Mesh(np.array(devs[:n]), (axis,))


def partial_then_psum(values, gmask_fn, num_groups: int, mesh, axis: str = "part"):
    """Group-aggregate values sharded by rows across the mesh; returns the
    globally-merged per-group (sums, counts) replicated on every device.

    values: [rows] array sharded on `axis`; gmask_fn(local_rows) -> bool
    masks [num_groups, local_rows].
    """
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    def local(vals):
        gm = gmask_fn(vals)
        sums = jnp.stack([jnp.where(gm[g], vals, 0).sum() for g in range(num_groups)])
        cnts = jnp.stack([gm[g].sum() for g in range(num_groups)])
        sums = jax.lax.psum(sums, axis)
        cnts = jax.lax.psum(cnts, axis)
        return sums, cnts

    return shard_map(local, mesh=mesh, in_specs=(P(axis),), out_specs=(P(), P()))(values)


def exchange_capacity_fits(key_arrays, n_devices: int, capacity: int) -> bool:
    """Host-side capacity check (the gate the docstring above promises):
    True iff, for every (sending device, destination) pair, the number of
    rows routed there fits in `capacity` slots. Uses the engine-wide key
    hash (ops/hashing.py — bit-exact twin of the device hash64), so the
    verdict matches what the device kernel will do. `key_arrays` is the
    per-device list of host int64 key arrays; rows beyond capacity would be
    dropped by the fixed-shape kernel, so a False verdict must route the
    exchange down the file-shuffle path instead."""
    from ballista_tpu.ops.hashing import splitmix64

    for k in key_arrays:
        k = np.asarray(k)
        dest = splitmix64(k.astype(np.uint64)) % np.uint64(n_devices)
        counts = np.bincount(dest.astype(np.int64), minlength=n_devices)
        if counts.max(initial=0) > capacity:
            return False
    return True


def hash_exchange_all_to_all(keys, payload, mesh, axis: str = "part", capacity: int | None = None):
    """Route (key, payload) rows to device hash(key) % n via all_to_all.

    keys/payload: [rows] int64 sharded on `axis`. Every device receives the
    rows whose key hashes to it, in fixed-capacity slots:
    returns (keys_out, payload_out, valid_out) with per-device shape
    [n_dev * capacity] where valid marks real rows.

    Overflow rows (more than `capacity` for one destination) land in a
    dump slot that is sliced away before the exchange — they can NEVER
    clobber a valid row. Callers gate dispatch with
    `exchange_capacity_fits` and fall back to the file shuffle when the
    data does not fit.
    """
    from ballista_tpu.ops.tpu.runtime import ensure_jax

    jax = ensure_jax()  # x64: the key hash works on uint64 lanes
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from ballista_tpu.ops.tpu.kernels import hash64

    n = mesh.devices.size
    local_rows = keys.shape[0] // n
    cap = capacity or local_rows  # worst case: all rows to one bucket

    def local(k, v):
        dest = (hash64(k.astype(jnp.uint64)) % jnp.uint64(n)).astype(jnp.int32)
        # stable slot assignment per destination bucket
        slot = jnp.zeros_like(dest)
        for d in range(n):
            is_d = dest == d
            slot = jnp.where(is_d, jnp.cumsum(is_d) - 1, slot)
        # scatter into [n, cap+1] send buffers: slot `cap` is a write-only
        # dump for overflow rows (duplicate-index .at[].set ordering is
        # unspecified, so overflow must never share a slot with valid data)
        ok = slot < cap
        slot_w = jnp.where(ok, slot, cap)
        send_k = jnp.zeros((n, cap + 1), dtype=k.dtype).at[dest, slot_w].set(k)
        send_v = jnp.zeros((n, cap + 1), dtype=v.dtype).at[dest, slot_w].set(v)
        send_ok = jnp.zeros((n, cap + 1), dtype=bool).at[dest, slot_w].set(ok)
        rk = jax.lax.all_to_all(send_k[:, :cap], axis, 0, 0, tiled=True)
        rv = jax.lax.all_to_all(send_v[:, :cap], axis, 0, 0, tiled=True)
        ro = jax.lax.all_to_all(send_ok[:, :cap], axis, 0, 0, tiled=True)
        return rk.reshape(-1), rv.reshape(-1), ro.reshape(-1)

    return shard_map(
        local, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=(P(axis), P(axis), P(axis))
    )(keys, payload)
