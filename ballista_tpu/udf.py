"""User-defined scalar function registry.

Rebuild of `BallistaFunctionRegistry` (reference: core/src/registry.rs:39):
the registry that lets deserialized plans resolve functions on executors.
Like the reference — where UDFs are code-registered on both sides, not
serialized over the wire — functions ship BY REFERENCE: the client records
the defining module in the session config (`ballista.udf.modules`), and
executors import those modules before running a task; importing a module
re-registers its UDFs process-locally.

    # analytics/udfs.py
    from ballista_tpu import udf
    def double(a: pa.Array) -> pa.Array: ...
    udf.register_udf("double", double, pa.int64())

    ctx.register_udf("double", double, pa.int64())   # local + ships module
    ctx.sql("select double(x) from t")               # works on executors
"""

from __future__ import annotations

import importlib
import logging
import threading
from dataclasses import dataclass
from typing import Callable

import pyarrow as pa

log = logging.getLogger(__name__)

UDF_MODULES = "ballista.udf.modules"  # session config key (comma-separated)


@dataclass(frozen=True)
class ScalarUDF:
    name: str
    fn: Callable  # (*pa.Array) -> pa.Array | pa.Scalar
    return_type: pa.DataType
    module: str | None = None  # importable module that registers this UDF


# analysis: ignore[bounded-cache] registration surface, not a cache: one entry per registered UDF
_REGISTRY: dict[str, ScalarUDF] = {}
_LOCK = threading.Lock()
# analysis: ignore[bounded-cache] load-once marker set; bounded by ballista.udf.modules
_LOADED_MODULES: set[str] = set()


def register_udf(name: str, fn: Callable, return_type: pa.DataType,
                 module: str | None = None) -> ScalarUDF:
    """Register a scalar UDF process-wide. `module` defaults to the
    function's defining module when importable (so remote executors can
    re-register it by import); pass None explicitly for local-only UDFs."""
    if module is None:
        m = getattr(fn, "__module__", None)
        if m and m not in ("__main__", "builtins"):
            module = m
    u = ScalarUDF(name.lower(), fn, return_type, module)
    with _LOCK:
        _REGISTRY[u.name] = u
    return u


def resolve(name: str) -> ScalarUDF | None:
    with _LOCK:
        return _REGISTRY.get(name.lower())


def load_modules(spec: str | None) -> None:
    """Import each module named in a comma-separated spec (executor side:
    re-registers the session's UDFs). Unknown modules log and continue —
    the task then fails with 'unknown scalar function', which names the
    actual problem."""
    if not spec:
        return
    for mod in (m.strip() for m in spec.split(",")):
        if not mod or mod in _LOADED_MODULES:
            continue
        try:
            importlib.import_module(mod)
            _LOADED_MODULES.add(mod)
        except Exception as e:  # noqa: BLE001
            log.warning("cannot import UDF module %s: %s", mod, e)
