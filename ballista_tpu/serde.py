"""Plan codec: physical plans and expressions ↔ protobuf.

Rebuild of BallistaCodec / BallistaPhysicalExtensionCodec
(ballista/core/src/serde/mod.rs:140,355): every operator the executor can
run round-trips through ballista.proto's PhysicalPlanNode, including the
distributed nodes (ShuffleWriter/ShuffleReader/UnresolvedShuffle). The
scheduler serializes per-task plans into TaskDefinition
(state/task_manager.rs:767); executors decode and hand the plan to the
configured engine.
"""

from __future__ import annotations

import datetime as _dt
import decimal as _decimal
import io
from typing import Optional

import pyarrow as pa
import pyarrow.ipc as ipc

from ballista_tpu.errors import GeneralError
from ballista_tpu.plan.expressions import (
    AggregateFunction,
    Alias,
    Between,
    BinaryExpr,
    Case,
    Cast,
    Column,
    Expr,
    InList,
    IsNotNull,
    IsNull,
    Like,
    Literal,
    Negative,
    Not,
    ScalarFunction,
    SortKey,
    WindowFunction,
)
from ballista_tpu.plan.physical import (
    AggDesc,
    CoalesceBatchesExec,
    CoalescePartitionsExec,
    CrossJoinExec,
    EmptyExec,
    ExecutionPlan,
    FilterExec,
    GlobalLimitExec,
    HashAggregateExec,
    HashJoinExec,
    LocalLimitExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    RepartitionExec,
    SortExec,
    WindowExec,
    SortPreservingMergeExec,
    UnionExec,
)
from ballista_tpu.ops.cpu.range_repartition import (
    BufferExec,
    RuntimeStatsExec,
    UnorderedRangeRepartitionExec,
)
from ballista_tpu.ops.tpu.mesh_stage import MeshExchangeExec
from ballista_tpu.plan.schema import DFField, DFSchema
from ballista_tpu.proto import pb
from ballista_tpu.shuffle.reader import ShuffleReaderExec, UnresolvedShuffleExec
from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats
from ballista_tpu.shuffle.writer import ShuffleWriterExec

# -- schema -------------------------------------------------------------------

_TYPE_TO_STR = {
    pa.int64(): "int64", pa.int32(): "int32", pa.int16(): "int16",
    pa.int8(): "int8", pa.float64(): "float64", pa.float32(): "float32",
    pa.string(): "utf8", pa.large_string(): "large_utf8", pa.date32(): "date32",
    pa.bool_(): "bool", pa.timestamp("us"): "timestamp_us", pa.null(): "null",
}
_STR_TO_TYPE = {v: k for k, v in _TYPE_TO_STR.items()}


def type_to_str(t: pa.DataType) -> str:
    s = _TYPE_TO_STR.get(t)
    if s is None:
        # parameterized tags (exact decimal policy: money survives the wire)
        if pa.types.is_decimal128(t):
            return f"decimal128({t.precision},{t.scale})"
        if pa.types.is_decimal256(t):
            return f"decimal256({t.precision},{t.scale})"
        raise GeneralError(f"unserializable arrow type {t}")
    return s


def str_to_type(s: str) -> pa.DataType:
    t = _STR_TO_TYPE.get(s)
    if t is None:
        if s.startswith("decimal128(") or s.startswith("decimal256("):
            p, sc = s[s.index("(") + 1:-1].split(",")
            mk = pa.decimal128 if s.startswith("decimal128") else pa.decimal256
            return mk(int(p), int(sc))
        raise GeneralError(f"unknown arrow type tag {s}")
    return t


def encode_schema(s: DFSchema) -> pb.SchemaProto:
    out = pb.SchemaProto()
    for f in s:
        out.fields.append(
            pb.FieldProto(name=f.name, arrow_type=type_to_str(f.dtype),
                          nullable=f.nullable, qualifier=f.qualifier or "")
        )
    return out


def decode_schema(p: pb.SchemaProto) -> DFSchema:
    return DFSchema(
        [DFField(f.name, str_to_type(f.arrow_type), f.nullable, f.qualifier or None) for f in p.fields]
    )


# -- expressions --------------------------------------------------------------


def encode_literal(v) -> pb.LiteralProto:
    out = pb.LiteralProto()
    if v is None:
        out.null_v = True
    elif isinstance(v, bool):
        out.bool_v = v
    elif isinstance(v, int):
        out.int_v = v
    elif isinstance(v, float):
        out.float_v = v
    elif isinstance(v, str):
        out.string_v = v
    elif isinstance(v, _dt.date):
        out.date_days = (v - _dt.date(1970, 1, 1)).days
    elif isinstance(v, _decimal.Decimal):
        out.decimal_v = str(v)  # exact text round-trip
    elif isinstance(v, tuple) and len(v) == 2:
        out.interval.n = v[0]
        out.interval.unit = v[1]
    else:
        raise GeneralError(f"unserializable literal {v!r}")
    return out


def decode_literal(p: pb.LiteralProto):
    which = p.WhichOneof("value")
    if which == "null_v" or which is None:
        return None
    if which == "bool_v":
        return p.bool_v
    if which == "int_v":
        return p.int_v
    if which == "float_v":
        return p.float_v
    if which == "string_v":
        return p.string_v
    if which == "date_days":
        return _dt.date(1970, 1, 1) + _dt.timedelta(days=p.date_days)
    if which == "decimal_v":
        return _decimal.Decimal(p.decimal_v)
    if which == "interval":
        return (p.interval.n, p.interval.unit)
    raise GeneralError(f"bad literal {p}")


def encode_expr(e: Expr) -> pb.ExprProto:
    out = pb.ExprProto()
    if isinstance(e, Column):
        out.column.name = e.name
        out.column.qualifier = e.qualifier or ""
    elif isinstance(e, Literal):
        out.literal.CopyFrom(encode_literal(e.value))
    elif isinstance(e, BinaryExpr):
        out.binary.left.CopyFrom(encode_expr(e.left))
        out.binary.op = e.op
        out.binary.right.CopyFrom(encode_expr(e.right))
    elif isinstance(e, Not):
        out.__getattribute__("not").expr.CopyFrom(encode_expr(e.expr))
    elif isinstance(e, Negative):
        out.negative.expr.CopyFrom(encode_expr(e.expr))
    elif isinstance(e, IsNull):
        out.is_null.expr.CopyFrom(encode_expr(e.expr))
    elif isinstance(e, IsNotNull):
        out.is_not_null.expr.CopyFrom(encode_expr(e.expr))
    elif isinstance(e, Alias):
        out.alias.expr.CopyFrom(encode_expr(e.expr))
        out.alias.name = e.name
    elif isinstance(e, Cast):
        out.cast.expr.CopyFrom(encode_expr(e.expr))
        out.cast.arrow_type = type_to_str(e.to)
    elif isinstance(e, Like):
        out.like.expr.CopyFrom(encode_expr(e.expr))
        out.like.pattern = e.pattern
        out.like.negated = e.negated
    elif isinstance(e, InList):
        out.in_list.expr.CopyFrom(encode_expr(e.expr))
        for v in e.values:
            out.in_list.values.append(encode_literal(v))
        out.in_list.negated = e.negated
    elif isinstance(e, Between):
        out.between.expr.CopyFrom(encode_expr(e.expr))
        out.between.low.CopyFrom(encode_expr(e.low))
        out.between.high.CopyFrom(encode_expr(e.high))
        out.between.negated = e.negated
    elif isinstance(e, Case):
        for w, t in e.branches:
            br = out.case_expr.branches.add()
            br.when.CopyFrom(encode_expr(w))
            br.then.CopyFrom(encode_expr(t))
        if e.else_expr is not None:
            out.case_expr.else_expr.CopyFrom(encode_expr(e.else_expr))
    elif isinstance(e, ScalarFunction):
        out.scalar_fn.name = e.name
        for a in e.args:
            out.scalar_fn.args.append(encode_expr(a))
    elif isinstance(e, WindowFunction):
        out.window_fn.func = e.func
        for a in e.args:
            out.window_fn.args.append(encode_expr(a))
        for pe in e.partition_by:
            out.window_fn.partition_by.append(encode_expr(pe))
        for k in e.order_by:
            out.window_fn.order_by.append(encode_sort_key(k))
        if e.frame is not None:
            out.window_fn.has_frame = True
            out.window_fn.start_unbounded = e.frame[1] is None
            out.window_fn.frame_start = e.frame[1] or 0
            out.window_fn.end_unbounded = e.frame[2] is None
            out.window_fn.frame_end = e.frame[2] or 0
    elif isinstance(e, AggregateFunction):
        out.agg_fn.func = e.func
        out.agg_fn.distinct = e.distinct
        if e.arg is None:
            out.agg_fn.no_arg = True
        else:
            out.agg_fn.arg.CopyFrom(encode_expr(e.arg))
    else:
        raise GeneralError(f"unserializable expr {type(e).__name__}: {e}")
    return out


def decode_expr(p: pb.ExprProto) -> Expr:
    which = p.WhichOneof("expr_type")
    if which == "column":
        return Column(p.column.name, p.column.qualifier or None)
    if which == "literal":
        return Literal(decode_literal(p.literal))
    if which == "binary":
        return BinaryExpr(decode_expr(p.binary.left), p.binary.op, decode_expr(p.binary.right))
    if which == "not":
        return Not(decode_expr(getattr(p, "not").expr))
    if which == "negative":
        return Negative(decode_expr(p.negative.expr))
    if which == "is_null":
        return IsNull(decode_expr(p.is_null.expr))
    if which == "is_not_null":
        return IsNotNull(decode_expr(p.is_not_null.expr))
    if which == "alias":
        return Alias(decode_expr(p.alias.expr), p.alias.name)
    if which == "cast":
        return Cast(decode_expr(p.cast.expr), str_to_type(p.cast.arrow_type))
    if which == "like":
        return Like(decode_expr(p.like.expr), p.like.pattern, p.like.negated)
    if which == "in_list":
        return InList(
            decode_expr(p.in_list.expr),
            tuple(decode_literal(v) for v in p.in_list.values),
            p.in_list.negated,
        )
    if which == "between":
        return Between(
            decode_expr(p.between.expr), decode_expr(p.between.low),
            decode_expr(p.between.high), p.between.negated,
        )
    if which == "case_expr":
        branches = tuple(
            (decode_expr(b.when), decode_expr(b.then)) for b in p.case_expr.branches
        )
        els = decode_expr(p.case_expr.else_expr) if p.case_expr.HasField("else_expr") else None
        return Case(branches, els)
    if which == "scalar_fn":
        return ScalarFunction(p.scalar_fn.name, tuple(decode_expr(a) for a in p.scalar_fn.args))
    if which == "window_fn":
        frame = None
        if p.window_fn.has_frame:
            frame = (
                "rows",
                None if p.window_fn.start_unbounded else p.window_fn.frame_start,
                None if p.window_fn.end_unbounded else p.window_fn.frame_end,
            )
        return WindowFunction(
            p.window_fn.func,
            tuple(decode_expr(a) for a in p.window_fn.args),
            tuple(decode_expr(a) for a in p.window_fn.partition_by),
            tuple(decode_sort_key(k) for k in p.window_fn.order_by),
            frame,
        )
    if which == "agg_fn":
        arg = None if p.agg_fn.no_arg else decode_expr(p.agg_fn.arg)
        return AggregateFunction(p.agg_fn.func, arg, p.agg_fn.distinct)
    raise GeneralError(f"bad expr proto {which}")


def encode_sort_key(k: SortKey) -> pb.SortKeyProto:
    return pb.SortKeyProto(expr=encode_expr(k.expr), ascending=k.ascending, nulls_first=k.nulls_first)


def decode_sort_key(p: pb.SortKeyProto) -> SortKey:
    return SortKey(decode_expr(p.expr), p.ascending, p.nulls_first)


# -- partition locations ------------------------------------------------------


def encode_location(l: PartitionLocation) -> pb.PartitionLocationProto:
    return pb.PartitionLocationProto(
        map_partition=l.map_partition, job_id=l.job_id, stage_id=l.stage_id,
        output_partition=l.output_partition, executor_id=l.executor_id,
        host=l.host, flight_port=l.flight_port, path=l.path, layout=l.layout,
        stats=pb.PartitionStatsProto(
            num_rows=l.stats.num_rows, num_batches=l.stats.num_batches, num_bytes=l.stats.num_bytes
        ),
    )


def decode_location(p: pb.PartitionLocationProto) -> PartitionLocation:
    return PartitionLocation(
        map_partition=p.map_partition, job_id=p.job_id, stage_id=p.stage_id,
        output_partition=p.output_partition, executor_id=p.executor_id,
        host=p.host, flight_port=p.flight_port, path=p.path, layout=p.layout or "hash",
        stats=PartitionStats(p.stats.num_rows, p.stats.num_batches, p.stats.num_bytes),
    )


# -- physical plan ------------------------------------------------------------


def _is_dynamic_join(plan) -> bool:
    from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

    return isinstance(plan, DynamicJoinSelectionExec)


def encode_plan(plan: ExecutionPlan) -> pb.PhysicalPlanNode:
    out = pb.PhysicalPlanNode()
    if isinstance(plan, ParquetScanExec):
        n = out.parquet_scan
        n.schema.CopyFrom(encode_schema(plan.df_schema))
        for part in plan.partitions:
            sp = n.partitions.add()
            sp.memory_partition = -1
            for f in part.get("files", []):
                fp = sp.files.add()
                fp.file = f["file"]
                if f.get("row_groups") is None:
                    fp.all_row_groups = True
                else:
                    fp.row_groups.extend(f["row_groups"])
        n.projection.extend(plan.projection)
        for f in plan.filters:
            n.filters.append(encode_expr(f))
        n.table_name = plan.table_name
    elif isinstance(plan, MemoryScanExec):
        n = out.memory_scan
        n.schema.CopyFrom(encode_schema(plan.df_schema))
        sink = io.BytesIO()
        from ballista_tpu.plan.physical import _align_batch

        with ipc.new_stream(sink, plan.schema()) as w:
            for b in plan.batches:
                # stored batches may be wider than the (pruned) scan schema
                w.write_batch(_align_batch(b, plan.schema()))
        n.arrow_ipc = sink.getvalue()
        n.partitions = plan.partitions
    elif isinstance(plan, FilterExec):
        out.filter.input.CopyFrom(encode_plan(plan.input))
        out.filter.predicate.CopyFrom(encode_expr(plan.predicate))
    elif isinstance(plan, ProjectionExec):
        out.projection.input.CopyFrom(encode_plan(plan.input))
        for e in plan.exprs:
            out.projection.exprs.append(encode_expr(e))
        out.projection.schema.CopyFrom(encode_schema(plan.df_schema))
    elif isinstance(plan, HashAggregateExec):
        n = out.aggregate
        n.input.CopyFrom(encode_plan(plan.input))
        for g in plan.group_exprs:
            n.group_exprs.append(encode_expr(g))
        for d in plan.aggs:
            dp = n.aggs.add()
            dp.func = d.func
            dp.name = d.name
            if d.expr is None:
                dp.no_arg = True
            else:
                dp.expr.CopyFrom(encode_expr(d.expr))
        n.mode = plan.mode
        n.schema.CopyFrom(encode_schema(plan.df_schema))
    elif isinstance(plan, HashJoinExec) or _is_dynamic_join(plan):
        n = out.hash_join
        n.left.CopyFrom(encode_plan(plan.left))
        n.right.CopyFrom(encode_plan(plan.right))
        for l, r in plan.on:
            kp = n.on.add()
            kp.left.CopyFrom(encode_expr(l))
            kp.right.CopyFrom(encode_expr(r))
        n.join_type = plan.join_type
        if plan.filter is not None:
            n.filter.CopyFrom(encode_expr(plan.filter))
        n.mode = plan.mode
        if _is_dynamic_join(plan) and getattr(plan, "planned_mode", "") == "collect_left":
            # a hedged broadcast's planned strategy rides the mode string
            # (frozen proto): the executor-side resolution needs it to tell
            # a demotion from a plain partitioned decision
            n.mode = f"{plan.mode}:planned=collect_left"
        n.schema.CopyFrom(encode_schema(plan.df_schema))
        n.dynamic = _is_dynamic_join(plan)
    elif isinstance(plan, CrossJoinExec):
        out.cross_join.left.CopyFrom(encode_plan(plan.left))
        out.cross_join.right.CopyFrom(encode_plan(plan.right))
        out.cross_join.schema.CopyFrom(encode_schema(plan.df_schema))
    elif isinstance(plan, SortPreservingMergeExec):
        n = out.sort_preserving_merge
        n.input.CopyFrom(encode_plan(plan.input))
        for k in plan.keys:
            n.keys.append(encode_sort_key(k))
        n.fetch = -1 if plan.fetch is None else plan.fetch
    elif isinstance(plan, WindowExec):
        n = out.window
        n.input.CopyFrom(encode_plan(plan.input))
        for w in plan.window_exprs:
            n.window_exprs.append(encode_expr(w))
        n.schema.CopyFrom(encode_schema(plan.df_schema))
    elif isinstance(plan, SortExec):
        n = out.sort
        n.input.CopyFrom(encode_plan(plan.input))
        for k in plan.keys:
            n.keys.append(encode_sort_key(k))
        n.fetch = -1 if plan.fetch is None else plan.fetch
    elif isinstance(plan, CoalescePartitionsExec):
        out.coalesce_partitions.input.CopyFrom(encode_plan(plan.input))
    elif isinstance(plan, CoalesceBatchesExec):
        out.coalesce_batches.input.CopyFrom(encode_plan(plan.input))
        out.coalesce_batches.target_rows = plan.target_rows
    elif isinstance(plan, LocalLimitExec):
        out.local_limit.input.CopyFrom(encode_plan(plan.input))
        out.local_limit.fetch = plan.fetch
    elif isinstance(plan, GlobalLimitExec):
        out.global_limit.input.CopyFrom(encode_plan(plan.input))
        out.global_limit.fetch = -1 if plan.fetch is None else plan.fetch
        out.global_limit.skip = plan.skip
    elif isinstance(plan, RepartitionExec):
        n = out.repartition
        n.input.CopyFrom(encode_plan(plan.input))
        n.scheme = plan.scheme
        n.n = plan.n
        for k in plan.keys:
            n.keys.append(encode_expr(k))
    elif isinstance(plan, MeshExchangeExec):
        # wire form: a repartition node with scheme "mesh_exchange" — the
        # checked-in generated proto predates the mesh node (and the image
        # carries no protoc to extend it); the scheme string disambiguates
        # losslessly since planner-made RepartitionExec schemes are a closed
        # set ("hash"/"round_robin")
        n = out.repartition
        n.input.CopyFrom(encode_plan(plan.producer))
        # an AQE demotion verdict (skew, oversized input) must survive the
        # wire — the executor-side exchange takes the host path and reports
        # the scheduler's reason, instead of re-litigating the device ladder
        n.scheme = ("mesh_exchange" if not plan.demote_reason
                    else f"mesh_exchange:demoted={plan.demote_reason}")
        n.n = plan.file_partitions
        for k in plan.keys:
            n.keys.append(encode_expr(k))
    elif isinstance(plan, UnorderedRangeRepartitionExec):
        # the dynamic range-repartition pipeline rides the repartition
        # oneof too (same frozen-proto constraint as mesh_exchange); the
        # SortKey's direction flags travel in the scheme string
        n = out.repartition
        n.input.CopyFrom(encode_plan(plan.input))
        n.scheme = (f"range_unordered:asc={int(plan.key.ascending)},"
                    f"nulls_first={int(plan.key.nulls_first)}")
        n.n = plan.n
        n.keys.append(encode_expr(plan.key.expr))
    elif isinstance(plan, RuntimeStatsExec):
        n = out.repartition
        n.input.CopyFrom(encode_plan(plan.input))
        n.scheme = "runtime_stats"
        n.n = 0
        if plan.sort_expr is not None:
            n.keys.append(encode_expr(plan.sort_expr))
    elif isinstance(plan, BufferExec):
        n = out.repartition
        n.input.CopyFrom(encode_plan(plan.input))
        n.scheme = "buffer"
        n.n = plan.max_bytes
    elif isinstance(plan, UnionExec):
        for c in plan.inputs:
            out.union.inputs.append(encode_plan(c))
        out.union.schema.CopyFrom(encode_schema(plan.df_schema))
    elif isinstance(plan, EmptyExec):
        out.empty.schema.CopyFrom(encode_schema(plan.df_schema))
        out.empty.produce_one_row = plan.produce_one_row
    elif isinstance(plan, ShuffleWriterExec):
        n = out.shuffle_writer
        n.input.CopyFrom(encode_plan(plan.input))
        n.job_id = plan.job_id
        n.stage_id = plan.stage_id
        n.output_partitions = plan.output_partitions
        for k in plan.keys:
            n.keys.append(encode_expr(k))
        n.sort_shuffle = plan.sort_shuffle
    elif isinstance(plan, ShuffleReaderExec):
        n = out.shuffle_reader
        n.schema.CopyFrom(encode_schema(plan.df_schema))
        for part in plan.partition_locations:
            pl = n.partition_locations.add()
            for loc in part:
                pl.locations.append(encode_location(loc))
        n.broadcast = plan.broadcast
    elif isinstance(plan, UnresolvedShuffleExec):
        n = out.unresolved_shuffle
        n.stage_id = plan.stage_id
        n.schema.CopyFrom(encode_schema(plan.df_schema))
        n.output_partitions = plan.output_partitions
        n.broadcast = plan.broadcast
    else:
        raise GeneralError(f"unserializable plan node {type(plan).__name__}")
    return out


def decode_plan(p: pb.PhysicalPlanNode) -> ExecutionPlan:
    which = p.WhichOneof("plan_type")
    if which == "parquet_scan":
        n = p.parquet_scan
        parts = []
        for sp in n.partitions:
            files = []
            for f in sp.files:
                files.append(
                    {"file": f.file, "row_groups": None if f.all_row_groups else list(f.row_groups)}
                )
            parts.append({"files": files})
        return ParquetScanExec(decode_schema(n.schema), parts, list(n.projection),
                               [decode_expr(f) for f in n.filters], n.table_name)
    if which == "memory_scan":
        n = p.memory_scan
        schema = decode_schema(n.schema)
        batches = []
        if n.arrow_ipc:
            reader = ipc.open_stream(pa.BufferReader(n.arrow_ipc))
            batches = list(reader)
        return MemoryScanExec(schema, batches, n.partitions or 1)
    if which == "filter":
        return FilterExec(decode_plan(p.filter.input), decode_expr(p.filter.predicate))
    if which == "projection":
        return ProjectionExec(
            decode_plan(p.projection.input),
            [decode_expr(e) for e in p.projection.exprs],
            decode_schema(p.projection.schema),
        )
    if which == "aggregate":
        n = p.aggregate
        aggs = [
            AggDesc(d.func, None if d.no_arg else decode_expr(d.expr), d.name) for d in n.aggs
        ]
        return HashAggregateExec(
            decode_plan(n.input), [decode_expr(g) for g in n.group_exprs], aggs,
            n.mode, decode_schema(n.schema),
        )
    if which == "hash_join":
        n = p.hash_join
        on = [(decode_expr(kp.left), decode_expr(kp.right)) for kp in n.on]
        filt = decode_expr(n.filter) if n.HasField("filter") else None
        if n.dynamic:
            from ballista_tpu.ops.cpu.dynamic_join import DynamicJoinSelectionExec

            mode, _, planned = n.mode.partition(":planned=")
            return DynamicJoinSelectionExec(
                decode_plan(n.left), decode_plan(n.right), on, n.join_type, filt,
                decode_schema(n.schema), mode, planned or "partitioned",
            )
        return HashJoinExec(
            decode_plan(n.left), decode_plan(n.right), on, n.join_type, filt,
            n.mode, decode_schema(n.schema),
        )
    if which == "cross_join":
        return CrossJoinExec(
            decode_plan(p.cross_join.left), decode_plan(p.cross_join.right),
            decode_schema(p.cross_join.schema),
        )
    if which == "window":
        return WindowExec(
            decode_plan(p.window.input),
            [decode_expr(w) for w in p.window.window_exprs],
            decode_schema(p.window.schema),
        )
    if which == "sort":
        n = p.sort
        return SortExec(decode_plan(n.input), [decode_sort_key(k) for k in n.keys],
                        None if n.fetch < 0 else n.fetch)
    if which == "sort_preserving_merge":
        n = p.sort_preserving_merge
        return SortPreservingMergeExec(decode_plan(n.input), [decode_sort_key(k) for k in n.keys],
                                       None if n.fetch < 0 else n.fetch)
    if which == "coalesce_partitions":
        return CoalescePartitionsExec(decode_plan(p.coalesce_partitions.input))
    if which == "coalesce_batches":
        return CoalesceBatchesExec(decode_plan(p.coalesce_batches.input), p.coalesce_batches.target_rows)
    if which == "local_limit":
        return LocalLimitExec(decode_plan(p.local_limit.input), p.local_limit.fetch)
    if which == "global_limit":
        n = p.global_limit
        return GlobalLimitExec(decode_plan(n.input), None if n.fetch < 0 else n.fetch, n.skip)
    if which == "repartition":
        n = p.repartition
        if n.scheme == "mesh_exchange" or n.scheme.startswith("mesh_exchange:"):
            ex = MeshExchangeExec(decode_plan(n.input), [decode_expr(k) for k in n.keys], n.n)
            if n.scheme.startswith("mesh_exchange:demoted="):
                ex.demote_reason = n.scheme.split("demoted=", 1)[1]
            return ex
        if n.scheme.startswith("range_unordered:"):
            flags = dict(kv.split("=") for kv in n.scheme.split(":", 1)[1].split(","))
            key = SortKey(decode_expr(n.keys[0]), ascending=flags["asc"] == "1",
                          nulls_first=flags["nulls_first"] == "1")
            return UnorderedRangeRepartitionExec(decode_plan(n.input), key, n.n)
        if n.scheme == "runtime_stats":
            expr = decode_expr(n.keys[0]) if n.keys else None
            return RuntimeStatsExec(decode_plan(n.input), expr)
        if n.scheme == "buffer":
            return BufferExec(decode_plan(n.input), n.n)
        return RepartitionExec(decode_plan(n.input), n.scheme, n.n, [decode_expr(k) for k in n.keys])
    if which == "union":
        return UnionExec([decode_plan(c) for c in p.union.inputs], decode_schema(p.union.schema))
    if which == "empty":
        return EmptyExec(decode_schema(p.empty.schema), p.empty.produce_one_row)
    if which == "shuffle_writer":
        n = p.shuffle_writer
        return ShuffleWriterExec(
            decode_plan(n.input), n.job_id, n.stage_id, n.output_partitions,
            [decode_expr(k) for k in n.keys], n.sort_shuffle,
        )
    if which == "shuffle_reader":
        n = p.shuffle_reader
        locs = [[decode_location(l) for l in part.locations] for part in n.partition_locations]
        return ShuffleReaderExec(decode_schema(n.schema), locs, n.broadcast)
    if which == "unresolved_shuffle":
        n = p.unresolved_shuffle
        return UnresolvedShuffleExec(n.stage_id, decode_schema(n.schema), n.output_partitions, n.broadcast)
    raise GeneralError(f"bad plan proto: {which}")


def plan_to_bytes(plan: ExecutionPlan) -> bytes:
    return encode_plan(plan).SerializeToString()


def plan_from_bytes(data: bytes) -> ExecutionPlan:
    p = pb.PhysicalPlanNode()
    p.ParseFromString(data)
    return decode_plan(p)
