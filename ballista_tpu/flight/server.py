"""Arrow Flight shuffle server (executor data plane).

Rebuild of ballista/executor/src/flight_service.rs:

- do_get(FetchPartition ticket): streams one shuffle output partition as
  decoded record batches (hash layout: whole file; sort layout: byte range
  through the index). The stream is generator-based — batches leave as they
  decode off the memory map, the partition is never materialized with
  read_all(). A coalesced ticket ({"locations": [...]}) streams several map
  outputs of the same stage back-to-back in one call.
- do_action("io_block_transport"): raw 8 MiB block streaming of the stored
  IPC bytes with NO decode/re-encode — the preferred fast path
  (flight_service.rs:243; 8 MiB buffer :77). Blocks are zero-copy slices
  of a memory map of the shuffle file. The client reassembles and decodes
  the stream once.
- do_action("io_coalesced_transport"): the coalesced raw path — body
  carries {"locations": [ticket, ...]} for one (executor, reduce
  partition) pair and the server streams every location back-to-back in
  ONE RPC. Each location is framed by a small JSON header Result
  ({"i": index, "nbytes": n}) followed by its data blocks, so the client
  keeps per-location accounting: a mid-stream failure is attributed to the
  exact map output being served, and FetchFailed carries the right map
  identity for stage recomputation.

Tickets are JSON: {path, layout, output_partition} — the location fields a
PartitionLocation already carries. The server does NOT trust the ticket
path: it is resolved and required to live under this executor's work dir
(the reference rebuilds paths server-side from structured fields for the
same reason), and job ids in GC actions are validated against traversal.

mmap serving defaults on; BALLISTA_SHUFFLE_MMAP=0 in the executor's
environment falls back to plain reads (the data plane has no session
config, so the escape hatch is environmental).

TLS: when the executor's control plane is configured with mTLS, the same
certificates secure the Flight listener (tls_certificates + client CA with
required verification) — the data plane is not left plaintext on 0.0.0.0.
"""

from __future__ import annotations

import json
import os
import threading

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

from ballista_tpu.config import _env_bool, _env_float, _env_int
from ballista_tpu.errors import ShortRead
from ballista_tpu.shuffle import paths
from ballista_tpu.shuffle.integrity import INTEGRITY, verify_blocks

BLOCK_SIZE = 8 * 1024 * 1024

COALESCED_ACTION = "io_coalesced_transport"

_EMPTY = pa.py_buffer(b"")


class _StreamGate:
    """Concurrent-stream cap with a bounded accept queue.

    Up to `max_streams` responses stream at once; up to `accept_queue`
    more callers may WAIT for a slot (bounded, so a flood of fetches
    holds a bounded amount of server state); anything past that is
    rejected immediately with FlightUnavailableError — the client's
    retry ladder treats it like any transient IO failure and backs off.
    max_streams <= 0 disables the gate."""

    def __init__(self, max_streams: int, accept_queue: int, acquire_timeout_s: float = 10.0):
        self.max_streams = max_streams
        self.accept_queue = accept_queue
        self.acquire_timeout_s = acquire_timeout_s
        self._sem = threading.Semaphore(max_streams) if max_streams > 0 else None
        self._waiters = 0
        self._lock = threading.Lock()

    def acquire(self) -> None:
        if self._sem is None:
            return
        if self._sem.acquire(blocking=False):
            return
        with self._lock:
            if self._waiters >= self.accept_queue:
                raise flight.FlightUnavailableError(
                    f"stream cap reached ({self.max_streams} active, "
                    f"{self._waiters} queued); retry")
            self._waiters += 1
        try:
            if not self._sem.acquire(timeout=self.acquire_timeout_s):
                raise flight.FlightUnavailableError(
                    f"no stream slot freed within {self.acquire_timeout_s:.0f}s; retry")
        finally:
            with self._lock:
                self._waiters -= 1

    def release(self) -> None:
        if self._sem is not None:
            self._sem.release()

    @property
    def waiters(self) -> int:
        with self._lock:
            return self._waiters


def _open_buffer(ticket: dict, work_dir: str) -> pa.Buffer:
    """One location's stored IPC bytes as a (zero-copy, mmap-backed)
    buffer; empty buffer for a partition absent from a sort index."""
    path = paths.contained_path(work_dir, ticket["path"])
    buf = paths.open_range_buffer(
        path, ticket.get("layout", "hash"), ticket.get("output_partition", 0),
        use_mmap=_env_bool("BALLISTA_SHUFFLE_MMAP", True),
    )
    return _EMPTY if buf is None else buf


def _ticket_list(t: dict) -> list[dict]:
    return t["locations"] if "locations" in t else [t]


def _chaos_roll(seed: int, key: str, p: float) -> bool:
    # lazy import: the chaos module pulls in the plan layer, which the
    # data plane otherwise never needs
    from ballista_tpu.executor.chaos import corrupt_roll

    return corrupt_roll(seed, key, p)


class BallistaFlightServer(flight.FlightServerBase):
    def __init__(self, host: str = "0.0.0.0", port: int = 0, work_dir: str = "",
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None):
        kwargs = {}
        scheme = "grpc"
        if tls_cert and tls_key:
            scheme = "grpc+tls"
            with open(tls_cert, "rb") as f:
                cert = f.read()
            with open(tls_key, "rb") as f:
                key = f.read()
            kwargs["tls_certificates"] = [(cert, key)]
            if tls_client_ca:
                with open(tls_client_ca, "rb") as f:
                    kwargs["root_certificates"] = f.read()
                kwargs["verify_client"] = True
        super().__init__(f"{scheme}://{host}:{port}", **kwargs)
        self.work_dir = work_dir
        self.host = host
        # data-plane counters (benchmarks / smoke tests read these):
        # RPCs by kind, locations served, payload bytes out, and overload
        # protection outcomes (rejected at the gate / stalled consumers)
        self.stats = {"do_get": 0, "block_rpc": 0, "coalesced_rpc": 0,
                      "locations_served": 0, "bytes_served": 0,
                      "streams_rejected": 0, "streams_stalled": 0,
                      "checksum_failures": 0, "short_reads": 0,
                      "chaos_corruptions": 0,
                      "lease_dispatch": 0, "lease_rejections": 0,
                      "migrations": 0, "migrated_bytes": 0}
        # executors attached for direct dispatch: lease grants/revocations
        # and scheduler-less task execution arrive as Flight actions
        self._executors: dict[str, object] = {}
        self._stats_lock = threading.Lock()
        # overload knobs are environmental: the data plane has no session
        # config (same precedent as BALLISTA_SHUFFLE_MMAP)
        self.gate = _StreamGate(
            _env_int("BALLISTA_FLIGHT_MAX_STREAMS", 64),
            _env_int("BALLISTA_FLIGHT_ACCEPT_QUEUE", 128),
        )
        self.stall_timeout_s = _env_float("BALLISTA_FLIGHT_STALL_TIMEOUT_S", 30.0)
        # integrity: ship stored checksums in serve headers (same env
        # escape hatch the session knob documents)
        self.checksum_env = _env_bool("BALLISTA_SHUFFLE_CHECKSUM", True)
        # chaos mode=corrupt — serve-time seeded bit-flips (stored files
        # stay pristine, so a refetch can heal); see config.CHAOS_MODE
        self.corrupt_p = _env_float("BALLISTA_CHAOS_CORRUPT_P", 0.0)
        self.corrupt_once = _env_bool("BALLISTA_CHAOS_CORRUPT_ONCE", True)
        self.chaos_seed = _env_int("BALLISTA_CHAOS_SEED", 0)
        self._serve_counts: dict[str, int] = {}

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _gate_acquire(self) -> None:
        try:
            self.gate.acquire()
        except flight.FlightUnavailableError:
            self._bump("streams_rejected")
            raise

    def _crc_for(self, tk: dict) -> str | None:
        """Stored checksum for one location ticket, or None when unchecked
        (knob off, pre-checksum file, unreadable sidecar/index). Never
        raises: serving must not fail because a checksum cannot be read."""
        if not self.checksum_env:
            return None
        try:
            path = paths.contained_path(self.work_dir, tk["path"])
            return paths.checksum_for(
                path, tk.get("layout", "hash"), tk.get("output_partition", 0))
        except Exception:
            return None

    def _maybe_corrupt(self, buf: pa.Buffer, tk: dict) -> pa.Buffer:
        """chaos mode=corrupt: seeded bit-flip of the SERVED copy. With
        BALLISTA_CHAOS_CORRUPT_ONCE (default) only the FIRST serve of each
        (path, partition) range is eligible — deterministic transient
        corruption that heals on the client's retry-once refetch. Without
        it, every serve rolls independently (mixing the serve count into
        the key), modelling a persistently bad disk/NIC."""
        if self.corrupt_p <= 0.0 or buf.size == 0:
            return buf
        key = f"{tk.get('path', '')}|{tk.get('output_partition', 0)}"
        with self._stats_lock:
            serve = self._serve_counts.get(key, 0)
            self._serve_counts[key] = serve + 1
        if self.corrupt_once:
            hit = serve == 0 and _chaos_roll(self.chaos_seed, key, self.corrupt_p)
        else:
            hit = _chaos_roll(self.chaos_seed, f"{key}|{serve}", self.corrupt_p)
        if not hit:
            return buf
        from ballista_tpu.executor.chaos import flip_bit

        self._bump("chaos_corruptions")
        return pa.py_buffer(flip_bit(buf.to_pybytes(), self.chaos_seed, key))

    def do_get(self, context, ticket):
        t = json.loads(ticket.ticket.decode())
        tickets = _ticket_list(t)
        self._gate_acquire()
        try:
            bufs = [_open_buffer(x, self.work_dir) for x in tickets]
        except PermissionError as e:
            self.gate.release()
            raise flight.FlightUnauthorizedError(str(e))
        except ShortRead as e:
            self.gate.release()
            self._bump("short_reads")
            raise flight.FlightUnavailableError(str(e))
        # do_get DECODES server-side, so the client never sees the stored
        # bytes to verify — verify here instead, before the first batch
        # leaves. Raw block/coalesced paths leave verification client-side.
        for x, b in zip(tickets, bufs):
            if b.size == 0:
                continue
            expected = self._crc_for(x)
            if expected and not verify_blocks([b], expected):
                self.gate.release()
                self._bump("checksum_failures")
                INTEGRITY.add("checksum_failures")
                raise flight.FlightInternalError(
                    f"stored shuffle bytes corrupted: {x.get('path')} "
                    f"partition={x.get('output_partition', 0)} fails {expected}")
        self._bump("do_get")
        self._bump("locations_served", len(tickets))
        readers = [ipc.open_stream(pa.BufferReader(b)) for b in bufs if b.size]
        if not readers:
            self.gate.release()
            return flight.RecordBatchStream(pa.table({}))

        def gen():
            import time

            served = 0
            try:
                for r in readers:
                    for batch in r:
                        served += batch.nbytes
                        t0 = time.monotonic()
                        yield batch
                        # a yield that took this long was backpressured by
                        # the consumer; kill the stream and free the mmap
                        # buffers instead of wedging a slot indefinitely
                        if self.stall_timeout_s and time.monotonic() - t0 > self.stall_timeout_s:
                            self._bump("streams_stalled")
                            raise flight.FlightTimedOutError(
                                f"consumer stalled > {self.stall_timeout_s:.0f}s; "
                                "stream dropped")
                self._bump("bytes_served", served)
            finally:
                self.gate.release()

        # generator-based: first batch leaves before the last is decoded;
        # nothing is materialized server-side (no read_all)
        return flight.GeneratorStream(readers[0].schema, gen())

    def _yield_blocks(self, buf: pa.Buffer):
        for off in range(0, buf.size, BLOCK_SIZE):
            # zero-copy: each Result body is a slice of the mmap buffer
            yield flight.Result(buf.slice(off, min(BLOCK_SIZE, buf.size - off)))

    def do_action(self, context, action):
        if action.type == "io_block_transport":
            t = json.loads(action.body.to_pybytes().decode())
            self._gate_acquire()
            try:
                try:
                    buf = _open_buffer(t, self.work_dir)
                except PermissionError as e:
                    raise flight.FlightUnauthorizedError(str(e))
                except ShortRead as e:
                    self._bump("short_reads")
                    raise flight.FlightUnavailableError(str(e))
                self._bump("block_rpc")
                self._bump("locations_served")
                self._bump("bytes_served", buf.size)
                buf = self._maybe_corrupt(buf, t)
                if t.get("want_crc"):
                    # opt-in header (new clients ask; old servers that don't
                    # understand the field just ignore it and the client
                    # detects the absence): {"nbytes": n, "crc": "..."}
                    header = {"nbytes": buf.size}
                    crc = self._crc_for(t)
                    if crc:
                        header["crc"] = crc
                    yield flight.Result(pa.py_buffer(json.dumps(header).encode()))
                yield from self._yield_blocks(buf)
            finally:
                self.gate.release()
            return
        if action.type == COALESCED_ACTION:
            t = json.loads(action.body.to_pybytes().decode())
            tickets = _ticket_list(t)
            self._gate_acquire()
            try:
                self._bump("coalesced_rpc")
                for i, tk in enumerate(tickets):
                    # open INSIDE the stream: a failure on location i surfaces
                    # after location i-1 completed, so the client's per-location
                    # accounting attributes it to the right map output
                    try:
                        buf = _open_buffer(tk, self.work_dir)
                    except PermissionError as e:
                        raise flight.FlightUnauthorizedError(str(e))
                    except ShortRead as e:
                        self._bump("short_reads")
                        raise flight.FlightUnavailableError(str(e))
                    buf = self._maybe_corrupt(buf, tk)
                    h = {"i": i, "nbytes": buf.size}
                    crc = self._crc_for(tk)
                    if crc:
                        # expected checksum travels WITH the location frame;
                        # clients that predate it ignore the extra key
                        h["crc"] = crc
                    yield flight.Result(pa.py_buffer(json.dumps(h).encode()))
                    yield from self._yield_blocks(buf)
                    self._bump("locations_served")
                    self._bump("bytes_served", buf.size)
            finally:
                self.gate.release()
            return
        if action.type == "migrate_pull":
            yield from self._migrate_pull(action.body.to_pybytes())
            return
        if action.type == "remove_job_data":
            t = json.loads(action.body.to_pybytes().decode())
            import shutil

            try:
                job_id = paths.validate_job_id(t["job_id"])
                d = paths.contained_path(self.work_dir, paths.job_dir(self.work_dir, job_id))
            except (ValueError, PermissionError) as e:
                raise flight.FlightUnauthorizedError(str(e))
            if os.path.isdir(d):
                shutil.rmtree(d, ignore_errors=True)
            yield flight.Result(pa.py_buffer(b"ok"))
            return
        if action.type == "lease_grant":
            t = json.loads(action.body.to_pybytes().decode())
            from ballista_tpu.serving.lease import ExecutorLease

            ex = self._executors.get(t.get("executor_id", ""))
            if ex is None:
                raise flight.FlightServerError(
                    f"no executor {t.get('executor_id')!r} attached")
            ex.lease_table.grant(ExecutorLease.from_wire(t))
            yield flight.Result(pa.py_buffer(b"ok"))
            return
        if action.type == "lease_revoke":
            t = json.loads(action.body.to_pybytes().decode())
            ex = self._executors.get(t.get("executor_id", ""))
            if ex is not None:
                ex.lease_table.revoke(str(t.get("lease_id", "")))
            yield flight.Result(pa.py_buffer(b"ok"))
            return
        if action.type == "lease_dispatch":
            # frame: one JSON header line, then a TaskDefinitionProto. The
            # response is a JSON header (admitted or rejection reason)
            # followed, when admitted, by the TaskStatusProto.
            yield from self._lease_dispatch(action.body.to_pybytes())
            return
        raise flight.FlightServerError(f"unknown action {action.type}")

    def _migrate_pull(self, body: bytes):
        """Drain handoff (docs/lifecycle.md#migration-commit-rules): this
        DESTINATION pulls shuffle byte ranges from a draining source over
        the existing coalesced Flight path and commits each one under its
        own work dir — hash layout, tmp + atomic rename, `.crc` sidecar
        carried over — then reports the new path so the scheduler can
        rewrite the PartitionLocation in place. Idempotent: the committed
        name is a pure function of the location's identity, so a replayed
        migration renames over an identical file."""
        from ballista_tpu.flight.client import fetch_partitions_bytes

        t = json.loads(body.decode())
        source = str(t["source"])
        locs = list(t.get("locations", []))
        for i, data, crc in fetch_partitions_bytes(source, locs):
            tk = locs[i]
            try:
                job_id = paths.validate_job_id(str(tk["job_id"]))
            except ValueError as e:
                raise flight.FlightUnauthorizedError(str(e))
            dest = paths.hash_data_path(
                self.work_dir, job_id, int(tk["stage_id"]),
                int(tk.get("output_partition", 0)),
                f"mig{int(tk.get('map_partition', 0))}")
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            tmp = dest + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(data)
                if crc:
                    with open(paths.crc_path(dest) + ".tmp", "w") as f:
                        f.write(crc)
                    os.replace(paths.crc_path(dest) + ".tmp", paths.crc_path(dest))
                os.replace(tmp, dest)
            except BaseException:
                for p in (tmp, paths.crc_path(dest) + ".tmp"):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                raise
            self._bump("migrations")
            self._bump("migrated_bytes", len(data))
            yield flight.Result(pa.py_buffer(json.dumps(
                {"i": i, "path": dest, "nbytes": len(data)}).encode()))

    def attach_executor(self, executor) -> None:
        """Register an in-process Executor as a direct-dispatch target of
        this data-plane endpoint (daemon/standalone wiring)."""
        self._executors[executor.metadata.id] = executor

    def _lease_dispatch(self, body: bytes):
        from ballista_tpu.proto import pb
        from ballista_tpu.serde_control import decode_task_definition, encode_task_status

        head, _, payload = body.partition(b"\n")
        t = json.loads(head.decode())
        lease_id = str(t.get("lease_id", ""))
        ex = self._executors.get(t.get("executor_id", ""))
        if ex is None:
            self._bump("lease_rejections")
            yield flight.Result(pa.py_buffer(json.dumps(
                {"rejected": "no-executor-attached"}).encode()))
            return
        task = decode_task_definition(pb.TaskDefinitionProto.FromString(payload))
        reason = ex.lease_table.admit(lease_id, task.task_id)
        if reason is not None:
            self._bump("lease_rejections")
            yield flight.Result(pa.py_buffer(json.dumps({"rejected": reason}).encode()))
            return
        try:
            result = ex.run_task(task)
        finally:
            ex.lease_table.release(lease_id)
        self._bump("lease_dispatch")
        status = encode_task_status(result, ex.metadata.id).SerializeToString()
        yield flight.Result(pa.py_buffer(json.dumps({"ok": True}).encode()))
        yield flight.Result(pa.py_buffer(status))

    def list_actions(self, context):
        return [("io_block_transport", "raw IPC block stream"),
                (COALESCED_ACTION, "framed multi-location raw IPC block stream"),
                ("remove_job_data", "GC a job's shuffle files"),
                ("migrate_pull", "pull + commit shuffle ranges from a draining executor"),
                ("lease_grant", "install a direct-dispatch lease on an attached executor"),
                ("lease_revoke", "revoke a direct-dispatch lease"),
                ("lease_dispatch", "run one leased single-stage task scheduler-less")]


def start_flight_server(work_dir: str, host: str = "0.0.0.0", port: int = 0,
                        tls_cert: str | None = None, tls_key: str | None = None,
                        tls_client_ca: str | None = None) -> tuple[BallistaFlightServer, int]:
    server = BallistaFlightServer(host, port, work_dir,
                                  tls_cert=tls_cert, tls_key=tls_key,
                                  tls_client_ca=tls_client_ca)
    bound = server.port
    t = threading.Thread(target=server.serve, daemon=True, name="flight-server")
    t.start()
    return server, bound
