"""Arrow Flight shuffle server (executor data plane).

Rebuild of ballista/executor/src/flight_service.rs:

- do_get(FetchPartition ticket): streams one shuffle output partition as
  decoded record batches (hash layout: whole file; sort layout: byte range
  through the index).
- do_action("io_block_transport"): raw 8 MiB block streaming of the stored
  IPC bytes with NO decode/re-encode — the preferred fast path
  (flight_service.rs:243; 8 MiB buffer :77). The client reassembles and
  decodes the stream once.

Tickets are JSON: {path, layout, output_partition} — the location fields a
PartitionLocation already carries. The server does NOT trust the ticket
path: it is resolved and required to live under this executor's work dir
(the reference rebuilds paths server-side from structured fields for the
same reason), and job ids in GC actions are validated against traversal.

TLS: when the executor's control plane is configured with mTLS, the same
certificates secure the Flight listener (tls_certificates + client CA with
required verification) — the data plane is not left plaintext on 0.0.0.0.
"""

from __future__ import annotations

import json
import os
import threading

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

from ballista_tpu.shuffle import paths
from ballista_tpu.shuffle.types import PartitionLocation

BLOCK_SIZE = 8 * 1024 * 1024


def _read_range(ticket: dict, work_dir: str) -> bytes:
    path = paths.contained_path(work_dir, ticket["path"])
    if paths.is_sort_layout(ticket.get("layout", "hash")):
        with open(paths.index_path(path)) as f:
            index = json.load(f)
        entry = index.get(str(ticket["output_partition"]))
        if entry is None:
            return b""
        offset, length = entry[0], entry[1]
        with open(path, "rb") as f:
            f.seek(offset)
            return f.read(length)
    with open(path, "rb") as f:
        return f.read()


class BallistaFlightServer(flight.FlightServerBase):
    def __init__(self, host: str = "0.0.0.0", port: int = 0, work_dir: str = "",
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None):
        kwargs = {}
        scheme = "grpc"
        if tls_cert and tls_key:
            scheme = "grpc+tls"
            with open(tls_cert, "rb") as f:
                cert = f.read()
            with open(tls_key, "rb") as f:
                key = f.read()
            kwargs["tls_certificates"] = [(cert, key)]
            if tls_client_ca:
                with open(tls_client_ca, "rb") as f:
                    kwargs["root_certificates"] = f.read()
                kwargs["verify_client"] = True
        super().__init__(f"{scheme}://{host}:{port}", **kwargs)
        self.work_dir = work_dir
        self.host = host

    def do_get(self, context, ticket):
        t = json.loads(ticket.ticket.decode())
        try:
            buf = _read_range(t, self.work_dir)
        except PermissionError as e:
            raise flight.FlightUnauthorizedError(str(e))
        if not buf:
            return flight.RecordBatchStream(pa.table({}))
        reader = ipc.open_stream(pa.BufferReader(buf))
        table = reader.read_all()
        return flight.RecordBatchStream(table)

    def do_action(self, context, action):
        if action.type == "io_block_transport":
            t = json.loads(action.body.to_pybytes().decode())
            try:
                buf = _read_range(t, self.work_dir)
            except PermissionError as e:
                raise flight.FlightUnauthorizedError(str(e))
            for off in range(0, len(buf), BLOCK_SIZE):
                yield flight.Result(pa.py_buffer(buf[off : off + BLOCK_SIZE]))
            return
        if action.type == "remove_job_data":
            t = json.loads(action.body.to_pybytes().decode())
            import shutil

            try:
                job_id = paths.validate_job_id(t["job_id"])
                d = paths.contained_path(self.work_dir, paths.job_dir(self.work_dir, job_id))
            except (ValueError, PermissionError) as e:
                raise flight.FlightUnauthorizedError(str(e))
            if os.path.isdir(d):
                shutil.rmtree(d, ignore_errors=True)
            yield flight.Result(pa.py_buffer(b"ok"))
            return
        raise flight.FlightServerError(f"unknown action {action.type}")

    def list_actions(self, context):
        return [("io_block_transport", "raw IPC block stream"), ("remove_job_data", "GC a job's shuffle files")]


def start_flight_server(work_dir: str, host: str = "0.0.0.0", port: int = 0,
                        tls_cert: str | None = None, tls_key: str | None = None,
                        tls_client_ca: str | None = None) -> tuple[BallistaFlightServer, int]:
    server = BallistaFlightServer(host, port, work_dir,
                                  tls_cert=tls_cert, tls_key=tls_key,
                                  tls_client_ca=tls_client_ca)
    bound = server.port
    t = threading.Thread(target=server.serve, daemon=True, name="flight-server")
    t.start()
    return server, bound
