"""Scheduler-side Flight result proxy.

Rebuild of BallistaFlightProxyService (scheduler/src/flight_proxy_service.rs:42,114)
+ the client's FlightProxy::External mode (core/src/execution_plans/
distributed_query.rs:754-783): clients that cannot reach executors directly
(NAT, k8s cluster networking) fetch result partitions from the scheduler,
which relays from the owning executor over the raw-block path.

Tickets are the normal fetch tickets plus the executor's {host, flight_port}
so the proxy knows where to relay from.
"""

from __future__ import annotations

import json
import threading

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

BLOCK_SIZE = 8 * 1024 * 1024


def _relay_bytes(ticket: dict) -> bytes:
    """Pull the stored IPC bytes from the owning executor (raw-block mode —
    no decode on the proxy hop)."""
    from ballista_tpu.flight.client import POOL

    addr = f"{ticket['host']}:{ticket['flight_port']}"
    client = POOL.get(addr)
    try:
        action = flight.Action("io_block_transport", json.dumps(ticket).encode())
        return b"".join(r.body.to_pybytes() for r in client.do_action(action))
    except Exception:
        POOL.discard(addr)
        raise


class FlightResultProxy(flight.FlightServerBase):
    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        super().__init__(f"grpc://{host}:{port}")

    def do_get(self, context, ticket):
        t = json.loads(ticket.ticket.decode())
        buf = _relay_bytes(t)
        if not buf:
            return flight.RecordBatchStream(pa.table({}))
        reader = ipc.open_stream(pa.BufferReader(buf))
        return flight.RecordBatchStream(reader.read_all())

    def do_action(self, context, action):
        if action.type == "io_block_transport":
            t = json.loads(action.body.to_pybytes().decode())
            buf = _relay_bytes(t)
            for off in range(0, len(buf), BLOCK_SIZE):
                yield flight.Result(pa.py_buffer(buf[off : off + BLOCK_SIZE]))
            return
        raise flight.FlightServerError(f"unknown action {action.type}")

    def list_actions(self, context):
        return [("io_block_transport", "relay raw IPC blocks from an executor")]


def start_flight_proxy(host: str = "0.0.0.0", port: int = 0) -> tuple[FlightResultProxy, int]:
    server = FlightResultProxy(host, port)
    bound = server.port
    t = threading.Thread(target=server.serve, daemon=True, name="flight-proxy")
    t.start()
    return server, bound
