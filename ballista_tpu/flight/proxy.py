"""Scheduler-side Flight result proxy.

Rebuild of BallistaFlightProxyService (scheduler/src/flight_proxy_service.rs:42,114)
+ the client's FlightProxy::External mode (core/src/execution_plans/
distributed_query.rs:754-783): clients that cannot reach executors directly
(NAT, k8s cluster networking) fetch result partitions from the scheduler,
which relays from the owning executor over the raw-block path.

Tickets are the normal fetch tickets plus the executor's {host, flight_port}
so the proxy knows where to relay from. The relay is a streaming
pass-through: each upstream Result body is forwarded verbatim (zero
re-chunking, nothing buffered), which also preserves the
io_coalesced_transport header framing byte-for-byte — the proxy needs no
knowledge of the coalesced wire format to relay it. The same property
carries the shuffle-integrity checksum headers ({"nbytes", "crc"} on the
block path, "crc" in coalesced frames) end to end: external clients verify
against the EXECUTOR's stored checksum, so a corruption introduced by the
relay hop itself is also caught.
"""

from __future__ import annotations

import json
import threading

import pyarrow.flight as flight

from ballista_tpu.config import _env_int

RELAY_ACTIONS = ("io_block_transport", "io_coalesced_transport")


class FlightResultProxy(flight.FlightServerBase):
    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None):
        kwargs = {}
        scheme = "grpc"
        if tls_cert and tls_key:
            scheme = "grpc+tls"
            with open(tls_cert, "rb") as f:
                cert = f.read()
            with open(tls_key, "rb") as f:
                key = f.read()
            kwargs["tls_certificates"] = [(cert, key)]
            if tls_client_ca:
                with open(tls_client_ca, "rb") as f:
                    kwargs["root_certificates"] = f.read()
                kwargs["verify_client"] = True
        super().__init__(f"{scheme}://{host}:{port}", **kwargs)
        # executor-side dial credentials: (ca, cert, key)
        self.relay_tls = (tls_client_ca, tls_cert, tls_key) if (tls_client_ca and tls_cert) else None
        self.stats = {"relayed_actions": 0, "relayed_gets": 0, "relays_rejected": 0}
        # the proxy multiplexes EVERY external client over one scheduler
        # host, so it gets the same bounded-stream gate as the executors'
        # data plane (same env knobs; no session config here either)
        from ballista_tpu.flight.server import _StreamGate

        self.gate = _StreamGate(
            _env_int("BALLISTA_FLIGHT_MAX_STREAMS", 64),
            _env_int("BALLISTA_FLIGHT_ACCEPT_QUEUE", 128),
        )

    def _gate_acquire(self) -> None:
        try:
            self.gate.acquire()
        except flight.FlightUnavailableError:
            self.stats["relays_rejected"] += 1
            raise

    def _upstream(self, ticket: dict) -> tuple[str, flight.FlightClient]:
        """Dial the owning executor. In a TLS cluster the proxy presents the
        scheduler's own credentials (the executors' data plane requires
        client certs)."""
        from ballista_tpu.flight.client import POOL

        addr = f"{ticket['host']}:{ticket['flight_port']}"
        return addr, POOL.get(addr, tls=self.relay_tls)

    def do_get(self, context, ticket):
        from ballista_tpu.flight.client import POOL

        t = json.loads(ticket.ticket.decode())
        self._gate_acquire()
        addr, client = self._upstream(t)
        try:
            reader = client.do_get(flight.Ticket(json.dumps(t).encode()))
            schema = reader.schema
        except Exception:
            self.gate.release()
            POOL.discard(addr)
            raise
        self.stats["relayed_gets"] += 1

        def gen():
            try:
                for chunk in reader:
                    yield chunk.data
            except Exception:
                POOL.discard(addr)
                raise
            finally:
                self.gate.release()

        return flight.GeneratorStream(schema, gen())

    def do_action(self, context, action):
        from ballista_tpu.flight.client import POOL

        if action.type in RELAY_ACTIONS:
            t = json.loads(action.body.to_pybytes().decode())
            self._gate_acquire()
            try:
                addr, client = self._upstream(t)
                self.stats["relayed_actions"] += 1
                try:
                    # forward the body unchanged — the executor ignores the
                    # routing keys — and pass every Result through verbatim
                    up = flight.Action(action.type, json.dumps(t).encode())
                    for r in client.do_action(up):
                        yield flight.Result(r.body)
                except Exception:
                    POOL.discard(addr)
                    raise
            finally:
                self.gate.release()
            return
        raise flight.FlightServerError(f"unknown action {action.type}")

    def list_actions(self, context):
        return [("io_block_transport", "relay raw IPC blocks from an executor"),
                ("io_coalesced_transport", "relay a framed multi-location block stream")]


def start_flight_proxy(host: str = "0.0.0.0", port: int = 0,
                       tls_cert: str | None = None, tls_key: str | None = None,
                       tls_client_ca: str | None = None) -> tuple[FlightResultProxy, int]:
    server = FlightResultProxy(host, port, tls_cert=tls_cert, tls_key=tls_key,
                               tls_client_ca=tls_client_ca)
    bound = server.port
    t = threading.Thread(target=server.serve, daemon=True, name="flight-proxy")
    t.start()
    return server, bound
