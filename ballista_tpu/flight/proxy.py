"""Scheduler-side Flight result proxy.

Rebuild of BallistaFlightProxyService (scheduler/src/flight_proxy_service.rs:42,114)
+ the client's FlightProxy::External mode (core/src/execution_plans/
distributed_query.rs:754-783): clients that cannot reach executors directly
(NAT, k8s cluster networking) fetch result partitions from the scheduler,
which relays from the owning executor over the raw-block path.

Tickets are the normal fetch tickets plus the executor's {host, flight_port}
so the proxy knows where to relay from.
"""

from __future__ import annotations

import json
import threading

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

BLOCK_SIZE = 8 * 1024 * 1024


def _relay_bytes(ticket: dict, relay_tls: tuple[str, str | None, str | None] | None) -> bytes:
    """Pull the stored IPC bytes from the owning executor (raw-block mode —
    no decode on the proxy hop). In a TLS cluster the proxy dials executors
    with the scheduler's own credentials (the executors' data plane requires
    client certs)."""
    from ballista_tpu.flight.client import POOL

    addr = f"{ticket['host']}:{ticket['flight_port']}"
    client = POOL.get(addr, tls=relay_tls)
    try:
        action = flight.Action("io_block_transport", json.dumps(ticket).encode())
        return b"".join(r.body.to_pybytes() for r in client.do_action(action))
    except Exception:
        POOL.discard(addr)
        raise


class FlightResultProxy(flight.FlightServerBase):
    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None):
        kwargs = {}
        scheme = "grpc"
        if tls_cert and tls_key:
            scheme = "grpc+tls"
            with open(tls_cert, "rb") as f:
                cert = f.read()
            with open(tls_key, "rb") as f:
                key = f.read()
            kwargs["tls_certificates"] = [(cert, key)]
            if tls_client_ca:
                with open(tls_client_ca, "rb") as f:
                    kwargs["root_certificates"] = f.read()
                kwargs["verify_client"] = True
        super().__init__(f"{scheme}://{host}:{port}", **kwargs)
        # executor-side dial credentials: (ca, cert, key)
        self.relay_tls = (tls_client_ca, tls_cert, tls_key) if (tls_client_ca and tls_cert) else None

    def do_get(self, context, ticket):
        t = json.loads(ticket.ticket.decode())
        buf = _relay_bytes(t, self.relay_tls)
        if not buf:
            return flight.RecordBatchStream(pa.table({}))
        reader = ipc.open_stream(pa.BufferReader(buf))
        return flight.RecordBatchStream(reader.read_all())

    def do_action(self, context, action):
        if action.type == "io_block_transport":
            t = json.loads(action.body.to_pybytes().decode())
            buf = _relay_bytes(t, self.relay_tls)
            for off in range(0, len(buf), BLOCK_SIZE):
                yield flight.Result(pa.py_buffer(buf[off : off + BLOCK_SIZE]))
            return
        raise flight.FlightServerError(f"unknown action {action.type}")

    def list_actions(self, context):
        return [("io_block_transport", "relay raw IPC blocks from an executor")]


def start_flight_proxy(host: str = "0.0.0.0", port: int = 0,
                       tls_cert: str | None = None, tls_key: str | None = None,
                       tls_client_ca: str | None = None) -> tuple[FlightResultProxy, int]:
    server = FlightResultProxy(host, port, tls_cert=tls_cert, tls_key=tls_key,
                               tls_client_ca=tls_client_ca)
    bound = server.port
    t = threading.Thread(target=server.serve, daemon=True, name="flight-proxy")
    t.start()
    return server, bound
