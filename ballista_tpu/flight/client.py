"""Flight data-plane client with connection pooling.

Rebuild of BallistaClient + BallistaClientPool (core/src/client.rs:54,
client_pool.rs:34): fetch_partition in decoded-stream mode (do_get) or
raw-block mode (do_action("io_block_transport"), client.rs:321 — ships the
stored IPC bytes and decodes once on the reduce side). Pooled clients are
discarded on error (PooledClient discard-on-error).

Two data-movement optimizations live here:

- Block streams decode through ChainedBufferReader — a file-like view over
  the received block list — instead of re-assembling them with
  b"".join(blocks), which doubled the partition's footprint on the reduce
  side for one decode pass.
- fetch_partitions_flight ships a reduce task's WHOLE want-list for one
  executor in a single io_coalesced_transport RPC. The server frames each
  map output with a JSON header Result, so this client yields per-location
  results as they complete and, when the stream dies, reports exactly which
  location was mid-flight (FetchStreamError.loc_index) — the reader turns
  that into a FetchFailed with the right map identity. Servers that predate
  the action (the native C++ data plane) reject it; that address is cached
  in _NO_COALESCE and the caller falls back to per-location fetches.
"""

from __future__ import annotations

import json
import threading
from typing import Iterator, Sequence

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

from ballista_tpu.config import SHUFFLE_BLOCK_TRANSPORT, SHUFFLE_CHECKSUM_ENABLED
from ballista_tpu.errors import CircuitOpen, DataCorrupted
from ballista_tpu.shuffle.integrity import verify_or_raise as _verify_or_raise
from ballista_tpu.plan.physical import TaskContext
from ballista_tpu.shuffle.types import PartitionLocation

COALESCED_ACTION = "io_coalesced_transport"


class ClientPool:
    def __init__(self):
        self._clients: dict[str, flight.FlightClient] = {}
        self._lock = threading.Lock()

    def get(self, addr: str, tls: tuple[str, str | None, str | None] | None = None) -> flight.FlightClient:
        """tls = (ca_path, cert_path, key_path): dial grpc+tls, presenting a
        client certificate when given (mTLS data plane). Pool entries are
        keyed on (addr, tls) so callers with different transports to one
        address never share a client."""
        key = (addr, tls)
        with self._lock:
            c = self._clients.get(key)
            if c is None:
                if tls:
                    ca, cert, key_path = tls
                    kwargs = {}
                    with open(ca, "rb") as f:
                        kwargs["tls_root_certs"] = f.read()
                    if cert and key_path:
                        with open(cert, "rb") as f:
                            kwargs["cert_chain"] = f.read()
                        with open(key_path, "rb") as f:
                            kwargs["private_key"] = f.read()
                    c = flight.FlightClient(f"grpc+tls://{addr}", **kwargs)
                else:
                    c = flight.FlightClient(f"grpc://{addr}")
                self._clients[key] = c
            return c

    def discard(self, addr: str) -> None:
        with self._lock:
            doomed = [k for k in self._clients if k[0] == addr]
            clients = [self._clients.pop(k) for k in doomed]
        for c in clients:
            try:
                c.close()
            except Exception:
                pass


POOL = ClientPool()

# addresses whose server rejected io_coalesced_transport (native data
# plane): don't re-probe them on every reduce task
# analysis: ignore[bounded-cache] one entry per executor address; bounded by fleet size
_NO_COALESCE: set[str] = set()
_NO_COALESCE_LOCK = threading.Lock()


class CircuitBreaker:
    """Per-address circuit breaker for the Flight data plane.

    Closed → `threshold` CONSECUTIVE failures → open: every fetch to that
    address fails fast with CircuitOpen (an IoError: the shuffle reader's
    retry ladder treats it like any transient fetch failure, so it
    eventually surfaces as FetchFailed and the stage recomputes
    elsewhere) instead of each reduce task independently burning a
    connect timeout against a dead or drowning peer. After `cooldown_s`
    the breaker goes half-open: exactly ONE caller probes the address;
    its outcome closes or re-opens the circuit.

    Orthogonal to _NO_COALESCE (a capability cache, not a health signal):
    CoalesceUnsupported never counts as a breaker failure."""

    def __init__(self, threshold: int | None = None, cooldown_s: float | None = None):
        if threshold is None or cooldown_s is None:
            from ballista_tpu.config import (
                FLIGHT_BREAKER_COOLDOWN_S,
                FLIGHT_BREAKER_THRESHOLD,
                BallistaConfig,
            )

            defaults = BallistaConfig()
            threshold = int(defaults.get(FLIGHT_BREAKER_THRESHOLD)) if threshold is None else threshold
            cooldown_s = float(defaults.get(FLIGHT_BREAKER_COOLDOWN_S)) if cooldown_s is None else cooldown_s
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        # addr -> [consecutive_failures, opened_at_monotonic, probing]
        self._state: dict[str, list] = {}
        self.trips = 0  # observability: times any circuit opened

    def check(self, addr: str) -> None:
        """Gate a fetch: raises CircuitOpen when the circuit is open and
        cooling down; lets exactly one probe through once it elapses."""
        if self.threshold <= 0:
            return
        import time

        with self._lock:
            st = self._state.get(addr)
            if st is None or st[1] == 0.0:
                return
            elapsed = time.monotonic() - st[1]
            if elapsed >= self.cooldown_s and not st[2]:
                st[2] = True  # half-open: this caller is the probe
                return
            raise CircuitOpen(addr, max(0.0, self.cooldown_s - elapsed))

    def success(self, addr: str) -> None:
        with self._lock:
            self._state.pop(addr, None)

    def failure(self, addr: str) -> None:
        if self.threshold <= 0:
            return
        import time

        with self._lock:
            st = self._state.setdefault(addr, [0, 0.0, False])
            st[0] += 1
            if st[1] != 0.0 and st[2]:
                # failed probe: re-open for another full cooldown
                st[1] = time.monotonic()
                st[2] = False
                self.trips += 1
            elif st[1] == 0.0 and st[0] >= self.threshold:
                st[1] = time.monotonic()
                st[2] = False
                self.trips += 1

    def is_open(self, addr: str) -> bool:
        with self._lock:
            st = self._state.get(addr)
            return st is not None and st[1] != 0.0

    def reset(self) -> None:
        with self._lock:
            self._state.clear()


BREAKER = CircuitBreaker()


class CoalesceUnsupported(Exception):
    """The server at this address has no io_coalesced_transport action —
    caller should fall back to per-location fetches."""


class FetchStreamError(Exception):
    """A coalesced stream died while (or before) serving location
    `loc_index` (index into the request's location list). Locations before
    it completed and were already yielded — only the tail needs refetching,
    and the failure is attributed to exactly this map output."""

    def __init__(self, loc_index: int, cause: BaseException):
        super().__init__(f"coalesced fetch failed at location {loc_index}: {cause}")
        self.loc_index = loc_index
        self.cause = cause


class ChainedBufferReader:
    """File-like view over a list of received blocks for ipc.open_stream —
    decodes a block stream without re-assembling it into one contiguous
    bytes object. pyarrow's PythonFile wrapper requires `closed` to be an
    attribute (a method object is truthy = treated as closed) and never
    retries short reads, so read(n) must return exactly n bytes until EOF;
    a span inside one block returns a zero-copy memoryview."""

    closed = False

    def __init__(self, blocks: Sequence) -> None:
        self._blocks = [memoryview(b) for b in blocks if len(memoryview(b))]
        self._bi = 0
        self._off = 0
        self._pos = 0

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def size(self) -> int:
        return sum(len(b) for b in self._blocks)

    def read(self, n: int = -1):
        blocks, bi, off = self._blocks, self._bi, self._off
        if n is None or n < 0:
            n = sum(len(b) for b in blocks[bi:]) - (off if bi < len(blocks) else 0)
        if bi < len(blocks) and len(blocks[bi]) - off >= n:
            out = blocks[bi][off:off + n]
            off += n
            if off == len(blocks[bi]):
                bi, off = bi + 1, 0
            self._bi, self._off = bi, off
            self._pos += n
            return out
        parts = []
        need = n
        while need and bi < len(blocks):
            take = min(need, len(blocks[bi]) - off)
            parts.append(blocks[bi][off:off + take])
            need -= take
            off += take
            if off == len(blocks[bi]):
                bi, off = bi + 1, 0
        self._bi, self._off = bi, off
        out = b"".join(parts)
        self._pos += len(out)
        return out


def _try_parse_header(body) -> dict | None:
    """Sniff an optional leading JSON header Result on the block path.

    New servers answering a want_crc ticket prepend {"nbytes": n, "crc":
    "..."} before the raw blocks; old servers ignore the ticket field and
    send blocks only. Arrow IPC bytes never begin with '{' (the stream
    opens with a length prefix / 0xFFFFFFFF continuation marker), so a
    small first body starting with '{' that parses as JSON with an
    "nbytes" key is unambiguously the header."""
    if body.size == 0 or body.size > 256:
        return None
    raw = body.to_pybytes()
    if raw[:1] != b"{":
        return None
    try:
        h = json.loads(raw.decode())
    except (UnicodeDecodeError, ValueError):
        return None
    return h if isinstance(h, dict) and "nbytes" in h else None


def _ticket(loc: PartitionLocation) -> dict:
    return {
        "path": loc.path,
        "layout": loc.layout,
        "output_partition": loc.output_partition,
        "job_id": loc.job_id,
        "stage_id": loc.stage_id,
    }


def _session_tls(config) -> tuple[str, str | None, str | None] | None:
    from ballista_tpu.config import GRPC_TLS_CA, GRPC_TLS_CERT, GRPC_TLS_KEY

    ca = str(config.get(GRPC_TLS_CA) or "")
    if not ca:
        return None
    return (ca, str(config.get(GRPC_TLS_CERT) or "") or None,
            str(config.get(GRPC_TLS_KEY) or "") or None)


def _route(ctx: TaskContext, loc: PartitionLocation, body: dict) -> tuple[str, dict]:
    """(dial address, wire body) — external mode relays through the
    scheduler's Flight proxy with the owning executor named in the body."""
    from ballista_tpu.config import FLIGHT_PROXY

    proxy = str(ctx.config.get(FLIGHT_PROXY) or "")
    if proxy:
        return proxy, {**body, "host": loc.host, "flight_port": loc.flight_port}
    return f"{loc.host}:{loc.flight_port}", body


def fetch_partition_flight(loc: PartitionLocation, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
    body = _ticket(loc)
    if bool(ctx.config.get(SHUFFLE_CHECKSUM_ENABLED)):
        # opt-in: ask the server to prepend its stored checksum header on
        # the block path (old servers ignore the field — no header comes
        # back and the bytes stay unchecked, exactly the legacy behavior)
        body["want_crc"] = True
    addr, ticket = _route(ctx, loc, body)
    BREAKER.check(addr)  # fail fast while the address's circuit is open
    client = POOL.get(addr, tls=_session_tls(ctx.config))
    try:
        if bool(ctx.config.get(SHUFFLE_BLOCK_TRANSPORT)):
            action = flight.Action("io_block_transport", json.dumps(ticket).encode())
            results = list(client.do_action(action))
            expected = declared = None
            if results:
                h = _try_parse_header(results[0].body)
                if h is not None:
                    expected = h.get("crc")
                    declared = int(h["nbytes"])
                    results = results[1:]
            blocks = [r.body for r in results]
            if not blocks and not declared:
                BREAKER.success(addr)
                return
            where = f"{loc.path}#p{loc.output_partition}"
            total = sum(b.size for b in blocks)
            if declared is not None and total != declared:
                raise DataCorrupted(where, f"{declared} bytes", f"{total} bytes",
                                    detail="stream length != declared")
            # verify the RAW received bytes before handing them to the
            # Arrow decoder: a flip surfaces as typed corruption, not an
            # opaque decode crash (or silent wrong rows)
            _verify_or_raise(blocks, expected, where)
            reader = ipc.open_stream(ChainedBufferReader(blocks))
            yield from reader
        else:
            t = flight.Ticket(json.dumps(ticket).encode())
            for chunk in client.do_get(t):
                yield chunk.data
        BREAKER.success(addr)
    except DataCorrupted:
        # corruption is a DISK/serve-path signal, not connection health:
        # it must not open the circuit (the retry-once refetch needs the
        # address reachable) and the pooled connection is fine
        raise
    except Exception:
        BREAKER.failure(addr)
        POOL.discard(addr)
        raise


def _is_unknown_action(e: BaseException) -> bool:
    return "unknown action" in str(e).lower()


def fetch_partitions_flight(locs: Sequence[PartitionLocation], ctx: TaskContext
                            ) -> Iterator[tuple[int, list[pa.RecordBatch], int]]:
    """Coalesced fetch: every location (all owned by ONE executor) streams
    back in a single RPC. Yields (index, batches, nbytes) per location, in
    request order, as each completes. Raises CoalesceUnsupported when the
    server lacks the action (native data plane) and FetchStreamError with
    the first incomplete location's index when the stream dies mid-flight.
    """
    addr, body = _route(ctx, locs[0], {"locations": [_ticket(l) for l in locs]})
    with _NO_COALESCE_LOCK:
        if addr in _NO_COALESCE:
            raise CoalesceUnsupported(addr)
    BREAKER.check(addr)  # fail fast while the address's circuit is open
    client = POOL.get(addr, tls=_session_tls(ctx.config))
    action = flight.Action(COALESCED_ACTION, json.dumps(body).encode())

    completed = 0          # locations fully received = first incomplete idx
    cur_need = 0           # bytes still owed for the current location
    cur_blocks: list = []
    cur_crc: str | None = None

    def fail(e: BaseException):
        if _is_unknown_action(e):
            # capability miss, not a health signal: never trips the breaker
            with _NO_COALESCE_LOCK:
                _NO_COALESCE.add(addr)
            return CoalesceUnsupported(addr)
        BREAKER.failure(addr)
        POOL.discard(addr)
        return FetchStreamError(completed, e)

    try:
        results = iter(client.do_action(action))
    except Exception as e:
        raise fail(e) from e
    while True:
        try:
            r = next(results)
        except StopIteration:
            break
        except Exception as e:
            raise fail(e) from e
        if cur_need == 0:
            # header Result: {"i": index, "nbytes": n, "crc": optional}
            h = json.loads(r.body.to_pybytes().decode())
            cur_need = int(h["nbytes"])
            cur_crc = h.get("crc")
            cur_blocks = []
            if cur_need == 0:
                yield completed, [], 0
                completed += 1
            continue
        cur_blocks.append(r.body)
        cur_need -= r.body.size
        if cur_need == 0:
            nbytes = sum(b.size for b in cur_blocks)
            if cur_crc:
                try:
                    _verify_or_raise(
                        cur_blocks, cur_crc,
                        f"{locs[completed].path}#p{locs[completed].output_partition}")
                except DataCorrupted as e:
                    # NOT fail(e): corruption must not trip the breaker or
                    # drop the pooled connection — the reader's retry-once
                    # refetch targets this same address
                    raise FetchStreamError(completed, e) from e
            try:
                batches = list(ipc.open_stream(ChainedBufferReader(cur_blocks)))
            except Exception as e:
                raise FetchStreamError(completed, e) from e
            cur_blocks = []
            yield completed, batches, nbytes
            completed += 1
    if cur_need:
        # server hung up inside the current location's data
        BREAKER.failure(addr)
        raise FetchStreamError(completed, EOFError(
            f"stream ended {cur_need} bytes short of location {completed}"))
    if completed < len(locs):
        BREAKER.failure(addr)
        raise FetchStreamError(completed, EOFError(
            f"stream served {completed}/{len(locs)} locations"))
    BREAKER.success(addr)


def fetch_partitions_bytes(addr: str, tickets: Sequence[dict],
                           tls: tuple[str, str | None, str | None] | None = None,
                           ) -> Iterator[tuple[int, bytes, str | None]]:
    """Raw-bytes coalesced fetch for shuffle MIGRATION (drain handoff,
    docs/lifecycle.md): streams every ticket's stored IPC byte range from
    `addr` over the existing io_coalesced_transport framing, verifies each
    range against the source's declared checksum BEFORE yielding, and
    returns the raw bytes untouched — the destination commits them as-is
    (no decode/re-encode), so the migrated file is byte-identical to the
    source range. Yields (index, bytes, crc_or_None) in request order."""
    client = POOL.get(addr, tls=tls)
    action = flight.Action(COALESCED_ACTION, json.dumps({"locations": list(tickets)}).encode())
    completed = 0
    cur_need = 0
    cur_blocks: list = []
    cur_crc: str | None = None
    for r in client.do_action(action):
        if cur_need == 0:
            h = json.loads(r.body.to_pybytes().decode())
            cur_need = int(h["nbytes"])
            cur_crc = h.get("crc")
            cur_blocks = []
            if cur_need == 0:
                yield completed, b"", None
                completed += 1
            continue
        cur_blocks.append(r.body)
        cur_need -= r.body.size
        if cur_need == 0:
            if cur_crc:
                tk = tickets[completed]
                _verify_or_raise(
                    cur_blocks, cur_crc,
                    f"migrate {tk.get('path')}#p{tk.get('output_partition', 0)}")
            yield completed, b"".join(b.to_pybytes() for b in cur_blocks), cur_crc
            completed += 1
    if cur_need or completed < len(tickets):
        raise EOFError(
            f"migration stream from {addr} served {completed}/{len(tickets)} locations")


def remove_job_data(host: str, flight_port: int, job_id: str) -> None:
    client = POOL.get(f"{host}:{flight_port}")
    action = flight.Action("remove_job_data", json.dumps({"job_id": job_id}).encode())
    list(client.do_action(action))
