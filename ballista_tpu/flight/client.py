"""Flight data-plane client with connection pooling.

Rebuild of BallistaClient + BallistaClientPool (core/src/client.rs:54,
client_pool.rs:34): fetch_partition in decoded-stream mode (do_get) or
raw-block mode (do_action("io_block_transport"), client.rs:321 — ships the
stored IPC bytes and decodes once on the reduce side). Pooled clients are
discarded on error (PooledClient discard-on-error).

Two data-movement optimizations live here:

- Block streams decode through ChainedBufferReader — a file-like view over
  the received block list — instead of re-assembling them with
  b"".join(blocks), which doubled the partition's footprint on the reduce
  side for one decode pass.
- fetch_partitions_flight ships a reduce task's WHOLE want-list for one
  executor in a single io_coalesced_transport RPC. The server frames each
  map output with a JSON header Result, so this client yields per-location
  results as they complete and, when the stream dies, reports exactly which
  location was mid-flight (FetchStreamError.loc_index) — the reader turns
  that into a FetchFailed with the right map identity. Servers that predate
  the action (the native C++ data plane) reject it; that address is cached
  in _NO_COALESCE and the caller falls back to per-location fetches.
"""

from __future__ import annotations

import json
import threading
from typing import Iterator, Sequence

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

from ballista_tpu.config import SHUFFLE_BLOCK_TRANSPORT
from ballista_tpu.plan.physical import TaskContext
from ballista_tpu.shuffle.types import PartitionLocation

COALESCED_ACTION = "io_coalesced_transport"


class ClientPool:
    def __init__(self):
        self._clients: dict[str, flight.FlightClient] = {}
        self._lock = threading.Lock()

    def get(self, addr: str, tls: tuple[str, str | None, str | None] | None = None) -> flight.FlightClient:
        """tls = (ca_path, cert_path, key_path): dial grpc+tls, presenting a
        client certificate when given (mTLS data plane). Pool entries are
        keyed on (addr, tls) so callers with different transports to one
        address never share a client."""
        key = (addr, tls)
        with self._lock:
            c = self._clients.get(key)
            if c is None:
                if tls:
                    ca, cert, key_path = tls
                    kwargs = {}
                    with open(ca, "rb") as f:
                        kwargs["tls_root_certs"] = f.read()
                    if cert and key_path:
                        with open(cert, "rb") as f:
                            kwargs["cert_chain"] = f.read()
                        with open(key_path, "rb") as f:
                            kwargs["private_key"] = f.read()
                    c = flight.FlightClient(f"grpc+tls://{addr}", **kwargs)
                else:
                    c = flight.FlightClient(f"grpc://{addr}")
                self._clients[key] = c
            return c

    def discard(self, addr: str) -> None:
        with self._lock:
            doomed = [k for k in self._clients if k[0] == addr]
            clients = [self._clients.pop(k) for k in doomed]
        for c in clients:
            try:
                c.close()
            except Exception:
                pass


POOL = ClientPool()

# addresses whose server rejected io_coalesced_transport (native data
# plane): don't re-probe them on every reduce task
_NO_COALESCE: set[str] = set()
_NO_COALESCE_LOCK = threading.Lock()


class CoalesceUnsupported(Exception):
    """The server at this address has no io_coalesced_transport action —
    caller should fall back to per-location fetches."""


class FetchStreamError(Exception):
    """A coalesced stream died while (or before) serving location
    `loc_index` (index into the request's location list). Locations before
    it completed and were already yielded — only the tail needs refetching,
    and the failure is attributed to exactly this map output."""

    def __init__(self, loc_index: int, cause: BaseException):
        super().__init__(f"coalesced fetch failed at location {loc_index}: {cause}")
        self.loc_index = loc_index
        self.cause = cause


class ChainedBufferReader:
    """File-like view over a list of received blocks for ipc.open_stream —
    decodes a block stream without re-assembling it into one contiguous
    bytes object. pyarrow's PythonFile wrapper requires `closed` to be an
    attribute (a method object is truthy = treated as closed) and never
    retries short reads, so read(n) must return exactly n bytes until EOF;
    a span inside one block returns a zero-copy memoryview."""

    closed = False

    def __init__(self, blocks: Sequence) -> None:
        self._blocks = [memoryview(b) for b in blocks if len(memoryview(b))]
        self._bi = 0
        self._off = 0
        self._pos = 0

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def size(self) -> int:
        return sum(len(b) for b in self._blocks)

    def read(self, n: int = -1):
        blocks, bi, off = self._blocks, self._bi, self._off
        if n is None or n < 0:
            n = sum(len(b) for b in blocks[bi:]) - (off if bi < len(blocks) else 0)
        if bi < len(blocks) and len(blocks[bi]) - off >= n:
            out = blocks[bi][off:off + n]
            off += n
            if off == len(blocks[bi]):
                bi, off = bi + 1, 0
            self._bi, self._off = bi, off
            self._pos += n
            return out
        parts = []
        need = n
        while need and bi < len(blocks):
            take = min(need, len(blocks[bi]) - off)
            parts.append(blocks[bi][off:off + take])
            need -= take
            off += take
            if off == len(blocks[bi]):
                bi, off = bi + 1, 0
        self._bi, self._off = bi, off
        out = b"".join(parts)
        self._pos += len(out)
        return out


def _ticket(loc: PartitionLocation) -> dict:
    return {
        "path": loc.path,
        "layout": loc.layout,
        "output_partition": loc.output_partition,
        "job_id": loc.job_id,
        "stage_id": loc.stage_id,
    }


def _session_tls(config) -> tuple[str, str | None, str | None] | None:
    from ballista_tpu.config import GRPC_TLS_CA, GRPC_TLS_CERT, GRPC_TLS_KEY

    ca = str(config.get(GRPC_TLS_CA) or "")
    if not ca:
        return None
    return (ca, str(config.get(GRPC_TLS_CERT) or "") or None,
            str(config.get(GRPC_TLS_KEY) or "") or None)


def _route(ctx: TaskContext, loc: PartitionLocation, body: dict) -> tuple[str, dict]:
    """(dial address, wire body) — external mode relays through the
    scheduler's Flight proxy with the owning executor named in the body."""
    from ballista_tpu.config import FLIGHT_PROXY

    proxy = str(ctx.config.get(FLIGHT_PROXY) or "")
    if proxy:
        return proxy, {**body, "host": loc.host, "flight_port": loc.flight_port}
    return f"{loc.host}:{loc.flight_port}", body


def fetch_partition_flight(loc: PartitionLocation, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
    addr, ticket = _route(ctx, loc, _ticket(loc))
    client = POOL.get(addr, tls=_session_tls(ctx.config))
    try:
        if bool(ctx.config.get(SHUFFLE_BLOCK_TRANSPORT)):
            action = flight.Action("io_block_transport", json.dumps(ticket).encode())
            blocks = [r.body for r in client.do_action(action)]
            if not blocks:
                return
            reader = ipc.open_stream(ChainedBufferReader(blocks))
            yield from reader
        else:
            t = flight.Ticket(json.dumps(ticket).encode())
            for chunk in client.do_get(t):
                yield chunk.data
    except Exception:
        POOL.discard(addr)
        raise


def _is_unknown_action(e: BaseException) -> bool:
    return "unknown action" in str(e).lower()


def fetch_partitions_flight(locs: Sequence[PartitionLocation], ctx: TaskContext
                            ) -> Iterator[tuple[int, list[pa.RecordBatch], int]]:
    """Coalesced fetch: every location (all owned by ONE executor) streams
    back in a single RPC. Yields (index, batches, nbytes) per location, in
    request order, as each completes. Raises CoalesceUnsupported when the
    server lacks the action (native data plane) and FetchStreamError with
    the first incomplete location's index when the stream dies mid-flight.
    """
    addr, body = _route(ctx, locs[0], {"locations": [_ticket(l) for l in locs]})
    with _NO_COALESCE_LOCK:
        if addr in _NO_COALESCE:
            raise CoalesceUnsupported(addr)
    client = POOL.get(addr, tls=_session_tls(ctx.config))
    action = flight.Action(COALESCED_ACTION, json.dumps(body).encode())

    completed = 0          # locations fully received = first incomplete idx
    cur_need = 0           # bytes still owed for the current location
    cur_blocks: list = []

    def fail(e: BaseException):
        if _is_unknown_action(e):
            with _NO_COALESCE_LOCK:
                _NO_COALESCE.add(addr)
            return CoalesceUnsupported(addr)
        POOL.discard(addr)
        return FetchStreamError(completed, e)

    try:
        results = iter(client.do_action(action))
    except Exception as e:
        raise fail(e) from e
    while True:
        try:
            r = next(results)
        except StopIteration:
            break
        except Exception as e:
            raise fail(e) from e
        if cur_need == 0:
            # header Result: {"i": index, "nbytes": n}
            h = json.loads(r.body.to_pybytes().decode())
            cur_need = int(h["nbytes"])
            cur_blocks = []
            if cur_need == 0:
                yield completed, [], 0
                completed += 1
            continue
        cur_blocks.append(r.body)
        cur_need -= r.body.size
        if cur_need == 0:
            nbytes = sum(b.size for b in cur_blocks)
            try:
                batches = list(ipc.open_stream(ChainedBufferReader(cur_blocks)))
            except Exception as e:
                raise FetchStreamError(completed, e) from e
            cur_blocks = []
            yield completed, batches, nbytes
            completed += 1
    if cur_need:
        # server hung up inside the current location's data
        raise FetchStreamError(completed, EOFError(
            f"stream ended {cur_need} bytes short of location {completed}"))
    if completed < len(locs):
        raise FetchStreamError(completed, EOFError(
            f"stream served {completed}/{len(locs)} locations"))


def remove_job_data(host: str, flight_port: int, job_id: str) -> None:
    client = POOL.get(f"{host}:{flight_port}")
    action = flight.Action("remove_job_data", json.dumps({"job_id": job_id}).encode())
    list(client.do_action(action))
