"""Flight data-plane client with connection pooling.

Rebuild of BallistaClient + BallistaClientPool (core/src/client.rs:54,
client_pool.rs:34): fetch_partition in decoded-stream mode (do_get) or
raw-block mode (do_action("io_block_transport"), client.rs:321 — ships the
stored IPC bytes and decodes once on the reduce side). Pooled clients are
discarded on error (PooledClient discard-on-error).
"""

from __future__ import annotations

import json
import threading
from typing import Iterator

import pyarrow as pa
import pyarrow.flight as flight
import pyarrow.ipc as ipc

from ballista_tpu.config import SHUFFLE_BLOCK_TRANSPORT
from ballista_tpu.plan.physical import TaskContext
from ballista_tpu.shuffle.types import PartitionLocation


class ClientPool:
    def __init__(self):
        self._clients: dict[str, flight.FlightClient] = {}
        self._lock = threading.Lock()

    def get(self, addr: str, tls: tuple[str, str | None, str | None] | None = None) -> flight.FlightClient:
        """tls = (ca_path, cert_path, key_path): dial grpc+tls, presenting a
        client certificate when given (mTLS data plane). Pool entries are
        keyed on (addr, tls) so callers with different transports to one
        address never share a client."""
        key = (addr, tls)
        with self._lock:
            c = self._clients.get(key)
            if c is None:
                if tls:
                    ca, cert, key = tls
                    kwargs = {}
                    with open(ca, "rb") as f:
                        kwargs["tls_root_certs"] = f.read()
                    if cert and key:
                        with open(cert, "rb") as f:
                            kwargs["cert_chain"] = f.read()
                        with open(key, "rb") as f:
                            kwargs["private_key"] = f.read()
                    c = flight.FlightClient(f"grpc+tls://{addr}", **kwargs)
                else:
                    c = flight.FlightClient(f"grpc://{addr}")
                self._clients[key] = c
            return c

    def discard(self, addr: str) -> None:
        with self._lock:
            doomed = [k for k in self._clients if k[0] == addr]
            clients = [self._clients.pop(k) for k in doomed]
        for c in clients:
            try:
                c.close()
            except Exception:
                pass


POOL = ClientPool()


def _ticket(loc: PartitionLocation) -> dict:
    return {
        "path": loc.path,
        "layout": loc.layout,
        "output_partition": loc.output_partition,
        "job_id": loc.job_id,
        "stage_id": loc.stage_id,
    }


def _session_tls(config) -> tuple[str, str | None, str | None] | None:
    from ballista_tpu.config import GRPC_TLS_CA, GRPC_TLS_CERT, GRPC_TLS_KEY

    ca = str(config.get(GRPC_TLS_CA) or "")
    if not ca:
        return None
    return (ca, str(config.get(GRPC_TLS_CERT) or "") or None,
            str(config.get(GRPC_TLS_KEY) or "") or None)


def fetch_partition_flight(loc: PartitionLocation, ctx: TaskContext) -> Iterator[pa.RecordBatch]:
    from ballista_tpu.config import FLIGHT_PROXY

    proxy = str(ctx.config.get(FLIGHT_PROXY) or "")
    if proxy:
        # external mode (distributed_query.rs:754-783): relay through the
        # scheduler's Flight proxy; the ticket carries the owning executor
        addr = proxy
        ticket = {**_ticket(loc), "host": loc.host, "flight_port": loc.flight_port}
    else:
        addr = f"{loc.host}:{loc.flight_port}"
        ticket = _ticket(loc)
    client = POOL.get(addr, tls=_session_tls(ctx.config))
    try:
        if bool(ctx.config.get(SHUFFLE_BLOCK_TRANSPORT)):
            action = flight.Action("io_block_transport", json.dumps(ticket).encode())
            blocks = [r.body.to_pybytes() for r in client.do_action(action)]
            if not blocks:
                return
            buf = b"".join(blocks)
            reader = ipc.open_stream(pa.BufferReader(buf))
            yield from reader
        else:
            t = flight.Ticket(json.dumps(ticket).encode())
            for chunk in client.do_get(t):
                yield chunk.data
    except Exception:
        POOL.discard(addr)
        raise


def remove_job_data(host: str, flight_port: int, job_id: str) -> None:
    client = POOL.get(f"{host}:{flight_port}")
    action = flight.Action("remove_job_data", json.dumps({"job_id": job_id}).encode())
    list(client.do_action(action))
