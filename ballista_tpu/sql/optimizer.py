"""Logical optimizer.

Passes, in order:
1. fold_constants     — literal arithmetic, date ± interval
2. factor_or          — (A∧X)∨(A∧Y) → A ∧ (X∨Y)   (q19's join key extraction)
3. decorrelate        — scalar/IN/EXISTS subqueries → joins
4. extract_joins      — Filter over CrossJoin chains → greedy left-deep Joins
5. push_filters       — single-side conjuncts below joins, scan-level
                        predicates into TableScan.filters
6. prune_columns      — projection pushdown into TableScan

The reference gets all of this from DataFusion's optimizer; the shapes the
distributed planner expects downstream (stage boundaries around joins and
aggregates) are the same.

NULL-semantics caveat: NOT IN (subquery) lowers to an anti join, which is
only equivalent when neither side of the key is NULL (true for every TPC-H
key column). A general three-valued-logic rewrite is future work.
"""

from __future__ import annotations

import datetime as _dt



from ballista_tpu.errors import PlanningError
from ballista_tpu.plan.expressions import (
    Alias,
    Between,
    BinaryExpr,
    Column,
    Exists,
    Expr,
    InList,
    InSubquery,
    IsNotNull,
    IsNull,
    Literal,
    Negative,
    Not,
    ScalarFunction,
    ScalarSubquery,
    and_,
    collect_columns,
    expr_any,
    split_conjunction,
    transform_expr,
)
from ballista_tpu.plan.logical import (
    Aggregate,
    CrossJoin,
    Distinct,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Projection,
    Sort,
    SubqueryAlias,
    TableScan,
    Union,
    transform_plan_up,
    Window,
)


def optimize(plan: LogicalPlan) -> LogicalPlan:
    plan = rewrite_exprs(plan, fold_constants)
    plan = rewrite_exprs(plan, factor_or)
    plan = Decorrelator().run(plan)
    plan = transform_plan_up(plan, extract_joins)
    plan = push_filters(plan)
    plan = prune_columns(plan)
    return plan


# -- 1. constant folding ----------------------------------------------------


def fold_constants(e: Expr) -> Expr:
    def fn(x: Expr) -> Expr:
        if isinstance(x, BinaryExpr) and isinstance(x.left, Literal) and isinstance(x.right, Literal):
            lv, rv = x.left.value, x.right.value
            # date ± interval
            if isinstance(lv, _dt.date) and isinstance(rv, tuple):
                return Literal(_date_add(lv, rv, -1 if x.op == "-" else 1))
            if isinstance(rv, _dt.date) and isinstance(lv, tuple) and x.op == "+":
                return Literal(_date_add(rv, lv, 1))
            import decimal as _dec

            if (isinstance(lv, (int, _dec.Decimal)) and isinstance(rv, (int, _dec.Decimal))
                    and (isinstance(lv, _dec.Decimal) or isinstance(rv, _dec.Decimal))
                    and not isinstance(lv, bool) and not isinstance(rv, bool)):
                # exact decimal folding for +,-,* at decimal256's 76-digit
                # cap (Python's default 28-digit context would silently
                # round wide folds the runtime computes exactly); division
                # folds nothing — the engine plans it as float64
                with _dec.localcontext() as ctx76:
                    ctx76.prec = 76
                    if x.op == "+":
                        return Literal(lv + rv)
                    if x.op == "-":
                        return Literal(lv - rv)
                    if x.op == "*":
                        return Literal(lv * rv)
                return x
            if isinstance(lv, (int, float)) and isinstance(rv, (int, float)) and not isinstance(lv, bool) and not isinstance(rv, bool):
                try:
                    if x.op == "+":
                        return Literal(lv + rv)
                    if x.op == "-":
                        return Literal(lv - rv)
                    if x.op == "*":
                        return Literal(lv * rv)
                    if x.op == "/":
                        return Literal(lv / rv)
                except ZeroDivisionError:
                    return x
        if isinstance(x, Negative) and isinstance(x.expr, Literal):
            import decimal as _dec

            if isinstance(x.expr.value, (int, float, _dec.Decimal)):
                return Literal(-x.expr.value)
        return x

    return transform_expr(e, fn)


def _date_add(d: _dt.date, interval: tuple, sign: int) -> _dt.date:
    n, unit = interval
    n *= sign
    if unit == "day":
        return d + _dt.timedelta(days=n)
    if unit in ("month", "year"):
        months = n * 12 if unit == "year" else n
        total = d.year * 12 + (d.month - 1) + months
        y, m = divmod(total, 12)
        day = min(d.day, _days_in_month(y, m + 1))
        return _dt.date(y, m + 1, day)
    raise PlanningError(f"bad interval unit {unit}")


def _days_in_month(y: int, m: int) -> int:
    if m == 12:
        return 31
    return (_dt.date(y, m + 1, 1) - _dt.timedelta(days=1)).day


# -- 2. OR factoring --------------------------------------------------------


def factor_or(e: Expr) -> Expr:
    def fn(x: Expr) -> Expr:
        if isinstance(x, BinaryExpr) and x.op == "or":
            branches = _split_disjunction(x)
            if len(branches) < 2:
                return x
            conj_sets = [split_conjunction(b) for b in branches]
            common = [c for c in conj_sets[0] if all(c in cs for cs in conj_sets[1:])]
            if not common:
                return x
            remainders = []
            for cs in conj_sets:
                rem = [c for c in cs if c not in common]
                remainders.append(and_(*rem) if rem else Literal(True))
            out = and_(*common)
            rem_or = remainders[0]
            for r in remainders[1:]:
                rem_or = BinaryExpr(rem_or, "or", r)
            if not all(isinstance(r, Literal) and r.value is True for r in remainders):
                out = BinaryExpr(out, "and", rem_or)
            return out
        return x

    return transform_expr(e, fn)


def _split_disjunction(e: Expr) -> list[Expr]:
    if isinstance(e, BinaryExpr) and e.op == "or":
        return _split_disjunction(e.left) + _split_disjunction(e.right)
    return [e]


# -- expression rewriting over a whole plan ---------------------------------


def rewrite_exprs(plan: LogicalPlan, fn) -> LogicalPlan:
    def node(p: LogicalPlan) -> LogicalPlan:
        if isinstance(p, Filter):
            return Filter(p.input, fn(p.predicate))
        if isinstance(p, Projection):
            return Projection(p.input, [fn(e) for e in p.exprs])
        if isinstance(p, Aggregate):
            return Aggregate(p.input, [fn(e) for e in p.group_exprs], [fn(e) for e in p.agg_exprs])
        if isinstance(p, Join) and p.filter is not None:
            return replace_join(p, filter=fn(p.filter))
        return p

    # also rewrite inside subquery plans
    def node_with_subqueries(p: LogicalPlan) -> LogicalPlan:
        p = node(p)
        return p

    return transform_plan_up(plan, node_with_subqueries)


def replace_join(j: Join, **kw) -> Join:
    out = Join(
        kw.get("left", j.left),
        kw.get("right", j.right),
        kw.get("on", j.on),
        kw.get("join_type", j.join_type),
        kw.get("filter", j.filter),
    )
    return out


# -- 3. decorrelation -------------------------------------------------------


class Decorrelator:
    def __init__(self):
        self.counter = 0

    def run(self, plan: LogicalPlan) -> LogicalPlan:
        def fn(p: LogicalPlan) -> LogicalPlan:
            if isinstance(p, Filter) and _has_subquery(p.predicate):
                return self.rewrite_filter(p)
            if isinstance(p, Projection) and any(_has_subquery(e) for e in p.exprs):
                return self.rewrite_projection(p)
            return p

        return transform_plan_up(plan, fn)

    def rewrite_projection(self, proj: Projection) -> LogicalPlan:
        """Scalar subqueries in the SELECT list (q9's CASE-of-aggregates
        shape): each lowers exactly like a WHERE-clause scalar — join/
        cross-join against the projection's input."""
        input_plan: LogicalPlan = proj.input
        new_exprs: list[Expr] = []
        for e in proj.exprs:
            if _has_subquery(e):
                orig_name = e.output_name()
                for sq in _collect_scalar_subqueries(e):
                    input_plan, repl = self._plan_scalar(
                        input_plan, self.run(sq.plan), join_type="left")
                    e = _replace_node(e, sq, repl)
                if _has_subquery(e):
                    raise PlanningError(
                        "only scalar subqueries are supported in the SELECT list")
                if e.output_name() != orig_name:  # don't leak __value
                    e = Alias(e, orig_name)
            new_exprs.append(e)
        return Projection(input_plan, new_exprs)

    def rewrite_filter(self, f: Filter) -> LogicalPlan:
        # Build the join tree from subquery-free conjuncts FIRST so the
        # subquery joins attach on top of a proper join tree instead of
        # burying the cross-join chain beneath them.
        conjs = split_conjunction(f.predicate)
        plain = [c for c in conjs if not _has_subquery(c)]
        with_sq = [c for c in conjs if _has_subquery(c)]
        input_plan: LogicalPlan = f.input
        if plain:
            input_plan = extract_joins(Filter(input_plan, and_(*plain)))
        remaining: list[Expr] = []
        for conj in with_sq:
            input_plan, kept = self.rewrite_conjunct(input_plan, conj)
            if kept is not None:
                remaining.append(kept)
        if remaining:
            return Filter(input_plan, and_(*remaining))
        return input_plan

    def rewrite_conjunct(self, outer: LogicalPlan, conj: Expr):
        # EXISTS / NOT EXISTS → semi / anti join, conjunct consumed
        if isinstance(conj, Exists) or (isinstance(conj, Not) and isinstance(conj.expr, Exists)):
            negated = isinstance(conj, Not) or (isinstance(conj, Exists) and conj.negated)
            ex = conj.expr if isinstance(conj, Not) else conj
            sub = self.run(ex.plan)
            keys, residual, sub = self._extract_correlation(sub, outer.schema, exists=True)
            if not keys and residual is None:
                raise PlanningError("uncorrelated EXISTS not supported")
            jt = "left_anti" if negated else "left_semi"
            return Join(outer, sub, keys, jt, residual), None
        # IN / NOT IN subquery → semi / anti join on first output column
        if isinstance(conj, InSubquery):
            sub = self.run(conj.plan)
            keys, residual, sub = self._extract_correlation(sub, outer.schema)
            f0 = sub.schema.field(0)
            keys = [(conj.expr, Column(f0.name, f0.qualifier))] + keys
            jt = "left_anti" if conj.negated else "left_semi"
            return Join(outer, sub, keys, jt, residual), None
        # scalar subqueries anywhere inside the conjunct
        if _has_subquery(conj):
            new_conj = conj
            subs = _collect_scalar_subqueries(conj)
            for sq in subs:
                outer, repl = self._plan_scalar(outer, self.run(sq.plan))
                new_conj = _replace_node(new_conj, sq, repl)
            # EXISTS nested under OR/NOT (not a top-level conjunct, so the
            # semi/anti-join lowering can't consume it) → MARK join: LEFT
            # JOIN a deduped projection of the correlation keys and replace
            # the EXISTS with a match-marker null test (the reference gets
            # this from DataFusion's mark-join decorrelation)
            for ex in _collect_exists(new_conj):
                outer, repl = self._plan_mark_exists(outer, ex)
                new_conj = _replace_node(new_conj, ex, repl)
            # IN (subquery) nested under OR/NOT: an UNCORRELATED subquery
            # evaluates EAGERLY at planning time and inlines as a literal
            # IN list (q45's `zip IN (...) OR item_id IN (subq)`); a
            # correlated one takes the mark-join path like EXISTS
            for isq in _collect_in_subqueries(new_conj):
                sub = self.run(isq.plan)
                # exists=True drops projections above the correlated filter so
                # correlation keys keep their qualified below-projection form;
                # the IN value is the projection's first expr, inlined
                keys, residual, sub2 = self._extract_correlation(
                    sub, outer.schema, exists=True)
                if not keys and residual is None:
                    values = _eval_uncorrelated_column(sub)
                    new_conj = _replace_node(
                        new_conj, isq, InList(isq.expr, tuple(values), isq.negated))
                else:
                    keys = [(isq.expr, _first_output_expr(sub))] + keys
                    outer, repl = self._plan_mark(outer, sub2, keys, residual,
                                                  negated=isq.negated)
                    new_conj = _replace_node(new_conj, isq, repl)
            return outer, new_conj
        return outer, conj

    def _plan_mark_exists(self, outer: LogicalPlan, ex: Exists):
        sub = self.run(ex.plan)
        keys, residual, sub2 = self._extract_correlation(sub, outer.schema, exists=True)
        if not keys and residual is None:
            raise PlanningError("uncorrelated EXISTS not supported")
        return self._plan_mark(outer, sub2, keys, residual, negated=ex.negated)

    def _plan_mark(self, outer: LogicalPlan, sub2: LogicalPlan, keys, residual,
                   negated: bool):
        """LEFT JOIN `outer` against the deduped correlation keys of `sub2`;
        the join's key columns double as the match marker. NULL-semantics
        caveat (same as the NOT IN inline path): a NULL probe key yields
        false where SQL says NULL — indistinguishable under WHERE unless
        wrapped in NOT."""
        if residual is not None:
            raise PlanningError(
                "correlated subquery under OR with non-equi correlation is unsupported")
        self.counter += 1
        alias = f"__mark{self.counter}"
        proj = Projection(sub2, [Alias(ik, f"__mk{i}") for i, (_, ik) in enumerate(keys)])
        build = SubqueryAlias(Distinct(proj), alias)
        join_on = [(ok, Column(f"__mk{i}", alias)) for i, (ok, _) in enumerate(keys)]
        new_outer = Join(outer, build, join_on, "left", None)
        mark = Column("__mk0", alias)
        return new_outer, (IsNull(mark) if negated else IsNotNull(mark))

    # ------------------------------------------------------------------

    def _extract_correlation(self, sub: LogicalPlan, outer_schema, exists: bool = False):
        """Pull conjuncts referencing outer columns out of the subplan's
        top-reachable Filter. Returns (equi_keys, residual_filter, new_sub).

        For EXISTS the select list is semantically void (only row existence
        matters), so Projection/Distinct nodes ABOVE the correlated Filter
        are DROPPED — `EXISTS (SELECT 1 FROM t WHERE t.k = outer.k)` must
        not narrow the build side to the literal and lose the correlation
        columns. Projections BELOW the filter stay: a derived table's
        renames/computed columns are what the extracted keys reference."""
        keys: list[tuple[Expr, Expr]] = []
        residual: list[Expr] = []

        def walk(p: LogicalPlan, above_filter: bool = True) -> LogicalPlan:
            if exists and above_filter and isinstance(p, (Projection, Distinct)):
                return walk(p.children()[0], above_filter)
            if isinstance(p, (Projection, SubqueryAlias, Distinct)):
                inner = walk(p.children()[0], above_filter)
                out = p.with_children([inner])
                return out
            if isinstance(p, Filter):
                inner_schema = p.input.schema
                keep: list[Expr] = []
                for c in split_conjunction(p.predicate):
                    if _references_outer(c, inner_schema):
                        pair = _corr_equi_pair(c, inner_schema, outer_schema)
                        if pair is not None:
                            keys.append(pair)
                        else:
                            residual.append(c)
                    else:
                        keep.append(c)
                new_input = walk(p.input, False)
                if keep:
                    return Filter(new_input, and_(*keep))
                return new_input
            return p

        new_sub = walk(sub)
        res = and_(*residual) if residual else None
        return keys, res, new_sub

    def _plan_scalar(self, outer: LogicalPlan, sub: LogicalPlan,
                     join_type: str = "inner"):
        """Turn a scalar subquery into a join; returns (new_outer, replacement).

        join_type: WHERE-context callers keep "inner" (a no-match row's NULL
        comparison filters it anyway); SELECT-list callers must pass "left"
        — the outer row survives with a NULL value."""
        self.counter += 1
        alias_name = f"__sq{self.counter}"
        # locate [Projection] -> Aggregate -> [Filter] -> input
        proj, agg, below = _find_agg_pattern(sub)
        if (agg is not None and join_type == "inner"
                and not agg.group_exprs and _is_count_only(agg)):
            # the inner-join premise (no-match NULL filters the row anyway)
            # is FALSE for COUNT: its no-match value is 0, so e.g.
            # `WHERE (SELECT count(*) ...) = 0` must keep the row
            join_type = "left"
        if agg is None:
            if not _plan_references_outer(sub, outer.schema):
                # uncorrelated non-aggregate subquery (e.g. SELECT col FROM
                # cte_that_aggregates): evaluate eagerly like the inline IN
                # path — this is also where SQL's one-row contract is
                # enforced (on ROWS, not distinct values) instead of
                # silently multiplying outer rows
                vals = _eval_uncorrelated_column(
                    sub, dedup=False, max_values=1, what="scalar subquery",
                    overflow_hint=" (SQL allows at most one row)")
                return outer, Literal(vals[0] if vals else None)
            raise PlanningError(f"scalar subquery must aggregate:\n{sub.display()}")
        corr_keys: list[tuple[Expr, Expr]] = []
        new_below = below
        if isinstance(below, Filter):
            inner_schema = below.input.schema
            keep = []
            for c in split_conjunction(below.predicate):
                if _references_outer(c, inner_schema):
                    pair = _corr_equi_pair(c, inner_schema, outer.schema)
                    if pair is None:
                        raise PlanningError(f"unsupported correlated predicate {c}")
                    corr_keys.append(pair)
                else:
                    keep.append(c)
            new_below = Filter(below.input, and_(*keep)) if keep else below.input

        value_expr: Expr = (
            proj.exprs[0] if proj is not None else Column(agg.schema.field(len(agg.group_exprs)).name)
        )
        if isinstance(value_expr, Alias):
            value_expr = value_expr.expr

        if not corr_keys:
            new_agg = Aggregate(new_below, list(agg.group_exprs), list(agg.agg_exprs))
            if agg.group_exprs:
                # grouped: may yield 0 or >1 rows — evaluate eagerly so an
                # empty result becomes NULL (a CrossJoin would wipe every
                # outer row) and >1 rows raises per SQL
                vals = _eval_uncorrelated_column(
                    Projection(new_agg, [Alias(value_expr, "__value")]),
                    dedup=False, max_values=1, what="scalar subquery",
                    overflow_hint=" (SQL allows at most one row)")
                return outer, Literal(vals[0] if vals else None)
            # ungrouped aggregate: exactly one row, cross join
            value = Projection(new_agg, [Alias(value_expr, "__value")])
            aliased = SubqueryAlias(value, alias_name)
            return CrossJoin(outer, aliased), Column("__value", alias_name)

        inner_cols = [ik for (_, ik) in corr_keys]
        if agg.group_exprs and any(g not in inner_cols for g in agg.group_exprs):
            # grouping by anything beyond the correlation keys can yield
            # several rows per outer row; the join lowering would silently
            # duplicate outer rows instead of raising SQL's one-row error
            raise PlanningError(
                "correlated scalar subquery with GROUP BY over "
                "non-correlation columns may return more than one row")
        group_exprs = list(agg.group_exprs) + [c for c in inner_cols if c not in agg.group_exprs]
        new_agg = Aggregate(new_below, group_exprs, list(agg.agg_exprs))
        # correlation keys get INTERNAL names: re-exposing e.g. `k` through
        # the __sqN alias makes any later unqualified `k` ambiguous
        proj_exprs: list[Expr] = [
            Alias(Column(c.output_name(), c.qualifier if isinstance(c, Column) else None),
                  f"__ck{i}")
            for i, c in enumerate(inner_cols)
        ]
        count_fallback = (
            join_type == "left" and not agg.group_exprs and _is_count_only(agg)
        )
        if count_fallback:
            # COUNT over no matching rows is 0, not NULL — but the 0 must
            # feed the subquery's post-aggregate computation (count(*)+1
            # over no rows is 1, not 0), so the subquery side exports the
            # RAW count columns and the value expression re-evaluates above
            # the join over coalesced counts. A user-grouped subquery
            # (agg.group_exprs non-empty) keeps NULL: its empty group set
            # yields no row at all per SQL.
            out_names = [
                new_agg.schema.field(len(group_exprs) + i).name
                for i in range(len(agg.agg_exprs))
            ]
            av_map = {nm: f"__av{i}" for i, nm in enumerate(out_names)}
            for nm, av in av_map.items():
                proj_exprs.append(Alias(Column(nm), av))

            def _coalesced(e: Expr) -> Expr:
                if isinstance(e, Column) and e.output_name() in av_map:
                    return ScalarFunction(
                        "coalesce",
                        (Column(av_map[e.output_name()], alias_name), Literal(0)),
                    )
                return e

            repl = transform_expr(value_expr, _coalesced)
        else:
            proj_exprs.append(Alias(value_expr, "__value"))
            repl = Column("__value", alias_name)
        value = Projection(new_agg, proj_exprs)
        aliased = SubqueryAlias(value, alias_name)
        join_on = [
            (ok, Column(f"__ck{i}", alias_name))
            for i, (ok, _) in enumerate(corr_keys)
        ]
        return Join(outer, aliased, join_on, join_type, None), repl


def _is_count_only(agg: Aggregate) -> bool:
    """True when every aggregate in the node is a count (the no-match value
    under a left join must then be 0, not NULL)."""
    from ballista_tpu.plan.expressions import AggregateFunction

    def fn(e: Expr):
        e = e.expr if isinstance(e, Alias) else e
        return isinstance(e, AggregateFunction) and e.func in ("count", "count_distinct")

    return bool(agg.agg_exprs) and all(fn(a) for a in agg.agg_exprs)


def _find_agg_pattern(sub: LogicalPlan):
    proj = None
    p = sub
    while isinstance(p, (SubqueryAlias,)):
        p = p.children()[0]
    if isinstance(p, Projection):
        proj = p
        p = p.input
    if isinstance(p, Aggregate):
        return proj, p, p.input
    return proj, None, None


def _has_subquery(e: Expr) -> bool:
    return expr_any(e, lambda x: isinstance(x, (ScalarSubquery, InSubquery, Exists)))


def _collect_nodes(e: Expr, cls, out: list | None = None) -> list:
    if out is None:
        out = []
    if isinstance(e, cls):
        out.append(e)
    for c in e.children():
        _collect_nodes(c, cls, out)
    return out


def _collect_scalar_subqueries(e: Expr) -> list:
    return _collect_nodes(e, ScalarSubquery)


def _collect_in_subqueries(e: Expr) -> list:
    return _collect_nodes(e, InSubquery)


def _first_output_expr(sub: LogicalPlan) -> Expr:
    """First output column of `sub` as an expression over the schema that
    remains after _extract_correlation(exists=True) drops the top
    Projection/Distinct wrappers."""
    p = sub
    while isinstance(p, (SubqueryAlias, Distinct)):
        p = p.children()[0]
    if isinstance(p, Projection):
        e = p.exprs[0]
        return e.expr if isinstance(e, Alias) else e
    f0 = p.schema.field(0)
    return Column(f0.name, f0.qualifier)


def _collect_exists(e: Expr) -> list:
    return _collect_nodes(e, Exists)


_EAGER_IN_MAX_VALUES = 10_000


def _eval_uncorrelated_column(
    sub: LogicalPlan,
    dedup: bool = True,
    max_values: int = _EAGER_IN_MAX_VALUES,
    what: str = "IN subquery inside a disjunction",
    overflow_hint: str = "; rewrite as a join",
) -> list:
    """Execute an uncorrelated subplan locally and return its first column's
    values (deduped + null-stripped for IN lists; raw rows for scalar
    callers, whose one-row contract counts rows, not distinct values). A
    correlated subplan fails binding (its outer columns don't resolve) and
    surfaces as a clean planning error."""
    from ballista_tpu.engine.physical_planner import PhysicalPlanner
    from ballista_tpu.plan.physical import TaskContext

    try:
        # run the full rewrite pipeline on the subplan: it was extracted from
        # an expression, so the plan-tree passes (join extraction, pushdown)
        # never saw it — planning it raw would execute comma-joins as
        # cartesian products
        phys = PhysicalPlanner().plan(optimize(sub))
        ctx = TaskContext()
        vals: list = []
        for p in range(phys.output_partition_count()):
            for b in phys.execute(p, ctx):
                vals.extend(b.column(0).to_pylist())
                if len(vals) > max_values:
                    raise PlanningError(
                        f"{what} yielded more than {max_values} "
                        f"value(s){overflow_hint}")
        if not dedup:
            return vals
        return sorted({v for v in vals if v is not None})
    except PlanningError:
        raise
    except Exception as e:  # noqa: BLE001
        raise PlanningError(f"cannot evaluate {what} (correlated?): {e}") from None


def _replace_node(e: Expr, target: Expr, repl: Expr) -> Expr:
    if e is target:
        return repl
    kids = e.children()
    if not kids:
        return e
    return e.with_children([_replace_node(k, target, repl) for k in kids])


def _references_outer(e: Expr, inner_schema) -> bool:
    cols = collect_columns(e)
    return any(inner_schema.maybe_index_of(c.name, c.qualifier) is None for c in cols)


def _plan_references_outer(plan: LogicalPlan, outer_schema) -> bool:
    """True if any Filter in `plan` references a column that does not
    resolve against its own input but DOES resolve against the outer query
    (a column resolving against neither is a plain unknown-column error and
    must not be classified as correlation)."""
    found = False

    def walk(p: LogicalPlan):
        nonlocal found
        if found:
            return
        if isinstance(p, Filter):
            for c in collect_columns(p.predicate):
                if (p.input.schema.maybe_index_of(c.name, c.qualifier) is None
                        and outer_schema.maybe_index_of(c.name, c.qualifier) is not None):
                    found = True
                    return
        for ch in p.children():
            walk(ch)

    walk(plan)
    return found


def _corr_equi_pair(c: Expr, inner_schema, outer_schema):
    """outer_expr = inner_expr pattern → (outer_expr, inner_expr)."""
    if isinstance(c, BinaryExpr) and c.op == "=":
        sides = [c.left, c.right]
        for i in (0, 1):
            a, b = sides[i], sides[1 - i]
            a_cols, b_cols = collect_columns(a), collect_columns(b)
            if not a_cols or not b_cols:
                continue
            a_outer = all(inner_schema.maybe_index_of(x.name, x.qualifier) is None for x in a_cols)
            b_inner = all(inner_schema.maybe_index_of(x.name, x.qualifier) is not None for x in b_cols)
            if a_outer and b_inner:
                return (a, b)
    return None


# -- 4. cross-join elimination ----------------------------------------------


def extract_joins(plan: LogicalPlan) -> LogicalPlan:
    if not isinstance(plan, Filter):
        return plan
    rels = _flatten_cross(plan.input)
    if len(rels) < 2:
        return plan
    conjs = split_conjunction(plan.predicate)

    local: list[Expr] = []  # single-relation or non-equi predicates
    edges: list[tuple[int, int, Expr, Expr]] = []  # (rel_a, rel_b, expr_a, expr_b)
    for c in conjs:
        edge = _classify_edge(c, rels)
        if edge is None:
            local.append(c)
        else:
            edges.append(edge)

    joined = {0}
    acc = rels[0]
    remaining = list(range(1, len(rels)))
    while remaining:
        pick = None
        for idx in remaining:
            if any((a in joined and b == idx) or (b in joined and a == idx) for a, b, _, _ in edges):
                pick = idx
                break
        if pick is None:
            pick = remaining[0]
            acc = CrossJoin(acc, rels[pick])
        else:
            keys = []
            for a, b, ea, eb in edges:
                if a in joined and b == pick:
                    keys.append((ea, eb))
                elif b in joined and a == pick:
                    keys.append((eb, ea))
            acc = Join(acc, rels[pick], keys, "inner", None)
        joined.add(pick)
        remaining.remove(pick)

    if local:
        return Filter(acc, and_(*local))
    return acc


def _flatten_cross(p: LogicalPlan) -> list[LogicalPlan]:
    if isinstance(p, CrossJoin):
        return _flatten_cross(p.left) + _flatten_cross(p.right)
    return [p]


def _rel_of(e: Expr, rels: list[LogicalPlan]) -> int | None:
    """Index of the single relation resolving ALL columns of e, else None."""
    cols = collect_columns(e)
    if not cols:
        return None
    owner = None
    for c in cols:
        found = None
        for i, r in enumerate(rels):
            if r.schema.maybe_index_of(c.name, c.qualifier) is not None:
                found = i
                break
        if found is None:
            return None
        if owner is None:
            owner = found
        elif owner != found:
            return -1  # spans multiple relations
    return owner


def _classify_edge(c: Expr, rels: list[LogicalPlan]):
    if isinstance(c, BinaryExpr) and c.op == "=":
        ra = _rel_of(c.left, rels)
        rb = _rel_of(c.right, rels)
        if ra is not None and rb is not None and ra >= 0 and rb >= 0 and ra != rb:
            return (ra, rb, c.left, c.right)
    return None


# -- 5. filter pushdown ------------------------------------------------------


def push_filters(plan: LogicalPlan) -> LogicalPlan:
    def fn(p: LogicalPlan) -> LogicalPlan:
        if not isinstance(p, Filter):
            return p
        return _push_filter_once(p)

    # run to fixpoint (filters migrate down one node per pass)
    prev = None
    while prev is not plan:
        prev = plan
        plan = transform_plan_up(plan, fn)
        if plan.display() == prev.display():
            break
    return plan


def _push_filter_once(f: Filter) -> LogicalPlan:
    child = f.input
    conjs = split_conjunction(f.predicate)

    if isinstance(child, Filter):
        return Filter(child.input, and_(*(conjs + split_conjunction(child.predicate))))

    if isinstance(child, (Join, CrossJoin)):
        left, right = child.children()
        jt = child.join_type if isinstance(child, Join) else "inner"
        push_left, push_right, keep = [], [], []
        allow_left = jt in ("inner", "left", "left_semi", "left_anti", "cross")
        allow_right = jt in ("inner", "right", "right_semi", "right_anti", "cross")
        if isinstance(child, CrossJoin):
            allow_left = allow_right = True
        for c in conjs:
            if _resolves_all(c, left.schema) and allow_left:
                push_left.append(c)
            elif _resolves_all(c, right.schema) and allow_right:
                push_right.append(c)
            else:
                keep.append(c)
        if not push_left and not push_right:
            return f
        nl = Filter(left, and_(*push_left)) if push_left else left
        nr = Filter(right, and_(*push_right)) if push_right else right
        new_child = child.with_children([nl, nr])
        return Filter(new_child, and_(*keep)) if keep else new_child

    if isinstance(child, Projection):
        # substitute projection defs into the predicate and push below
        mapping: dict[tuple[str, str | None], Expr] = {}
        for e in child.exprs:
            inner = e.expr if isinstance(e, Alias) else e
            key = (e.output_name(), inner.qualifier if isinstance(inner, Column) else None)
            mapping[(e.output_name(), None)] = inner
            mapping[key] = inner
        ok = True
        new_conjs = []
        for c in conjs:
            try:
                new_conjs.append(_substitute_cols(c, mapping))
            except KeyError:
                ok = False
                break
        if ok:
            return Projection(Filter(child.input, and_(*new_conjs)), child.exprs)
        return f

    if isinstance(child, SubqueryAlias):
        inner_schema = child.input.schema
        mapping = {}
        for i, fld in enumerate(child.schema.fields):
            inner_f = inner_schema.field(i)
            mapping[(fld.name, child.alias)] = Column(inner_f.name, inner_f.qualifier)
            mapping[(fld.name, None)] = Column(inner_f.name, inner_f.qualifier)
        try:
            new_conjs = [_substitute_cols(c, mapping) for c in conjs]
        except KeyError:
            return f
        return SubqueryAlias(Filter(child.input, and_(*new_conjs)), child.alias)

    if isinstance(child, Aggregate):
        group_ok, keep = [], []
        group_names = {g.output_name() for g in child.group_exprs}
        for c in conjs:
            cols = collect_columns(c)
            if cols and all(col.name in group_names for col in cols):
                mapping = {}
                for g in child.group_exprs:
                    mapping[(g.output_name(), None)] = g
                    if isinstance(g, Column):
                        mapping[(g.output_name(), g.qualifier)] = g
                try:
                    group_ok.append(_substitute_cols(c, mapping))
                    continue
                except KeyError:
                    pass
            keep.append(c)
        if group_ok:
            new_agg = Aggregate(Filter(child.input, and_(*group_ok)), child.group_exprs, child.agg_exprs)
            return Filter(new_agg, and_(*keep)) if keep else new_agg
        return f

    if isinstance(child, TableScan):
        pushable, keep = [], []
        for c in conjs:
            if _scan_pushable(c):
                pushable.append(c)
            else:
                keep.append(c)
        if pushable:
            new_scan = TableScan(
                child.table_name, child.provider, child.projection,
                child.filters + pushable, child.alias,
            )
            return Filter(new_scan, and_(*keep)) if keep else new_scan
        return f

    return f


def _resolves_all(e: Expr, schema) -> bool:
    cols = collect_columns(e)
    return bool(cols) and all(schema.maybe_index_of(c.name, c.qualifier) is not None for c in cols)


def _substitute_cols(e: Expr, mapping: dict) -> Expr:
    if isinstance(e, Column):
        key = (e.name, e.qualifier)
        if key in mapping:
            return mapping[key]
        if (e.name, None) in mapping:
            return mapping[(e.name, None)]
        raise KeyError(key)
    kids = e.children()
    if not kids:
        return e
    return e.with_children([_substitute_cols(k, mapping) for k in kids])


def _scan_pushable(c: Expr) -> bool:
    """Exactly-evaluable at scan time (column vs literal comparisons)."""
    if isinstance(c, BinaryExpr) and c.op in ("=", "<>", "<", "<=", ">", ">="):
        return (isinstance(c.left, Column) and isinstance(c.right, Literal)) or (
            isinstance(c.right, Column) and isinstance(c.left, Literal)
        )
    if isinstance(c, InList):
        return isinstance(c.expr, Column)
    if isinstance(c, Between):
        return (
            isinstance(c.expr, Column)
            and isinstance(c.low, Literal)
            and isinstance(c.high, Literal)
        )
    return False


# -- 6. column pruning -------------------------------------------------------


def prune_columns(plan: LogicalPlan) -> LogicalPlan:
    required = [Column(f.name, f.qualifier) for f in plan.schema]
    return _prune(plan, required)


def _expr_cols(exprs) -> list[Column]:
    out: list[Column] = []
    seen = set()
    for e in exprs:
        for c in collect_columns(e):
            k = (c.name, c.qualifier)
            if k not in seen:
                seen.add(k)
                out.append(c)
    return out


def _side_split(cols, left_schema, right_schema):
    l, r = [], []
    for c in cols:
        if left_schema.maybe_index_of(c.name, c.qualifier) is not None:
            l.append(c)
        elif right_schema.maybe_index_of(c.name, c.qualifier) is not None:
            r.append(c)
    return l, r


def _prune(plan: LogicalPlan, required: list[Column]) -> LogicalPlan:
    if isinstance(plan, Projection):
        needed = _expr_cols(plan.exprs)
        return Projection(_prune(plan.input, needed), plan.exprs)
    if isinstance(plan, Filter):
        needed = _dedup(required + _expr_cols([plan.predicate]))
        return Filter(_prune(plan.input, needed), plan.predicate)
    if isinstance(plan, Aggregate):
        needed = _expr_cols(plan.group_exprs + plan.agg_exprs)
        return Aggregate(_prune(plan.input, needed), plan.group_exprs, plan.agg_exprs)
    if isinstance(plan, Sort):
        needed = _dedup(required + _expr_cols([k.expr for k in plan.keys]))
        return Sort(_prune(plan.input, needed), plan.keys, plan.fetch)
    if isinstance(plan, Window):
        win_cols = _expr_cols([
            e for w in plan.window_exprs
            for e in (list(w.args) + list(w.partition_by) + [k.expr for k in w.order_by])
        ])
        # __win{i} outputs are produced here, not read from the child
        passthrough = [c for c in required if not c.name.startswith("__win")]
        needed = _dedup(passthrough + win_cols)
        return Window(_prune(plan.input, needed), plan.window_exprs)
    if isinstance(plan, (Limit, Distinct)):
        if isinstance(plan, Distinct):
            required = [Column(f.name, f.qualifier) for f in plan.schema]
        return plan.with_children([_prune(plan.children()[0], required)])
    if isinstance(plan, SubqueryAlias):
        inner_schema = plan.input.schema
        inner_req = []
        for c in required:
            i = plan.schema.maybe_index_of(c.name, c.qualifier)
            if i is None:
                i = plan.schema.maybe_index_of(c.name, None)
            if i is not None:
                f = inner_schema.field(i)
                inner_req.append(Column(f.name, f.qualifier))
        # keep full schema shape: SubqueryAlias renames positionally
        if len(inner_req) < len(inner_schema):
            inner_req = [Column(f.name, f.qualifier) for f in inner_schema]
        return SubqueryAlias(_prune(plan.input, inner_req), plan.alias)
    if isinstance(plan, Join):
        key_cols = _expr_cols([e for pair in plan.on for e in pair])
        filt_cols = _expr_cols([plan.filter]) if plan.filter is not None else []
        all_cols = _dedup(required + key_cols + filt_cols)
        lcols, rcols = _side_split(all_cols, plan.left.schema, plan.right.schema)
        return Join(
            _prune(plan.left, lcols), _prune(plan.right, rcols), plan.on, plan.join_type, plan.filter
        )
    if isinstance(plan, CrossJoin):
        lcols, rcols = _side_split(_dedup(required), plan.left.schema, plan.right.schema)
        return CrossJoin(_prune(plan.left, lcols), _prune(plan.right, rcols))
    if isinstance(plan, Union):
        return Union([_prune(c, required) for c in plan.inputs], plan.all)
    if isinstance(plan, TableScan):
        filter_cols = _expr_cols(plan.filters)
        idxs = []
        full = plan.provider.df_schema().with_qualifier(plan.alias or plan.table_name)
        for c in _dedup(required + filter_cols):
            i = full.maybe_index_of(c.name, c.qualifier)
            if i is None:
                i = full.maybe_index_of(c.name, None)
            if i is not None and i not in idxs:
                idxs.append(i)
        idxs.sort()
        if not idxs:
            idxs = [0]  # count(*)-style scans still need one column
        return TableScan(plan.table_name, plan.provider, idxs, plan.filters, plan.alias)
    return plan.with_children([_prune(c, [Column(f.name, f.qualifier) for f in c.schema]) for c in plan.children()])


def _dedup(cols: list[Column]) -> list[Column]:
    out, seen = [], set()
    for c in cols:
        k = (c.name, c.qualifier)
        if k not in seen:
            seen.add(k)
            out.append(c)
    return out
