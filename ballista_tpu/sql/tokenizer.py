"""SQL tokenizer.

The reference outsources SQL to DataFusion's sqlparser; we need our own.
Produces a flat token stream: keywords (uppercased), identifiers, string /
number literals, operators, punctuation. Comments (`--` and `/* */`) are
stripped. Case-insensitive keywords; identifiers keep original case but are
matched case-insensitively downstream.
"""

from __future__ import annotations

from dataclasses import dataclass

from ballista_tpu.errors import SqlParseError

KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "OFFSET", "AS", "AND", "OR", "NOT", "IN", "EXISTS", "BETWEEN", "LIKE",
    "IS", "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
    "CAST", "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "ON",
    "USING", "UNION", "ALL", "DISTINCT", "ASC", "DESC", "NULLS", "FIRST",
    "LAST", "WITH", "DATE", "INTERVAL", "EXTRACT", "SUBSTRING", "FOR",
    "VALUES", "EXPLAIN", "ANALYZE", "VERBOSE", "CREATE", "EXTERNAL", "TABLE",
    "STORED", "LOCATION", "DROP", "SHOW", "TABLES", "COLUMNS", "SET", "SEMI",
    "ANTI", "NATURAL", "OVER", "PARTITION", "ROLLUP", "CUBE", "GROUPING", "SETS",
    "EXCEPT", "INTERSECT",
}


@dataclass(frozen=True)
class Token:
    kind: str  # kw | ident | string | number | op | punct | eof
    value: str
    pos: int

    def is_kw(self, *kws: str) -> bool:
        return self.kind == "kw" and self.value in kws


_OPS = ["<>", "!=", ">=", "<=", "||", "=", "<", ">", "+", "-", "*", "/", "%"]
_PUNCT = "(),.;"


def tokenize(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "-" and i + 1 < n and sql[i + 1] == "-":
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and i + 1 < n and sql[i + 1] == "*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlParseError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":  # escaped ''
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            if j >= n:
                raise SqlParseError(f"unterminated string at {i}")
            toks.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlParseError(f"unterminated quoted identifier at {i}")
            toks.append(Token("ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_e = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_e:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_e and j > i:
                    seen_e = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            toks.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            up = word.upper()
            if up in KEYWORDS:
                toks.append(Token("kw", up, i))
            else:
                toks.append(Token("ident", word, i))
            i = j
            continue
        matched = False
        for op in _OPS:
            if sql.startswith(op, i):
                toks.append(Token("op", "<>" if op == "!=" else op, i))
                i += len(op)
                matched = True
                break
        if matched:
            continue
        if c in _PUNCT:
            toks.append(Token("punct", c, i))
            i += 1
            continue
        raise SqlParseError(f"unexpected character {c!r} at position {i}")
    toks.append(Token("eof", "", n))
    return toks
