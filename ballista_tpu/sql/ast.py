"""Statement-level AST.

Expressions inside these nodes are already `ballista_tpu.plan.expressions`
objects (the parser emits the expression IR directly); subquery expressions
carry a raw `SelectStmt` that the planner replaces with a planned
LogicalPlan during binding.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ballista_tpu.plan.expressions import Expr, SortKey


@dataclass
class TableName:
    name: str
    alias: Optional[str] = None


@dataclass
class DerivedTable:
    select: "SelectStmt"
    alias: str


@dataclass
class JoinClause:
    left: Any  # TableName | DerivedTable | JoinClause
    right: Any
    join_type: str  # inner/left/right/full/cross
    on: Optional[Expr] = None


@dataclass
class SelectStmt:
    projections: list[Expr] = field(default_factory=list)
    distinct: bool = False
    from_tables: list[Any] = field(default_factory=list)  # comma-separated refs
    where: Optional[Expr] = None
    group_by: list[Any] = field(default_factory=list)  # Expr | int ordinal
    # ROLLUP/CUBE/GROUPING SETS: list of grouping sets (each a list of
    # indices into group_by); None = plain GROUP BY
    grouping_sets: Optional[list[list[int]]] = None
    having: Optional[Expr] = None
    order_by: list[SortKey] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    ctes: list[tuple[str, "SelectStmt"]] = field(default_factory=list)
    set_op: Optional[tuple[str, "SelectStmt"]] = None  # ("union"|"union_all", rhs)


@dataclass
class ExplainStmt:
    inner: Any
    analyze: bool = False
    verbose: bool = False


@dataclass
class CreateExternalTable:
    name: str
    location: str
    file_format: str = "parquet"


@dataclass
class DropTable:
    name: str
    if_exists: bool = False


@dataclass
class ValuesClause:
    rows: list  # list of literal rows
    alias: str = "__values__"
    column_names: list = None  # optional t(c1, c2, ...) renames


@dataclass
class ShowColumns:
    table: str


@dataclass
class ShowTables:
    pass


@dataclass
class SetVariable:
    key: str
    value: str
